"""Per-shard search execution: query phase → (sorted top docs, aggs) → fetch.

Re-design of `search/SearchService.java:365` + `search/query/QueryPhase.java:171`
+ `search/fetch/FetchPhase.java` (SURVEY.md §3.2). One shard executes:

  1. parse the request body (query + knn + post_filter + sort + aggs ...),
  2. QUERY phase: evaluate the query to (rows, scores) — vectorized/device —
     apply min_score/post_filter, sort (score or doc-values), cut the
     [from, from+size) window, compute aggregations,
  3. FETCH phase: materialize hits (_source filtering, docvalue_fields,
     script_fields, highlight, sort values).

The shard-level result (`QuerySearchResult` analog) carries enough for the
coordinator's cross-shard merge: sort keys, scores, shard-local doc rows.
"""

from __future__ import annotations

import fnmatch
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu import native
from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError
from elasticsearch_tpu.index.mapping import MapperService, TextFieldMapper
from elasticsearch_tpu.index.segment import ShardReader
from elasticsearch_tpu.search.aggregations import compute_aggs
from elasticsearch_tpu.search.queries import (
    BoolQuery, DocSet, MatchAllQuery, Query, SearchContext, parse_query,
)

DEFAULT_SIZE = 10
MAX_RESULT_WINDOW = 10_000
TRACK_TOTAL_HITS_DEFAULT = 10_000


_SEARCH_BODY_KEYS = {
    "query", "from", "size", "sort", "track_total_hits", "track_scores",
    "aggs", "aggregations", "post_filter", "highlight", "_source", "fields",
    "docvalue_fields", "stored_fields", "script_fields", "suggest",
    "rescore", "explain", "version", "seq_no_primary_term", "min_score",
    "search_after", "collapse", "profile", "timeout", "terminate_after",
    "indices_boost", "knn", "rank", "pit", "runtime_mappings", "slice",
    "ext", "stats", "point_in_time", "batched_reduce_size",
    "pre_filter_shard_size", "scroll", "max_concurrent_shard_requests",
    "request_cache",
}


def _check_request_limits(body: dict, settings: dict) -> None:
    """Per-index request guardrails (IndexSettings MAX_* settings +
    SearchService validation): reject before any work happens."""
    for key in body:
        if key not in _SEARCH_BODY_KEYS and not key.startswith("__"):
            # dunder keys are internal coordinator annotations
            raise ParsingError(
                f"unknown key [{key}] for a search request body "
                f"(SearchSourceBuilder)")
    tth = body.get("track_total_hits")
    if isinstance(tth, int) and not isinstance(tth, bool) \
            and tth < 0 and tth != -1:
        raise IllegalArgumentError(
            f"[track_total_hits] parameter must be positive or equals "
            f"to -1, got {tth}")
    if body.get("collapse") is not None:
        if body.get("rescore") is not None:
            raise IllegalArgumentError(
                "cannot use `collapse` in conjunction with `rescore`")
        inner = (body["collapse"] or {}).get("inner_hits")
        inner_list = inner if isinstance(inner, list) else \
            [inner] if inner else []
        for ih in inner_list:
            # a second-level collapse inside inner_hits is legal; IT may
            # not define inner_hits or a third collapse (CollapseBuilder)
            nested = (ih or {}).get("collapse") if isinstance(ih, dict) \
                else None
            if isinstance(nested, dict) and (
                    nested.get("inner_hits") is not None
                    or nested.get("collapse") is not None):
                raise ParsingError(
                    "parse_exception: [collapse] inner collapse cannot "
                    "define inner_hits or another collapse")
    frm = body.get("from")
    if frm is not None and int(frm) < 0:
        raise IllegalArgumentError("[from] parameter cannot be negative")
    size = body.get("size")
    if size is not None and int(size) < 0:
        raise IllegalArgumentError(f"[size] parameter cannot be negative, "
                                   f"found [{size}]")
    max_dvf = int(settings.get("index.max_docvalue_fields_search", 100))
    if len(body.get("docvalue_fields") or []) > max_dvf:
        raise IllegalArgumentError(
            f"Trying to retrieve too many docvalue_fields. Must be less "
            f"than or equal to: [{max_dvf}] but was "
            f"[{len(body['docvalue_fields'])}]. This limit can be set by "
            f"changing the [index.max_docvalue_fields_search] index level "
            f"setting.")
    max_sf = int(settings.get("index.max_script_fields", 32))
    if len(body.get("script_fields") or {}) > max_sf:
        raise IllegalArgumentError(
            f"Trying to retrieve too many script_fields. Must be less than "
            f"or equal to: [{max_sf}] but was "
            f"[{len(body['script_fields'])}]. This limit can be set by "
            f"changing the [index.max_script_fields] index level setting.")
    rescore_spec = body.get("rescore")
    if rescore_spec is not None:
        max_rw = int(settings.get("index.max_rescore_window", 10_000))
        specs = rescore_spec if isinstance(rescore_spec, list) else [rescore_spec]
        for spec in specs:
            window = int(spec.get("window_size", 10))
            if window > max_rw:
                raise IllegalArgumentError(
                    f"Rescore window [{window}] is too large. It must be "
                    f"less than [{max_rw}]. This prevents allocating "
                    f"massive heaps for storing the results to be "
                    f"rescored. This limit can be set by changing the "
                    f"[index.max_rescore_window] index level setting.")


class ShardSearchResult:
    """Per-shard query-phase output (QuerySearchResult analog)."""

    __slots__ = ("shard_id", "rows", "scores", "sort_values", "total_hits",
                 "total_relation", "aggregations", "max_score", "failures",
                 "knn_phases", "aggs_profile")

    def __init__(self, shard_id, rows, scores, sort_values, total_hits,
                 total_relation, aggregations, max_score, failures=None,
                 knn_phases=None, aggs_profile=None):
        self.shard_id = shard_id
        self.rows = rows
        self.scores = scores
        self.sort_values = sort_values  # list of per-doc sort key tuples (or None)
        self.total_hits = total_hits
        self.total_relation = total_relation
        self.aggregations = aggregations
        self.max_score = max_score
        self.failures = failures or []  # partial per-shard failures
        self.knn_phases = knn_phases    # tpu_ivf route/score/merge timings
        self.aggs_profile = aggs_profile  # device-agg engine breakdown


def execute_query_phase(reader: ShardReader, mapper_service: MapperService,
                        body: dict, shard_id: int = 0,
                        vector_store=None,
                        partial_aggs: bool = False,
                        query_cache=None,
                        index_settings: Optional[dict] = None,
                        max_buckets: Optional[int] = None,
                        allow_expensive: bool = True,
                        index_name: str = "index",
                        agg_engine=None,
                        deadline_at: Optional[float] = None
                        ) -> ShardSearchResult:
    ctx = SearchContext(reader, mapper_service, query_cache=query_cache)
    ctx.vector_store = vector_store
    # propagated cross-node deadline (monotonic s): device-work legs pass
    # it into the continuous batcher so the EDF queue sheds expired
    # sub-requests at THIS node's admission layer (serving/fanout.py)
    ctx.deadline_at = deadline_at
    ctx.index_settings = index_settings or {}
    ctx.max_buckets = max_buckets
    ctx.allow_expensive = allow_expensive
    ctx.index_name = index_name
    ctx.shard_failures = []
    _check_request_limits(body, ctx.index_settings)

    query = parse_query(body.get("query")) if body.get("query") is not None else MatchAllQuery()

    # top-level knn (ES 8 API shape): combined as should-clause with the query
    knn_spec = body.get("knn")
    if knn_spec is not None:
        from elasticsearch_tpu.search.knn_query import KnnQuery
        specs = knn_spec if isinstance(knn_spec, list) else [knn_spec]
        knn_queries: List[Query] = []
        for spec in specs:
            knn_queries.append(KnnQuery(
                field=spec["field"], query_vector=spec["query_vector"],
                k=int(spec.get("k", spec.get("num_candidates", 10))),
                num_candidates=int(spec.get("num_candidates", spec.get("k", 10))),
                filter_query=parse_query(spec["filter"]) if spec.get("filter") else None,
                boost=float(spec.get("boost", 1.0))))
        if body.get("query") is None:
            query = knn_queries[0] if len(knn_queries) == 1 else BoolQuery(should=knn_queries)
        else:
            query = BoolQuery(should=[query] + knn_queries)

    result = query.execute(ctx).with_scores()
    rows, scores = result.rows, result.scores

    # sliced scroll (reference: SliceBuilder -> TermsSliceQuery on _id:
    # floorMod(murmur3(id, seed 7919), max) == id selects this slice)
    slice_spec = body.get("slice")
    if slice_spec is not None:
        try:
            sid = int(slice_spec["id"])
            smax = int(slice_spec["max"])
        except (TypeError, ValueError, AttributeError, KeyError):
            raise IllegalArgumentError(
                f"malformed slice [{slice_spec!r}]: expected {{id, max}}")
        if smax <= 1:
            raise IllegalArgumentError("max must be greater than 1")
        max_slices = int(ctx.index_settings.get(
            "index.max_slices_per_scroll", 1024))
        if smax > max_slices:
            raise IllegalArgumentError(
                f"The number of slices [{smax}] is too large. It must be "
                f"less than [{max_slices}]. This limit can be set by "
                f"changing the [index.max_slices_per_scroll] index level "
                f"setting.")
        if sid < 0 or sid >= smax:
            raise IllegalArgumentError(
                f"id must be greater than or equal to 0 and less than "
                f"max ({smax})")
        num_shards = int(ctx.index_settings.get(
            "index.number_of_shards", 1))
        if smax <= num_shards:
            # fewer slices than shards: a slice owns whole shards
            # (SliceBuilder.toFilter shard-level short circuit); shard
            # membership recomputes from the routing hash, which holds
            # for combined readers too
            from elasticsearch_tpu.cluster.routing import shard_id_for
            keep = np.asarray([
                shard_id_for(str(reader.get_id(int(r))),
                             num_shards) % smax == sid
                for r in rows], dtype=bool)
        else:
            from elasticsearch_tpu.search.aggregations import (
                _murmur3_x86_32)
            keep = np.asarray([
                _murmur3_x86_32(_encode_uid(str(reader.get_id(int(r)))),
                                7919) % smax == sid
                for r in rows], dtype=bool)
        rows, scores = rows[keep], scores[keep]

    # post_filter: applied after aggs scope (reference: POST_FILTER applies to
    # hits only, not aggs)
    agg_rows = rows
    # scripted_metric map_scripts may read _score: expose the agg-scope
    # scores (aligned with agg_rows) on the context
    ctx.agg_score_rows, ctx.agg_scores = rows, scores
    post_filter = body.get("post_filter")
    if post_filter is not None:
        pf_rows = parse_query(post_filter).execute(ctx).rows
        keep = np.isin(rows, pf_rows)
        rows, scores = rows[keep], scores[keep]

    min_score = body.get("min_score")
    if min_score is not None:
        keep = scores >= float(min_score)
        rows, scores = rows[keep], scores[keep]

    # rescore on the top window (reference: search/rescore/ — the BM25+kNN
    # fusion point)
    rescore_spec = body.get("rescore")
    if rescore_spec is not None:
        rows, scores = _apply_rescore(ctx, rows, scores, rescore_spec)

    total_hits = int(len(rows))
    track = body.get("track_total_hits", TRACK_TOTAL_HITS_DEFAULT)
    if track is True:
        relation = "eq"
    else:
        limit = TRACK_TOTAL_HITS_DEFAULT if track is False else int(track)
        relation = "eq" if total_hits <= limit else "gte"
        if relation == "gte":
            total_hits = limit

    # sorting
    sort_spec = _normalize_sort(body.get("sort"))
    if sort_spec:
        for sfield, _o, _m in sort_spec:
            m = mapper_service.get(sfield)
            if getattr(m, "type_name", None) == "text" \
                    and (m.params or {}).get("fielddata"):
                # sorting on text fielddata materializes it (stats report
                # bytes only for actually-loaded fields)
                mapper_service.mark_fielddata_loaded(sfield)
    search_after = body.get("search_after")
    frm_ = int(body.get("from", 0) or 0)
    size_ = int(body.get("size", DEFAULT_SIZE)
                if body.get("size") is not None else DEFAULT_SIZE)
    collapse_spec = body.get("collapse")
    if collapse_spec is not None and search_after is not None:
        raise IllegalArgumentError(
            "cannot use `collapse` in conjunction with `search_after`")
    if sort_spec is None and search_after is None and collapse_spec is None:
        # score ranking: partial top-(from+size) selection via the native
        # heap (the Lucene TopScoreDocCollector analog) instead of a full
        # argsort; ties break by row asc, identical to the lexsort below
        # because candidate rows are already ascending
        max_score_early = float(scores.max()) if len(scores) else None
        k = min(frm_ + size_, len(rows))
        idx = native.topk(scores, k)
        order = idx
        sort_values = None
        rows, scores = rows[order], scores[order]
        # note: `rows` is now the ranked top window only; total_hits and
        # aggs were computed from the full sets above
    else:
        max_score_early = None
        order, sort_values = _sort_docs(ctx, rows, scores, sort_spec)
        rows, scores = rows[order], scores[order]
        if sort_values is not None:
            sort_values = [sort_values[i] for i in order]

    # search_after
    if search_after is not None:
        if sort_spec is None:
            raise IllegalArgumentError("search_after requires a sort")
        start = _search_after_cut(sort_values, scores, search_after, sort_spec)
        rows, scores = rows[start:], scores[start:]
        if sort_values is not None:
            sort_values = sort_values[start:]

    # field collapsing: keep only the best-ranked hit per group value; the
    # total stays uncollapsed (CollapseBuilder / CollapsingTopDocsCollector)
    if collapse_spec is not None:
        cfield = collapse_spec["field"]
        seen_groups = set()
        keep = []
        for i, r in enumerate(rows):
            v = ctx.reader.get_doc_value(cfield, int(r))
            if isinstance(v, list):
                v = v[0] if v else None
            if v in seen_groups:
                continue
            seen_groups.add(v)
            keep.append(i)
            # the window below only keeps from+size entries: once that many
            # distinct groups are ranked, later rows cannot surface
            if len(keep) >= frm_ + size_:
                break
        rows, scores = rows[keep], scores[keep]
        if sort_values is not None:
            sort_values = [sort_values[i] for i in keep]

    frm, size = frm_, size_
    # scroll snapshots page past the window by design (internal flag); normal
    # searches enforce the reference's index.max_result_window guard
    mrw = int(ctx.index_settings.get("index.max_result_window",
                                     MAX_RESULT_WINDOW))
    if frm + size > mrw and not body.get("__unbounded_window__"):
        raise IllegalArgumentError(
            f"Result window is too large, from + size must be less than or equal "
            f"to: [{mrw}] but was [{frm + size}]. See the scroll api for a "
            f"more efficient way to request large data sets. This limit can "
            f"be set by changing the [index.max_result_window] index level "
            f"setting.")
    window = slice(0, frm + size)  # shard returns from+size, coordinator skips
    w_rows, w_scores = rows[window], scores[window]
    w_sort = sort_values[window.start:window.stop] if sort_values is not None else None

    aggs = None
    aggs_profile = None
    aggs_spec = body.get("aggs") or body.get("aggregations")
    if aggs_spec:
        if agg_engine is not None:
            # device-resident aggregations (search/agg_plan.py): supported
            # nodes reduce on device as fused filter→aggregate dispatches,
            # everything else falls through per node to the host walkers;
            # None means no node was device-eligible — unchanged host path
            device_out = agg_engine.compute(ctx, agg_rows, aggs_spec,
                                            partial=partial_aggs)
            if device_out is not None:
                aggs, aggs_profile = device_out
        if aggs is None:
            if partial_aggs:
                # distributed search: ship mergeable partial states, the
                # coordinator reduces + finalizes
                # (InternalAggregation.reduce)
                from elasticsearch_tpu.search.agg_partials import (
                    compute_partial_aggs)
                aggs = compute_partial_aggs(ctx, agg_rows, aggs_spec)
            else:
                aggs = compute_aggs(ctx, agg_rows, aggs_spec)

    if max_score_early is not None:
        max_score = max_score_early
    else:
        max_score = float(scores.max()) if len(scores) and sort_spec is None else None
    return ShardSearchResult(shard_id, w_rows, w_scores, w_sort, total_hits,
                             relation, aggs, max_score,
                             failures=getattr(ctx, "shard_failures", None),
                             knn_phases=getattr(ctx, "knn_phases", None),
                             aggs_profile=aggs_profile)


def _apply_rescore(ctx, rows, scores, rescore_spec):
    specs = rescore_spec if isinstance(rescore_spec, list) else [rescore_spec]
    for spec in specs:
        window = int(spec.get("window_size", 10))
        rq = spec.get("query", {})
        rescore_query = parse_query(rq.get("rescore_query"))
        qw = float(rq.get("query_weight", 1.0))
        rqw = float(rq.get("rescore_query_weight", 1.0))
        mode = rq.get("score_mode", "total")
        # take current top-window docs
        order = np.argsort(-scores, kind="stable")
        top = order[:window]
        rest = order[window:]
        rs = rescore_query.execute(ctx).with_scores()
        idx = np.searchsorted(rs.rows, rows[top])
        idx = np.clip(idx, 0, max(len(rs.rows) - 1, 0))
        matched = len(rs.rows) > 0
        # candidates OUTSIDE the window keep the weighted query score
        # (210_rescore_explain: explanation must match the final score)
        new_scores = scores * qw
        if matched:
            hit = rs.rows[idx] == rows[top]
            second = np.where(hit, rs.scores[idx], 0.0)
            if mode == "total":
                new_scores[top] = qw * scores[top] + rqw * second
            elif mode == "multiply":
                new_scores[top] = np.where(hit, scores[top] * qw * second * rqw, scores[top] * qw)
            elif mode == "max":
                new_scores[top] = np.maximum(qw * scores[top], rqw * second)
            elif mode == "min":
                new_scores[top] = np.where(hit, np.minimum(qw * scores[top], rqw * second),
                                           qw * scores[top])
            elif mode == "avg":
                new_scores[top] = np.where(hit, (qw * scores[top] + rqw * second) / 2,
                                           qw * scores[top])
        scores = new_scores
    return rows, scores


def _normalize_sort(sort) -> Optional[List[Tuple[str, str, Any]]]:
    """Returns [(field, order, spec)] or None for default score sort."""
    if sort is None:
        return None
    if isinstance(sort, (str, dict)):
        sort = [sort]
    out = []
    for item in sort:
        if isinstance(item, str):
            if item == "_score":
                out.append(("_score", "desc", {}))
            elif item == "_doc":
                out.append(("_doc", "asc", {}))
            else:
                out.append((item, "asc", {}))
        elif isinstance(item, dict):
            ((field, spec),) = item.items()
            if isinstance(spec, str):
                out.append((field, spec, {}))
            else:
                out.append((field, spec.get("order", "asc" if field != "_score" else "desc"),
                            spec))
        else:
            raise ParsingError(f"malformed sort clause {item!r}")
    if len(out) == 1 and out[0][0] == "_score":
        return None
    return out


_MISSING_LAST = float("inf")


def _sort_docs(ctx: SearchContext, rows, scores, sort_spec):
    """Returns (order array, per-doc sort value tuples or None)."""
    if sort_spec is None:
        # score desc, row asc tiebreak (stable shard-level order)
        order = np.lexsort((rows, -scores))
        return order, None
    keys = []
    sort_values = [[] for _ in range(len(rows))]
    for field, direction, spec in sort_spec:
        if field == "_score":
            vals = scores.astype(np.float64)
            for i, v in enumerate(vals):
                sort_values[i].append(float(v))
        elif field == "_doc":
            vals = rows.astype(np.float64)
            for i, v in enumerate(vals):
                sort_values[i].append(int(v))
        else:
            from elasticsearch_tpu.search.aggregations import numeric_values
            nums, present = numeric_values(ctx, rows, field)
            # numeric_type coercion: cross-index sorts over date/date_nanos
            # compare in one domain (FieldSortBuilder#setNumericType)
            ntype = spec.get("numeric_type")
            ftype = getattr(ctx.mapper_service.get(field), "type_name", None)
            if ntype == "date" and ftype == "date_nanos":
                nums = nums / 1e6
            elif ntype == "date_nanos" and ftype == "date":
                nums = nums * 1e6
            if present.any() or ctx.mapper_service.get(field) is None or \
               ctx.mapper_service.get(field).type_name in (
                   "long", "integer", "short", "byte", "double", "float",
                   "half_float", "date", "date_nanos", "boolean", "ip",
                   "scaled_float"):
                missing = spec.get("missing", "_last")
                fill = _MISSING_LAST if (missing == "_last") == (direction == "asc") else -_MISSING_LAST
                if isinstance(missing, (int, float)) and not isinstance(missing, bool):
                    fill = float(missing)
                vals = np.where(present, nums, fill)
                integral = ctx.mapper_service.get(field) is not None and \
                    ctx.mapper_service.get(field).type_name in (
                        "long", "integer", "short", "byte", "date",
                        "date_nanos")
                for i in range(len(rows)):
                    if not present[i]:
                        sort_values[i].append(None)
                    elif integral:
                        # int64-domain sort values keep full precision:
                        # nanosecond timestamps don't survive float64
                        raw = ctx.reader.get_doc_value(field, int(rows[i]))
                        if isinstance(raw, list):
                            raw = raw[0] if raw else None
                        if isinstance(raw, (int, float)):
                            rv = int(raw)
                            if ntype == "date" and ftype == "date_nanos":
                                rv = rv // 1_000_000
                            elif ntype == "date_nanos" and ftype == "date":
                                rv = rv * 1_000_000
                            sort_values[i].append(rv)
                        else:
                            sort_values[i].append(float(nums[i]))
                    else:
                        sort_values[i].append(float(nums[i]))
            else:
                # string sort via object dtype
                svals = []
                for r in rows:
                    v = ctx.reader.get_doc_value(field, int(r))
                    if isinstance(v, list):
                        v = v[0] if v else None
                    svals.append(v)
                for i, v in enumerate(svals):
                    sort_values[i].append(v)
                # encode strings to sortable floats via rank
                uniq = sorted({s for s in svals if s is not None}, key=str)
                rank = {s: float(i) for i, s in enumerate(uniq)}
                vals = np.asarray([rank.get(s, _MISSING_LAST if direction == "asc" else -_MISSING_LAST)
                                   for s in svals], dtype=np.float64)
        keys.append(vals if direction == "asc" else -vals)
    keys.append(rows.astype(np.float64))  # final tiebreak
    order = np.lexsort(tuple(reversed(keys)))
    return order, [tuple(sort_values[i]) for i in range(len(rows))]


def _search_after_cut(sort_values, scores, after, sort_spec) -> int:
    """Index of the first doc strictly after the search_after key."""
    def cmp_key(sv):
        out = []
        for (field, direction, _), v in zip(sort_spec, sv):
            if v is None:
                v = _MISSING_LAST
            if isinstance(v, str):
                out.append((v, direction))
            else:
                out.append((float(v), direction))
        return out

    def is_after(sv):
        for (v, direction), a in zip(cmp_key(sv), after):
            av = float(a) if isinstance(a, (int, float)) and not isinstance(a, bool) else a
            try:
                if v == av:
                    continue
                gt = v > av
            except TypeError:
                continue
            return gt if direction == "asc" else not gt
        return False

    for i, sv in enumerate(sort_values):
        if is_after(sv):
            return i
    return len(sort_values)


# ---------------------------------------------------------------------------
# fetch phase
# ---------------------------------------------------------------------------

def _filter_source(source: dict, includes, excludes) -> dict:
    if not includes and not excludes:
        return source

    def flatten(obj, prefix=""):
        for k, v in obj.items():
            path = f"{prefix}{k}"
            if isinstance(v, dict):
                yield from flatten(v, path + ".")
            else:
                yield path, v

    def matches(path, patterns):
        return any(fnmatch.fnmatch(path, p) or path.startswith(p + ".")
                   for p in patterns)

    out: dict = {}
    for path, v in flatten(source):
        if includes and not matches(path, includes):
            continue
        if excludes and matches(path, excludes):
            continue
        node = out
        parts = path.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def execute_fetch_phase(reader: ShardReader, mapper_service: MapperService,
                        body: dict, result: ShardSearchResult,
                        index_name: str = "index",
                        from_offset: int = 0,
                        index_settings: Optional[dict] = None) -> List[dict]:
    """Materialize hits for the (already coordinator-trimmed) doc window."""
    ctx = SearchContext(reader, mapper_service)
    source_spec = body.get("_source", True)
    includes: List[str] = []
    excludes: List[str] = []
    want_source = True
    if source_spec is False:
        want_source = False
    elif isinstance(source_spec, str):
        includes = [source_spec]
    elif isinstance(source_spec, list):
        includes = source_spec
    elif isinstance(source_spec, dict):
        includes = source_spec.get("includes", source_spec.get("include", [])) or []
        excludes = source_spec.get("excludes", source_spec.get("exclude", [])) or []
        if isinstance(includes, str):
            includes = [includes]
        if isinstance(excludes, str):
            excludes = [excludes]

    docvalue_fields = body.get("docvalue_fields", [])
    script_fields = body.get("script_fields", {})
    highlight_spec = body.get("highlight")
    sort_spec = _normalize_sort(body.get("sort"))
    explain = bool(body.get("explain", False))

    # stored_fields (FetchPhase/StoredFieldsContext): [] keeps metadata but
    # drops _source; "_none_" drops metadata too; a field list loads
    # store:true fields and suppresses _source unless asked for
    stored_spec = body.get("stored_fields")
    want_id = True
    stored_list: List[str] = []
    if stored_spec is not None:
        if stored_spec == "_none_":
            want_id = False
            want_source = False
        else:
            stored_list = ([stored_spec] if isinstance(stored_spec, str)
                           else list(stored_spec))
            if "_source" not in stored_list:
                want_source = False

    nested_ih_specs = _nested_inner_hits_specs(body.get("query"))
    hits = []
    for i in range(from_offset, len(result.rows)):
        row = int(result.rows[i])
        hit: Dict[str, Any] = {
            "_index": index_name,
            "_score": None if sort_spec is not None else float(result.scores[i]),
        }
        if want_id:
            hit["_id"] = reader.get_id(row)
        if sort_spec is not None and result.sort_values is not None:
            hit["sort"] = list(result.sort_values[i])
        if body.get("seq_no_primary_term"):
            hit["_seq_no"] = reader.get_seq_no(row)
            pt = reader.get_doc_value("_primary_term", row)
            hit["_primary_term"] = int(pt) if pt is not None else 1
        if body.get("version"):
            v = reader.get_doc_value("_version", row)
            hit["_version"] = int(v) if v is not None else 1
        if stored_list:
            sf = {}
            src_for_fields = reader.get_source(row) or {}
            for fname in stored_list:
                if fname.startswith("_"):
                    continue
                mapper = mapper_service.get(fname)
                if mapper is None or not mapper.params.get("store"):
                    continue
                val = _get_path(src_for_fields, fname)
                if val is not None:
                    sf[fname] = val if isinstance(val, list) else [val]
            if sf:
                hit["fields"] = sf
        routing = reader.get_doc_value("_routing", row)
        if routing is not None:
            hit["_routing"] = routing
        ignored = reader.get_doc_value("_ignored", row)
        if ignored:
            hit["_ignored"] = sorted(ignored) \
                if isinstance(ignored, list) else [ignored]
        if want_source:
            src = reader.get_source(row) or {}
            hit["_source"] = _filter_source(src, includes, excludes)
        if docvalue_fields:
            fields = {}
            for f in docvalue_fields:
                fname = f["field"] if isinstance(f, dict) else f
                fmt = f.get("format") if isinstance(f, dict) else None
                if fname == "_seq_no":
                    v = reader.get_seq_no(row)
                elif fname == "_primary_term":
                    v = 1
                else:
                    v = reader.get_doc_value(fname, row)
                if v is not None:
                    vals = v if isinstance(v, list) else [v]
                    # the same field may repeat with different formats:
                    # values append in request order (FieldAndFormat list)
                    fields.setdefault(fname, []).extend(
                        _format_doc_value(x, mapper_service.get(fname), fmt)
                        for x in vals)
            if fields:
                hit.setdefault("fields", {}).update(fields)
        if script_fields:
            from elasticsearch_tpu.search.script_score import Script
            sf = hit.setdefault("fields", {})
            for name, spec in script_fields.items():
                s = Script(spec.get("script", spec))
                val = s.evaluate(ctx, np.asarray([row]), np.zeros(1, dtype=np.float32))
                sf[name] = [float(val[0])]
        if highlight_spec:
            hl = _highlight(ctx, mapper_service, body, highlight_spec, row,
                            index_settings=index_settings)
            if hl:
                hit["highlight"] = hl
        collapse_spec = body.get("collapse")
        if collapse_spec:
            _decorate_collapsed_hit(ctx, reader, mapper_service, body,
                                    collapse_spec, row, hit, index_name)
        for path, ih_spec, ih_query in nested_ih_specs:
            _decorate_nested_inner_hits(reader, row, hit, path, ih_spec,
                                        ih_query, index_name)
        if explain:
            hit["_explanation"] = {"value": hit["_score"] or 0.0,
                                   "description": "vectorized score", "details": []}
        hits.append(hit)
    return hits


def _decorate_collapsed_hit(ctx, reader, mapper_service, body, collapse_spec,
                            row, hit, index_name) -> None:
    """Collapsed hits carry the group value under `fields` and, when asked,
    the group's own ranked window under `inner_hits`
    (ExpandSearchPhase.java:42 runs one sub-search per collapsed hit)."""
    cfield = collapse_spec["field"]
    # field aliases resolve to their concrete path per index
    # (FieldAliasMapper: collapse on an alias collapses the target)
    from elasticsearch_tpu.index.mapping import AliasFieldMapper
    read_field = cfield
    raw_mapper = mapper_service.get_raw(cfield) \
        if hasattr(mapper_service, "get_raw") else mapper_service.get(cfield)
    if isinstance(raw_mapper, AliasFieldMapper):
        read_field = (raw_mapper.params or {}).get("path", cfield)
    v = reader.get_doc_value(read_field, row)
    if isinstance(v, list):
        v = v[0] if v else None
    hit.setdefault("fields", {})[cfield] = [v]
    inner = collapse_spec.get("inner_hits")
    if not inner:
        return
    specs = inner if isinstance(inner, list) else [inner]
    for spec in specs:
        name = spec.get("name", cfield)
        sub_collapse = spec.get("collapse")
        want = int(spec.get("size", 3))
        sub_body = {"query": {"bool": {
            "must": [body["query"]] if body.get("query") else [],
            "filter": [{"term": {read_field: v}}]}},
            "size": want * 10 if sub_collapse else want,
            "from": int(spec.get("from", 0))}
        for key in ("sort", "version", "seq_no_primary_term",
                    "docvalue_fields", "_source"):
            if spec.get(key) is not None:
                sub_body[key] = spec[key]
        sub_result = execute_query_phase(reader, mapper_service, sub_body)
        sub_hits = execute_fetch_phase(reader, mapper_service, sub_body,
                                       sub_result, index_name=index_name,
                                       from_offset=int(spec.get("from", 0)))
        if sub_collapse:
            # a second-level collapse inside inner_hits dedups the window
            # by the inner group value (ExpandSearchPhase nested collapse);
            # fetch skipped `from` rows, so pair hits with the same slice
            seen = set()
            deduped = []
            for h, r2 in zip(sub_hits,
                             sub_result.rows[int(spec.get("from", 0)):]):
                gv = reader.get_doc_value(sub_collapse["field"], int(r2))
                if isinstance(gv, list):
                    gv = gv[0] if gv else None
                h.setdefault("fields", {})[sub_collapse["field"]] = [gv]
                if gv in seen:
                    continue
                seen.add(gv)
                deduped.append(h)
            sub_hits = deduped[:want]
        hit.setdefault("inner_hits", {})[name] = {"hits": {
            "total": {"value": sub_result.total_hits,
                      "relation": sub_result.total_relation},
            "max_score": sub_result.max_score,
            "hits": sub_hits}}


_TAG_DEFAULT = ("<em>", "</em>")


def _highlight(ctx, mapper_service, body, spec, row,
               index_settings=None) -> Dict[str, List[str]]:
    """Unified/plain/fvh highlighting: wrap query-matched terms in the
    stored text (reference: `search/fetch/subphase/highlight/`).

    Term predicates (exact terms + prefixes) come from the search query or
    a per-field highlight_query; `require_field_match: false` lets any
    field's predicates light up any highlighted field. Keyword fields wrap
    whole matching values (ignored-above values never highlight); analyzed
    fields re-tokenize, so index.highlight.max_analyzed_offset guards the
    plain/unified-without-offsets paths."""
    from elasticsearch_tpu.index.mapping import KeywordFieldMapper

    source = ctx.reader.get_source(row) or {}
    index_settings = index_settings or getattr(ctx, "index_settings", {}) \
        or {}

    # field -> (exact terms, prefixes); terms analyzed per target field
    query_terms: Dict[str, set] = {}
    query_prefixes: Dict[str, set] = {}

    def field_names():
        return [p for p, _m in mapper_service.all_mappers()]

    def add_terms(field, text):
        mapper = mapper_service.get(field)
        if isinstance(mapper, TextFieldMapper):
            terms = mapper.search_analyzer.terms(str(text))
        else:
            terms = [str(text)]
        query_terms.setdefault(field, set()).update(terms)

    def collect_terms(q: dict):
        if not isinstance(q, dict):
            return
        for kind, qspec in q.items():
            if kind in ("match", "match_phrase", "term",
                        "match_phrase_prefix"):
                if not isinstance(qspec, dict) or not qspec:
                    continue
                ((field, v),) = list(qspec.items())[:1]
                text = v.get("query", v.get("value")) \
                    if isinstance(v, dict) else v
                add_terms(field, text)
            elif kind == "prefix":
                if not isinstance(qspec, dict) or not qspec:
                    continue
                ((field, v),) = list(qspec.items())[:1]
                text = v.get("value", v.get("prefix")) \
                    if isinstance(v, dict) else v
                query_prefixes.setdefault(field, set()).add(
                    str(text).lower())
            elif kind == "multi_match":
                import fnmatch as _fn
                text = qspec.get("query", "")
                for f in qspec.get("fields", []):
                    pat = f.split("^")[0]
                    targets = ([pat] if "*" not in pat else
                               [n for n in field_names()
                                if _fn.fnmatch(n, pat)])
                    for fname in targets:
                        add_terms(fname, text)
            elif kind == "query_string":
                text = qspec.get("query", "")
                f = qspec.get("default_field")
                if f and "*" not in str(f):
                    add_terms(f, text)
            elif kind == "bool":
                for clause in ("must", "should", "filter"):
                    items = qspec.get(clause, [])
                    if isinstance(items, dict):
                        items = [items]
                    for sub in items:
                        collect_terms(sub)

    collect_terms(body.get("query", {}))
    pre = spec.get("pre_tags", [_TAG_DEFAULT[0]])[0]
    post = spec.get("post_tags", [_TAG_DEFAULT[1]])[0]
    require_match = spec.get("require_field_match", True)
    if isinstance(require_match, str):
        require_match = require_match != "false"
    default_type = spec.get("type")
    max_offset = int(index_settings.get(
        "index.highlight.max_analyzed_offset", 1_000_000))

    import fnmatch as _fn
    fields_spec = spec.get("fields", {})
    if isinstance(fields_spec, list):
        merged = {}
        for entry in fields_spec:
            merged.update(entry or {})
        fields_spec = merged
    expanded: Dict[str, dict] = {}
    for pattern, fspec in fields_spec.items():
        if "*" in pattern:
            for name in field_names():
                m = mapper_service.get(name)
                if isinstance(m, (TextFieldMapper, KeywordFieldMapper)) \
                        and _fn.fnmatch(name, pattern):
                    expanded.setdefault(name, fspec or {})
        else:
            expanded[pattern] = fspec or {}

    out = {}
    for field, fspec in expanded.items():
        mapper = mapper_service.get(field)
        if mapper is None:
            continue
        terms = set()
        prefixes = set()
        hq = (fspec or {}).get("highlight_query")
        if hq:
            saved_t, saved_p = query_terms, query_prefixes
            query_terms, query_prefixes = {}, {}
            collect_terms(hq)
            terms = query_terms.get(field, set())
            prefixes = query_prefixes.get(field, set())
            query_terms, query_prefixes = saved_t, saved_p
        elif require_match:
            terms = query_terms.get(field, set())
            prefixes = query_prefixes.get(field, set())
        else:
            for s in query_terms.values():
                terms |= s
            for s in query_prefixes.values():
                prefixes |= s
        if not terms and not prefixes:
            continue
        # multi-fields highlight the PARENT's stored value
        raw = _get_path(source, field)
        if raw is None and "." in field:
            raw = _get_path(source, field.rsplit(".", 1)[0])
        if raw is None:
            continue

        def matches(term: str) -> bool:
            return term in terms or any(str(term).lower().startswith(p)
                                        for p in prefixes)

        if isinstance(mapper, KeywordFieldMapper):
            vals = raw if isinstance(raw, list) else [raw]
            frags = []
            ignore_above = (mapper.params or {}).get("ignore_above")
            for v in vals:
                v = str(v)
                if ignore_above is not None and len(v) > int(ignore_above):
                    continue  # the value was never indexed: nothing matched
                if matches(v):
                    frags.append(pre + v + post)
            if frags:
                out[field] = frags
            continue
        if not isinstance(mapper, TextFieldMapper):
            continue
        text = str(raw)
        htype = (fspec or {}).get("type") or default_type or "unified"
        tv = str((mapper.params or {}).get("term_vector", ""))
        has_offsets = "offsets" in tv or \
            (mapper.params or {}).get("index_options") == "offsets"
        if len(text) > max_offset and (htype == "plain" or not has_offsets):
            raise IllegalArgumentError(
                f"The length [{len(text)}] of field [{field}] in doc/index "
                f"has exceeded [{max_offset}] - maximum allowed to be "
                f"analyzed for highlighting. This maximum can be set by "
                f"changing the [index.highlight.max_analyzed_offset] index "
                f"level setting. For large texts, indexing with offsets or "
                f"term vectors is recommended!")
        tokens = mapper.analyzer.analyze(text)
        matched = [(t.start_offset, t.end_offset) for t in tokens
                   if matches(t.term)]
        if not matched:
            continue
        frag = text
        for s0, e0 in sorted(set(matched), reverse=True):
            frag = frag[:s0] + pre + frag[s0:e0] + post + frag[e0:]
        out[field] = [frag]
    return out


def _nested_inner_hits_specs(q):
    """(path, inner_hits spec, inner query) for nested queries asking."""
    out = []

    def walk(node):
        if isinstance(node, dict):
            nested = node.get("nested")
            if isinstance(nested, dict) and "inner_hits" in nested:
                out.append((nested.get("path"), nested["inner_hits"] or {},
                            nested.get("query")))
            for v in node.values():
                walk(v)
        elif isinstance(node, list):
            for item in node:
                walk(item)

    walk(q)
    return out


def _nested_item_matches(item: dict, path: str, q) -> bool:
    """Evaluate the nested query against ONE nested object (enough for the
    simple term/match shapes inner_hits are asked with; unknown query
    kinds match everything rather than dropping hits)."""
    if not isinstance(q, dict) or not q:
        return True
    for kind, qspec in q.items():
        if kind == "match_all":
            return True
        if kind in ("match", "term") and isinstance(qspec, dict) and qspec:
            ((f, v),) = list(qspec.items())[:1]
            want = v.get("query", v.get("value")) if isinstance(v, dict) \
                else v
            rel = f[len(path) + 1:] if f.startswith(path + ".") else f
            cur = item
            for part in rel.split("."):
                cur = cur.get(part) if isinstance(cur, dict) else None
            if cur is None:
                return False
            if kind == "term":
                return str(cur) == str(want)
            return str(want).lower() in str(cur).lower()
        if kind == "bool" and isinstance(qspec, dict):
            for clause in ("must", "filter"):
                items = qspec.get(clause, [])
                if isinstance(items, dict):
                    items = [items]
                if not all(_nested_item_matches(item, path, sub)
                           for sub in items):
                    return False
            return True
    return True


def _decorate_nested_inner_hits(reader, row, hit, path, spec, query,
                                index_name) -> None:
    """Per-hit nested inner_hits (InnerHitsPhase): the matching nested
    documents with their _nested locators."""
    src = reader.get_source(row) or {}
    items = src
    for part in str(path or "").split("."):
        items = items.get(part) if isinstance(items, dict) else None
    if isinstance(items, dict):
        items = [items]
    if not isinstance(items, list):
        return
    name = spec.get("name", path)
    size = int(spec.get("size", 3))
    matching = [(off, item) for off, item in enumerate(items)
                if isinstance(item, dict)
                and _nested_item_matches(item, str(path), query)]
    inner_hits = []
    for off, item in matching[:size]:
        ih = {"_index": index_name, "_id": hit.get("_id"),
              "_nested": {"field": path, "offset": off},
              "_score": 1.0, "_source": item}
        if spec.get("version"):
            ih["_version"] = hit.get("_version", 1)
        for df in spec.get("docvalue_fields") or []:
            fname = df["field"] if isinstance(df, dict) else df
            if fname == "_seq_no":
                sq = reader.get_seq_no(row)
                ih.setdefault("fields", {})[fname] = [
                    int(sq) if sq is not None else 0]
            elif fname == "_primary_term":
                ih.setdefault("fields", {})[fname] = [1]
        inner_hits.append(ih)
    hit.setdefault("inner_hits", {})[name] = {
        "hits": {"total": {"value": len(matching), "relation": "eq"},
                 "max_score": 1.0, "hits": inner_hits}}


def _encode_uid(doc_id: str) -> bytes:
    """The _id term encoding (reference: index/mapper/Uid.encodeId):
    numeric ids pack as nibble pairs, base64-able ids as raw bytes,
    everything else utf8 — slicing hashes the ENCODED term."""
    if doc_id and all(c in "0123456789" for c in doc_id) \
            and (len(doc_id) == 1 or doc_id[0] != "0"):
        out = bytearray([0xFE])
        for i in range(0, len(doc_id), 2):
            b1 = ord(doc_id[i]) - ord("0")
            b2 = (ord(doc_id[i + 1]) - ord("0")
                  if i + 1 < len(doc_id) else 0x0F)
            out.append((b1 << 4) | b2)
        return bytes(out)
    import re as _re
    if doc_id and len(doc_id) % 4 != 1 \
            and _re.fullmatch(r"[A-Za-z0-9_-]+", doc_id):
        import base64 as _b64
        try:
            raw = _b64.urlsafe_b64decode(doc_id + "=" * (-len(doc_id) % 4))
            if raw and raw[0] >= 0xFD:
                return bytes([0xFD]) + raw
            return raw
        except Exception:
            pass
    return bytes([0xFF]) + doc_id.encode("utf-8")


def _format_doc_value(v, mapper, fmt):
    """DocValueFormat rendering for docvalue_fields: dates render as ISO
    strings (or per the requested joda pattern), numerics honor
    DecimalFormat patterns like '#.0', everything else passes through."""
    tname = getattr(mapper, "type_name", None)
    if tname in ("date", "date_nanos") and isinstance(v, (int, float)):
        from elasticsearch_tpu.search.aggregations import (
            _format_date_key, _millis_to_iso)
        millis = int(v) // 1_000_000 if tname == "date_nanos" else int(v)
        if fmt == "epoch_millis":
            if tname == "date_nanos":
                nanos = int(v)
                return f"{nanos // 1_000_000}.{nanos % 1_000_000:06d}"
            return str(int(v))
        def nanos_iso(digits=9, strip=False):
            nanos = int(v)
            frac = nanos % 1_000_000_000
            import datetime as _dt
            base = _dt.datetime.fromtimestamp(
                nanos // 1_000_000_000, _dt.timezone.utc)
            fs = f".{frac:09d}"[: digits + 1]
            if strip:
                fs = fs.rstrip("0").ljust(2, "0")
            return base.strftime("%Y-%m-%dT%H:%M:%S") + fs + "Z"

        if fmt and "SSSSSSSSS" in fmt:
            # nanosecond joda/java patterns (uuuu-MM-dd'T'HH:mm:ss.SSSSSSSSSX)
            if tname == "date_nanos":
                return nanos_iso(9)
            return _millis_to_iso(millis)[:-1] + "000000Z" \
                if _millis_to_iso(millis).endswith("Z") \
                else _millis_to_iso(millis)
        if fmt == "strict_date_optional_time":
            # millisecond-resolution rendering even for nanos fields
            return _millis_to_iso(millis)
        if fmt:
            return _format_date_key(millis, fmt)
        if tname == "date_nanos":
            return nanos_iso(9, strip=True)
        return _millis_to_iso(millis)
    if fmt and isinstance(v, (int, float)) and not isinstance(v, bool) \
            and any(c in fmt for c in "#0"):
        from elasticsearch_tpu.search.aggregations import _decimal_format
        return _decimal_format(v, fmt)
    return v


def _get_path(obj: dict, path: str):
    node = obj
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node
