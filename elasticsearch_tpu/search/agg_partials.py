"""Mergeable partial aggregation states for the distributed reduce.

Re-design of the reference's internal-aggregation reduce
(`search/aggregations/InternalAggregation.java` reduce(),
`action/search/SearchPhaseController.java:734`): shards never ship
finalized JSON for aggregations — they ship *partial states* (sum/count
pairs, HyperLogLog sketches for cardinality, t-digest sketches for
percentiles, per-term sub-agg trees) that the coordinator merges
associatively and finalizes once.  This is what makes `avg`,
`cardinality`, `percentiles`, and `terms`-with-sub-aggs correct across
shards with divergent data.

Three spec-driven walkers:

  compute_partial_aggs(ctx, rows, spec)  — per-shard, partial states
  merge_partial_aggs(a, b, spec)         — associative coordinator merge
  finalize_aggs(partial, spec)           — final JSON + pipeline aggs

Partial states are plain JSON-safe dicts tagged with "$p" so they
serialize over the node-to-node transport unchanged.
"""

from __future__ import annotations

import hashlib
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import ParsingError
from elasticsearch_tpu.search import aggregations as A
from elasticsearch_tpu.search.aggregations import (
    BUCKET_AGGS, METRIC_AGGS, PIPELINE_AGGS, SearchContext, _hashable,
    _sort_key, all_values, numeric_values,
)

# single-bucket aggs: one {doc_count, subs...} object, no bucket list
SINGLE_BUCKET = {"filter", "global", "missing", "sampler", "nested",
                 "reverse_nested"}

# ---------------------------------------------------------------------------
# HyperLogLog (cardinality) — reference: HyperLogLogPlusPlus in
# search/aggregations/metrics/; here: classic HLL, p=12 (4096 registers,
# ~1.6% stderr), sparse representation below 512 occupied registers.
# ---------------------------------------------------------------------------

_HLL_P = 12
_HLL_M = 1 << _HLL_P
_HLL_ALPHA = 0.7213 / (1 + 1.079 / _HLL_M)
_HLL_SPARSE_MAX = 512


def _hll_hash(v) -> int:
    if isinstance(v, bool):
        b = b"b1" if v else b"b0"
    elif isinstance(v, (int, float)):
        b = repr(float(v)).encode()
    else:
        b = repr(v).encode()
    return int.from_bytes(hashlib.blake2b(b, digest_size=8).digest(), "big")


def _hll_from_values(values) -> dict:
    regs: Dict[int, int] = {}
    for v in values:
        h = _hll_hash(v)
        idx = h & (_HLL_M - 1)
        rest = h >> _HLL_P
        rank = (64 - _HLL_P) - rest.bit_length() + 1
        if rank > regs.get(idx, 0):
            regs[idx] = rank
    return _hll_pack(regs)


def _hll_pack(regs: Dict[int, int]) -> dict:
    if len(regs) <= _HLL_SPARSE_MAX:
        return {"$p": "hll", "sparse": {str(k): v for k, v in regs.items()}}
    dense = [0] * _HLL_M
    for k, v in regs.items():
        dense[k] = v
    return {"$p": "hll", "dense": dense}


def _hll_regs(state: dict) -> Dict[int, int]:
    if "sparse" in state:
        return {int(k): v for k, v in state["sparse"].items()}
    return {i: v for i, v in enumerate(state["dense"]) if v}


def _hll_merge(a: dict, b: dict) -> dict:
    regs = _hll_regs(a)
    for k, v in _hll_regs(b).items():
        if v > regs.get(k, 0):
            regs[k] = v
    return _hll_pack(regs)


def _hll_estimate(state: dict) -> int:
    regs = _hll_regs(state)
    zeros = _HLL_M - len(regs)
    inv_sum = zeros + sum(2.0 ** -r for r in regs.values())
    raw = _HLL_ALPHA * _HLL_M * _HLL_M / inv_sum
    if raw <= 2.5 * _HLL_M and zeros:
        raw = _HLL_M * math.log(_HLL_M / zeros)
    return int(round(raw))


# ---------------------------------------------------------------------------
# t-digest (percentiles / ranks / MAD / boxplot) — reference: TDigestState in
# search/aggregations/metrics/. Merging-digest variant; centroid weights are
# bounded by 4·W·q(1−q)/δ, so with ≤δ values the sketch is exact.
# ---------------------------------------------------------------------------

_TD_COMPRESSION = 200


def _td_compress(cents: List[List[float]]) -> List[List[float]]:
    if not cents:
        return []
    cents = sorted(cents)
    total = sum(w for _, w in cents)
    out: List[List[float]] = []
    cum = 0.0
    for mean, w in cents:
        if out:
            q = (cum + out[-1][1] / 2) / total
            limit = max(1.0, 4.0 * total * q * (1 - q) / _TD_COMPRESSION)
            if out[-1][1] + w <= limit:
                m0, w0 = out[-1]
                out[-1] = [(m0 * w0 + mean * w) / (w0 + w), w0 + w]
                continue
            cum += out[-1][1]
        out.append([float(mean), float(w)])
    return out


def _td_from_values(vals: np.ndarray) -> dict:
    cents = _td_compress([[float(v), 1.0] for v in vals])
    return {"$p": "tdigest",
            "c": cents,
            "min": float(vals.min()) if len(vals) else None,
            "max": float(vals.max()) if len(vals) else None,
            "n": int(len(vals))}


def _td_merge(a: dict, b: dict) -> dict:
    mins = [x for x in (a.get("min"), b.get("min")) if x is not None]
    maxs = [x for x in (a.get("max"), b.get("max")) if x is not None]
    return {"$p": "tdigest",
            "c": _td_compress([list(c) for c in a["c"]] + [list(c) for c in b["c"]]),
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "n": a.get("n", 0) + b.get("n", 0)}


def _td_quantile(state: dict, q: float) -> Optional[float]:
    cents = state["c"]
    if not cents:
        return None
    total = sum(w for _, w in cents)
    if total == 1 or len(cents) == 1:
        return cents[0][0] if len(cents) == 1 else None
    target = q * total
    # centroid i's mass is centered at cum + w/2
    cum = 0.0
    prev_mean, prev_mid = state["min"], 0.0
    for mean, w in cents:
        mid = cum + w / 2.0
        if target <= mid:
            if mid == prev_mid:
                return float(mean)
            t = (target - prev_mid) / (mid - prev_mid)
            return float(prev_mean + t * (mean - prev_mean))
        prev_mean, prev_mid = mean, mid
        cum += w
    return float(state["max"])


def _td_cdf(state: dict, x: float) -> float:
    cents = state["c"]
    if not cents:
        return 0.0
    total = sum(w for _, w in cents)
    if state["min"] is not None and x < state["min"]:
        return 0.0
    if state["max"] is not None and x >= state["max"]:
        return 1.0
    cum = 0.0
    prev_mean, prev_mid = state["min"], 0.0
    for mean, w in cents:
        mid = cum + w / 2.0
        if x < mean:
            if mean == prev_mean:
                return prev_mid / total
            t = (x - prev_mean) / (mean - prev_mean)
            return (prev_mid + t * (mid - prev_mid)) / total
        prev_mean, prev_mid = mean, mid
        cum += w
    return 1.0


# ---------------------------------------------------------------------------
# per-shard partial computation
# ---------------------------------------------------------------------------


def compute_partial_aggs(ctx: SearchContext, rows: np.ndarray,
                         aggs_spec: dict) -> dict:
    """Per-shard partial agg tree. Pipelines are deferred to finalize."""
    out: Dict[str, Any] = {}
    for name, spec in (aggs_spec or {}).items():
        if not isinstance(spec, dict):
            raise ParsingError(f"aggregation [{name}] must be an object")
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            raise ParsingError(f"aggregation [{name}] must define exactly one type")
        kind = kinds[0]
        if kind in PIPELINE_AGGS:
            continue
        if kind in METRIC_AGGS:
            out[name] = _compute_metric_partial(ctx, rows, kind, spec[kind])
        elif kind in BUCKET_AGGS or kind in ("nested", "reverse_nested"):
            sub_normal = {
                sname: sspec for sname, sspec in sub.items()
                if not _is_pipeline(sspec)
            }
            out[name] = A._compute_bucket(
                ctx, rows, kind, _partial_spec(kind, spec[kind]), sub_normal,
                recurse=compute_partial_aggs)
        else:
            raise ParsingError(f"unknown aggregation type [{kind}]")
    return out


def _is_pipeline(sspec: dict) -> bool:
    skinds = [k for k in sspec if k not in ("aggs", "aggregations", "meta")]
    return len(skinds) == 1 and skinds[0] in PIPELINE_AGGS


def _partial_spec(kind: str, spec: dict) -> dict:
    """Shard-side spec: ordering/pruning/threshold filtering move to the
    coordinator (post-merge), and per-shard candidate sets are bounded by
    `shard_size` exactly like the reference (TermsAggregatorFactory:
    shard_size defaults to size*1.5+10) so a high-cardinality field does
    not ship its full term dictionary."""
    if kind == "terms":
        s = {k: v for k, v in spec.items() if k != "order"}
        size = int(spec.get("size", 10))
        s["size"] = int(spec.get("shard_size") or (size * 3 // 2 + 10))
        return s
    if kind in ("significant_terms", "significant_text"):
        # ship unpruned candidates: the min_doc_count threshold and JLH
        # ranking re-apply at the coordinator over merged fg/bg counts
        size = int(spec.get("size", 10))
        return {**spec, "min_doc_count": 1,
                "size": int(spec.get("shard_size") or (size * 3 // 2 + 10))}
    if kind == "rare_terms":
        # unpruned counts (max_doc_count filter applies post-merge); the
        # shard_size cap bounds the rarest-candidates set per shard, the
        # role the reference's CuckooFilters play
        return {**spec, "max_doc_count": 1 << 60,
                "size": int(spec.get("shard_size", 1000))}
    if kind in ("geohash_grid", "geotile_grid"):
        size = int(spec.get("size", 10000))
        return {**spec,
                "size": int(spec.get("shard_size") or (size * 3 // 2 + 10))}
    if kind in ("histogram", "date_histogram"):
        # -1: disable threshold pruning WITHOUT enabling the per-shard
        # zero-fill that min_doc_count=0 implies (the coordinator
        # re-fills gaps after the merge)
        return {**spec, "min_doc_count": -1}
    return spec


def _metric_numeric(ctx, rows, spec):
    field = spec.get("field")
    script = spec.get("script")
    if script is not None and field is None:
        from elasticsearch_tpu.search.script_score import Script
        s = Script(script)
        vals = s.evaluate(ctx, rows,
                          np.zeros(len(rows), dtype=np.float32)).astype(np.float64)
        return vals, np.ones(len(rows), dtype=bool)
    return numeric_values(ctx, rows, field, spec.get("missing"))


def _compute_metric_partial(ctx: SearchContext, rows: np.ndarray, kind: str,
                            spec: dict) -> dict:
    field = spec.get("field")

    if kind == "scripted_metric":
        # the shard ships its COMBINED state (init+map+combine run here);
        # reduce_script runs once at the coordinator over all states —
        # exactly the reference's wire contract (ScriptedMetricAggregator
        # ships InternalScriptedMetric with the combine result)
        return {"$p": "scripted_metric",
                "states": [A.scripted_metric_map_combine(ctx, rows, spec)]}

    if kind == "value_count":
        n = len(rows) if field is None else len(all_values(ctx, rows, field))
        return {"$p": "value_count", "n": int(n)}

    if kind == "cardinality":
        return _hll_from_values(
            _hashable(v) for _, v in all_values(ctx, rows, field))

    if kind == "top_hits":
        final = A.compute_metric(ctx, rows, "top_hits", spec)
        return {"$p": "top_hits", "size": int(spec.get("size", 3)),
                "total": final["hits"]["total"]["value"],
                "hits": final["hits"]["hits"]}

    if kind == "top_metrics":
        final = A.compute_top_metrics(ctx, rows, spec)
        return {"$p": "top_metrics", "top": final["top"]}

    if kind == "string_stats":
        values = [str(v) for _, v in all_values(ctx, rows, field)]
        freq: Dict[str, int] = {}
        for v in values:
            for ch in v:
                freq[ch] = freq.get(ch, 0) + 1
        return {"$p": "string_stats", "n": len(values),
                "len_sum": sum(len(v) for v in values),
                "min_len": min((len(v) for v in values), default=None),
                "max_len": max((len(v) for v in values), default=None),
                "freq": freq}

    if kind == "matrix_stats":
        return _matrix_partial(ctx, rows, spec)

    if kind in ("geo_bounds", "geo_centroid"):
        pts = A._gather_geo_points(ctx, rows, field)
        if kind == "geo_bounds":
            if not pts:
                return {"$p": "geo_bounds", "n": 0}
            lats = [p[1] for p in pts]
            lons = [p[2] for p in pts]
            return {"$p": "geo_bounds", "n": len(pts),
                    "minlat": min(lats), "maxlat": max(lats),
                    "minlon": min(lons), "maxlon": max(lons)}
        return {"$p": "geo_centroid", "n": len(pts),
                "lat_sum": sum(p[1] for p in pts),
                "lon_sum": sum(p[2] for p in pts)}

    if kind == "weighted_avg":
        vspec = spec.get("value", {})
        wspec = spec.get("weight", {})
        vv, vp = numeric_values(ctx, rows, vspec.get("field"), vspec.get("missing"))
        wv, wp = numeric_values(ctx, rows, wspec.get("field"),
                                wspec.get("missing", 1.0))
        both = vp & wp
        return {"$p": "weighted_avg",
                "vw": float((vv[both] * wv[both]).sum()),
                "w": float(wv[both].sum())}

    vals, present = _metric_numeric(ctx, rows, spec)
    v = vals[present]

    if kind == "avg":
        return {"$p": "avg", "sum": float(v.sum()), "n": int(len(v))}
    if kind == "sum":
        return {"$p": "sum", "sum": float(v.sum())}
    if kind == "min":
        return {"$p": "min", "v": float(v.min()) if len(v) else None}
    if kind == "max":
        return {"$p": "max", "v": float(v.max()) if len(v) else None}
    if kind == "stats":
        return {"$p": "stats", "n": int(len(v)), "sum": float(v.sum()),
                "min": float(v.min()) if len(v) else None,
                "max": float(v.max()) if len(v) else None}
    if kind == "extended_stats":
        return {"$p": "extended_stats", "n": int(len(v)), "sum": float(v.sum()),
                "ss": float((v ** 2).sum()),
                "min": float(v.min()) if len(v) else None,
                "max": float(v.max()) if len(v) else None}
    if kind in ("percentiles", "percentile_ranks",
                "median_absolute_deviation", "boxplot"):
        return _td_from_values(v)
    raise ParsingError(f"unknown metric aggregation [{kind}]")


def _matrix_partial(ctx, rows, spec) -> dict:
    fields = spec.get("fields", [])
    cols, presents = {}, {}
    for f in fields:
        cols[f], presents[f] = numeric_values(ctx, rows, f)
    if fields:
        mask = np.logical_and.reduce([presents[f] for f in fields])
    else:
        mask = np.zeros(0, dtype=bool)
    n = int(mask.sum())
    # power sums merge by addition; moments are recovered at finalize
    s = {f: [float((cols[f][mask] ** k).sum()) for k in (1, 2, 3, 4)]
         for f in fields}
    sxy = {}
    for i, f in enumerate(fields):
        for g in fields[i + 1:]:
            sxy[f + "|" + g] = float((cols[f][mask] * cols[g][mask]).sum())
    return {"$p": "matrix_stats", "n": n, "fields": list(fields),
            "s": s, "sxy": sxy}


# ---------------------------------------------------------------------------
# coordinator merge
# ---------------------------------------------------------------------------


def merge_partial_aggs(a: dict, b: dict, aggs_spec: dict) -> dict:
    out = dict(a)
    for name, spec in (aggs_spec or {}).items():
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1 or kinds[0] in PIPELINE_AGGS:
            continue
        kind = kinds[0]
        if name not in b:
            continue
        if name not in out:
            out[name] = b[name]
            continue
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        sub = {sn: ss for sn, ss in sub.items() if not _is_pipeline(ss)}
        if kind in METRIC_AGGS:
            out[name] = _merge_metric(out[name], b[name])
        else:
            out[name] = _merge_bucket_agg(kind, spec[kind], out[name],
                                          b[name], sub)
    return out


def _merge_metric(a: dict, b: dict) -> dict:
    tag = a.get("$p")
    if tag != b.get("$p"):
        raise ParsingError(f"partial agg mismatch: {tag} vs {b.get('$p')}")
    if tag == "hll":
        return _hll_merge(a, b)
    if tag == "tdigest":
        return _td_merge(a, b)
    if tag == "value_count":
        return {"$p": tag, "n": a["n"] + b["n"]}
    if tag == "scripted_metric":
        return {"$p": tag, "states": a["states"] + b["states"]}
    if tag == "avg":
        return {"$p": tag, "sum": a["sum"] + b["sum"], "n": a["n"] + b["n"]}
    if tag == "sum":
        return {"$p": tag, "sum": a["sum"] + b["sum"]}
    if tag in ("min", "max"):
        vs = [x for x in (a["v"], b["v"]) if x is not None]
        pick = (min if tag == "min" else max)(vs) if vs else None
        return {"$p": tag, "v": pick}
    if tag == "stats":
        return {"$p": tag, "n": a["n"] + b["n"], "sum": a["sum"] + b["sum"],
                "min": _opt(min, a["min"], b["min"]),
                "max": _opt(max, a["max"], b["max"])}
    if tag == "extended_stats":
        return {"$p": tag, "n": a["n"] + b["n"], "sum": a["sum"] + b["sum"],
                "ss": a["ss"] + b["ss"],
                "min": _opt(min, a["min"], b["min"]),
                "max": _opt(max, a["max"], b["max"])}
    if tag == "weighted_avg":
        return {"$p": tag, "vw": a["vw"] + b["vw"], "w": a["w"] + b["w"]}
    if tag == "geo_bounds":
        if not a["n"]:
            return b
        if not b["n"]:
            return a
        return {"$p": tag, "n": a["n"] + b["n"],
                "minlat": min(a["minlat"], b["minlat"]),
                "maxlat": max(a["maxlat"], b["maxlat"]),
                "minlon": min(a["minlon"], b["minlon"]),
                "maxlon": max(a["maxlon"], b["maxlon"])}
    if tag == "geo_centroid":
        return {"$p": tag, "n": a["n"] + b["n"],
                "lat_sum": a["lat_sum"] + b["lat_sum"],
                "lon_sum": a["lon_sum"] + b["lon_sum"]}
    if tag == "top_hits":
        return {"$p": tag, "size": a["size"], "total": a["total"] + b["total"],
                "hits": (a["hits"] + b["hits"])[:a["size"]]}
    if tag == "top_metrics":
        return {"$p": tag, "top": a["top"] + b["top"]}
    if tag == "string_stats":
        freq = dict(a["freq"])
        for ch, c in b["freq"].items():
            freq[ch] = freq.get(ch, 0) + c
        return {"$p": tag, "n": a["n"] + b["n"],
                "len_sum": a["len_sum"] + b["len_sum"],
                "min_len": _opt(min, a["min_len"], b["min_len"]),
                "max_len": _opt(max, a["max_len"], b["max_len"]),
                "freq": freq}
    if tag == "matrix_stats":
        s = {f: [x + y for x, y in zip(a["s"][f], b["s"][f])]
             for f in a["fields"]}
        sxy = {k: a["sxy"][k] + b["sxy"][k] for k in a["sxy"]}
        return {"$p": tag, "n": a["n"] + b["n"], "fields": a["fields"],
                "s": s, "sxy": sxy}
    raise ParsingError(f"unmergeable partial state [{tag}]")


def _opt(fn, *vals):
    vs = [v for v in vals if v is not None]
    return fn(vs) if vs else None


def _bucket_key(kind: str, bucket: dict):
    key = bucket.get("key")
    if isinstance(key, dict):  # composite
        return tuple(sorted(key.items()))
    return _hashable(key)


def _merge_buckets(kind: str, a_bucket: dict, b_bucket: dict,
                   sub_spec: dict) -> dict:
    m = dict(a_bucket)
    m["doc_count"] = a_bucket.get("doc_count", 0) + b_bucket.get("doc_count", 0)
    if "bg_count" in a_bucket or "bg_count" in b_bucket:
        # significant buckets: background freqs sum; the score recomputes
        # at finalize from the merged counts (SignificanceHeuristic)
        m["bg_count"] = a_bucket.get("bg_count", 0) + b_bucket.get("bg_count", 0)
    a_subs = {n: a_bucket[n] for n in (sub_spec or {}) if n in a_bucket}
    b_subs = {n: b_bucket[n] for n in (sub_spec or {}) if n in b_bucket}
    m.update(merge_partial_aggs(a_subs, b_subs, sub_spec))
    return m


def _merge_bucket_agg(kind: str, spec: dict, a, b, sub_spec: dict):
    if kind in SINGLE_BUCKET:
        return _merge_buckets(kind, a, b, sub_spec)

    if kind == "filters":
        if isinstance(a.get("buckets"), dict):
            merged = dict(a["buckets"])
            for bname, bb in b.get("buckets", {}).items():
                merged[bname] = (_merge_buckets(kind, merged[bname], bb, sub_spec)
                                 if bname in merged else bb)
            return {**a, "buckets": merged}
        merged_list = []
        bl = b.get("buckets", [])
        for i, ab in enumerate(a.get("buckets", [])):
            merged_list.append(_merge_buckets(kind, ab, bl[i], sub_spec)
                               if i < len(bl) else ab)
        merged_list.extend(bl[len(merged_list):])
        return {**a, "buckets": merged_list}

    if kind == "auto_date_histogram":
        ia = int(str(a.get("interval", "1ms")).rstrip("ms") or 1)
        ib = int(str(b.get("interval", "1ms")).rstrip("ms") or 1)
        interval = max(ia, ib)
        a_buckets = _rebucket(a.get("buckets", []), interval, sub_spec)
        b_buckets = _rebucket(b.get("buckets", []), interval, sub_spec)
        merged = _merge_keyed(kind, a_buckets, b_buckets, sub_spec)
        return {"buckets": merged, "interval": f"{interval}ms"}

    # keyed bucket lists: terms/histograms/ranges/grids/composite/adjacency
    merged = _merge_keyed(kind, a.get("buckets", []), b.get("buckets", []),
                          sub_spec)
    out = {**a, "buckets": merged}
    out.pop("after_key", None)  # recomputed at finalize (composite)
    if "sum_other_doc_count" in out:
        out["sum_other_doc_count"] = (a.get("sum_other_doc_count", 0)
                                      + b.get("sum_other_doc_count", 0))
    for k in ("doc_count", "bg_count"):  # significant_* totals
        if k in a or k in b:
            out[k] = a.get(k, 0) + b.get(k, 0)
    return out


def _merge_keyed(kind: str, a_buckets: list, b_buckets: list,
                 sub_spec: dict) -> list:
    index: Dict[Any, int] = {}
    merged: List[dict] = []
    for bucket in a_buckets:
        index[_bucket_key(kind, bucket)] = len(merged)
        merged.append(bucket)
    for bucket in b_buckets:
        k = _bucket_key(kind, bucket)
        if k in index:
            merged[index[k]] = _merge_buckets(kind, merged[index[k]],
                                              bucket, sub_spec)
        else:
            index[k] = len(merged)
            merged.append(bucket)
    return merged


def _rebucket(buckets: list, interval: int, sub_spec: dict) -> list:
    """Re-floor date_histogram buckets onto a coarser interval, merging
    sub-agg partials of collapsed buckets (auto_date_histogram reduce)."""
    out: Dict[float, dict] = {}
    for bucket in buckets:
        key = float(np.floor(float(bucket["key"]) / interval) * interval)
        if key in out:
            out[key] = _merge_buckets("date_histogram", out[key],
                                      {**bucket, "key": key}, sub_spec)
        else:
            out[key] = {**bucket, "key": int(key),
                        "key_as_string": A._millis_to_iso(int(key))}
    return [out[k] for k in sorted(out)]


# ---------------------------------------------------------------------------
# finalize (coordinator, once, after all merges)
# ---------------------------------------------------------------------------


def finalize_aggs(partial: dict, aggs_spec: dict) -> dict:
    out: Dict[str, Any] = {}
    pipelines: List[Tuple[str, str, dict]] = []
    for name, spec in (aggs_spec or {}).items():
        kinds = [k for k in spec if k not in ("aggs", "aggregations", "meta")]
        kind = kinds[0]
        if kind in PIPELINE_AGGS:
            pipelines.append((name, kind, spec[kind]))
            continue
        if name not in partial:
            continue
        sub = spec.get("aggs") or spec.get("aggregations") or {}
        if kind in METRIC_AGGS:
            out[name] = _finalize_metric(kind, spec[kind], partial[name])
            continue
        sub_normal = {sn: ss for sn, ss in sub.items() if not _is_pipeline(ss)}
        sub_pipes = [(sn, next(k for k in ss if k not in ("aggs", "aggregations", "meta")), ss)
                     for sn, ss in sub.items() if _is_pipeline(ss)]
        out[name] = _finalize_bucket_agg(kind, spec[kind], partial[name],
                                         sub_normal)
        # parent pipelines (cumulative_sum/derivative/... as sub-aggs) run on
        # the final bucket list, same as compute_aggs
        for pname, pkind, psub in sub_pipes:
            pspec = dict(psub[pkind])
            wrapper = {"__parent__": out[name]}
            bp = pspec.get("buckets_path")
            if isinstance(bp, str):
                pspec["buckets_path"] = "__parent__>" + bp
            elif isinstance(bp, dict):
                pspec["buckets_path"] = {k: "__parent__>" + v
                                         for k, v in bp.items()}
            res = A._compute_pipeline(wrapper, pkind, pspec, pname)
            if not (isinstance(res, dict) and "_applied" in res):
                out[name].setdefault("__pipeline_results__", {})[pname] = res
    for name, kind, spec in pipelines:
        res = A._compute_pipeline(out, kind, spec, name)
        if not (isinstance(res, dict) and "_applied" in res):
            out[name] = res
    return out


def _finalize_metric(kind: str, spec: dict, state: dict):
    if kind == "value_count":
        return {"value": state["n"]}
    if kind == "scripted_metric":
        return {"value": A.scripted_metric_reduce(spec, state["states"])}
    if kind == "cardinality":
        return {"value": _hll_estimate(state)}
    if kind == "avg":
        return {"value": state["sum"] / state["n"] if state["n"] else None}
    if kind == "sum":
        return {"value": state["sum"]}
    if kind in ("min", "max"):
        return {"value": state["v"]}
    if kind == "stats":
        n = state["n"]
        return {"count": n, "min": state["min"], "max": state["max"],
                "avg": state["sum"] / n if n else None,
                "sum": state["sum"]}
    if kind == "extended_stats":
        n = state["n"]
        base = {"count": n, "min": state["min"], "max": state["max"],
                "avg": state["sum"] / n if n else None, "sum": state["sum"]}
        if n == 0:
            base.update({"sum_of_squares": None, "variance": None,
                         "std_deviation": None,
                         "std_deviation_bounds": {"upper": None, "lower": None}})
            return base
        mean = state["sum"] / n
        var = max(state["ss"] / n - mean * mean, 0.0)
        std = math.sqrt(var)
        sigma = float(spec.get("sigma", 2.0))
        base.update({
            "sum_of_squares": state["ss"], "variance": var,
            "variance_population": var,
            "variance_sampling": (max(state["ss"] - n * mean * mean, 0.0)
                                  / (n - 1)) if n > 1 else 0.0,
            "std_deviation": std,
            "std_deviation_bounds": {"upper": mean + sigma * std,
                                     "lower": mean - sigma * std},
        })
        return base
    if kind == "weighted_avg":
        return {"value": state["vw"] / state["w"] if state["w"] else None}
    if kind == "percentiles":
        pcts = spec.get("percents", [1, 5, 25, 50, 75, 95, 99])
        return {"values": {f"{float(p)}": _td_quantile(state, p / 100.0)
                           for p in pcts}}
    if kind == "percentile_ranks":
        targets = spec.get("values", [])
        empty = not state["c"]
        return {"values": {
            f"{float(t)}": None if empty else 100.0 * _td_cdf(state, float(t))
            for t in targets}}
    if kind == "median_absolute_deviation":
        return {"value": _td_mad(state)}
    if kind == "boxplot":
        return _finalize_boxplot(state)
    if kind == "geo_bounds":
        if not state["n"]:
            return {"bounds": None}
        return {"bounds": {
            "top_left": {"lat": state["maxlat"], "lon": state["minlon"]},
            "bottom_right": {"lat": state["minlat"], "lon": state["maxlon"]}}}
    if kind == "geo_centroid":
        if not state["n"]:
            return {"count": 0}
        return {"location": {"lat": state["lat_sum"] / state["n"],
                             "lon": state["lon_sum"] / state["n"]},
                "count": state["n"]}
    if kind == "top_hits":
        return {"hits": {"total": {"value": state["total"], "relation": "eq"},
                         "hits": state["hits"][:state["size"]]}}
    if kind == "top_metrics":
        size = int(spec.get("size", 1))
        order = _top_metrics_order(spec)
        top = sorted(state["top"],
                     key=lambda t: t["sort"][0],
                     reverse=(order == "desc"))
        return {"top": top[:size]}
    if kind == "string_stats":
        return _finalize_string_stats(spec, state)
    if kind == "matrix_stats":
        return _finalize_matrix(state)
    raise ParsingError(f"unknown metric aggregation [{kind}]")


def _top_metrics_order(spec) -> str:
    sort_spec = spec.get("sort", [{"_doc": "asc"}])
    if isinstance(sort_spec, (str, dict)):
        sort_spec = [sort_spec]
    entry = sort_spec[0]
    if isinstance(entry, str):
        return "asc"
    _, order = next(iter(entry.items()))
    if isinstance(order, dict):
        order = order.get("order", "asc")
    return order


def _td_mad(state: dict):
    if not state["c"]:
        return None
    med = _td_quantile(state, 0.5)
    lo, hi = 0.0, max(state["max"] - state["min"], 0.0)
    if hi == 0.0:
        return 0.0
    for _ in range(50):
        mid = (lo + hi) / 2
        mass = _td_cdf(state, med + mid) - _td_cdf(state, med - mid)
        if mass >= 0.5:
            hi = mid
        else:
            lo = mid
    return hi


def _finalize_boxplot(state: dict):
    if not state["c"]:
        return {"min": None, "max": None, "q1": None, "q2": None,
                "q3": None, "lower": None, "upper": None}
    q1, q2, q3 = (_td_quantile(state, q) for q in (0.25, 0.5, 0.75))
    iqr = q3 - q1
    inside = [m for m, _ in state["c"]
              if q1 - 1.5 * iqr <= m <= q3 + 1.5 * iqr]
    return {"min": state["min"], "max": state["max"],
            "q1": q1, "q2": q2, "q3": q3,
            "lower": min(inside) if inside else q1,
            "upper": max(inside) if inside else q3}


def _finalize_string_stats(spec: dict, state: dict):
    if state["n"] == 0:
        return {"count": 0, "min_length": None, "max_length": None,
                "avg_length": None, "entropy": 0.0}
    total_chars = sum(state["freq"].values())
    entropy = 0.0
    for c in state["freq"].values():
        p = c / total_chars
        entropy -= p * math.log2(p)
    out = {"count": state["n"], "min_length": state["min_len"],
           "max_length": state["max_len"],
           "avg_length": state["len_sum"] / state["n"],
           "entropy": round(entropy, 10)}
    if spec.get("show_distribution"):
        out["distribution"] = {ch: c / total_chars
                               for ch, c in sorted(state["freq"].items())}
    return out


def _finalize_matrix(state: dict):
    n = state["n"]
    fields = state["fields"]
    if n == 0:
        return {"doc_count": 0, "fields": []}
    mean = {f: state["s"][f][0] / n for f in fields}
    var = {f: max((state["s"][f][1] - n * mean[f] ** 2) / (n - 1), 0.0)
           if n > 1 else 0.0 for f in fields}
    sd = {f: math.sqrt(var[f]) for f in fields}

    def comoment(f, g):
        if f == g:
            return state["s"][f][1] - n * mean[f] ** 2
        k = f + "|" + g if f + "|" + g in state["sxy"] else g + "|" + f
        return state["sxy"][k] - n * mean[f] * mean[g]

    out_fields = []
    for f in fields:
        s1, s2, s3, s4 = state["s"][f]
        if sd[f]:
            m = mean[f]
            # central power sums from raw power sums
            c3 = s3 - 3 * m * s2 + 2 * n * m ** 3
            c4 = s4 - 4 * m * s3 + 6 * m * m * s2 - 3 * n * m ** 4
            pop_var = max(s2 / n - m * m, 0.0)
            psd = math.sqrt(pop_var)
            skew = (c3 / n) / psd ** 3 if psd else 0.0
            kurt = (c4 / n) / psd ** 4 if psd else 0.0
        else:
            skew = kurt = 0.0
        cov = {}
        corr = {}
        for g in fields:
            c = comoment(f, g) / (n - 1) if n > 1 else 0.0
            cov[g] = c
            corr[g] = (c / (sd[f] * sd[g])) if sd[f] and sd[g] else (
                1.0 if f == g else 0.0)
        out_fields.append({"name": f, "count": n, "mean": mean[f],
                           "variance": var[f], "skewness": skew,
                           "kurtosis": kurt, "covariance": cov,
                           "correlation": corr})
    return {"doc_count": n, "fields": out_fields}


def _finalize_bucket_agg(kind: str, spec: dict, node, sub_spec: dict):
    if kind in SINGLE_BUCKET:
        return _finalize_one_bucket(node, sub_spec)

    if kind == "filters":
        if isinstance(node.get("buckets"), dict):
            return {"buckets": {n: _finalize_one_bucket(b, sub_spec)
                                for n, b in node["buckets"].items()}}
        return {"buckets": [_finalize_one_bucket(b, sub_spec)
                            for b in node.get("buckets", [])]}

    if kind == "auto_date_histogram":
        # coarsen on the RAW partial buckets (sub-agg states still
        # mergeable), then finalize once
        target = int(spec.get("buckets", 10))
        interval = int(str(node.get("interval", "1ms")).rstrip("ms") or 1)
        raw = node.get("buckets", [])
        while len(raw) > target:
            for unit in (1, 1000, 60_000, 3_600_000, 86_400_000,
                         2_592_000_000, 31_536_000_000):
                if unit > interval:
                    interval = unit
                    break
            else:
                interval *= 2
            raw = _rebucket(raw, interval, sub_spec)
        buckets = [_finalize_one_bucket(b, sub_spec) for b in raw]
        buckets.sort(key=lambda b: float(b["key"]))
        return {"buckets": buckets, "interval": f"{interval}ms"}

    buckets = [_finalize_one_bucket(b, sub_spec)
               for b in node.get("buckets", [])]

    if kind in ("significant_terms", "significant_text"):
        size = int(spec.get("size", 10))
        min_count = int(spec.get("min_doc_count", 3))
        fg_total = int(node.get("doc_count", 0))
        bg_total = int(node.get("bg_count", 0)) or fg_total
        rescored = []
        for b in buckets:
            fg, bg = b.get("doc_count", 0), b.get("bg_count", 0)
            if fg < min_count or bg == 0:
                continue
            fg_freq = fg / fg_total if fg_total else 0.0
            bg_freq = bg / bg_total if bg_total else 0.0
            if fg_freq <= bg_freq or bg_freq == 0:
                continue
            rescored.append({**b, "score":
                             (fg_freq - bg_freq) * (fg_freq / bg_freq)})
        rescored.sort(key=lambda b: (-b["score"], _sort_key(b["key"])))
        return {"doc_count": fg_total, "bg_count": bg_total,
                "buckets": rescored[:size]}

    if kind == "terms":
        size = int(spec.get("size", 10))
        order_spec = spec.get("order")
        if order_spec and isinstance(order_spec, dict):
            ((okey, odir),) = order_spec.items()
            reverse = odir == "desc"
            if okey == "_key":
                buckets.sort(key=lambda b: _sort_key(b["key"]), reverse=reverse)
            elif okey == "_count":
                buckets.sort(key=lambda b: b["doc_count"], reverse=reverse)
            else:
                def metric_val(b, path=okey):
                    v = b
                    for part in path.split("."):
                        v = v.get(part) if isinstance(v, dict) else None
                    if isinstance(v, (int, float)):
                        return v
                    return (v or {}).get("value", 0) if isinstance(v, dict) else 0
                buckets.sort(key=metric_val, reverse=reverse)
        else:
            buckets.sort(key=lambda b: (-b["doc_count"], _sort_key(b["key"])))
        other = sum(b["doc_count"] for b in buckets[size:])
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(other), "buckets": buckets[:size]}

    if kind == "rare_terms":
        max_count = int(spec.get("max_doc_count", 1))
        buckets = [b for b in buckets if b["doc_count"] <= max_count]
        buckets.sort(key=lambda b: (b["doc_count"], _sort_key(b["key"])))
        return {"doc_count_error_upper_bound": 0, "sum_other_doc_count": 0,
                "buckets": buckets}

    if kind in ("histogram", "date_histogram"):
        min_count = int(spec.get("min_doc_count", 0))
        buckets.sort(key=lambda b: float(b["key"]))
        if min_count > 0:
            buckets = [b for b in buckets if b["doc_count"] >= min_count]
        elif buckets and kind == "histogram" and spec.get("interval"):
            buckets = _fill_gaps(buckets, float(spec["interval"]), date=False)
        elif buckets and kind == "date_histogram":
            interval_ms, calendar = A._date_interval(spec)
            if not calendar:
                buckets = _fill_gaps(buckets, interval_ms, date=True)
        return {"buckets": buckets}

    if kind in ("geohash_grid", "geotile_grid"):
        size = int(spec.get("size", 10000))
        buckets.sort(key=lambda b: (-b["doc_count"], b["key"]))
        return {"buckets": buckets[:size]}

    if kind == "composite":
        size = int(spec.get("size", 10))
        names = [next(iter(src)) for src in spec.get("sources", [])]
        buckets.sort(key=lambda b: tuple(_sort_key(b["key"].get(n))
                                         for n in names))
        buckets = buckets[:size]
        out = {"buckets": buckets}
        if buckets:
            out["after_key"] = buckets[-1]["key"]
        return out

    if kind == "adjacency_matrix":
        buckets.sort(key=lambda b: b["key"])
        return {"buckets": buckets}

    # range / date_range / ip_range: keep spec order (a-side first)
    return {**{k: v for k, v in node.items() if k != "buckets"},
            "buckets": buckets}


def _finalize_one_bucket(bucket: dict, sub_spec: dict) -> dict:
    out = {k: v for k, v in bucket.items() if k not in (sub_spec or {})}
    if sub_spec:
        subs = {n: bucket[n] for n in sub_spec if n in bucket}
        out.update(finalize_aggs(subs, sub_spec))
    return out


def _fill_gaps(buckets: List[dict], interval: float, date: bool) -> List[dict]:
    """Zero-fill inter-shard gaps after the merge (min_doc_count=0)."""
    if not buckets or interval <= 0:
        return buckets
    out = []
    cur = float(buckets[0]["key"])
    by_key = {float(b["key"]): b for b in buckets}
    last = float(buckets[-1]["key"])
    guard = 0
    while cur <= last + 1e-9 and guard < 100_000:
        b = by_key.get(round(cur, 10)) or by_key.get(cur)
        if b is None:
            b = {"key": int(cur) if date else round(cur, 10), "doc_count": 0}
            if date:
                b["key_as_string"] = A._millis_to_iso(int(cur))
        out.append(b)
        cur += interval
        guard += 1
    return out if guard < 100_000 else buckets
