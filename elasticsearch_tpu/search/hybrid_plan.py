"""Fused hybrid execution: one plan, one dispatch per leg kind, RRF, fetch.

Before this module, a hybrid `rank: {rrf}` search paid per query: a DSL
parse, a host-Python BM25 pass per term, a device round-trip for the kNN
leg, a dict-based fusion, and a fetch — and only the kNN leg's device
dispatch could coalesce with concurrent traffic. This is the structural
reason config 3 was the record's one losing row vs the reference's
BulkScorer (`QueryPhase.java:171`).

The fused path compiles the body ONCE into a `HybridPlan` (cached per
index, keyed on the normalized body — repeated shapes skip parse/plan
entirely) and executes whole *batches* of hybrid queries that coalesced in
the serving layer (`serving/batcher.py` BoundedBatcher):

  plan    normalize → classify sub-searches into legs:
            lexical  — match/term on text fields → `ops/bm25.py` device
                       engine (tile-padded precomputed impacts)
            knn      — dense_vector → `vectors/store.py` batched corpus
            generic  — anything else → the per-query query phase
  score   ONE lexical dispatch per text field for the whole batch + ONE
          kNN dispatch per vector field for the whole batch; filters for
          filtered kNN legs evaluate host-side per query (the same
          pre-filter contract as `search/knn_query.py`)
  fuse    reciprocal-rank fusion, vectorized over the batch; f64
          accumulation in sub-search order reproduces the coordinator
          dict fold bit-for-bit, so fused results are byte-identical to
          the two-phase path (`tests/test_hybrid_plan.py` pins this)
  hydrate fetch only the final `from+size` window per query

Per-phase timings thread into `profile.hybrid` and the node's
`_nodes/stats` hybrid section.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu import native
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.index.mapping import TextFieldMapper
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.bm25 import LexicalShard
from elasticsearch_tpu.search.queries import (
    SearchContext, parse_query, resolve_msm,
)
from elasticsearch_tpu.search.service import (
    ShardSearchResult, execute_fetch_phase, execute_query_phase,
)
from elasticsearch_tpu.serving.batcher import BoundedBatcher

DEFAULT_RANK_CONSTANT = 60
DEFAULT_WINDOW = 100


class LexicalLeg:
    """match/term sub-search on a text field, lowered to the device
    lexical engine."""

    __slots__ = ("field", "terms", "required", "boost")

    def __init__(self, field: str, terms: List[str], required: int,
                 boost: float):
        self.field = field
        self.terms = terms
        self.required = required
        self.boost = boost


class EmptyLeg:
    """A leg whose analysis produced nothing searchable (match text that
    analyzes to zero terms): contributes an empty ranked list — the same
    empty-DocSet semantics the host query phase returns for it."""

    __slots__ = ()


class LexicalTemplate:
    """Compile-time half of a lexical leg: everything except the query
    TEXT, which is normalized out of the plan-cache key and bound per
    query (`bind`). operator/msm/boost are structural (part of the key)."""

    __slots__ = ("field", "kind", "operator", "msm", "boost")

    def __init__(self, field: str, kind: str, operator: str, msm,
                 boost: float):
        self.field = field
        self.kind = kind          # "match" | "term"
        self.operator = operator
        self.msm = msm
        self.boost = boost

    def bind(self, qspec, mapper_service):
        if self.kind == "term":
            text = qspec.get("value") if isinstance(qspec, dict) else qspec
            return LexicalLeg(self.field, [str(text)], 1, self.boost)
        text = qspec.get("query") if isinstance(qspec, dict) else qspec
        mapper = mapper_service.get(self.field)
        terms = mapper.search_analyzer.terms(str(text))
        if not terms:
            return EmptyLeg()
        required = len(terms) if self.operator == "and" \
            else resolve_msm(self.msm, len(terms))
        return LexicalLeg(self.field, terms, required, self.boost)


class KnnTemplate:
    """Compile-time half of a kNN leg: the query VECTOR is normalized out
    of the plan-cache key (only its dimensionality is structural) and
    bound per query; k/num_candidates/filter/boost/metric live in the key
    and are resolved once at compile."""

    __slots__ = ("field", "dims", "k", "num_candidates", "filter_spec",
                 "boost", "metric")

    def __init__(self, field, dims, k, num_candidates, filter_spec, boost,
                 metric):
        self.field = field
        self.dims = dims
        self.k = k
        self.num_candidates = num_candidates
        self.filter_spec = filter_spec
        self.boost = boost
        self.metric = metric

    def bind(self, spec):
        qv = np.asarray(spec["query_vector"], dtype=np.float32)
        if qv.shape[0] != self.dims:
            # same 400 KnnQuery._metric raises on the oracle — validated
            # per QUERY (the cached plan only pins the field's dims)
            raise IllegalArgumentError(
                f"[knn] query vector has {qv.shape[0]} dims, field "
                f"[{self.field}] expects {self.dims}")
        return KnnLeg(self.field, qv, self.k, self.num_candidates,
                      self.filter_spec, self.boost, self.metric)


class GenericTemplate:
    """Anything the specialized engines don't cover: bound to the BODY's
    own sub-query at execution (never the compile-time body's — generic
    values may legitimately be normalized out of the key by the
    match/term scrubbing)."""

    __slots__ = ()

    @staticmethod
    def bind(qspec):
        return GenericLeg(qspec)


class SparseTemplate:
    """Compile-time half of a learned-sparse leg (`sparse_vector` /
    `weighted_tokens` on a rank_features-style field): the query TOKEN
    MAP is normalized out of the plan-cache key and bound per query;
    field/boost are structural. Token COUNT is a bind-time concern: a
    body wider than the device grid binds to a counted host-walker
    fallback (the EmptyLeg precedent — same template, per-body leg)."""

    __slots__ = ("field", "kind", "boost")

    def __init__(self, field: str, kind: str, boost: float):
        self.field = field
        self.kind = kind          # "sparse_vector" | "weighted_tokens"
        self.boost = boost

    def bind(self, qspec: dict):
        from elasticsearch_tpu.ops.sparse import MAX_QUERY_TOKENS
        spec = qspec[self.kind]
        if self.kind == "sparse_vector":
            tokens = spec.get("query_vector") or {}
        else:
            tokens = (spec[self.field] or {}).get("tokens") or {}
        if not tokens:
            return EmptyLeg()
        if len(tokens) > MAX_QUERY_TOKENS:
            return SparseFallbackLeg(
                qspec, f"query tokens {len(tokens)} exceed device grid "
                f"cap {MAX_QUERY_TOKENS}")
        return SparseLeg(self.field, tokens, self.boost)


class MaxSimTemplate:
    """Compile-time half of a late-interaction leg (`late_interaction`
    on a `rank_vectors` field): query TOKEN VECTORS are normalized out
    of the key (their dimensionality is structural, like knn's);
    field/k/boost are structural. Over-grid token counts bind to a
    counted host-walker fallback."""

    __slots__ = ("field", "dims", "k", "boost")

    def __init__(self, field: str, dims: int, k: int, boost: float):
        self.field = field
        self.dims = dims
        self.k = k
        self.boost = boost

    def bind(self, qspec: dict):
        from elasticsearch_tpu.vectors.late_interaction import (
            MAX_QUERY_TOKENS)
        spec = qspec["late_interaction"]
        qt = np.asarray(spec["query_tokens"], dtype=np.float32)
        if qt.ndim == 1:
            qt = qt.reshape(1, -1)
        if qt.ndim != 2 or qt.shape[1] != self.dims:
            raise IllegalArgumentError(
                f"[late_interaction] query tokens have "
                f"{qt.shape[-1] if qt.ndim else 0} dims, field "
                f"[{self.field}] expects {self.dims}")
        if qt.shape[0] > MAX_QUERY_TOKENS:
            return MaxSimFallbackLeg(
                qspec, f"query tokens {qt.shape[0]} exceed device grid "
                f"cap {MAX_QUERY_TOKENS}")
        return MaxSimLeg(self.field, qt, self.k, self.boost)


class SparseLeg:
    __slots__ = ("field", "tokens", "boost")

    def __init__(self, field: str, tokens: Dict[str, float], boost: float):
        self.field = field
        self.tokens = tokens
        self.boost = boost


class MaxSimLeg:
    __slots__ = ("field", "query_tokens", "k", "boost")

    def __init__(self, field: str, query_tokens, k: int, boost: float):
        self.field = field
        self.query_tokens = query_tokens
        self.k = k
        self.boost = boost


class KnnLeg:
    __slots__ = ("field", "query_vector", "k", "num_candidates",
                 "filter_spec", "boost", "metric")

    def __init__(self, field: str, query_vector, k: int,
                 num_candidates: int, filter_spec: Optional[dict],
                 boost: float, metric: str):
        self.field = field
        self.query_vector = np.asarray(query_vector, dtype=np.float32)
        self.k = k
        self.num_candidates = num_candidates
        self.filter_spec = filter_spec
        self.boost = boost
        self.metric = metric


class GenericLeg:
    """Fallback: any sub-search the specialized engines don't cover runs
    through the ordinary per-query query phase (still inside the batch's
    single runner, still fused + fetched with the rest)."""

    __slots__ = ("query",)

    def __init__(self, query: dict):
        self.query = query


class SparseFallbackLeg(GenericLeg):
    """A sparse leg that fell off the device grid (query wider than the
    tile-scan cap): runs the host walker via the query phase, with the
    reason surfaced in leg profiles and counted in executor stats."""

    __slots__ = ("reason",)

    def __init__(self, query: dict, reason: str):
        super().__init__(query)
        self.reason = reason


class MaxSimFallbackLeg(GenericLeg):
    """A late-interaction leg that fell off the device grid: runs the
    exact host MaxSim walker via the query phase, reason counted."""

    __slots__ = ("reason",)

    def __init__(self, query: dict, reason: str):
        super().__init__(query)
        self.reason = reason


class HybridPlan:
    """Compiled structure of a hybrid body: leg templates + fusion
    parameters. Per-query VALUES (query vectors, match text) are NOT part
    of the plan — `bind` extracts them from each body, so one cached plan
    serves every query with the same shape (the r06 bench showed
    `plan_cache_hits: 0` across 108 structurally identical bodies because
    the old key hashed the values too)."""

    __slots__ = ("legs", "rank_constant", "window", "size", "frm",
                 "fetch_body")

    def __init__(self, legs, rank_constant, window, size, frm, fetch_body):
        self.legs = legs          # templates (Lexical/Knn/Generic)
        self.rank_constant = rank_constant
        self.window = window
        self.size = size
        self.frm = frm
        self.fetch_body = fetch_body

    def bind(self, body: dict, mapper_service) -> List[Any]:
        """Resolve the per-query values of `body` against the templates →
        executable legs. O(legs), no DSL parse, no classification."""
        subs = _sub_queries_of(body)
        bound: List[Any] = []
        for template, q in zip(self.legs, subs):
            if isinstance(template, LexicalTemplate):
                bound.append(template.bind(q[template.kind][template.field],
                                           mapper_service))
            elif isinstance(template, KnnTemplate):
                bound.append(template.bind(q["knn"]))
            elif isinstance(template, (SparseTemplate, MaxSimTemplate)):
                bound.append(template.bind(q))
            else:
                bound.append(GenericTemplate.bind(q))
        return bound


def _canonical_settings(svc) -> str:
    """Flat index settings as canonical JSON — the settings component of
    the request-cache epoch (a put_settings change must miss)."""
    import json
    return json.dumps(svc.settings.as_flat_dict(), sort_keys=True,
                      default=str)


def plan_cache_key(body: dict) -> str:
    """Normalized plan-cache key: the body with per-query VALUE slots
    scrubbed — `knn.query_vector` → its length (shape is structural,
    content is not), match/term text → a placeholder. Everything else
    (fields, k, num_candidates, filters, boosts, rank params, size/from,
    fuzziness) stays: those change the compiled plan."""
    def scrub_query(q):
        if not isinstance(q, dict) or len(q) != 1:
            return q
        ((kind, spec),) = q.items()
        if kind == "knn" and isinstance(spec, dict) \
                and "query_vector" in spec:
            qv = spec["query_vector"]
            spec = {**spec,
                    "query_vector": {"__dims__": len(qv)
                                     if hasattr(qv, "__len__") else 0}}
            return {kind: spec}
        if kind == "sparse_vector" and isinstance(spec, dict) \
                and "query_vector" in spec:
            # token MAPS scrub whole (count is NOT structural — the tile
            # planner pads it, and over-cap bodies fall back at bind)
            return {kind: {**spec, "query_vector": "__tokens__"}}
        if kind == "weighted_tokens" and isinstance(spec, dict) \
                and len(spec) == 1:
            ((field, v),) = spec.items()
            if isinstance(v, dict) and "tokens" in v:
                return {kind: {field: {**v, "tokens": "__tokens__"}}}
            return q
        if kind == "late_interaction" and isinstance(spec, dict) \
                and "query_tokens" in spec:
            qt = spec["query_tokens"]
            first = qt[0] if isinstance(qt, (list, tuple)) and qt else qt
            dims = len(first) if hasattr(first, "__len__") else 0
            return {kind: {**spec, "query_tokens": {"__dims__": dims}}}
        if kind in ("match", "term") and isinstance(spec, dict) \
                and len(spec) == 1:
            ((field, v),) = spec.items()
            if kind == "term":
                v = {**v, "value": "__text__"} if isinstance(v, dict) \
                    else "__text__"
            else:
                v = {**v, "query": "__text__"} if isinstance(v, dict) \
                    else "__text__"
            return {kind: {field: v}}
        return q

    norm = dict(body)
    if norm.get("sub_searches"):
        norm["sub_searches"] = [
            {**s, "query": scrub_query(s.get("query", {"match_all": {}}))}
            for s in norm["sub_searches"]]
    else:
        if norm.get("query") is not None:
            norm["query"] = scrub_query(norm["query"])
        if norm.get("knn") is not None:
            knn = norm["knn"]
            if isinstance(knn, list):
                norm["knn"] = [scrub_query({"knn": s})["knn"] for s in knn]
            else:
                norm["knn"] = scrub_query({"knn": knn})["knn"]
    from elasticsearch_tpu.search.caches import _canonical
    return _canonical(norm)


def _sub_queries_of(body: dict) -> List[dict]:
    subs: List[dict] = []
    if body.get("sub_searches"):
        subs = [s.get("query", {"match_all": {}})
                for s in body["sub_searches"]]
    else:
        if body.get("query") is not None:
            subs.append(body["query"])
        if body.get("knn") is not None:
            knn = body["knn"]
            if isinstance(knn, list):
                subs.extend({"knn": spec} for spec in knn)
            else:
                subs.append({"knn": knn})
    return subs


def _compile_lexical(spec_kind: str, qspec: dict,
                     mapper_service) -> Optional[LexicalTemplate]:
    """Lower a match/term sub-search to a lexical-engine template when it
    scores exactly like the host path would (text field, no fuzziness).
    Classification is purely STRUCTURAL (field type + spec shape), never
    value-dependent — the plan-cache key scrubs values out, so two bodies
    with one key must classify identically."""
    if not isinstance(qspec, dict) or len(qspec) != 1:
        return None
    ((field, v),) = qspec.items()
    mapper = mapper_service.get(field)
    if not isinstance(mapper, TextFieldMapper):
        return None
    if spec_kind == "term":
        boost = float(v.get("boost", 1.0)) if isinstance(v, dict) else 1.0
        return LexicalTemplate(field, "term", "or", None, boost)
    # match
    if isinstance(v, dict):
        if v.get("fuzziness") is not None:
            return None
        operator = str(v.get("operator", "or")).lower()
        msm = v.get("minimum_should_match")
        boost = float(v.get("boost", 1.0))
    else:
        operator, msm, boost = "or", None, 1.0
    return LexicalTemplate(field, "match", operator, msm, boost)


def _compile_sparse(spec_kind: str, qspec,
                    mapper_service) -> Optional[SparseTemplate]:
    """Lower a sparse_vector/weighted_tokens sub-search to the learned-
    sparse device engine when the field stores feature→weight maps
    (`rank_features` or the legacy `sparse_vector` mapping). Purely
    STRUCTURAL, like `_compile_lexical` — token values never reach the
    plan-cache key."""
    if not isinstance(qspec, dict):
        return None
    if spec_kind == "sparse_vector":
        field = qspec.get("field")
        boost = float(qspec.get("boost", 1.0))
    else:
        if len(qspec) != 1:
            return None
        ((field, v),) = qspec.items()
        boost = float(v.get("boost", 1.0)) if isinstance(v, dict) else 1.0
    if not field:
        return None
    mapper = mapper_service.get(field)
    if getattr(mapper, "type_name", "") not in ("rank_features",
                                                "sparse_vector"):
        return None
    return SparseTemplate(field, spec_kind, boost)


def compile_plan(body: dict, mapper_service) -> HybridPlan:
    """Parse + classify ONE hybrid body into an executable plan."""
    rrf = (body.get("rank") or {}).get("rrf") or {}
    rank_constant = int(rrf.get("rank_constant", DEFAULT_RANK_CONSTANT))
    window = int(rrf.get("rank_window_size",
                         rrf.get("window_size", DEFAULT_WINDOW)))
    size = int(body.get("size", 10))
    frm = int(body.get("from", 0) or 0)
    subs = _sub_queries_of(body)
    if len(subs) < 2:
        raise IllegalArgumentError(
            "[rrf] requires at least 2 ranked lists (sub_searches, or "
            "query + knn)")
    legs: List[Any] = []
    for q in subs:
        leg: Any = None
        if isinstance(q, dict) and len(q) == 1:
            kind = next(iter(q))
            spec = q[kind]
            if kind == "knn" and isinstance(spec, dict):
                from elasticsearch_tpu.index.mapping import (
                    DenseVectorFieldMapper)
                from elasticsearch_tpu.vectors.store import _METRIC_MAP
                mapper = mapper_service.get(spec["field"])
                if isinstance(mapper, DenseVectorFieldMapper):
                    # EXACT parse_query("knn") semantics — the oracle's:
                    # k defaults to 10 (not num_candidates), and
                    # num_candidates clamps up to k (KnnQuery.__init__)
                    k = int(spec.get("k", 10))
                    nc = max(int(spec.get("num_candidates",
                                          spec.get("k", 10))), k)
                    leg = KnnTemplate(
                        spec["field"], mapper.dims, k, nc,
                        spec.get("filter"), float(spec.get("boost", 1.0)),
                        _METRIC_MAP[mapper.similarity])
            elif kind in ("match", "term"):
                leg = _compile_lexical(kind, spec, mapper_service)
            elif kind in ("sparse_vector", "weighted_tokens"):
                leg = _compile_sparse(kind, spec, mapper_service)
            elif kind == "late_interaction" and isinstance(spec, dict):
                from elasticsearch_tpu.index.mapping import (
                    RankVectorsFieldMapper)
                mapper = mapper_service.get(spec.get("field", ""))
                if isinstance(mapper, RankVectorsFieldMapper):
                    leg = MaxSimTemplate(
                        spec["field"], mapper.dims,
                        int(spec.get("k", 10)),
                        float(spec.get("boost", 1.0)))
        if leg is None:
            leg = GenericTemplate()
        legs.append(leg)
    fetch_body = {k: v for k, v in body.items()
                  if k in ("_source", "docvalue_fields")}
    fetch_body["size"] = size
    return HybridPlan(legs, rank_constant, window, size, frm, fetch_body)


def fuse_rrf(leg_rows: List[np.ndarray], rank_constant: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """RRF over ranked row lists → (unique rows ascending, f64 scores).

    f64 accumulation in leg order reproduces the coordinator's python-dict
    fold exactly: per row, contributions add one leg at a time, so the
    floating-point sum order (and hence every last bit) matches."""
    non_empty = [r for r in leg_rows if len(r)]
    if not non_empty:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float64))
    uniq = np.unique(np.concatenate(non_empty))
    scores = np.zeros(len(uniq), dtype=np.float64)
    for rows in leg_rows:
        if not len(rows):
            continue
        idx = np.searchsorted(uniq, rows)
        np.add.at(scores, idx,
                  1.0 / (rank_constant + np.arange(1, len(rows) + 1,
                                                   dtype=np.float64)))
    return uniq, scores


class HybridExecutor:
    """Per-index hybrid serving path: plan cache + bounded combining queue.

    Whole hybrid queries (not just their kNN legs) coalesce here: the
    first thread in becomes the runner and executes every body that
    accumulated while the previous batch was in flight — one lexical
    dispatch per text field, one kNN dispatch per vector field, for the
    entire batch. Admission control (depth + deadline) sheds overload as
    HTTP 429 instead of queueing into the p99 tail.
    """

    def __init__(self, node, svc, max_batch: int = 64,
                 max_queue_depth: int = 256,
                 deadline_ms: Optional[float] = 10_000.0,
                 plan_cache_entries: int = 256, topup: bool = True,
                 target_batch_latency_ms: float = 2.0,
                 async_depth: int = 2):
        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.search.caches import LruCache
        self.node = node
        self.svc = svc
        self.lexical = LexicalShard(
            dtype=str(svc.settings.get("index.lexical.impact_dtype",
                                       "f32")))
        from elasticsearch_tpu.ops.sparse import SparseShard
        from elasticsearch_tpu.vectors.late_interaction import (
            LateInteractionShard)
        self.sparse = SparseShard(
            dtype=str(svc.settings.get("index.sparse.impact_dtype",
                                       "f32")))
        self.late = LateInteractionShard()
        self.plan_cache = LruCache(max_entries=plan_cache_entries)
        # pipelined continuous batching: the runner holds the scheduler
        # lock only for plan-bind + the un-synced leg dispatches
        # (_dispatch_batch); device sync, RRF fusion and hydrate run
        # outside it (_finalize_batch), overlapping the next batch's
        # device dispatch. `_run_batch` stays the synchronous
        # (dispatch+finalize) path for poisoned-batch serial retries.
        self.batcher = BoundedBatcher(self._run_batch, max_batch=max_batch,
                                      max_queue_depth=max_queue_depth,
                                      deadline_ms=deadline_ms,
                                      warmup=self._warmup
                                      if _dispatch.warmup_enabled()
                                      else None,
                                      dispatch_fn=self._dispatch_batch,
                                      finalize_fn=self._finalize_batch,
                                      topup=topup,
                                      target_batch_latency_ms=(
                                          target_batch_latency_ms),
                                      async_depth=async_depth)
        self.stats = {"searches": 0, "batches": 0, "max_batch_seen": 0,
                      "plan_cache_hits": 0, "plan_cache_misses": 0,
                      "plan_nanos": 0, "score_nanos": 0, "fuse_nanos": 0,
                      "hydrate_nanos": 0, "queue_wait_nanos": 0,
                      "dispatch_nanos": 0, "sync_nanos": 0,
                      "request_cache_hits": 0, "request_cache_misses": 0,
                      "request_cache_stores": 0,
                      "sparse_grid_fallbacks": 0,
                      "maxsim_grid_fallbacks": 0}
        # finalize stages of different batches run CONCURRENTLY when
        # async_depth > 1; their stats writes must not lose updates
        # (dispatch-stage writes serialize under the batcher lock)
        self._stats_lock = threading.Lock()

    # ------------------------------------------------------------- entry
    def submit(self, body: dict) -> dict:
        """Request-cache short-circuit, then the bounded batcher.

        The shard request cache sits BEFORE the batcher: a repeated
        dashboard body (same shape, same values, same reader content,
        same live settings) returns the stored response without
        occupying a batch slot or a device dispatch. Refresh rotates the
        reader fingerprint inside the key, so invalidation is free.
        Profiled bodies are never SERVED from cache — the profile must
        describe a real execution — but report the cache state in a
        `cache` annotation."""
        key = self._request_cache_key(body)
        if key is None:
            return self.batcher.submit(body)
        cache = self.node.caches.device_request
        if not body.get("profile"):
            cached = cache.get(key)
            if cached is not None:
                with self._stats_lock:
                    self.stats["request_cache_hits"] += 1
                return self._serve_cached(cached)
            with self._stats_lock:
                self.stats["request_cache_misses"] += 1
        resp = self.batcher.submit(body)
        if body.get("profile"):
            prof = resp.get("profile")
            if prof is not None and "hybrid" in prof:
                prof["hybrid"]["cache"] = {
                    "rung": "device_request", "served": False,
                    "policy": "profile_bypass"}
        else:
            import copy as _copy
            entry = _copy.deepcopy(
                {k: v for k, v in resp.items()
                 if k not in ("took", "_took_phases")})
            cache.put(key, entry)
            with self._stats_lock:
                self.stats["request_cache_stores"] += 1
        return resp

    def _request_cache_key(self, body: dict):
        """None when this body must not cache (disabled, opted out, or
        non-deterministic); otherwise the sanctioned layered key:
        normalized plan key + value digest + reader content fingerprint
        + live settings epoch (`search/caches.request_cache_key`)."""
        node = self.node
        if not getattr(node, "_device_request_cache_enabled", lambda: False)():
            return None
        from elasticsearch_tpu.search import caches as _caches
        cache = node.caches.device_request
        flag = body.get("request_cache")
        if flag is False:
            return None
        if not cache.deterministic(body):
            if flag is True:
                cache.skipped_uncacheable += 1
            return None
        svc = self.svc
        reader = svc.combined_reader()
        # epoch: everything outside the body the response depends on —
        # the index identity (uuid guards same-name recreation reusing
        # segment ids), its live settings, and the node's dynamic limits
        from elasticsearch_tpu.parallel import policy as _policy
        epoch = (svc.name, getattr(svc, "uuid", None),
                 hash(_canonical_settings(svc)),
                 node._max_buckets(), node._allow_expensive(),
                 _policy.config_epoch())
        return _caches.request_cache_key(
            plan_cache_key(body), body,
            fingerprint=_caches.reader_fingerprint(reader),
            epoch=epoch)

    @staticmethod
    def _serve_cached(entry: dict) -> dict:
        import copy as _copy
        resp = _copy.deepcopy(entry)
        resp["took"] = 0
        return resp

    def _warmup(self) -> None:
        """Batcher-start warmup (runs on the batcher's daemon thread):
        build the lexical impact layout for every text field NOW instead
        of inside the first hybrid query, and pre-compile the BM25
        scatter-add kernel for the interactive bucket grid against that
        layout's board width. Vector-field grids warm separately at
        corpus sync (`vectors/store._schedule_warmup`)."""
        import jax
        import jax.numpy as _jnp

        from elasticsearch_tpu.index.mapping import TextFieldMapper
        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.ops.bm25 import _pow2
        reader = self.svc.combined_reader()
        entries = []

        def scatter_entries(lf, kernel: str):
            """Shape-only warmup entries for one impact layout (bm25 or
            learned-sparse — same scoring program, own dispatch name).

            The kernel's term-tile dimension pads pow-2 to the batch's
            max TOTAL tile count (`plan_queries` sums a query's terms),
            and a zipf-popular term alone can span dozens of impact
            tiles — warm the m ladder up to a few-wide-term query over
            this field's layout (4 × widest term), not a fixed {1,2,4}.
            The r06-shape closed-loop bench showed exactly this gap: a
            timed-loop batch hit m=16 and paid a 750 ms XLA compile
            mid-flight. Still a floor, not a ceiling — a many-term
            query over several wide terms can exceed the cap and
            compile once; the persistent cache absorbs it across
            restarts."""
            width = _pow2(max(lf.n_slots, 1)) + 1
            imp_dtype = {"f32": _jnp.float32, "bf16": _jnp.bfloat16,
                         "int8": _jnp.int8}[lf.dtype]
            n_tiles = max(int(lf.tile_slots.shape[0]), 1)
            scales = (jax.ShapeDtypeStruct((n_tiles,), _jnp.float32)
                      if lf.dtype == "int8" else None)
            max_nt = max((nt for _first, nt in lf.term_tiles.values()),
                         default=1)
            m_cap = _pow2(min(max(4 * max_nt, 4), 256))
            m_rungs = [m for m in (1, 2, 4, 8, 16, 32, 64, 128, 256)
                       if m <= m_cap]
            for q in (1, 8, 16):
                for m in m_rungs:
                    entries.append((
                        kernel,
                        (jax.ShapeDtypeStruct((q, width), _jnp.float32),
                         jax.ShapeDtypeStruct((q, width), _jnp.int32),
                         jax.ShapeDtypeStruct((q, m), _jnp.int32),
                         jax.ShapeDtypeStruct((q, m), _jnp.float32),
                         jax.ShapeDtypeStruct((q,), _jnp.int32),
                         jax.ShapeDtypeStruct((n_tiles, 128), _jnp.int32),
                         jax.ShapeDtypeStruct((n_tiles, 128), imp_dtype),
                         scales),
                        {"k": _dispatch.bucket_k(
                            min(DEFAULT_WINDOW, lf.n_slots),
                            limit=width - 1)}))

        for field, mapper in self.svc.mapper_service.all_mappers():
            type_name = getattr(mapper, "type_name", "")
            if isinstance(mapper, TextFieldMapper):
                lf = self.lexical.field(reader, field)
                if lf.n_slots:
                    scatter_entries(lf, "bm25.topk")
            elif type_name in ("rank_features", "sparse_vector"):
                sf = self.sparse.field(reader, field)
                if sf.n_slots:
                    scatter_entries(sf, "sparse.topk")
            elif type_name == "rank_vectors":
                entries.extend(self.late.warmup_entries(reader, mapper))
        if entries:
            _dispatch.DISPATCH.warmup(entries, background=False)

    def plan_for(self, body: dict) -> Tuple[HybridPlan, bool]:
        """Plan-cache lookup (hit) or compile (miss), keyed on the
        normalized body — per-query values (query vectors, match text)
        are scrubbed from the key, so repeated SHAPES hit regardless of
        what they search for."""
        key = plan_cache_key(body)
        plan = self.plan_cache.get(key)
        if plan is not None:
            self.stats["plan_cache_hits"] += 1
            return plan, True
        plan = compile_plan(body, self.svc.mapper_service)
        self.plan_cache.put(key, plan)
        self.stats["plan_cache_misses"] += 1
        return plan, False

    # ------------------------------------------------------------- batch
    def _run_batch(self, bodies: List[dict]) -> List[dict]:
        """Synchronous serving of one batch: dispatch + finalize back to
        back. The batcher's main path splits the two stages so finalize
        overlaps the next dispatch; this entry is the poisoned-batch
        serial-retry path and the parity oracle for tests."""
        return self._finalize_batch(self._dispatch_batch(bodies))

    def _dispatch_batch(self, bodies: List[dict]):
        """Dispatch stage (runs under the batcher's scheduler lock):
        plan-cache bind, generic/lexical leg execution, and the UN-SYNCED
        kNN device dispatches. Returns the in-flight handle
        `_finalize_batch` lands; no blocking device sync happens here."""
        start = time.perf_counter()
        svc = self.svc
        reader = svc.combined_reader()
        from elasticsearch_tpu.node import _MultiShardVectorStore
        store = _MultiShardVectorStore(svc)
        self.stats["searches"] += len(bodies)
        self.stats["batches"] += 1
        self.stats["max_batch_seen"] = max(self.stats["max_batch_seen"],
                                           len(bodies))
        sched_meta = self.batcher.batch_meta()
        self.stats["queue_wait_nanos"] += sched_meta.get(
            "queue_wait_max_nanos", 0)

        t0 = time.perf_counter_ns()
        plans: List[HybridPlan] = []
        bound: List[List[Any]] = []
        cache_state: List[bool] = []
        for body in bodies:
            plan, hit = self.plan_for(body)
            plans.append(plan)
            bound.append(plan.bind(body, self.svc.mapper_service))
            cache_state.append(hit)
        plan_nanos = time.perf_counter_ns() - t0
        self.stats["plan_nanos"] += plan_nanos

        breaker_bytes = reader.num_docs * 16 * max(len(bodies), 1)
        self.node.breakers.add_estimate("request", breaker_bytes,
                                        "<hybrid>")
        # the per-dispatch event trace costs a dict per kernel call;
        # only pay it when some query in the batch asked to profile
        trace = any(body.get("profile") for body in bodies)
        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.parallel import policy as _mesh_policy
        mesh_before = _mesh_policy.stats() if trace else None
        if trace:
            _dispatch.DISPATCH.record_events(True)
        try:
            ctx = SearchContext(reader, svc.mapper_service,
                                query_cache=self.node.caches.query)
            ctx.index_settings = svc.settings.as_flat_dict()
            ctx.vector_store = store

            t0 = time.perf_counter_ns()
            leg_results, leg_info, pending = self._score_legs_async(
                reader, store, ctx, plans, bound)
            dispatch_nanos = time.perf_counter_ns() - t0
            self.stats["dispatch_nanos"] += dispatch_nanos
        except BaseException:
            if trace:
                _dispatch.DISPATCH.drain_events()
                _dispatch.DISPATCH.record_events(False)
            self.node.breakers.release("request", breaker_bytes)
            raise
        return {"start": start, "reader": reader, "store": store,
                "bodies": bodies, "plans": plans,
                "cache_state": cache_state, "plan_nanos": plan_nanos,
                "dispatch_nanos": dispatch_nanos,
                "leg_results": leg_results, "leg_info": leg_info,
                "pending": pending, "trace": trace,
                "mesh_before": mesh_before,
                "breaker_bytes": breaker_bytes,
                "sched_meta": sched_meta}

    def _finalize_batch(self, handle) -> List[dict]:
        """Finalize stage (runs OUTSIDE the scheduler lock, overlapping
        the next batch's dispatch): land the un-synced kNN boards, fuse
        RRF, hydrate the final windows, assemble responses. Byte-
        identical to the pre-pipeline single-stage path — only the
        timing moved."""
        svc = self.svc
        reader = handle["reader"]
        store = handle["store"]
        bodies = handle["bodies"]
        plans = handle["plans"]
        cache_state = handle["cache_state"]
        plan_nanos = handle["plan_nanos"]
        leg_results = handle["leg_results"]
        leg_info = handle["leg_info"]
        trace = handle["trace"]
        start = handle["start"]
        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.parallel import policy as _mesh_policy
        try:
            dispatch_events = []
            mesh_delta = None
            try:
                t0 = time.perf_counter_ns()
                self._land_knn_legs(handle["pending"], plans, leg_results,
                                    leg_info, store)
                sync_nanos = time.perf_counter_ns() - t0
                with self._stats_lock:
                    self.stats["sync_nanos"] += sync_nanos
            finally:
                if trace:
                    dispatch_events = _dispatch.DISPATCH.drain_events()
                    _dispatch.DISPATCH.record_events(False)
            if trace:
                # which legs of this batch rode the serving mesh
                # (process-wide counters, so concurrent batches can bleed
                # into the delta — `_nodes/stats indices.mesh` stays the
                # authoritative total, same caveat as the dispatch trace)
                from elasticsearch_tpu.search.profile import (
                    mesh_stats_delta)
                mesh_delta = mesh_stats_delta(handle["mesh_before"],
                                              _mesh_policy.stats())
            # score = launch + device wait: the pre-pipeline figure,
            # preserved so dashboards comparing rounds stay meaningful
            score_nanos = handle["dispatch_nanos"] + sync_nanos
            with self._stats_lock:
                self.stats["score_nanos"] += score_nanos

            t0 = time.perf_counter_ns()
            fused = []
            for bi, plan in enumerate(plans):
                rows, scores = fuse_rrf(
                    [leg_results[(bi, li)]
                     for li in range(len(plan.legs))],
                    plan.rank_constant)
                # exact two-phase ordering: (-score, row asc)
                order = np.lexsort((rows, -scores))
                top = order[plan.frm:plan.frm + plan.size]
                fused.append((rows, scores, top))
            fuse_nanos = time.perf_counter_ns() - t0
            with self._stats_lock:
                self.stats["fuse_nanos"] += fuse_nanos

            t0 = time.perf_counter_ns()
            out = []
            for bi, (plan, body, (rows, scores, top)) in enumerate(
                    zip(plans, bodies, fused)):
                top_rows = rows[top]
                top_scores = scores[top]
                final = ShardSearchResult(
                    0, top_rows.astype(np.int64),
                    top_scores.astype(np.float32), None, len(rows), "eq",
                    None, float(top_scores[0]) if len(top) else None)
                hits = execute_fetch_phase(
                    reader, svc.mapper_service, plan.fetch_body, final,
                    index_name=svc.name)
                for h, s in zip(hits, top_scores):
                    h["_score"] = float(s)
                resp = {
                    "took": int((time.perf_counter() - start) * 1000),
                    "timed_out": False,
                    "hits": {"total": {"value": int(len(rows)),
                                       "relation": "eq"},
                             "max_score": hits[0]["_score"] if hits
                             else None,
                             "hits": hits}}
                if body.get("profile"):
                    from elasticsearch_tpu.search.profile import (
                        hybrid_profile)
                    resp["profile"] = hybrid_profile(
                        svc.name, plan_nanos, score_nanos, fuse_nanos,
                        0, cache_state[bi], len(bodies),
                        [leg_info[(bi, li)]
                         for li in range(len(plan.legs))],
                        dispatch_events=dispatch_events,
                        mesh=mesh_delta,
                        queue_wait_nanos=handle["sched_meta"].get(
                            "queue_wait_max_nanos", 0),
                        device_dispatch_nanos=handle["dispatch_nanos"],
                        device_sync_nanos=sync_nanos,
                        scheduler=self.scheduler_snapshot())
                out.append(resp)
            hydrate_nanos = time.perf_counter_ns() - t0
            with self._stats_lock:
                self.stats["hydrate_nanos"] += hydrate_nanos
            # private key (popped by _search_rrf): the slow log needs
            # the phase breakdown on EVERY breach, not just profiled
            # requests — batch-scoped figures, same semantics as the
            # profile breakdown
            took_phases = {
                "plan_nanos": plan_nanos,
                "queue_wait_nanos": handle["sched_meta"].get(
                    "queue_wait_max_nanos", 0),
                "device_dispatch_nanos": handle["dispatch_nanos"],
                "device_sync_nanos": sync_nanos,
                "fuse_nanos": fuse_nanos,
                "hydrate_nanos": hydrate_nanos,
                "batch_size": len(bodies)}
            for resp in out:
                resp["_took_phases"] = dict(took_phases)
                prof = resp.get("profile")
                if prof is not None:
                    prof["hybrid"]["breakdown"]["hydrate_nanos"] = \
                        hydrate_nanos
            tr = handle["sched_meta"].get("trace")
            if tr is not None:
                # fine-grained stage attribution on the batch LEADER's
                # trace (the batcher already recorded the coarse
                # batch.dispatch/batch.finalize pair and linked
                # followers): every duration below was measured at an
                # existing sync point — retroactive spans, zero added
                # host syncs
                parent = handle["sched_meta"].get("trace_parent")
                tr.record_span("hybrid.plan", plan_nanos, parent_id=parent)
                tr.record_span("hybrid.device_dispatch",
                               handle["dispatch_nanos"], parent_id=parent,
                               coalesced=len(bodies))
                tr.record_span("hybrid.device_sync", sync_nanos,
                               parent_id=parent)
                tr.record_span("hybrid.fuse", fuse_nanos, parent_id=parent)
                tr.record_span("hybrid.hydrate", hydrate_nanos,
                               parent_id=parent)
            return out
        finally:
            self.node.breakers.release("request",
                                       handle["breaker_bytes"])

    def scheduler_snapshot(self) -> dict:
        """The continuous batcher's scheduler counters (topups, deadline
        sheds, dispatch/finalize overlap hits) — profile + stats feed."""
        sched = self.batcher.sched
        return {"topups": sched["topups"],
                "deadline_sheds": sched["deadline_sheds"],
                "overlap_hits": sched["overlap_hits"],
                "pipelined_batches": sched["pipelined_batches"]}

    # -------------------------------------------------------------- legs
    def _score_legs_async(self, reader, store, ctx, plans, bound):
        """Execute every body's BOUND legs, grouped so each engine sees
        ONE batched dispatch: lexical legs group per text field, kNN legs
        per (field, k, num_candidates). Generic and lexical legs complete
        here; kNN legs LAUNCH un-synced (`search_many_async`) and return
        as pending handles `_land_knn_legs` finalizes. Returns
        ({(body_idx, leg_idx): ranked row array}, per-leg profile info,
        pending kNN groups)."""
        leg_results: Dict[Tuple[int, int], np.ndarray] = {}
        leg_info: Dict[Tuple[int, int], dict] = {}

        lex_groups: Dict[str, List[Tuple[int, int, LexicalLeg]]] = {}
        sparse_groups: Dict[str, List[Tuple[int, int, SparseLeg]]] = {}
        maxsim_groups: Dict[Tuple[str, int],
                            List[Tuple[int, int, MaxSimLeg]]] = {}
        knn_groups: Dict[Tuple[str, int, Optional[int]],
                         List[Tuple[int, int, KnnLeg]]] = {}
        for bi, legs in enumerate(bound):
            for li, leg in enumerate(legs):
                if isinstance(leg, EmptyLeg):
                    leg_results[(bi, li)] = np.zeros(0, dtype=np.int64)
                    leg_info[(bi, li)] = {"type": "empty"}
                elif isinstance(leg, LexicalLeg):
                    lex_groups.setdefault(leg.field, []).append(
                        (bi, li, leg))
                elif isinstance(leg, SparseLeg):
                    sparse_groups.setdefault(leg.field, []).append(
                        (bi, li, leg))
                elif isinstance(leg, MaxSimLeg):
                    maxsim_groups.setdefault((leg.field, leg.k),
                                             []).append((bi, li, leg))
                elif isinstance(leg, KnnLeg):
                    knn_groups.setdefault(
                        (leg.field, leg.k, leg.num_candidates),
                        []).append((bi, li, leg))
                else:
                    result = execute_query_phase(
                        reader, self.svc.mapper_service,
                        {"query": leg.query, "size": plans[bi].window},
                        vector_store=store,
                        query_cache=self.node.caches.query,
                        index_settings=self.svc.settings.as_flat_dict(),
                        max_buckets=self.node._max_buckets(),
                        allow_expensive=self.node._allow_expensive(),
                        index_name=self.svc.name)
                    leg_results[(bi, li)] = np.asarray(result.rows,
                                                       dtype=np.int64)
                    if isinstance(leg, (SparseFallbackLeg,
                                        MaxSimFallbackLeg)):
                        key = ("sparse_grid_fallbacks"
                               if isinstance(leg, SparseFallbackLeg)
                               else "maxsim_grid_fallbacks")
                        self.stats[key] += 1
                        leg_info[(bi, li)] = {
                            "type": "query_phase_fallback",
                            "reason": leg.reason}
                    else:
                        leg_info[(bi, li)] = {"type": "query_phase"}

        for field, entries in lex_groups.items():
            window = max(plans[bi].window for bi, _li, _leg in entries)
            queries = [(leg.terms, leg.boost) for _bi, _li, leg in entries]
            required = [leg.required for _bi, _li, leg in entries]
            results = self.lexical.search_batch(
                reader, field, queries, window, required=required)
            lf = self.lexical.field(reader, field)
            for (bi, li, leg), (rows, _scores) in zip(entries, results):
                leg_results[(bi, li)] = rows[:plans[bi].window]
                leg_info[(bi, li)] = {
                    "type": "lexical_device", "field": field,
                    "terms": len(leg.terms), "corpus_slots": lf.n_slots,
                    "impact_tiles": int(lf.tile_slots.shape[0])}

        for field, entries in sparse_groups.items():
            window = max(plans[bi].window for bi, _li, _leg in entries)
            queries = [(leg.tokens, leg.boost) for _bi, _li, leg in entries]
            results = self.sparse.search_batch(reader, field, queries,
                                               window)
            sf = self.sparse.field(reader, field)
            for (bi, li, leg), (rows, _scores) in zip(entries, results):
                leg_results[(bi, li)] = rows[:plans[bi].window]
                leg_info[(bi, li)] = {
                    "type": "sparse_device", "field": field,
                    "tokens": len(leg.tokens), "corpus_slots": sf.n_slots,
                    "impact_tiles": int(sf.tile_slots.shape[0])}

        # MaxSim legs complete synchronously in the dispatch stage: the
        # fused rescore's inputs depend on its own coarse phase's ids,
        # so there is no un-synced board to land later
        for (field, k), entries in maxsim_groups.items():
            mapper = self.svc.mapper_service.get(field)
            queries = [(leg.query_tokens, leg.boost)
                       for _bi, _li, leg in entries]
            results = self.late.search_batch(reader, mapper, queries, k)
            lf = self.late.field(reader, mapper)
            for (bi, li, leg), (rows, _scores) in zip(entries, results):
                leg_results[(bi, li)] = rows[:plans[bi].window]
                leg_info[(bi, li)] = {
                    "type": "maxsim_device", "field": field, "k": k,
                    "encoding": lf.encoding,
                    "coarse_window": (lf.coarse_window(k)
                                      if lf.n_docs else 0),
                    "docs": lf.n_docs}

        pending = []
        for (field, k, num_candidates), entries in knn_groups.items():
            reqs = []
            for _bi, _li, leg in entries:
                filter_rows = None
                if leg.filter_spec is not None:
                    filter_rows = parse_query(
                        leg.filter_spec).execute(ctx).rows
                reqs.append((leg.query_vector, filter_rows))
            # launch only: the device arrays stay un-synced until the
            # finalize stage lands them (batch N's host work overlaps
            # batch N+1's dispatch)
            knn_handle = store.search_many_async(
                field, reqs, k, num_candidates=num_candidates)
            phases = dict(getattr(store, "last_knn_phases", None) or {})
            pending.append((entries, knn_handle, field, k, phases))
        return leg_results, leg_info, pending

    def _land_knn_legs(self, pending, plans, leg_results, leg_info,
                       store) -> None:
        """Finalize the batch's kNN legs: one bulk device→host landing
        per group, then post-processing identical to KnnQuery.execute +
        the query phase's score-ranked cut."""
        for entries, knn_handle, field, k, phases in pending:
            batch_out = store.finalize_many(knn_handle)
            for (bi, li, leg), (rows, raw) in zip(entries, batch_out):
                scores = (np.asarray(sim.to_es_score(raw, leg.metric))
                          * leg.boost)
                order = np.argsort(rows, kind="stable")
                rows = rows[order].astype(np.int64)
                scores = scores[order].astype(np.float32)
                kk = min(plans[bi].window, len(rows))
                idx = native.topk(scores, kk)
                leg_results[(bi, li)] = rows[idx]
                leg_info[(bi, li)] = {
                    "type": "knn_device", "field": field, "k": k,
                    **({"engine": phases.get("engine")}
                       if phases.get("engine") else {})}
