"""Fused hybrid execution: one plan, one dispatch per leg kind, RRF, fetch.

Before this module, a hybrid `rank: {rrf}` search paid per query: a DSL
parse, a host-Python BM25 pass per term, a device round-trip for the kNN
leg, a dict-based fusion, and a fetch — and only the kNN leg's device
dispatch could coalesce with concurrent traffic. This is the structural
reason config 3 was the record's one losing row vs the reference's
BulkScorer (`QueryPhase.java:171`).

The fused path compiles the body ONCE into a `HybridPlan` (cached per
index, keyed on the normalized body — repeated shapes skip parse/plan
entirely) and executes whole *batches* of hybrid queries that coalesced in
the serving layer (`serving/batcher.py` BoundedBatcher):

  plan    normalize → classify sub-searches into legs:
            lexical  — match/term on text fields → `ops/bm25.py` device
                       engine (tile-padded precomputed impacts)
            knn      — dense_vector → `vectors/store.py` batched corpus
            generic  — anything else → the per-query query phase
  score   ONE lexical dispatch per text field for the whole batch + ONE
          kNN dispatch per vector field for the whole batch; filters for
          filtered kNN legs evaluate host-side per query (the same
          pre-filter contract as `search/knn_query.py`)
  fuse    reciprocal-rank fusion, vectorized over the batch; f64
          accumulation in sub-search order reproduces the coordinator
          dict fold bit-for-bit, so fused results are byte-identical to
          the two-phase path (`tests/test_hybrid_plan.py` pins this)
  hydrate fetch only the final `from+size` window per query

Per-phase timings thread into `profile.hybrid` and the node's
`_nodes/stats` hybrid section.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu import native
from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.index.mapping import TextFieldMapper
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.bm25 import LexicalShard
from elasticsearch_tpu.search.queries import (
    SearchContext, parse_query, resolve_msm,
)
from elasticsearch_tpu.search.service import (
    ShardSearchResult, execute_fetch_phase, execute_query_phase,
)
from elasticsearch_tpu.serving.batcher import BoundedBatcher

DEFAULT_RANK_CONSTANT = 60
DEFAULT_WINDOW = 100


class LexicalLeg:
    """match/term sub-search on a text field, lowered to the device
    lexical engine."""

    __slots__ = ("field", "terms", "required", "boost")

    def __init__(self, field: str, terms: List[str], required: int,
                 boost: float):
        self.field = field
        self.terms = terms
        self.required = required
        self.boost = boost


class KnnLeg:
    __slots__ = ("field", "query_vector", "k", "num_candidates",
                 "filter_spec", "boost", "metric")

    def __init__(self, field: str, query_vector, k: int,
                 num_candidates: int, filter_spec: Optional[dict],
                 boost: float, metric: str):
        self.field = field
        self.query_vector = np.asarray(query_vector, dtype=np.float32)
        self.k = k
        self.num_candidates = num_candidates
        self.filter_spec = filter_spec
        self.boost = boost
        self.metric = metric


class GenericLeg:
    """Fallback: any sub-search the specialized engines don't cover runs
    through the ordinary per-query query phase (still inside the batch's
    single runner, still fused + fetched with the rest)."""

    __slots__ = ("query",)

    def __init__(self, query: dict):
        self.query = query


class HybridPlan:
    __slots__ = ("legs", "rank_constant", "window", "size", "frm",
                 "fetch_body")

    def __init__(self, legs, rank_constant, window, size, frm, fetch_body):
        self.legs = legs
        self.rank_constant = rank_constant
        self.window = window
        self.size = size
        self.frm = frm
        self.fetch_body = fetch_body


def _sub_queries_of(body: dict) -> List[dict]:
    subs: List[dict] = []
    if body.get("sub_searches"):
        subs = [s.get("query", {"match_all": {}})
                for s in body["sub_searches"]]
    else:
        if body.get("query") is not None:
            subs.append(body["query"])
        if body.get("knn") is not None:
            knn = body["knn"]
            if isinstance(knn, list):
                subs.extend({"knn": spec} for spec in knn)
            else:
                subs.append({"knn": knn})
    return subs


def _compile_lexical(spec_kind: str, qspec: dict,
                     mapper_service) -> Optional[LexicalLeg]:
    """Lower a match/term sub-search to the lexical engine when it scores
    exactly like the host path would (text field, no fuzziness)."""
    if not isinstance(qspec, dict) or len(qspec) != 1:
        return None
    ((field, v),) = qspec.items()
    mapper = mapper_service.get(field)
    if not isinstance(mapper, TextFieldMapper):
        return None
    if spec_kind == "term":
        text = v.get("value") if isinstance(v, dict) else v
        boost = float(v.get("boost", 1.0)) if isinstance(v, dict) else 1.0
        return LexicalLeg(field, [str(text)], 1, boost)
    # match
    if isinstance(v, dict):
        if v.get("fuzziness") is not None:
            return None
        text = v.get("query")
        operator = str(v.get("operator", "or")).lower()
        msm = v.get("minimum_should_match")
        boost = float(v.get("boost", 1.0))
    else:
        text, operator, msm, boost = v, "or", None, 1.0
    terms = mapper.search_analyzer.terms(str(text))
    if not terms:
        return None  # empty analysis → host path (empty DocSet) semantics
    required = len(terms) if operator == "and" \
        else resolve_msm(msm, len(terms))
    return LexicalLeg(field, terms, required, boost)


def compile_plan(body: dict, mapper_service) -> HybridPlan:
    """Parse + classify ONE hybrid body into an executable plan."""
    rrf = (body.get("rank") or {}).get("rrf") or {}
    rank_constant = int(rrf.get("rank_constant", DEFAULT_RANK_CONSTANT))
    window = int(rrf.get("rank_window_size",
                         rrf.get("window_size", DEFAULT_WINDOW)))
    size = int(body.get("size", 10))
    frm = int(body.get("from", 0) or 0)
    subs = _sub_queries_of(body)
    if len(subs) < 2:
        raise IllegalArgumentError(
            "[rrf] requires at least 2 ranked lists (sub_searches, or "
            "query + knn)")
    legs: List[Any] = []
    for q in subs:
        leg: Any = None
        if isinstance(q, dict) and len(q) == 1:
            kind = next(iter(q))
            spec = q[kind]
            if kind == "knn" and isinstance(spec, dict):
                from elasticsearch_tpu.index.mapping import (
                    DenseVectorFieldMapper)
                from elasticsearch_tpu.vectors.store import _METRIC_MAP
                mapper = mapper_service.get(spec["field"])
                if isinstance(mapper, DenseVectorFieldMapper):
                    qv = np.asarray(spec["query_vector"],
                                    dtype=np.float32)
                    if qv.shape[0] != mapper.dims:
                        # same 400 KnnQuery._metric raises on the oracle
                        raise IllegalArgumentError(
                            f"[knn] query vector has {qv.shape[0]} dims, "
                            f"field [{spec['field']}] expects "
                            f"{mapper.dims}")
                    # EXACT parse_query("knn") semantics — the oracle's:
                    # k defaults to 10 (not num_candidates), and
                    # num_candidates clamps up to k (KnnQuery.__init__)
                    k = int(spec.get("k", 10))
                    nc = max(int(spec.get("num_candidates",
                                          spec.get("k", 10))), k)
                    leg = KnnLeg(
                        spec["field"], qv, k, nc, spec.get("filter"),
                        float(spec.get("boost", 1.0)),
                        _METRIC_MAP[mapper.similarity])
            elif kind in ("match", "term"):
                leg = _compile_lexical(kind, spec, mapper_service)
        if leg is None:
            leg = GenericLeg(q)
        legs.append(leg)
    fetch_body = {k: v for k, v in body.items()
                  if k in ("_source", "docvalue_fields")}
    fetch_body["size"] = size
    return HybridPlan(legs, rank_constant, window, size, frm, fetch_body)


def fuse_rrf(leg_rows: List[np.ndarray], rank_constant: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """RRF over ranked row lists → (unique rows ascending, f64 scores).

    f64 accumulation in leg order reproduces the coordinator's python-dict
    fold exactly: per row, contributions add one leg at a time, so the
    floating-point sum order (and hence every last bit) matches."""
    non_empty = [r for r in leg_rows if len(r)]
    if not non_empty:
        return (np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float64))
    uniq = np.unique(np.concatenate(non_empty))
    scores = np.zeros(len(uniq), dtype=np.float64)
    for rows in leg_rows:
        if not len(rows):
            continue
        idx = np.searchsorted(uniq, rows)
        np.add.at(scores, idx,
                  1.0 / (rank_constant + np.arange(1, len(rows) + 1,
                                                   dtype=np.float64)))
    return uniq, scores


class HybridExecutor:
    """Per-index hybrid serving path: plan cache + bounded combining queue.

    Whole hybrid queries (not just their kNN legs) coalesce here: the
    first thread in becomes the runner and executes every body that
    accumulated while the previous batch was in flight — one lexical
    dispatch per text field, one kNN dispatch per vector field, for the
    entire batch. Admission control (depth + deadline) sheds overload as
    HTTP 429 instead of queueing into the p99 tail.
    """

    def __init__(self, node, svc, max_batch: int = 64,
                 max_queue_depth: int = 256,
                 deadline_ms: Optional[float] = 10_000.0,
                 plan_cache_entries: int = 256):
        from elasticsearch_tpu.search.caches import LruCache
        self.node = node
        self.svc = svc
        self.lexical = LexicalShard(
            dtype=str(svc.settings.get("index.lexical.impact_dtype",
                                       "f32")))
        self.plan_cache = LruCache(max_entries=plan_cache_entries)
        self.batcher = BoundedBatcher(self._run_batch, max_batch=max_batch,
                                      max_queue_depth=max_queue_depth,
                                      deadline_ms=deadline_ms)
        self.stats = {"searches": 0, "batches": 0, "max_batch_seen": 0,
                      "plan_cache_hits": 0, "plan_cache_misses": 0,
                      "plan_nanos": 0, "score_nanos": 0, "fuse_nanos": 0,
                      "hydrate_nanos": 0}

    # ------------------------------------------------------------- entry
    def submit(self, body: dict) -> dict:
        return self.batcher.submit(body)

    def plan_for(self, body: dict) -> Tuple[HybridPlan, bool]:
        """Plan-cache lookup (hit) or compile (miss), keyed on the
        normalized body."""
        from elasticsearch_tpu.search.caches import _canonical
        key = _canonical(body)
        plan = self.plan_cache.get(key)
        if plan is not None:
            self.stats["plan_cache_hits"] += 1
            return plan, True
        plan = compile_plan(body, self.svc.mapper_service)
        self.plan_cache.put(key, plan)
        self.stats["plan_cache_misses"] += 1
        return plan, False

    # ------------------------------------------------------------- batch
    def _run_batch(self, bodies: List[dict]) -> List[dict]:
        start = time.perf_counter()
        svc = self.svc
        reader = svc.combined_reader()
        from elasticsearch_tpu.node import _MultiShardVectorStore
        store = _MultiShardVectorStore(svc)
        self.stats["searches"] += len(bodies)
        self.stats["batches"] += 1
        self.stats["max_batch_seen"] = max(self.stats["max_batch_seen"],
                                           len(bodies))

        t0 = time.perf_counter_ns()
        plans: List[HybridPlan] = []
        cache_state: List[bool] = []
        for body in bodies:
            plan, hit = self.plan_for(body)
            plans.append(plan)
            cache_state.append(hit)
        plan_nanos = time.perf_counter_ns() - t0
        self.stats["plan_nanos"] += plan_nanos

        breaker_bytes = reader.num_docs * 16 * max(len(bodies), 1)
        self.node.breakers.add_estimate("request", breaker_bytes,
                                        "<hybrid>")
        try:
            ctx = SearchContext(reader, svc.mapper_service,
                                query_cache=self.node.caches.query)
            ctx.index_settings = svc.settings.as_flat_dict()
            ctx.vector_store = store

            t0 = time.perf_counter_ns()
            leg_results, leg_info = self._score_legs(
                reader, store, ctx, plans)
            score_nanos = time.perf_counter_ns() - t0
            self.stats["score_nanos"] += score_nanos

            t0 = time.perf_counter_ns()
            fused = []
            for bi, plan in enumerate(plans):
                rows, scores = fuse_rrf(
                    [leg_results[(bi, li)]
                     for li in range(len(plan.legs))],
                    plan.rank_constant)
                # exact two-phase ordering: (-score, row asc)
                order = np.lexsort((rows, -scores))
                top = order[plan.frm:plan.frm + plan.size]
                fused.append((rows, scores, top))
            fuse_nanos = time.perf_counter_ns() - t0
            self.stats["fuse_nanos"] += fuse_nanos

            t0 = time.perf_counter_ns()
            out = []
            for bi, (plan, body, (rows, scores, top)) in enumerate(
                    zip(plans, bodies, fused)):
                top_rows = rows[top]
                top_scores = scores[top]
                final = ShardSearchResult(
                    0, top_rows.astype(np.int64),
                    top_scores.astype(np.float32), None, len(rows), "eq",
                    None, float(top_scores[0]) if len(top) else None)
                hits = execute_fetch_phase(
                    reader, svc.mapper_service, plan.fetch_body, final,
                    index_name=svc.name)
                for h, s in zip(hits, top_scores):
                    h["_score"] = float(s)
                resp = {
                    "took": int((time.perf_counter() - start) * 1000),
                    "timed_out": False,
                    "hits": {"total": {"value": int(len(rows)),
                                       "relation": "eq"},
                             "max_score": hits[0]["_score"] if hits
                             else None,
                             "hits": hits}}
                if body.get("profile"):
                    from elasticsearch_tpu.search.profile import (
                        hybrid_profile)
                    resp["profile"] = hybrid_profile(
                        svc.name, plan_nanos, score_nanos, fuse_nanos,
                        0, cache_state[bi], len(bodies),
                        [leg_info[(bi, li)]
                         for li in range(len(plan.legs))])
                out.append(resp)
            hydrate_nanos = time.perf_counter_ns() - t0
            self.stats["hydrate_nanos"] += hydrate_nanos
            for resp in out:
                prof = resp.get("profile")
                if prof is not None:
                    prof["hybrid"]["breakdown"]["hydrate_nanos"] = \
                        hydrate_nanos
            return out
        finally:
            self.node.breakers.release("request", breaker_bytes)

    # -------------------------------------------------------------- legs
    def _score_legs(self, reader, store, ctx, plans):
        """Execute every plan's legs, grouped so each engine sees ONE
        batched dispatch: lexical legs group per text field, kNN legs per
        (field, k, num_candidates). Returns {(body_idx, leg_idx): ranked
        row array} + per-leg profile info."""
        leg_results: Dict[Tuple[int, int], np.ndarray] = {}
        leg_info: Dict[Tuple[int, int], dict] = {}

        lex_groups: Dict[str, List[Tuple[int, int, LexicalLeg]]] = {}
        knn_groups: Dict[Tuple[str, int, Optional[int]],
                         List[Tuple[int, int, KnnLeg]]] = {}
        for bi, plan in enumerate(plans):
            for li, leg in enumerate(plan.legs):
                if isinstance(leg, LexicalLeg):
                    lex_groups.setdefault(leg.field, []).append(
                        (bi, li, leg))
                elif isinstance(leg, KnnLeg):
                    knn_groups.setdefault(
                        (leg.field, leg.k, leg.num_candidates),
                        []).append((bi, li, leg))
                else:
                    result = execute_query_phase(
                        reader, self.svc.mapper_service,
                        {"query": leg.query, "size": plans[bi].window},
                        vector_store=store,
                        query_cache=self.node.caches.query,
                        index_settings=self.svc.settings.as_flat_dict(),
                        max_buckets=self.node._max_buckets(),
                        allow_expensive=self.node._allow_expensive(),
                        index_name=self.svc.name)
                    leg_results[(bi, li)] = np.asarray(result.rows,
                                                       dtype=np.int64)
                    leg_info[(bi, li)] = {"type": "query_phase"}

        for field, entries in lex_groups.items():
            window = max(plans[bi].window for bi, _li, _leg in entries)
            queries = [(leg.terms, leg.boost) for _bi, _li, leg in entries]
            required = [leg.required for _bi, _li, leg in entries]
            results = self.lexical.search_batch(
                reader, field, queries, window, required=required)
            lf = self.lexical.field(reader, field)
            for (bi, li, leg), (rows, _scores) in zip(entries, results):
                leg_results[(bi, li)] = rows[:plans[bi].window]
                leg_info[(bi, li)] = {
                    "type": "lexical_device", "field": field,
                    "terms": len(leg.terms), "corpus_slots": lf.n_slots,
                    "impact_tiles": int(lf.tile_slots.shape[0])}

        for (field, k, num_candidates), entries in knn_groups.items():
            reqs = []
            for _bi, _li, leg in entries:
                filter_rows = None
                if leg.filter_spec is not None:
                    filter_rows = parse_query(
                        leg.filter_spec).execute(ctx).rows
                reqs.append((leg.query_vector, filter_rows))
            batch_out = store.search_many(field, reqs, k,
                                          num_candidates=num_candidates)
            phases = dict(getattr(store, "last_knn_phases", None) or {})
            for (bi, li, leg), (rows, raw) in zip(entries, batch_out):
                # identical post-processing to KnnQuery.execute + the
                # query phase's score-ranked cut
                scores = (np.asarray(sim.to_es_score(raw, leg.metric))
                          * leg.boost)
                order = np.argsort(rows, kind="stable")
                rows = rows[order].astype(np.int64)
                scores = scores[order].astype(np.float32)
                kk = min(plans[bi].window, len(rows))
                idx = native.topk(scores, kk)
                leg_results[(bi, li)] = rows[idx]
                leg_info[(bi, li)] = {
                    "type": "knn_device", "field": field, "k": k,
                    **({"engine": phases.get("engine")}
                       if phases.get("engine") else {})}
        return leg_results, leg_info
