"""Fused filter→aggregate device plans for the core aggregation family.

The execution layer over `ops/aggs.py`: an agg body compiles ONCE into an
`AggPlan` (cached per index on the normalized body — the hybrid plan-cache
template trick generalized to agg bodies, so a dashboard's repeated shape
plans once and only the per-query numeric slots re-bind), and each search
executes the plan as a handful of pre-compiled dispatches: the matched row
set becomes a boolean mask over the columnar store's row bucket, bucket
ids derive in-kernel from resident key columns, and scatter-add boards
come back as `n_buckets + 1` lanes of counts / sums / mins / maxs.

Supported on device — numerically IDENTICAL to `compute_aggs` (final
mode) and `compute_partial_aggs` (distributed partial mode), pinned by
tests/test_device_aggs.py:

  terms            keyword / numeric / boolean / date / ip fields
                   (size, shard_size, missing, min_doc_count incl. 0,
                   order by _key/_count)
  histogram        interval, offset, missing, min_doc_count,
                   extended_bounds, format
  date_histogram   fixed intervals (+ offset, format, time_zone
                   rendering); calendar intervals fall back
  range            numeric from/to/key ranges (overlaps allowed)
  metrics          avg, sum, min, max, stats, value_count — top-level and
                   as one-level sub-aggs of any bucket agg above

Everything else — geo, cardinality/HLL, percentiles, pipelines as
sub-aggs, scripted, include/exclude, nested, composite, multi-valued
fields — falls through PER NODE to the host path (`compute_aggs` /
`compute_partial_aggs`), and sum-bearing metrics (sum/avg/stats) ride the
device only for integral columns where f64 scatter-adds are provably
order-free (see ops/aggs.py): exactness is a contract, not a tolerance.

Partial mode emits the SAME `$p`-tagged partial-reduction states
`search/agg_partials.py` merges today, so mesh/multi-index serving gets
per-shard device partials merged through the existing
`merge_partial_aggs` with zero coordinator changes. The SPMD row-sharded
twins route through `parallel/policy.py` like every other kernel.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError, SearchEngineError,
)
from elasticsearch_tpu.ops import aggs as aggs_ops
from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.search import aggregations as A

logger = logging.getLogger("elasticsearch_tpu.agg_plan")

SUPPORTED_METRICS = ("avg", "sum", "min", "max", "stats", "value_count")
SUM_KINDS = ("avg", "sum", "stats")

# mapper types whose doc values live faithfully in the f64 column
_NUMERIC_TNAMES = ("long", "integer", "short", "byte", "double", "float",
                   "half_float", "scaled_float", "date", "date_nanos",
                   "boolean", "ip")

_TERMS_ALLOWED_KEYS = {"field", "size", "shard_size", "missing",
                       "min_doc_count", "order", "value_type"}
_HISTO_ALLOWED_KEYS = {"field", "interval", "offset", "min_doc_count",
                       "missing", "extended_bounds", "format"}
_DATE_HISTO_ALLOWED_KEYS = {"field", "interval", "fixed_interval",
                            "calendar_interval", "offset", "min_doc_count",
                            "format", "time_zone"}
_RANGE_ALLOWED_KEYS = {"field", "ranges", "keyed"}
_CARD_ALLOWED_KEYS = {"field", "precision_threshold", "missing"}

# composite sub-agg trees: bucket-in-bucket nesting compiles to ONE flat
# board per (depth, metric) whose lane is parent_id * k_child + child_id
MAX_TREE_DEPTH = aggs_ops.TREE_MAX_DEPTH

# nominal calendar-unit lengths in millis — probe steps for the boundary
# walk, NOT bucket widths (DST/leap realities come from _calendar_floor)
_CAL_NOMINAL = {"T": 60_000, "H": 3_600_000, "D": 86_400_000,
                "W": 604_800_000, "M": 28 * 86_400_000,
                "Q": 90 * 86_400_000, "Y": 365 * 86_400_000}



def _mesh_call(name, *args, mesh, **kw):
    """Launch-guarded mesh dispatch: collective programs that share
    devices must ENQUEUE in one global order (`parallel/mesh.
    launch_guard`) — an aggs reduce racing a kNN/BM25 mesh launch on
    overlapping devices could otherwise deadlock the all-gather
    rendezvous. Execution stays async; the guard covers only the
    enqueue."""
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    with mesh_lib.launch_guard(mesh):
        return dispatch.call(name, *args, mesh=mesh, **kw)


class _Fallback(Exception):
    """Bind-time device rejection: run this node on the host instead.
    `observed` optionally carries the measured quantity that busted the
    grid (e.g. the ordinal cardinality) so ladder growth is data-driven."""

    def __init__(self, reason: str, observed: Optional[int] = None):
        super().__init__(reason)
        self.reason = reason
        self.observed = observed


class _SubMetric:
    __slots__ = ("name", "kind", "field")

    def __init__(self, name, kind, field):
        self.name = name
        self.kind = kind
        self.field = field


class _Node:
    """One agg's compiled classification. mode: 'host' | 'metric' |
    'cardinality' | 'terms' | 'histogram' | 'date_histogram' | 'range'.
    Bucket nodes may carry `children` (nested bucket _Nodes — the
    composite-id tree) and `cards` (cardinality leaves) next to the
    metric `subs`."""

    __slots__ = ("name", "mode", "kind", "field", "subs", "host_reason",
                 "children", "cards")

    def __init__(self, name, mode, kind=None, field=None, subs=(),
                 host_reason=None, children=(), cards=()):
        self.name = name
        self.mode = mode
        self.kind = kind
        self.field = field
        self.subs = list(subs)
        self.host_reason = host_reason
        self.children = list(children)
        self.cards = list(cards)


class AggPlan:
    __slots__ = ("nodes", "device_count")

    def __init__(self, nodes: Dict[str, _Node]):
        self.nodes = nodes
        self.device_count = sum(1 for n in nodes.values()
                                if n.mode != "host")


# ---------------------------------------------------------------------------
# plan cache key: the hybrid `plan_cache_key` trick for agg bodies — the
# per-query numeric slots (interval/offset/bounds/missing) scrub to
# placeholders so a dashboard sweeping a slider re-uses one plan; kinds,
# fields, sizes and everything classification reads stay structural.
# ---------------------------------------------------------------------------


def plan_cache_key(aggs_spec: dict) -> str:
    def scrub_node(spec):
        if not isinstance(spec, dict):
            return spec
        out = {}
        for kind, body in spec.items():
            if kind in ("aggs", "aggregations"):
                out[kind] = {n: scrub_node(s)
                             for n, s in (body or {}).items()}
                continue
            if not isinstance(body, dict):
                out[kind] = body
                continue
            b = dict(body)
            if kind == "histogram":
                for key in ("interval", "offset", "missing",
                            "extended_bounds"):
                    if key in b:
                        b[key] = "__v__"
            elif kind == "date_histogram":
                # interval strings stay: "month" vs "1h" changes the
                # calendar-vs-fixed classification itself
                for key in ("offset", "missing"):
                    if key in b:
                        b[key] = "__v__"
            elif kind == "range":
                if isinstance(b.get("ranges"), list):
                    b["ranges"] = [
                        {k: ("__v__" if k in ("from", "to") else v)
                         for k, v in r.items()} if isinstance(r, dict)
                        else r
                        for r in b["ranges"]]
            elif kind in SUPPORTED_METRICS or kind == "cardinality":
                if "missing" in b:
                    b["missing"] = "__v__"
            out[kind] = b
        return out

    from elasticsearch_tpu.search.caches import _canonical
    return _canonical({n: scrub_node(s)
                       for n, s in (aggs_spec or {}).items()})


# ---------------------------------------------------------------------------
# plan compile (structural classification only — column-dependent checks
# happen at bind time, because columns change with every refresh)
# ---------------------------------------------------------------------------


def _classify_metric(kind: str, body, mapper_service) -> Optional[str]:
    """None = device-eligible; otherwise the host-fallback reason."""
    if not isinstance(body, dict):
        return "malformed"
    if body.get("script") is not None:
        return "script"
    field = body.get("field")
    if not isinstance(field, str):
        return "no_field"
    mapper = mapper_service.get(field)
    tname = getattr(mapper, "type_name", None)
    if tname is None:
        return "unmapped_field"
    if tname not in _NUMERIC_TNAMES:
        # keyword/text raise host-side for numeric-only metrics, and
        # value_count over keyword counts string values the f64 column
        # can't see — both are host business
        return "non_numeric_field"
    return None


def _classify_cardinality(body, mapper_service) -> Optional[str]:
    """None = device-eligible cardinality; otherwise the fallback
    reason. Keyword fields are in: the HLL register columns hash the raw
    doc values, not the f64 view."""
    if not isinstance(body, dict):
        return "malformed"
    if not set(body) <= _CARD_ALLOWED_KEYS:
        return "unsupported_param"
    if body.get("script") is not None:
        return "script"
    field = body.get("field")
    if not isinstance(field, str):
        return "no_field"
    mapper = mapper_service.get(field)
    tname = getattr(mapper, "type_name", None)
    if tname is None:
        return "unmapped_field"
    if tname not in _NUMERIC_TNAMES + ("keyword",):
        return "unsupported_field_type"
    return None


def _classify_subs(sub_spec: dict, mapper_service, depth: int = 1,
                   allow_buckets: bool = True
                   ) -> Tuple[list, list, list, str]:
    """Classify one bucket agg's sub-agg spec → (metric leaves,
    cardinality leaves, nested bucket children, reason). Bucket children
    recurse up to MAX_TREE_DEPTH levels (the composite-id tree); range
    parents pass allow_buckets=False (ranges overlap, so their members
    don't partition into composite ids)."""
    subs: List[_SubMetric] = []
    cards: List[_SubMetric] = []
    children: List[_Node] = []
    for sname, sspec in (sub_spec or {}).items():
        if not isinstance(sspec, dict):
            return [], [], [], "malformed_sub"
        skinds = [k for k in sspec
                  if k not in ("aggs", "aggregations", "meta")]
        if len(skinds) != 1:
            return [], [], [], "unsupported_sub_agg"
        skind = skinds[0]
        inner = sspec.get("aggs") or sspec.get("aggregations") or {}
        if skind in SUPPORTED_METRICS:
            if inner:
                return [], [], [], "sub_sub_aggs"
            reason = _classify_metric(skind, sspec[skind], mapper_service)
            if reason is not None:
                return [], [], [], f"sub_{reason}"
            subs.append(_SubMetric(sname, skind, sspec[skind]["field"]))
            continue
        if skind == "cardinality":
            if inner:
                return [], [], [], "unsupported_sub_agg"
            reason = _classify_cardinality(sspec[skind], mapper_service)
            if reason is not None:
                return [], [], [], f"sub_{reason}"
            cards.append(_SubMetric(sname, skind, sspec[skind]["field"]))
            continue
        if skind in ("terms", "histogram", "date_histogram") \
                and isinstance(sspec[skind], dict):
            if not allow_buckets:
                return [], [], [], "unsupported_sub_agg"
            if depth >= MAX_TREE_DEPTH:
                return [], [], [], "tree_too_deep"
            body = sspec[skind]
            reason = _classify_bucket(skind, body, mapper_service)
            if reason:
                return [], [], [], f"sub_{reason}"
            if skind == "terms" and isinstance(body.get("order"), dict) \
                    and next(iter(body["order"])) == "_count":
                # explicit _count order below the root would need per-row
                # first-occurrence tie-breaks inside every parent bucket —
                # host business (the DEFAULT sort's count tie-break is by
                # _key, which the device reproduces fine)
                return [], [], [], "order_count_in_subtree"
            csubs, ccards, cchildren, creason = _classify_subs(
                inner, mapper_service, depth + 1)
            if creason:
                return [], [], [], creason
            children.append(_Node(sname, skind, kind=skind,
                                  field=body.get("field"), subs=csubs,
                                  cards=ccards, children=cchildren))
            continue
        return [], [], [], "unsupported_sub_agg"
    return subs, cards, children, ""


def compile_plan(aggs_spec: dict, mapper_service) -> AggPlan:
    nodes: Dict[str, _Node] = {}
    for name, spec in (aggs_spec or {}).items():
        if not isinstance(spec, dict):
            nodes[name] = _Node(name, "host", host_reason="malformed")
            continue
        kinds = [k for k in spec
                 if k not in ("aggs", "aggregations", "meta")]
        if len(kinds) != 1:
            nodes[name] = _Node(name, "host", host_reason="malformed")
            continue
        kind = kinds[0]
        body = spec[kind]
        sub_spec = spec.get("aggs") or spec.get("aggregations") or {}
        if kind in A.PIPELINE_AGGS:
            nodes[name] = _Node(name, "host", kind=kind,
                                host_reason="pipeline")
            continue
        if kind in SUPPORTED_METRICS and not sub_spec:
            reason = _classify_metric(kind, body, mapper_service)
            if reason is None:
                nodes[name] = _Node(name, "metric", kind=kind,
                                    field=body["field"])
            else:
                nodes[name] = _Node(name, "host", kind=kind,
                                    host_reason=reason)
            continue
        if kind == "cardinality" and not sub_spec:
            reason = _classify_cardinality(body, mapper_service)
            if reason is None:
                nodes[name] = _Node(name, "cardinality", kind=kind,
                                    field=body["field"])
            else:
                nodes[name] = _Node(name, "host", kind=kind,
                                    host_reason=reason)
            continue
        if kind in ("terms", "histogram", "date_histogram", "range") \
                and isinstance(body, dict):
            reason = _classify_bucket(kind, body, mapper_service)
            subs, cards, children = [], [], []
            if not reason:
                subs, cards, children, reason = _classify_subs(
                    sub_spec, mapper_service,
                    allow_buckets=kind != "range")
            if not reason and kind == "range" and cards:
                # range members overlap — no composite-id partition for
                # the per-bucket HLL boards to scatter into
                reason = "unsupported_sub_agg"
            if not reason:
                nodes[name] = _Node(name, kind, kind=kind,
                                    field=body.get("field"), subs=subs,
                                    cards=cards, children=children)
                continue
            nodes[name] = _Node(name, "host", kind=kind,
                                host_reason=reason)
            continue
        nodes[name] = _Node(name, "host", kind=kind,
                            host_reason="unsupported_agg")
    return AggPlan(nodes)


def _classify_bucket(kind: str, body: dict, mapper_service) -> str:
    field = body.get("field")
    if not isinstance(field, str) or field == "_index":
        return "no_field"
    if body.get("script") is not None:
        return "script"
    if kind == "terms":
        if not set(body) <= _TERMS_ALLOWED_KEYS:
            return "unsupported_param"
        order = body.get("order")
        if order is not None:
            if not (isinstance(order, dict) and len(order) == 1
                    and next(iter(order)) in ("_key", "_count")):
                return "order_by_metric"
            if next(iter(order)) == "_count" \
                    and int(body.get("min_doc_count", 1)) == 0:
                # zero-count buckets tie at 0 and the host breaks that tie
                # by its term-universe SET iteration order — not a
                # contract the device path can reproduce
                return "order_count_zero_buckets"
        return ""
    mapper = mapper_service.get(field)
    tname = getattr(mapper, "type_name", None)
    if kind == "histogram":
        if not set(body) <= _HISTO_ALLOWED_KEYS:
            return "unsupported_param"
        return ""
    if kind == "date_histogram":
        if not set(body) <= _DATE_HISTO_ALLOWED_KEYS:
            return "unsupported_param"
        from elasticsearch_tpu.index.mapping import RangeFieldMapperBase
        if isinstance(mapper, RangeFieldMapperBase):
            return "range_field"
        return ""
    if kind == "range":
        if not set(body) <= _RANGE_ALLOWED_KEYS:
            return "unsupported_param"
        ranges = body.get("ranges")
        if not isinstance(ranges, list) or not ranges or any(
                not isinstance(r, dict) or "mask" in r for r in ranges):
            return "unsupported_ranges"
        return ""
    return "unsupported_agg"


# ---------------------------------------------------------------------------
# measured cost router
# ---------------------------------------------------------------------------


class CostRouter:
    """Per-kernel-family device-vs-host cost model calibrated from live
    timings: device legs record end-to-end (dispatch + assembly) nanos
    per family, host walkers record nanos per matched doc. A node routes
    to the device only when the device estimate beats the host estimate
    with margin — so tiny corpora on CPU floors take the host walker
    instead of paying the fixed dispatch cost — and every REPROBE-th
    otherwise-host decision probes the device to keep the model live.

    Priors (before any measurement) deliberately favor the device: the
    router exists to catch the measured-slow case, not to predict it.

    `persist_path` makes the learned EWMAs durable: every observation
    writes the snapshot (atomic tmp+rename, a few hundred bytes) and a
    restart seeds the tables back from disk instead of re-probing cold —
    the per-NODE router state, so one file serves every index's engine
    (`<data>/_state/agg_router.json`, wired in `node._agg_cost_router`).
    `restores` counts families seeded at boot (`_nodes/stats
    indices.aggs router_restores`)."""

    EWMA = 0.25
    MARGIN = 1.25
    REPROBE = 32
    DEV_PRIOR_BASE = 250_000.0      # ~fixed dispatch+assembly floor (ns)
    DEV_PRIOR_PER_ROW = 0.5         # ns per padded row
    HOST_PRIOR_BASE = 30_000.0
    HOST_PRIOR_PER_DOC = 400.0      # ns per matched doc (python walker)

    def __init__(self, persist_path: Optional[str] = None):
        self._lock = threading.Lock()
        self._dev: Dict[str, float] = {}       # family -> ewma ns
        self._host: Dict[str, float] = {}      # family -> ewma ns/doc
        self._miss: Dict[str, int] = {}        # family -> host streak
        self.persist_path = persist_path
        self.restores = 0
        if persist_path:
            self._load(persist_path)

    def _load(self, path: str) -> None:
        """Seed the EWMA tables from a prior run's snapshot. Corrupt or
        missing files mean cold priors, never a boot failure."""
        import json as _json
        try:
            with open(path, "r", encoding="utf-8") as f:
                state = _json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(state, dict):
            return
        restored = 0
        with self._lock:
            for table, key in ((self._dev, "device_ns"),
                               (self._host, "host_ns_per_doc")):
                ent = state.get(key)
                if not isinstance(ent, dict):
                    continue
                for fam, v in ent.items():
                    try:
                        table[str(fam)] = float(v)
                    except (TypeError, ValueError):
                        continue
                    restored += 1
        self.restores = restored

    def _persist(self) -> None:
        if not self.persist_path:
            return
        import json as _json
        import os as _os
        tmp = self.persist_path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                _json.dump(self.snapshot(), f, sort_keys=True)
            _os.replace(tmp, self.persist_path)
        except OSError:  # pragma: no cover - disk-full/readonly boot
            pass

    def est_device(self, fam: str, r_pad: int) -> float:
        with self._lock:
            d = self._dev.get(fam)
        return d if d is not None else (
            self.DEV_PRIOR_BASE + self.DEV_PRIOR_PER_ROW * r_pad)

    def est_host(self, fam: str, n_docs: int) -> float:
        with self._lock:
            rate = self._host.get(fam)
        if rate is None:
            rate = self.HOST_PRIOR_PER_DOC
        return self.HOST_PRIOR_BASE + rate * max(n_docs, 1)

    def decide(self, fam: str, n_docs: int, r_pad: int) -> str:
        """'device' | 'probe' | 'host'. A probe runs on the device and
        feeds the model, keeping a stale host-favored estimate honest."""
        if self.est_host(fam, n_docs) * self.MARGIN \
                >= self.est_device(fam, r_pad):
            with self._lock:
                self._miss.pop(fam, None)
            return "device"
        with self._lock:
            streak = self._miss.get(fam, 0) + 1
            if streak >= self.REPROBE:
                self._miss[fam] = 0
                return "probe"
            self._miss[fam] = streak
        return "host"

    def _ewma(self, table: Dict[str, float], fam: str, x: float) -> None:
        with self._lock:
            prev = table.get(fam)
            table[fam] = x if prev is None else (
                prev + self.EWMA * (x - prev))

    def observe_device(self, fam: str, nanos: int) -> None:
        self._ewma(self._dev, fam, float(nanos))
        self._persist()

    def observe_host(self, fam: str, nanos: int, n_docs: int) -> None:
        self._ewma(self._host, fam, float(nanos) / max(n_docs, 1))
        self._persist()

    def snapshot(self) -> dict:
        with self._lock:
            return {"device_ns": dict(self._dev),
                    "host_ns_per_doc": dict(self._host)}


def _family(node: _Node) -> str:
    """Cost-model family: the top-level mode, with '_tree' marking the
    composite multi-board shape (very different cost profile)."""
    fam = node.mode
    if node.children or node.cards:
        fam += "_tree"
    return fam


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------


class AggEngine:
    """Per-index device aggregation engine: columnar store + plan cache +
    per-node device/host routing. `compute` returns (aggregations tree,
    profile info) — final JSON in single-pass mode, `$p` partial states in
    distributed-partial mode — or None when no node is device-eligible
    (the caller then runs the unchanged host path)."""

    def __init__(self, mapper_service, plan_cache_entries: int = 128,
                 warmup: Optional[bool] = None,
                 cost_router=False):
        from elasticsearch_tpu.search.caches import LruCache
        self.mapper_service = mapper_service
        self.store = aggs_ops.AggFieldStore(warmup=warmup)
        self.plan_cache = LruCache(max_entries=plan_cache_entries)
        # bool (own fresh router) or a CostRouter INSTANCE — the node
        # passes one shared, disk-backed router so every index's engine
        # trains (and restores) the same per-node cost model
        self.cost_router = (cost_router if isinstance(cost_router, CostRouter)
                            else (CostRouter() if cost_router else None))
        self._lock = threading.Lock()
        self._cal_cache = LruCache(max_entries=64)
        self.stats = {
            "searches": 0, "device_nodes": 0, "host_nodes": 0,
            "plan_cache_hits": 0, "plan_cache_misses": 0,
            "device_nanos": 0, "assemble_nanos": 0, "host_nanos": 0,
            "mesh_dispatches": 0, "router_host_routed": 0,
            "router_probes": 0, "fallback_reasons": {},
        }

    # ---------------------------------------------------------------- plan
    def plan_for(self, aggs_spec: dict) -> AggPlan:
        key = plan_cache_key(aggs_spec)
        plan = self.plan_cache.get(key)
        if plan is not None:
            with self._lock:
                self.stats["plan_cache_hits"] += 1
            return plan
        plan = compile_plan(aggs_spec, self.mapper_service)
        self.plan_cache.put(key, plan)
        with self._lock:
            self.stats["plan_cache_misses"] += 1
        return plan

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.stats[key] += n

    def _reason(self, reason: str, docs: int = 0,
                observed: Optional[int] = None) -> None:
        """fallback_reasons entries are {count, docs[, observed_max]}:
        matched-doc totals rank reasons by WORK routed host, not request
        volume, and observed_max (e.g. the ordinal cardinality that
        busted the ladder) makes grid growth data-driven."""
        with self._lock:
            r = self.stats["fallback_reasons"]
            ent = r.get(reason)
            if ent is None:
                ent = r[reason] = {"count": 0, "docs": 0}
            ent["count"] += 1
            ent["docs"] += int(docs)
            if observed is not None:
                ent["observed_max"] = max(int(observed),
                                          ent.get("observed_max", 0))

    # ------------------------------------------------------------- compute
    def compute(self, ctx, rows: np.ndarray, aggs_spec: dict,
                partial: bool = False) -> Optional[Tuple[dict, dict]]:
        if getattr(ctx, "nested_path", None):
            return None
        plan = self.plan_for(aggs_spec)
        if plan.device_count == 0:
            return None
        self._count("searches")
        # one immutable row-space snapshot for the whole pass: a refresh
        # resync advancing the store mid-request can't skew the mask
        mask_box: Dict[str, Any] = {"snap": self.store.snapshot(ctx.reader)}
        out: Dict[str, Any] = {}
        pipelines: List[Tuple[str, str, dict]] = []
        prof_nodes: List[dict] = []
        device_nanos = 0
        assemble_nanos = 0
        host_nanos = 0
        for name, spec in aggs_spec.items():
            if not isinstance(spec, dict):
                raise ParsingError(f"aggregation [{name}] must be an object")
            kinds = [k for k in spec
                     if k not in ("aggs", "aggregations", "meta")]
            if len(kinds) != 1:
                raise ParsingError(
                    f"aggregation [{name}] must define exactly one type")
            kind = kinds[0]
            if kind in A.PIPELINE_AGGS:
                if not partial:
                    pipelines.append((name, kind, spec[kind]))
                continue
            node = plan.nodes.get(name)
            res = None
            engine = "host"
            fam = None
            reason = node.host_reason if node is not None else None
            if node is not None and node.mode != "host":
                fam = _family(node)
                route = "device"
                if self.cost_router is not None:
                    route = self.cost_router.decide(
                        fam, len(rows), mask_box["snap"].r_pad)
                if route == "host":
                    reason = "routed_host_cheaper"
                    self._reason(reason, docs=len(rows))
                    self._count("router_host_routed")
                else:
                    if route == "probe":
                        self._count("router_probes")
                    try:
                        t0 = time.perf_counter_ns()
                        boards, mesh_used = self._run_device_node(
                            ctx, node, spec, rows, mask_box, partial)
                        t1 = time.perf_counter_ns()
                        res = self._assemble_node(
                            ctx, node, spec, rows, boards, partial)
                        t2 = time.perf_counter_ns()
                        device_nanos += t1 - t0
                        assemble_nanos += t2 - t1
                        engine = "device_mesh" if mesh_used else "device"
                        self._count("device_nodes")
                        if self.cost_router is not None:
                            self.cost_router.observe_device(fam, t2 - t0)
                    except _Fallback as fb:
                        reason = fb.reason
                        self._reason(fb.reason, docs=len(rows),
                                     observed=fb.observed)
                    except SearchEngineError:
                        raise  # parity errors (max_buckets, bad params)
                    except Exception as exc:  # pragma: no cover - safety
                        reason = "device_error"
                        self._reason("device_error", docs=len(rows))
                        logger.warning(
                            "device agg [%s] failed; serving from host: %s",
                            name, exc)
            if res is None:
                if node is not None and node.mode == "host" \
                        and node.host_reason:
                    self._reason(node.host_reason, docs=len(rows))
                sub = {name: spec}
                th0 = time.perf_counter_ns()
                if partial:
                    from elasticsearch_tpu.search.agg_partials import (
                        compute_partial_aggs)
                    res = compute_partial_aggs(ctx, rows, sub).get(name)
                else:
                    res = A.compute_aggs(ctx, rows, sub).get(name)
                th1 = time.perf_counter_ns()
                host_nanos += th1 - th0
                self._count("host_nodes")
                if self.cost_router is not None and fam is not None:
                    self.cost_router.observe_host(fam, th1 - th0,
                                                  len(rows))
            elif not partial and isinstance(spec.get("meta"), dict) \
                    and isinstance(res, dict):
                res["meta"] = spec["meta"]
            out[name] = res
            prof_nodes.append({"name": name, "engine": engine,
                               **({"fallback_reason": reason}
                                  if engine == "host" and reason else {})})
        # top-level pipelines run over the combined outputs, exactly as
        # compute_aggs does (partial mode defers them to the coordinator's
        # finalize, like agg_partials)
        for name, kind, spec in pipelines:
            res = A._compute_pipeline(out, kind, spec, name)
            if not (isinstance(res, dict) and "_applied" in res):
                out[name] = res
        with self._lock:
            self.stats["device_nanos"] += device_nanos
            self.stats["assemble_nanos"] += assemble_nanos
            self.stats["host_nanos"] += host_nanos
        profile = {"nodes": prof_nodes, "device_nanos": device_nanos,
                   "assemble_nanos": assemble_nanos}
        if self.store.columnar_refresh:
            # per-field segment-block-store composition of the last
            # column (re)build — surfaces as profile.aggregations[].
            # columnar so the delta-vs-full extraction story is visible
            # per request
            profile["columnar"] = {
                f: dict(v)
                for f, v in self.store.columnar_refresh.items()}
        return out, profile

    # ----------------------------------------------------------- dispatch
    def _mask_for(self, rows, mask_box) -> np.ndarray:
        mask = mask_box.get("mask")
        if mask is None:
            mask = mask_box["snap"].filter_mask(rows)
            mask_box["mask"] = mask
        return mask

    def _mesh_for(self, mask_box):
        """Route this node's reduce: mesh or single-device (counted by
        parallel/policy like every other kernel leg)."""
        from elasticsearch_tpu.parallel import policy
        snap = mask_box["snap"]
        mesh = policy.decide("aggs", snap.n_rows,
                             has_mesh_state=self.store.mesh_ready(
                                 snap, policy.serving_mesh()))
        return mesh

    @staticmethod
    def _check_metric_col(kind: str, col) -> None:
        if kind in SUM_KINDS and not col.integral_exact:
            raise _Fallback("non_integral_sum")
        if kind == "value_count" and col.multi_valued:
            # value_count counts every VALUE (all_values) while the f64
            # column keeps only a doc's first — host business
            raise _Fallback("multi_valued_field")

    def _metric_cols(self, ctx, node, snap):
        cols = {}
        for m in node.subs:
            col = self.store.column(ctx.reader, m.field, snap=snap)
            self._check_metric_col(m.kind, col)
            cols[m.name] = (m, col)
        return cols

    @staticmethod
    def _mparams(mspec: dict) -> np.ndarray:
        missing = mspec.get("missing")
        if missing is None:
            return np.zeros(2, dtype=np.float64)
        try:
            return np.asarray([1.0, float(missing)], dtype=np.float64)
        except (TypeError, ValueError):
            raise _Fallback("bad_missing_value")

    def _sharded(self, mesh, arrays):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elasticsearch_tpu.ops.dispatch import _x64_scope
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        row = NamedSharding(mesh, P(mesh_lib.SHARD_AXIS))
        with _x64_scope(True):
            return [jax.device_put(jnp.asarray(a), row) for a in arrays]

    def _run_device_node(self, ctx, node, spec, rows, mask_box,
                         partial=False):
        store = self.store
        reader = ctx.reader
        snap = mask_box["snap"]
        if node.mode == "cardinality" or node.children or node.cards:
            return self._run_tree_node(ctx, node, spec, rows, mask_box,
                                       partial)
        body = spec[node.kind]
        mask = self._mask_for(rows, mask_box)
        mesh = self._mesh_for(mask_box)
        boards: Dict[str, Any] = {"n_matched": int(len(rows))}
        mesh_used = False

        if node.mode == "terms":
            col = store.column(reader, node.field, want_ords=True,
                               snap=snap)
            if col.multi_valued:
                raise _Fallback("multi_valued_field")
            b = aggs_ops.bucket_count(max(len(col.ord_keys), 1))
            if b is None:
                raise _Fallback("cardinality_off_grid",
                                observed=len(col.ord_keys))
            mcols = self._metric_cols(ctx, node, snap)
            if mesh is not None:
                vals_d, pres_d, ords_d = col.device_arrays_mesh(mesh)
                (mask_d,) = self._sharded(mesh, [mask])
                counts = _mesh_call("aggs.mesh_ord_counts", ords_d,
                                       mask_d, n_buckets=b, mesh=mesh)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays_mesh(mesh)
                    mboards[mname] = _mesh_call(
                        "aggs.mesh_ord_metric", ords_d, mask_d, mv_d,
                        mp_d, self._mparams(_sub_body(spec, mname)),
                        n_buckets=b, mesh=mesh)
                mesh_used = True
            else:
                _v, _p, ords_d = col.device_arrays()
                counts = dispatch.call("aggs.ord_counts", ords_d, mask,
                                       n_buckets=b)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays()
                    mboards[mname] = dispatch.call(
                        "aggs.ord_metric", ords_d, mask,
                        self._mparams(_sub_body(spec, mname)), mv_d,
                        mp_d, n_buckets=b)
            boards.update(counts=np.asarray(counts),
                          metrics=_np_boards(mboards), col=col, mask=mask)

        elif node.mode in ("histogram", "date_histogram"):
            col = store.column(reader, node.field, snap=snap)
            hparams, meta = self._hist_params(node, body, col)
            boards["hist_meta"] = meta
            b = meta["n_buckets"]
            mcols = self._metric_cols(ctx, node, snap)
            if b == 0:
                # nothing present and no missing substitute: zero boards
                boards.update(
                    counts=np.zeros(1, dtype=np.int64),
                    metrics={n: (np.zeros(1, np.int64),
                                 np.zeros(1, np.float64),
                                 np.full(1, np.inf), np.full(1, -np.inf))
                             for n in mcols},
                    col=col)
                return boards, False
            cal_args = meta.get("cal_args")
            if mesh is not None:
                keys_d, kp_d, _ = col.device_arrays_mesh(mesh)
                (mask_d,) = self._sharded(mesh, [mask])
                if cal_args is not None:
                    cbounds, cparams = cal_args
                    counts = _mesh_call("aggs.mesh_cal_counts", keys_d,
                                        kp_d, mask_d, cbounds, cparams,
                                        n_buckets=b, mesh=mesh)
                else:
                    counts = _mesh_call("aggs.mesh_hist_counts", keys_d,
                                        kp_d, mask_d, hparams,
                                        n_buckets=b, mesh=mesh)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays_mesh(mesh)
                    if cal_args is not None:
                        cbounds, cparams = cal_args
                        mboards[mname] = _mesh_call(
                            "aggs.mesh_cal_metric", keys_d, kp_d, mask_d,
                            mv_d, mp_d, cbounds, cparams,
                            self._mparams(_sub_body(spec, mname)),
                            n_buckets=b, mesh=mesh)
                    else:
                        mboards[mname] = _mesh_call(
                            "aggs.mesh_hist_metric", keys_d, kp_d, mask_d,
                            mv_d, mp_d, hparams,
                            self._mparams(_sub_body(spec, mname)),
                            n_buckets=b, mesh=mesh)
                mesh_used = True
            else:
                keys_d, kp_d, _ = col.device_arrays()
                if cal_args is not None:
                    cbounds, cparams = cal_args
                    counts = dispatch.call("aggs.cal_counts", keys_d,
                                           kp_d, mask, cbounds, cparams,
                                           n_buckets=b)
                else:
                    counts = dispatch.call("aggs.hist_counts", keys_d,
                                           kp_d, mask, hparams,
                                           n_buckets=b)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays()
                    if cal_args is not None:
                        cbounds, cparams = cal_args
                        mboards[mname] = dispatch.call(
                            "aggs.cal_metric", keys_d, kp_d, mask,
                            cbounds, cparams,
                            self._mparams(_sub_body(spec, mname)), mv_d,
                            mp_d, n_buckets=b)
                    else:
                        mboards[mname] = dispatch.call(
                            "aggs.hist_metric", keys_d, kp_d, mask,
                            hparams, self._mparams(_sub_body(spec, mname)),
                            mv_d, mp_d, n_buckets=b)
            boards.update(counts=np.asarray(counts),
                          metrics=_np_boards(mboards), col=col)

        elif node.mode == "range":
            col = store.column(reader, node.field, snap=snap)
            bounds, frm_to = self._range_bounds(body)
            boards["frm_to"] = frm_to
            rparams = self._mparams(body)
            mcols = self._metric_cols(ctx, node, snap)
            if mesh is not None:
                keys_d, kp_d, _ = col.device_arrays_mesh(mesh)
                (mask_d,) = self._sharded(mesh, [mask])
                counts = _mesh_call("aggs.mesh_range_counts", keys_d,
                                       kp_d, mask_d, bounds, rparams,
                                       mesh=mesh)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays_mesh(mesh)
                    mboards[mname] = _mesh_call(
                        "aggs.mesh_range_metric", keys_d, kp_d, mask_d,
                        mv_d, mp_d, bounds, rparams,
                        self._mparams(_sub_body(spec, mname)), mesh=mesh)
                mesh_used = True
            else:
                keys_d, kp_d, _ = col.device_arrays()
                counts = dispatch.call("aggs.range_counts", keys_d, kp_d,
                                       mask, bounds, rparams)
                mboards = {}
                for mname, (m, mc) in mcols.items():
                    mv_d, mp_d, _ = mc.device_arrays()
                    mboards[mname] = dispatch.call(
                        "aggs.range_metric", keys_d, kp_d, mask, bounds,
                        rparams, self._mparams(_sub_body(spec, mname)),
                        mv_d, mp_d)
            boards.update(counts=np.asarray(counts),
                          metrics=_np_boards(mboards), col=col)

        elif node.mode == "metric":
            col = store.column(reader, node.field, snap=snap)
            self._check_metric_col(node.kind, col)
            zeros = store.zero_ords(snap.r_pad, mesh)
            mparams = self._mparams(body)
            mv_d, mp_d, _ = (col.device_arrays_mesh(mesh)
                             if mesh is not None else col.device_arrays())
            if mesh is not None:
                (mask_d,) = self._sharded(mesh, [mask])
                board = _mesh_call("aggs.mesh_ord_metric", zeros,
                                      mask_d, mv_d, mp_d, mparams,
                                      n_buckets=aggs_ops.AGG_B_LADDER[0],
                                      mesh=mesh)
                mesh_used = True
            else:
                board = dispatch.call("aggs.ord_metric", zeros, mask,
                                      mparams, mv_d, mp_d,
                                      n_buckets=aggs_ops.AGG_B_LADDER[0])
            boards.update(metric=_np_board(board), col=col)

        if mesh_used:
            from elasticsearch_tpu.parallel import mesh as mesh_lib
            from elasticsearch_tpu.parallel import policy
            s = int(mesh.shape[mesh_lib.SHARD_AXIS])
            n_boards = 1 + 4 * len(node.subs)
            b_len = len(boards.get("counts",
                                   boards.get("metric", (np.zeros(1),))[0]))
            policy.record_leg("aggs", 0, 0,
                              policy.gather_bytes(s, n_boards, b_len))
            self._count("mesh_dispatches")
        return boards, mesh_used

    # ------------------------------------------------- composite trees --
    def _run_tree_node(self, ctx, node, spec, rows, mask_box, partial):
        """Composite-id tree dispatch: each bucket level along a path
        binds an in-kernel id source (ordinals / histogram floor /
        calendar table), and every tree node gets ONE flat board per
        (counts | metric leaf | cardinality leaf) whose lane is the
        composite `parent_id * k_child + child_id` over its level chain.
        Top-level `cardinality` is the zero-level degenerate case."""
        store = self.store
        reader = ctx.reader
        snap = mask_box["snap"]
        mask = self._mask_for(rows, mask_box)
        mesh = self._mesh_for(mask_box)
        boards: Dict[str, Any] = {"n_matched": int(len(rows)),
                                  "mask": mask}
        mesh_used = mesh is not None
        n_dispatch = [0]
        lanes_out = [0]
        if mesh is not None:
            (mask_io,) = self._sharded(mesh, [mask])
        else:
            mask_io = mask

        def level_arrays(col):
            return (col.device_arrays_mesh(mesh) if mesh is not None
                    else col.device_arrays())

        def call(name, *args, **statics):
            n_dispatch[0] += 1
            if mesh is not None:
                return _mesh_call(name.replace("aggs.", "aggs.mesh_"),
                                  *args, mesh=mesh, **statics)
            return dispatch.call(name, *args, **statics)

        def bind_level(child, body):
            if child.kind == "terms":
                col = store.column(reader, child.field, want_ords=True,
                                   snap=snap)
                if col.multi_valued:
                    raise _Fallback("multi_valued_field")
                n_keys = len(col.ord_keys)
                miss = body.get("missing") is not None
                k = aggs_ops.bucket_count(max(n_keys, 1)
                                          + (1 if miss else 0))
                if k is None:
                    raise _Fallback("cardinality_off_grid",
                                    observed=n_keys)
                _v, _p, ords_d = level_arrays(col)
                oparams = np.asarray([1.0 if miss else 0.0],
                                     dtype=np.float64)
                return {"kind": "ord", "k": k, "args": (ords_d, oparams),
                        "col": col, "miss": miss, "meta": None,
                        "body": body}
            col = store.column(reader, child.field, snap=snap)
            hparams, meta = self._hist_params(child, body, col)
            k = meta["n_buckets"]
            if k == 0:
                # empty key column and no missing substitute: the whole
                # subtree reduces to zero boards (assembly-only)
                return {"kind": "empty", "k": 0, "args": (), "col": col,
                        "miss": False, "meta": meta, "body": body}
            keys_d, kp_d, _ = level_arrays(col)
            if meta.get("cal_args") is not None:
                cbounds, cparams = meta["cal_args"]
                return {"kind": "cal", "k": k,
                        "args": (keys_d, kp_d, cbounds, cparams),
                        "col": col, "miss": False, "meta": meta,
                        "body": body}
            return {"kind": "hist", "k": k,
                    "args": (keys_d, kp_d, hparams), "col": col,
                    "miss": False, "meta": meta, "body": body}

        def bind_card(body, levels, ks, flat, empty):
            field = body.get("field")
            total = 1
            for kk in ks:
                total *= kk
            if partial:
                # partial mode mirrors the host's HLL walker (which
                # ignores `missing` — host parity, not an oversight)
                col = store.column(reader, field, want_hll=True,
                                   snap=snap)
                if col.multi_valued:
                    raise _Fallback("multi_valued_field")
                if total > aggs_ops.HLL_MAX_LANES:
                    raise _Fallback("hll_off_grid")
                if empty:
                    return {"partial": True, "board": None, "col": col,
                            "body": body}
                hh = (col.hll_device_arrays_mesh(mesh)
                      if mesh is not None else col.hll_device_arrays())
                board = call("aggs.hll_board", mask_io, hh[0], hh[1],
                             *flat, levels=levels, n_buckets=ks)
                lanes_out[0] += (total + 1) * aggs_ops.HLL_M
                return {"partial": True, "board": np.asarray(board),
                        "col": col, "body": body}
            # final mode is EXACT (host counts a distinct set): the card
            # field rides one more ord level on the counts board
            col = store.column(reader, field, want_ords=True, snap=snap)
            if col.multi_valued:
                raise _Fallback("multi_valued_field")
            n_keys = len(col.ord_keys)
            miss = body.get("missing") is not None
            k_card = aggs_ops.bucket_count(max(n_keys, 1)
                                           + (1 if miss else 0))
            if k_card is None:
                raise _Fallback("cardinality_off_grid", observed=n_keys)
            if total * k_card > aggs_ops.TREE_MAX_LANES:
                raise _Fallback("tree_off_grid")
            if empty:
                return {"partial": False, "board": None, "k": k_card,
                        "col": col, "miss": miss, "body": body}
            _v, _p, ords_d = level_arrays(col)
            oparams = np.asarray([1.0 if miss else 0.0],
                                 dtype=np.float64)
            board = call("aggs.tree_counts", mask_io, *flat, ords_d,
                         oparams, levels=levels + ("ord",),
                         n_buckets=ks + (k_card,))
            lanes_out[0] += total * k_card + 1
            return {"partial": False, "board": np.asarray(board),
                    "k": k_card, "col": col, "miss": miss, "body": body}

        def run_node(node_, spec_node, chain):
            levels = tuple(lv["kind"] for lv in chain)
            ks = tuple(lv["k"] for lv in chain)
            empty = "empty" in levels
            total = 1
            for kk in ks:
                total *= kk
            if not empty and total > aggs_ops.TREE_MAX_LANES:
                raise _Fallback("tree_off_grid")
            flat = tuple(a for lv in chain for a in lv["args"])
            tnode: Dict[str, Any] = {"node": node_, "chain": chain,
                                     "ks": ks}
            if empty:
                tnode["counts"] = None
            else:
                tnode["counts"] = np.asarray(call(
                    "aggs.tree_counts", mask_io, *flat, levels=levels,
                    n_buckets=ks))
                lanes_out[0] += total + 1
            metrics = {}
            for m in node_.subs:
                mcol = store.column(reader, m.field, snap=snap)
                self._check_metric_col(m.kind, mcol)
                if empty:
                    metrics[m.name] = None
                    continue
                mv_d, mp_d, _ = level_arrays(mcol)
                mp = self._mparams(_sub_body(spec_node, m.name))
                metrics[m.name] = _np_board(call(
                    "aggs.tree_metric", mask_io, mp, mv_d, mp_d, *flat,
                    levels=levels, n_buckets=ks))
                lanes_out[0] += 4 * (total + 1)
            tnode["metrics"] = metrics
            cards = {}
            for c in node_.cards:
                cards[c.name] = bind_card(_sub_body(spec_node, c.name),
                                          levels, ks, flat, empty)
            tnode["cards"] = cards
            children = {}
            sub_spec = (spec_node.get("aggs")
                        or spec_node.get("aggregations") or {})
            for ch in node_.children:
                ch_spec = sub_spec[ch.name]
                lvl = bind_level(ch, ch_spec[ch.kind])
                children[ch.name] = run_node(ch, ch_spec,
                                             chain + [lvl])
            tnode["children"] = children
            return tnode

        if node.mode == "cardinality":
            troot: Dict[str, Any] = {
                "node": node, "chain": [], "ks": (), "counts": None,
                "metrics": {}, "children": {},
                "cards": {node.name: bind_card(spec[node.kind], (), (),
                                               (), False)}}
        else:
            lvl0 = bind_level(node, spec[node.kind])
            troot = run_node(node, spec, [lvl0])
        boards["tree"] = troot
        if mesh is not None and n_dispatch[0]:
            from elasticsearch_tpu.parallel import mesh as mesh_lib
            from elasticsearch_tpu.parallel import policy
            s = int(mesh.shape[mesh_lib.SHARD_AXIS])
            policy.record_leg("aggs", 0, 0,
                              policy.gather_bytes(s, 1, lanes_out[0]))
            self._count("mesh_dispatches")
        return boards, mesh_used

    def _calendar_bounds(self, field, col, unit, tz_spec, offset, div):
        """Sorted `_calendar_floor` boundary table spanning the column's
        [vmin, vmax] for one (unit, tz): host wall-clock math runs ONCE
        here (cached per column version), the kernel only searchsorts.
        Walks boundary-to-boundary by probing a nominal step then
        correcting with the true floor, so DST-shifted days and variable
        months/years land exactly where the host walker puts them."""
        key = (field, col.version, unit, str(tz_spec), offset, div)
        cached = self._cal_cache.get(key)
        if cached is not None:
            return cached
        tz = A._resolve_tz(tz_spec)
        lo = math.trunc(col.vmin / div - offset)
        hi = math.trunc(col.vmax / div - offset)
        nominal = _CAL_NOMINAL[unit]
        if (hi - lo) / nominal + 2 > aggs_ops.AGG_B_LADDER[-1]:
            raise _Fallback("span_off_grid")
        start = A._calendar_floor(int(lo), unit, tz)
        bounds = [start]
        cur = start
        limit = aggs_ops.AGG_B_LADDER[-1] + 2
        while True:
            # probe past the current boundary, escalating if a short
            # nominal step lands inside the same bucket (long months)
            step = nominal
            nxt = A._calendar_floor(int(cur + step), unit, tz)
            while nxt <= cur:
                step += 3_600_000
                nxt = A._calendar_floor(int(cur + step), unit, tz)
            # back up if the probe overshot a boundary (DST-short days)
            back = A._calendar_floor(int(nxt - 1), unit, tz)
            while back > cur:
                nxt = back
                back = A._calendar_floor(int(nxt - 1), unit, tz)
            if nxt > hi:
                break
            bounds.append(nxt)
            cur = nxt
            if len(bounds) > limit:
                raise _Fallback("span_off_grid")
        entry = (tuple(bounds), tz)
        self._cal_cache.put(key, entry)
        return entry

    def _hist_params(self, node, body, col):
        date = node.mode == "date_histogram"
        if date:
            interval, calendar = A._date_interval(body)
            offset = A._date_offset_ms(body.get("offset"))
            mapper = self.mapper_service.get(node.field)
            div = 1e6 if getattr(mapper, "type_name", None) == "date_nanos" \
                else 1.0
            missing = None
            if calendar:
                fmt = body.get("format")
                if col.vmin is None:
                    meta = {"interval": 0.0, "offset": offset, "base": 0.0,
                            "date": True, "n_buckets": 0, "fmt": fmt,
                            "tz": A._resolve_tz(body.get("time_zone")),
                            "cal_bounds": ()}
                    return None, meta
                if not (math.isfinite(col.vmin)
                        and math.isfinite(col.vmax)):
                    raise _Fallback("non_finite_keys")
                real, tz = self._calendar_bounds(
                    node.field, col, calendar, body.get("time_zone"),
                    offset, div)
                b = aggs_ops.bucket_count(len(real))
                if b is None:
                    raise _Fallback("span_off_grid")
                cbounds = np.full(b, np.inf, dtype=np.float64)
                cbounds[: len(real)] = real
                cparams = np.asarray([div, offset], dtype=np.float64)
                meta = {"interval": 0.0, "offset": offset, "base": 0.0,
                        "date": True, "n_buckets": b, "fmt": fmt,
                        "tz": tz, "cal_bounds": real,
                        "cal_args": (cbounds, cparams)}
                return None, meta
        else:
            try:
                interval = float(body["interval"])
            except (KeyError, TypeError, ValueError):
                raise _Fallback("bad_interval")
            offset = float(body.get("offset", 0.0))
            div = 1.0
            missing = body.get("missing")
        if not (interval > 0) or not math.isfinite(interval):
            raise _Fallback("bad_interval")
        vmin, vmax = col.vmin, col.vmax
        if div != 1.0:
            vmin = None if vmin is None else vmin / div
            vmax = None if vmax is None else vmax / div
        kflag, kmiss = 0.0, 0.0
        if missing is not None:
            try:
                kmiss = float(missing)
            except (TypeError, ValueError):
                raise _Fallback("bad_missing_value")
            kflag = 1.0
            has_absent = not bool(col.present[: col.n_rows].all())
            if vmin is None:
                vmin = vmax = kmiss
            elif has_absent:
                vmin, vmax = min(vmin, kmiss), max(vmax, kmiss)
        if vmin is None or not (math.isfinite(vmin) and math.isfinite(vmax)):
            base = 0.0
            n_buckets = 0 if vmin is None else None
            if n_buckets is None:
                raise _Fallback("non_finite_keys")
        else:
            base = math.floor((vmin - offset) / interval)
            top = math.floor((vmax - offset) / interval)
            span = int(top - base) + 1
            bb = aggs_ops.bucket_count(span)
            if bb is None:
                raise _Fallback("span_off_grid")
            n_buckets = bb
        hparams = np.asarray([interval, offset, base, div, kflag, kmiss],
                             dtype=np.float64)
        meta = {"interval": interval, "offset": offset, "base": base,
                "date": date, "n_buckets": n_buckets,
                "fmt": body.get("format"),
                "tz": A._resolve_tz(body.get("time_zone")) if date
                else None}
        return hparams, meta

    @staticmethod
    def _range_bounds(body):
        ranges = body.get("ranges", [])
        b = aggs_ops.bucket_count(len(ranges))
        if b is None:
            raise _Fallback("ranges_off_grid")
        bounds = np.full((b, 2), np.inf, dtype=np.float64)
        frm_to = []
        for i, r in enumerate(ranges):
            try:
                frm = float(r["from"]) if r.get("from") is not None else None
                to = float(r["to"]) if r.get("to") is not None else None
            except (TypeError, ValueError):
                raise _Fallback("bad_range_bound")
            bounds[i, 0] = -np.inf if frm is None else frm
            bounds[i, 1] = np.inf if to is None else to
            frm_to.append((frm, to))
        return bounds, frm_to

    # ----------------------------------------------------------- assembly
    def _assemble_node(self, ctx, node, spec, rows, boards, partial):
        if "tree" in boards:
            if node.mode == "cardinality":
                rec = boards["tree"]["cards"][node.name]
                return self._card_out(ctx, rec, [0], partial, node.name)
            return self._assemble_tree(ctx, boards["tree"], spec, [0],
                                       partial, boards)
        body = spec[node.kind]
        sub_bodies = {m.name: _sub_body(spec, m.name) for m in node.subs}
        sub_kinds = {m.name: m.kind for m in node.subs}
        if node.mode == "metric":
            cnt, s, mn, mx = boards["metric"]
            return self._metric_out(node.kind, body, int(cnt[0]),
                                    float(s[0]), float(mn[0]),
                                    float(mx[0]), node.field, partial)
        if node.mode == "terms":
            return self._assemble_terms(ctx, node, body, boards,
                                        sub_kinds, sub_bodies, partial)
        if node.mode in ("histogram", "date_histogram"):
            return self._assemble_histo(ctx, node, body, boards,
                                        sub_kinds, sub_bodies, partial)
        if node.mode == "range":
            return self._assemble_range(ctx, node, body, boards,
                                        sub_kinds, sub_bodies, partial)
        raise _Fallback("unsupported_agg")

    def _metric_out(self, kind, mspec, cnt, s, mn, mx, field, partial):
        if partial:
            if kind == "value_count":
                return {"$p": "value_count", "n": int(cnt)}
            if kind == "avg":
                return {"$p": "avg", "sum": float(s), "n": int(cnt)}
            if kind == "sum":
                return {"$p": "sum", "sum": float(s)}
            if kind == "min":
                return {"$p": "min", "v": float(mn) if cnt else None}
            if kind == "max":
                return {"$p": "max", "v": float(mx) if cnt else None}
            if kind == "stats":
                return {"$p": "stats", "n": int(cnt), "sum": float(s),
                        "min": float(mn) if cnt else None,
                        "max": float(mx) if cnt else None}
            raise _Fallback("unsupported_metric")
        if kind == "value_count":
            return {"value": int(cnt)}
        if kind == "avg":
            out = {"value": s / cnt if cnt else None}
            tname = getattr(self.mapper_service.get(field), "type_name",
                            None) if field else None
            if out["value"] is not None and tname in ("date", "date_nanos"):
                ms = out["value"] / 1e6 if tname == "date_nanos" \
                    else out["value"]
                out["value_as_string"] = A._millis_to_iso(int(round(ms)))
            return out
        if kind == "sum":
            return {"value": float(s)}
        if kind == "min":
            return {"value": float(mn) if cnt else None}
        if kind == "max":
            return {"value": float(mx) if cnt else None}
        if kind == "stats":
            if cnt == 0:
                return {"count": 0, "min": None, "max": None, "avg": None,
                        "sum": 0.0}
            return {"count": int(cnt), "min": float(mn), "max": float(mx),
                    "avg": s / cnt, "sum": float(s)}
        raise _Fallback("unsupported_metric")

    def _sub_outputs(self, b, lane, metrics, sub_kinds, sub_bodies,
                     partial, merge_lane=None):
        for mname, (cnt, s, mn, mx) in metrics.items():
            c, ss, m1, m2 = (int(cnt[lane]), float(s[lane]),
                             float(mn[lane]), float(mx[lane]))
            if merge_lane is not None:
                c += int(cnt[merge_lane])
                ss += float(s[merge_lane])
                m1 = min(m1, float(mn[merge_lane]))
                m2 = max(m2, float(mx[merge_lane]))
            mbody = sub_bodies[mname]
            field = mbody.get("field")
            b[mname] = self._metric_out(sub_kinds[mname], mbody, c, ss,
                                        m1, m2, field, partial)

    def _empty_sub_outputs(self, b, metrics, sub_kinds, sub_bodies,
                           partial):
        # a zero-count (gap-filled) bucket has no rows, so its metrics are
        # the empty-set outputs regardless of any `missing` substitute
        for mname in metrics:
            mbody = sub_bodies[mname]
            b[mname] = self._metric_out(sub_kinds[mname], mbody, 0, 0.0,
                                        float("inf"), float("-inf"),
                                        mbody.get("field"), partial)

    # ------------------------------------------------------------- terms
    def _assemble_terms(self, ctx, node, body, boards, sub_kinds,
                        sub_bodies, partial):
        from elasticsearch_tpu.index.mapping import parse_date_millis
        col = boards["col"]
        counts = boards["counts"]
        metrics = boards["metrics"]
        trash = len(counts) - 1
        field = node.field
        mapper = self.mapper_service.get(field) if field else None
        tname = getattr(mapper, "type_name", None) or body.get("value_type")

        size = int(body.get("size", 10))
        if partial:
            size = int(body.get("shard_size") or (size * 3 // 2 + 10))

        def fmt_key(k):
            if tname == "ip":
                from elasticsearch_tpu.index.mapping import IpFieldMapper
                try:
                    return IpFieldMapper.format_value(int(k))
                except (ValueError, TypeError):
                    return k
            return k

        key_index = {A._hashable(k): i for i, k in enumerate(col.ord_keys)}
        items: List[Tuple[Any, int, Any]] = []  # (key, count, lane)
        for i, k in enumerate(col.ord_keys):
            items.append([A._hashable(k), int(counts[i]), i, None])

        missing_val = body.get("missing")
        if missing_val is not None:
            mv = missing_val
            if tname in ("date", "date_nanos") and isinstance(mv, str):
                try:
                    mv = parse_date_millis(mv)
                except Exception:
                    pass
            elif tname in ("long", "integer", "short", "byte"):
                try:
                    mv = int(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a long")
            elif tname in ("double", "float", "half_float"):
                try:
                    mv = float(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a "
                        f"double")
            miss_cnt = int(counts[trash])
            ki = key_index.get(A._hashable(mv))
            if ki is not None:
                items[ki][1] += miss_cnt
                items[ki][3] = trash
            elif miss_cnt > 0:
                items.append([A._hashable(mv), miss_cnt, trash, None])

        mdc = int(body.get("min_doc_count", 1))
        if mdc != 0:
            items = [it for it in items if it[1] > 0]

        if mapper is not None:
            _tn = getattr(mapper, "type_name", None)
            if (_tn == "keyword" or (_tn == "text"
                                     and (mapper.params or {})
                                     .get("fielddata"))):
                self.mapper_service.mark_fielddata_loaded(field)

        order_spec = body.get("order")
        if not partial and order_spec and isinstance(order_spec, dict):
            ((okey, odir),) = order_spec.items()
            reverse = odir == "desc"
            if okey == "_key":
                items.sort(key=lambda it: A._sort_key(it[0]),
                           reverse=reverse)
            else:  # "_count" (order-by-metric never compiles to device)
                # host ties break by groups-dict insertion order = first
                # occurrence among the MATCHED rows; reproduce it from the
                # mask, then stable-sort by count so ties keep that order
                # under both directions (python's reverse=True keeps the
                # pre-sort order for equal keys, like the host's)
                mask = boards["mask"]
                marr = col.ords[: col.n_rows][mask[: col.n_rows]]
                marr = marr[marr >= 0]
                uniq, first = np.unique(marr, return_index=True)
                pos = {int(o): int(f) for o, f in zip(uniq, first)}
                items.sort(key=lambda it: pos.get(it[2], float("inf")))
                items.sort(key=lambda it: (it[1],), reverse=reverse)
        else:
            items.sort(key=lambda it: (-it[1], A._sort_key(it[0])))

        total_other = sum(it[1] for it in items[size:])
        A._check_max_buckets(ctx, min(len(items), size))
        buckets = []
        for key, c, lane, merge_lane in items[:size]:
            b = {"key": key, "doc_count": int(c)}
            if metrics:
                self._sub_outputs(b, lane, metrics, sub_kinds, sub_bodies,
                                  partial, merge_lane=merge_lane)
            buckets.append(b)
        if tname == "ip":
            for b in buckets:
                b["key"] = fmt_key(b["key"])
        elif tname == "boolean":
            for b in buckets:
                truthy = bool(b["key"])
                b["key"] = 1 if truthy else 0
                b["key_as_string"] = "true" if truthy else "false"
        elif tname == "date":
            for b in buckets:
                if isinstance(b["key"], (int, float)):
                    b["key_as_string"] = A._millis_to_iso(int(b["key"]))
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(total_other),
                "buckets": buckets}

    # ---------------------------------------------------------- histogram
    def _assemble_histo(self, ctx, node, body, boards, sub_kinds,
                        sub_bodies, partial):
        meta = boards["hist_meta"]
        counts = boards["counts"]
        metrics = boards["metrics"]
        interval = meta["interval"]
        offset = meta["offset"]
        base = meta["base"]
        date = meta["date"]
        fmt = meta["fmt"]
        tz = meta["tz"]
        n_b = meta["n_buckets"]
        min_count = -1 if partial else int(body.get("min_doc_count", 0))
        extended_bounds = body.get("extended_bounds")

        cal_bounds = meta.get("cal_bounds")
        groups: Dict[float, int] = {}  # float key -> board lane
        if cal_bounds is not None:
            # calendar lanes map to the precomputed boundary table, not
            # to a fixed-width arithmetic progression
            for i in range(min(n_b, len(cal_bounds))):
                if int(counts[i]) > 0:
                    groups[float(cal_bounds[i] + offset)] = i
        else:
            for i in range(n_b):
                if int(counts[i]) > 0:
                    key = float((base + i) * interval + offset)
                    groups[key] = i
        all_keys = sorted(groups)

        def _guard_span(lo_key, hi_key):
            if interval and (hi_key - lo_key) / interval > A.MAX_BUCKETS:
                raise IllegalArgumentError(
                    f"Trying to create too many buckets. Must be less "
                    f"than or equal to: [{A.MAX_BUCKETS}].")

        if extended_bounds and interval:
            lo = float(extended_bounds.get("min", np.inf))
            hi = float(extended_bounds.get("max", -np.inf))
            k = min([lo] + all_keys) if all_keys or lo != np.inf else lo
            top = max([hi] + all_keys) if all_keys or hi != -np.inf else hi
            _guard_span(k, top)
            cur = k
            full = []
            while cur <= top + 1e-9:
                full.append(round(cur, 10))
                cur += interval
            all_keys = full
        elif min_count == 0 and all_keys and interval:
            _guard_span(all_keys[0], all_keys[-1])
            full = []
            cur = all_keys[0]
            while cur <= all_keys[-1] + 1e-9:
                full.append(round(cur, 10))
                cur += interval
            all_keys = full
        A._check_max_buckets(ctx, len(all_keys))
        buckets = []
        for key in all_keys:
            lane = groups.get(key)
            c = int(counts[lane]) if lane is not None else 0
            if c < min_count and min_count > 0:
                continue
            b = {"key": int(key) if date else key, "doc_count": c}
            if date:
                b["key_as_string"] = A._format_date_key(int(key), fmt, tz) \
                    if fmt else A._millis_to_iso_tz(int(key), tz)
            if metrics:
                if lane is not None:
                    self._sub_outputs(b, lane, metrics, sub_kinds,
                                      sub_bodies, partial)
                else:
                    self._empty_sub_outputs(b, metrics, sub_kinds,
                                            sub_bodies, partial)
            buckets.append(b)
        out = {"buckets": buckets}
        if not date:
            f = body.get("format")
            if f:
                for b in out["buckets"]:
                    b["key_as_string"] = A._decimal_format(b["key"], f)
        return out

    # -------------------------------------------------------------- range
    def _assemble_range(self, ctx, node, body, boards, sub_kinds,
                        sub_bodies, partial):
        counts = boards["counts"]
        metrics = boards["metrics"]
        frm_to = boards["frm_to"]
        ranges = body.get("ranges", [])
        buckets = []
        for i, r in enumerate(ranges):
            frm, to = frm_to[i]
            key = r.get("key")
            if key is None:
                lo_s = "*" if frm is None else float(frm)
                hi_s = "*" if to is None else float(to)
                key = f"{lo_s}-{hi_s}"
            b = {"key": key, "doc_count": int(counts[i])}
            if frm is not None:
                b["from"] = float(frm)
            if to is not None:
                b["to"] = float(to)
            if metrics:
                self._sub_outputs(b, i, metrics, sub_kinds, sub_bodies,
                                  partial)
            b["_sort"] = (frm if frm is not None else -np.inf,
                          to if to is not None else np.inf)
            buckets.append(b)
        buckets.sort(key=lambda b: b.pop("_sort"))
        return {"buckets": buckets}

    # ------------------------------------------------- tree assembly ----
    def _tree_eff_counts(self, tnode, P) -> np.ndarray:
        """Per-lane doc counts of this node's level given the parent
        composite selection P (ids over the chain MINUS the last level).
        The flat board reshapes to (parents, k) and the selected parent
        rows sum — exact int64 adds, order-free."""
        ks = tnode["ks"]
        k = ks[-1]
        counts = tnode["counts"]
        if counts is None or not P or k == 0:
            return np.zeros(max(k, 0), dtype=np.int64)
        total = 1
        for kk in ks:
            total *= kk
        return counts[:total].reshape(total // k, k)[
            np.asarray(P)].sum(axis=0)

    def _tree_sub_outputs(self, b, P_i, tnode, spec_node, partial):
        for mname, board4 in tnode["metrics"].items():
            mbody = _sub_body(spec_node, mname)
            kind = next(k for k in (spec_node.get("aggs")
                                    or spec_node.get("aggregations")
                                    or {})[mname]
                        if k not in ("aggs", "aggregations", "meta"))
            if board4 is None or not P_i:
                c, ss, m1, m2 = 0, 0.0, float("inf"), float("-inf")
            else:
                cnt, s, mn, mx = board4
                idx = np.asarray(P_i)
                c = int(cnt[idx].sum())
                ss = float(s[idx].sum())
                m1 = float(mn[idx].min())
                m2 = float(mx[idx].max())
            b[mname] = self._metric_out(kind, mbody, c, ss, m1, m2,
                                        mbody.get("field"), partial)

    def _card_out(self, ctx, rec, P, partial, name):
        from elasticsearch_tpu.search import agg_partials as AP
        body = rec["body"]
        if partial:
            board = rec["board"]
            if board is None or not P:
                regs: Dict[int, int] = {}
            else:
                v = board[np.asarray(P)].max(axis=0)
                nz = np.nonzero(v)[0]
                regs = {int(i): int(v[i]) for i in nz}
            return AP._hll_pack(regs)
        pt = body.get("precision_threshold")
        if pt is not None and int(pt) < 0:
            raise IllegalArgumentError(
                f"[precisionThreshold] must be greater than or equal to "
                f"0. Found [{int(pt)}] in [{name}]")
        board = rec["board"]
        k_card = rec["k"]
        col = rec["col"]
        n_keys = len(col.ord_keys)
        if board is None or not P:
            sub = np.zeros(k_card, dtype=np.int64)
        else:
            total = (len(board) - 1) // k_card
            sub = board[: total * k_card].reshape(total, k_card)[
                np.asarray(P)].sum(axis=0)
        distinct = int(np.count_nonzero(sub[:n_keys]))
        if rec["miss"] and int(sub[k_card - 1]) > 0:
            # the host adds _hashable(missing) to the distinct SET — it
            # only grows the count when no counted key already equals it
            mi = None
            mv = A._hashable(body.get("missing"))
            for i, kk in enumerate(col.ord_keys):
                if A._hashable(kk) == mv:
                    mi = i
                    break
            if mi is None or int(sub[mi]) == 0:
                distinct += 1
        return {"value": distinct}

    def _assemble_tree(self, ctx, tnode, spec_node, P, partial, boards):
        """Assemble one tree node's bucket list for the parent composite
        selection P, recursing into children with each bucket's own
        composite list — the flat boards decompose into exactly the
        nested JSON the host's `_bucketize` recursion emits."""
        node_ = tnode["node"]
        lvl = tnode["chain"][-1]
        k = lvl["k"]
        body = spec_node[node_.kind]
        eff = self._tree_eff_counts(tnode, P)

        def bucket_fill(b, P_i):
            self._tree_sub_outputs(b, P_i, tnode, spec_node, partial)
            for cname, rec in tnode["cards"].items():
                b[cname] = self._card_out(ctx, rec, P_i, partial, cname)
            sub_spec = (spec_node.get("aggs")
                        or spec_node.get("aggregations") or {})
            for chname, ch in tnode["children"].items():
                res = self._assemble_tree(ctx, ch, sub_spec[chname],
                                          P_i, partial, boards)
                if not partial \
                        and isinstance(sub_spec[chname].get("meta"),
                                       dict) and isinstance(res, dict):
                    res["meta"] = sub_spec[chname]["meta"]
                b[chname] = res

        if lvl["kind"] == "ord":
            return self._tree_terms(ctx, node_, body, lvl, eff, P, k,
                                    partial, bucket_fill, tnode, boards)
        return self._tree_histo(ctx, node_, body, lvl, eff, P, k,
                                partial, bucket_fill)

    def _tree_terms(self, ctx, node, body, lvl, eff, P, k, partial,
                    bucket_fill, tnode, boards):
        from elasticsearch_tpu.index.mapping import parse_date_millis
        col = lvl["col"]
        field = node.field
        mapper = self.mapper_service.get(field) if field else None
        tname = getattr(mapper, "type_name", None) or body.get(
            "value_type")
        size = int(body.get("size", 10))
        if partial:
            size = int(body.get("shard_size") or (size * 3 // 2 + 10))

        key_index = {A._hashable(kk): i
                     for i, kk in enumerate(col.ord_keys)}
        items: List[list] = []
        for i, kk in enumerate(col.ord_keys):
            items.append([A._hashable(kk), int(eff[i]), i, None])

        missing_val = body.get("missing")
        if missing_val is not None:
            mv = missing_val
            if tname in ("date", "date_nanos") and isinstance(mv, str):
                try:
                    mv = parse_date_millis(mv)
                except Exception:
                    pass
            elif tname in ("long", "integer", "short", "byte"):
                try:
                    mv = int(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a "
                        f"long")
            elif tname in ("double", "float", "half_float"):
                try:
                    mv = float(mv)
                except (TypeError, ValueError):
                    raise ParsingError(
                        f"failed to parse [missing] value [{mv}] as a "
                        f"double")
            miss_cnt = int(eff[k - 1])
            ki = key_index.get(A._hashable(mv))
            if ki is not None:
                items[ki][1] += miss_cnt
                items[ki][3] = k - 1
            elif miss_cnt > 0:
                items.append([A._hashable(mv), miss_cnt, k - 1, None])

        mdc = int(body.get("min_doc_count", 1))
        if mdc != 0:
            items = [it for it in items if it[1] > 0]

        if mapper is not None:
            _tn = getattr(mapper, "type_name", None)
            if (_tn == "keyword" or (_tn == "text"
                                     and (mapper.params or {})
                                     .get("fielddata"))):
                self.mapper_service.mark_fielddata_loaded(field)

        order_spec = body.get("order")
        if not partial and order_spec and isinstance(order_spec, dict):
            ((okey, odir),) = order_spec.items()
            reverse = odir == "desc"
            if okey == "_key":
                items.sort(key=lambda it: A._sort_key(it[0]),
                           reverse=reverse)
            else:
                # "_count" compiles to the tree only at depth 1 (the
                # classifier rejects it deeper): the host tie-break is
                # first occurrence among matched rows, recovered from
                # the mask exactly like the single-level path
                mask = boards["mask"]
                marr = col.ords[: col.n_rows][mask[: col.n_rows]]
                marr = marr[marr >= 0]
                uniq, first = np.unique(marr, return_index=True)
                pos = {int(o): int(f) for o, f in zip(uniq, first)}
                items.sort(key=lambda it: pos.get(it[2], float("inf")))
                items.sort(key=lambda it: (it[1],), reverse=reverse)
        else:
            items.sort(key=lambda it: (-it[1], A._sort_key(it[0])))

        total_other = sum(it[1] for it in items[size:])
        A._check_max_buckets(ctx, min(len(items), size))
        buckets = []
        for key, c, lane, merge_lane in items[:size]:
            b = {"key": key, "doc_count": int(c)}
            P_i = [p * k + lane for p in P]
            if merge_lane is not None:
                P_i += [p * k + merge_lane for p in P]
            bucket_fill(b, P_i)
            buckets.append(b)
        if tname == "ip":
            from elasticsearch_tpu.index.mapping import IpFieldMapper
            for b in buckets:
                try:
                    b["key"] = IpFieldMapper.format_value(int(b["key"]))
                except (ValueError, TypeError):
                    pass
        elif tname == "boolean":
            for b in buckets:
                truthy = bool(b["key"])
                b["key"] = 1 if truthy else 0
                b["key_as_string"] = "true" if truthy else "false"
        elif tname == "date":
            for b in buckets:
                if isinstance(b["key"], (int, float)):
                    b["key_as_string"] = A._millis_to_iso(int(b["key"]))
        return {"doc_count_error_upper_bound": 0,
                "sum_other_doc_count": int(total_other),
                "buckets": buckets}

    def _tree_histo(self, ctx, node, body, lvl, eff, P, k, partial,
                    bucket_fill):
        meta = lvl["meta"]
        interval = meta["interval"]
        offset = meta["offset"]
        base = meta["base"]
        date = meta["date"]
        fmt = meta["fmt"]
        tz = meta["tz"]
        cal_bounds = meta.get("cal_bounds")
        min_count = -1 if partial else int(body.get("min_doc_count", 0))
        extended_bounds = body.get("extended_bounds")

        groups: Dict[float, int] = {}
        if cal_bounds is not None:
            for i in range(len(cal_bounds)):
                if i < len(eff) and int(eff[i]) > 0:
                    groups[float(cal_bounds[i] + offset)] = i
        else:
            for i in range(k):
                if int(eff[i]) > 0:
                    groups[float((base + i) * interval + offset)] = i
        all_keys = sorted(groups)

        def _guard_span(lo_key, hi_key):
            if interval and (hi_key - lo_key) / interval > A.MAX_BUCKETS:
                raise IllegalArgumentError(
                    f"Trying to create too many buckets. Must be less "
                    f"than or equal to: [{A.MAX_BUCKETS}].")

        if extended_bounds and interval:
            lo = float(extended_bounds.get("min", np.inf))
            hi = float(extended_bounds.get("max", -np.inf))
            kk = min([lo] + all_keys) if all_keys or lo != np.inf else lo
            top = max([hi] + all_keys) if all_keys or hi != -np.inf \
                else hi
            _guard_span(kk, top)
            cur = kk
            full = []
            while cur <= top + 1e-9:
                full.append(round(cur, 10))
                cur += interval
            all_keys = full
        elif min_count == 0 and all_keys and interval:
            _guard_span(all_keys[0], all_keys[-1])
            full = []
            cur = all_keys[0]
            while cur <= all_keys[-1] + 1e-9:
                full.append(round(cur, 10))
                cur += interval
            all_keys = full
        A._check_max_buckets(ctx, len(all_keys))
        buckets = []
        for key in all_keys:
            lane = groups.get(key)
            c = int(eff[lane]) if lane is not None else 0
            if c < min_count and min_count > 0:
                continue
            b = {"key": int(key) if date else key, "doc_count": c}
            if date:
                b["key_as_string"] = A._format_date_key(int(key), fmt,
                                                        tz) \
                    if fmt else A._millis_to_iso_tz(int(key), tz)
            P_i = [p * k + lane for p in P] if lane is not None else []
            bucket_fill(b, P_i)
            buckets.append(b)
        out = {"buckets": buckets}
        if not date:
            f = body.get("format")
            if f:
                for b in out["buckets"]:
                    b["key_as_string"] = A._decimal_format(b["key"], f)
        return out


def _sub_body(spec: dict, sub_name: str) -> dict:
    sub = spec.get("aggs") or spec.get("aggregations") or {}
    sspec = sub.get(sub_name) or {}
    for k, v in sspec.items():
        if k not in ("aggs", "aggregations", "meta"):
            return v if isinstance(v, dict) else {}
    return {}


def _np_board(board) -> tuple:
    return tuple(np.asarray(x) for x in board)


def _np_boards(mboards: dict) -> dict:
    return {n: _np_board(b) for n, b in mboards.items()}
