from elasticsearch_tpu.transport.tcp import (
    AsyncioScheduler, ConnectTransportError, RemoteTransportError,
    TcpTransportService, channel_type_for,
)
from elasticsearch_tpu.transport.wire import (
    WIRE_VERSION, WireFormatError, decode_frames, encode_frame, encode_ping,
)

__all__ = [
    "AsyncioScheduler", "ConnectTransportError", "RemoteTransportError",
    "TcpTransportService", "channel_type_for", "WIRE_VERSION",
    "WireFormatError", "decode_frames", "encode_frame", "encode_ping",
]
