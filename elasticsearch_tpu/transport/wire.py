"""Framed binary wire protocol for inter-node RPC.

Redesign of the reference's TCP wire format (SURVEY.md §2.2;
`transport/TcpHeader.java:29-45`, `OutboundMessage`, `InboundDecoder`):
a 2-byte marker, frame length, 8-byte request id, a status byte whose bits
distinguish request/response/error/compressed/handshake/ping, and a wire
version — followed by the action name (requests only) and a
generic-serialized payload (`common/serialization.py`, the StreamOutput
analog). Compression is zlib (the reference uses Deflate,
`transport/CompressibleBytesOutputStream`), applied to the variable section
only when it crosses a threshold.

Unlike the reference there is no separate variable-header section: request
headers (task ids, security context) travel inside the payload envelope,
which keeps the frame layout static-shaped and trivially incremental to
decode.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Optional, Tuple

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.common.serialization import StreamInput, StreamOutput

MARKER = b"ET"
HEADER_LEN = 2 + 4 + 8 + 1 + 4  # marker, length, request id, status, version

# status bits (reference: TransportStatus)
STATUS_REQUEST = 1 << 0      # set = request, clear = response
STATUS_ERROR = 1 << 1
STATUS_COMPRESS = 1 << 2
STATUS_HANDSHAKE = 1 << 3
STATUS_PING = 1 << 4

WIRE_VERSION = 1
MIN_COMPATIBLE_VERSION = 1
COMPRESS_THRESHOLD = 4 * 1024


class WireFormatError(SearchEngineError):
    """Malformed frame: bad marker, truncated header, unknown version."""


def encode_frame(request_id: int, status: int, version: int,
                 action: Optional[str], payload: Any,
                 compress: bool = True) -> bytes:
    """Serialize one frame. `action` is required iff STATUS_REQUEST is set."""
    body = StreamOutput(version)
    if status & STATUS_REQUEST:
        body.write_string(action or "")
    body.write_generic(payload)
    variable = body.bytes()
    if compress and len(variable) >= COMPRESS_THRESHOLD:
        status |= STATUS_COMPRESS
        variable = zlib.compress(variable)
    header = MARKER + struct.pack(
        ">iqBi", len(variable) + HEADER_LEN - 6, request_id, status, version)
    return header + variable


def encode_ping() -> bytes:
    """Zero-payload keep-alive frame (reference: TransportKeepAlive's -1
    length ping; here a status bit keeps the decoder uniform)."""
    return MARKER + struct.pack(">iqBi", HEADER_LEN - 6, 0, STATUS_PING,
                                WIRE_VERSION)


def decode_frames(buf: bytearray):
    """Incremental decoder: yield (request_id, status, version, action,
    payload) tuples for every complete frame in `buf`, consuming them.
    Leaves any trailing partial frame in place."""
    out = []
    while True:
        if len(buf) < 6:
            break
        if bytes(buf[:2]) != MARKER:
            raise WireFormatError(f"invalid frame marker {bytes(buf[:2])!r}")
        (length,) = struct.unpack(">i", bytes(buf[2:6]))
        if len(buf) < 6 + length:
            break
        frame = bytes(buf[6:6 + length])
        del buf[:6 + length]
        request_id, status, version = struct.unpack(">qBi", frame[:13])
        if status & STATUS_PING:
            out.append((0, status, version, None, None))
            continue
        if version < MIN_COMPATIBLE_VERSION:
            raise WireFormatError(
                f"remote wire version [{version}] below minimum compatible "
                f"[{MIN_COMPATIBLE_VERSION}]")
        variable = frame[13:]
        if status & STATUS_COMPRESS:
            variable = zlib.decompress(variable)
        stream = StreamInput(variable, version)
        action = stream.read_string() if status & STATUS_REQUEST else None
        payload = stream.read_generic()
        out.append((request_id, status, version, action, payload))
    return out
