"""Transport TLS + inter-node authentication context.

Re-design of the reference's transport security composition
(`libs/ssl-config` ~3.5k LoC PEM/JKS loading + `x-pack/.../transport/
SecurityServerTransportInterceptor.java:50`): the inter-node socket runs
TLS (mutual by default, like xpack.security.transport.ssl), and every RPC
envelope carries a signed authentication context that the receiving node
validates BEFORE dispatching to the handler — a peer that completed the
TCP/TLS handshake still cannot invoke actions without proving cluster
membership.

Settings (the `transport.ssl.*` family mirrors `xpack.security.transport.
ssl.*`):

  transport.ssl.enabled                  bool
  transport.ssl.certificate             PEM cert (this node)
  transport.ssl.key                     PEM private key
  transport.ssl.certificate_authorities PEM CA bundle (peer verification)
  transport.ssl.verification_mode       full | certificate | none
  transport.ssl.client_authentication   required | optional | none

The auth context is HMAC-SHA256 over (sender, action, user, roles) with a
shared cluster key (sourced from the keystore as `cluster.auth.key`, like
the reference sources TLS material from secure settings). The reference
derives trust purely from mTLS identity + its realm chain; the explicit
per-message MAC here additionally covers deployments that terminate TLS
at a sidecar.

`python -m elasticsearch_tpu.transport.tls certutil --out DIR` generates a
CA + node certificate the way `elasticsearch-certutil` does.
"""

from __future__ import annotations

import contextvars
import datetime
import hashlib
import hmac
import os
import ssl
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import SearchEngineError

VERIFICATION_MODES = ("full", "certificate", "none")


class TlsConfigError(SearchEngineError):
    pass


class TransportAuthError(SearchEngineError):
    """Envelope failed authentication: wrong/missing MAC or tampered ctx."""


class TlsConfig:
    """Builds the server/client SSLContext pair from PEM material."""

    def __init__(self, certificate: str, key: str,
                 certificate_authorities: Optional[str] = None,
                 verification_mode: str = "full",
                 client_authentication: str = "required",
                 key_password: Optional[str] = None):
        if verification_mode not in VERIFICATION_MODES:
            raise TlsConfigError(
                f"transport.ssl.verification_mode must be one of "
                f"{VERIFICATION_MODES}, got [{verification_mode}]")
        for label, path in (("certificate", certificate), ("key", key)):
            if not os.path.exists(path):
                raise TlsConfigError(f"transport.ssl.{label} not found: {path}")
        self.certificate = certificate
        self.key = key
        self.certificate_authorities = certificate_authorities
        self.verification_mode = verification_mode
        self.client_authentication = client_authentication
        self.key_password = key_password

    @staticmethod
    def from_settings(settings: Dict[str, Any],
                      prefix: str = "transport.ssl",
                      default_client_auth: str = "required",
                      ) -> Optional["TlsConfig"]:
        """Build from `<prefix>.*` settings; `http.ssl` mirrors
        xpack.security.http.ssl (client auth defaults to none there —
        browsers don't present certificates)."""
        enabled = str(settings.get(f"{prefix}.enabled", "false")).lower()
        if enabled not in ("true", "1", "yes"):
            return None
        cert = settings.get(f"{prefix}.certificate")
        key = settings.get(f"{prefix}.key")
        if not cert or not key:
            raise TlsConfigError(
                f"{prefix}.enabled requires {prefix}.certificate "
                f"and {prefix}.key")
        return TlsConfig(
            cert, key,
            certificate_authorities=settings.get(
                f"{prefix}.certificate_authorities"),
            verification_mode=str(settings.get(
                f"{prefix}.verification_mode", "full")),
            client_authentication=str(settings.get(
                f"{prefix}.client_authentication", default_client_auth)),
            key_password=settings.get(f"{prefix}.key_password"))

    def _load_identity(self, ctx: ssl.SSLContext) -> None:
        ctx.load_cert_chain(self.certificate, self.key,
                            password=self.key_password)
        if self.certificate_authorities:
            ctx.load_verify_locations(self.certificate_authorities)

    def server_context(self) -> ssl.SSLContext:
        # PEM material is immutable for the process lifetime: build each
        # context once instead of re-reading cert files per connection
        cached = getattr(self, "_server_ctx", None)
        if cached is not None:
            return cached
        self._server_ctx = self._build_server_context()
        return self._server_ctx

    def _build_server_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        self._load_identity(ctx)
        if self.client_authentication == "required":
            ctx.verify_mode = ssl.CERT_REQUIRED
        elif self.client_authentication == "optional":
            ctx.verify_mode = ssl.CERT_OPTIONAL
        else:
            ctx.verify_mode = ssl.CERT_NONE
        return ctx

    def client_context(self) -> ssl.SSLContext:
        cached = getattr(self, "_client_ctx", None)
        if cached is not None:
            return cached
        self._client_ctx = self._build_client_context()
        return self._client_ctx

    def _build_client_context(self) -> ssl.SSLContext:
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.minimum_version = ssl.TLSVersion.TLSv1_2
        self._load_identity(ctx)
        if self.verification_mode == "none":
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.verification_mode == "certificate":
            # trust chain verified, hostname not (the common mode for
            # inter-node certs without per-host SANs)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_REQUIRED
        else:
            ctx.check_hostname = True
            ctx.verify_mode = ssl.CERT_REQUIRED
        return ctx


# ---------------------------------------------------------------------------
# per-message authentication context
# ---------------------------------------------------------------------------

# the authenticated context of the RPC currently being handled on this task
# (ThreadContext analog: SecurityServerTransportInterceptor stashes the
# authentication in the thread context before the handler runs)
current_auth: contextvars.ContextVar[Optional[dict]] = \
    contextvars.ContextVar("transport_auth", default=None)


def _payload_digest(request: Any) -> str:
    """Canonical digest of the request payload via the wire serializer —
    the same deterministic encoding both ends already share."""
    from elasticsearch_tpu.common.serialization import StreamOutput
    out = StreamOutput(1)
    out.write_generic(request)
    return hashlib.sha256(out.bytes()).hexdigest()


def _mac(key: bytes, sender: str, action: str, user: str,
         roles: List[str], rid: int, payload_digest: str,
         ts_ms: int) -> str:
    msg = "\x00".join([sender, action, user, ",".join(sorted(roles)),
                       str(rid), payload_digest, str(ts_ms)])
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).hexdigest()


class TransportAuth:
    """Signs outgoing envelopes and validates inbound ones with the shared
    cluster key. The MAC binds (sender, action, request id, payload digest,
    user, roles): a captured envelope cannot be replayed onto a different
    action, request id, or body. The default outbound identity is the node's
    system context (`_system`, the reference's SystemUser for internal
    actions); REST-layer code can push the authenticated end-user instead."""

    # envelopes older than this are rejected even with a valid MAC; the
    # replay window below keeps every (sender, rid, mac) seen within it,
    # so a captured envelope cannot be re-executed in the TLS-at-sidecar
    # (auth-only) deployment
    MAX_SKEW_MS = 120_000

    def __init__(self, key: bytes, node_user: str = "_system",
                 node_roles: Optional[List[str]] = None):
        if not key:
            raise TlsConfigError("transport auth key must be non-empty")
        self.key = key
        self.node_user = node_user
        self.node_roles = list(node_roles or ["_internal"])
        self._seen: Dict[str, int] = {}  # mac -> ts_ms within the window
        self._seen_order: deque = deque()  # (ts_ms, mac) FIFO for pruning
        self._seen_lock = threading.Lock()

    def outbound_context(self, sender: str, action: str, rid: int = 0,
                         request: Any = None) -> dict:
        auth = current_auth.get()
        user = (auth or {}).get("user", self.node_user)
        roles = (auth or {}).get("roles", self.node_roles)
        ts_ms = int(time.time() * 1000)
        return {"user": user, "roles": list(roles), "ts": ts_ms,
                "mac": _mac(self.key, sender, action, user, list(roles),
                            rid, _payload_digest(request), ts_ms)}

    def validate(self, sender: str, action: str, ctx: Any, rid: int = 0,
                 request: Any = None) -> dict:
        if not isinstance(ctx, dict):
            raise TransportAuthError(
                f"[{action}] from [{sender}] carried no authentication "
                f"context")
        user = str(ctx.get("user", ""))
        roles = [str(r) for r in ctx.get("roles", [])]
        ts_ms = int(ctx.get("ts", 0))
        expected = _mac(self.key, sender, action, user, roles, rid,
                        _payload_digest(request), ts_ms)
        if not hmac.compare_digest(expected, str(ctx.get("mac", ""))):
            raise TransportAuthError(
                f"[{action}] from [{sender}] failed authentication")
        now_ms = int(time.time() * 1000)
        if abs(now_ms - ts_ms) > self.MAX_SKEW_MS:
            raise TransportAuthError(
                f"[{action}] from [{sender}] rejected: stale envelope "
                f"(ts skew {abs(now_ms - ts_ms)}ms)")
        with self._seen_lock:
            if expected in self._seen:
                raise TransportAuthError(
                    f"[{action}] from [{sender}] rejected: replayed "
                    f"envelope")
            self._seen[expected] = ts_ms
            self._seen_order.append((ts_ms, expected))
            # amortized O(1): only expired entries pop off the front
            cutoff = now_ms - self.MAX_SKEW_MS
            while self._seen_order and self._seen_order[0][0] < cutoff:
                _, old_mac = self._seen_order.popleft()
                self._seen.pop(old_mac, None)
        return {"user": user, "roles": roles}


# ---------------------------------------------------------------------------
# certutil
# ---------------------------------------------------------------------------

def generate_ca(out_dir: str, name: str = "tpu-search-ca",
                days: int = 3650) -> Dict[str, str]:
    """Self-signed CA (elasticsearch-certutil ca analog)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(subject).issuer_name(subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(key, hashes.SHA256()))
    os.makedirs(out_dir, exist_ok=True)
    ca_cert = os.path.join(out_dir, "ca.crt")
    ca_key = os.path.join(out_dir, "ca.key")
    with open(ca_cert, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(ca_key, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    os.chmod(ca_key, 0o600)
    return {"cert": ca_cert, "key": ca_key}


def generate_node_cert(out_dir: str, ca_cert_path: str, ca_key_path: str,
                       name: str = "node",
                       hosts: Optional[List[str]] = None,
                       days: int = 1095) -> Dict[str, str]:
    """CA-signed node certificate with IP/DNS SANs
    (elasticsearch-certutil cert analog)."""
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    with open(ca_cert_path, "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    with open(ca_key_path, "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), password=None)

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    sans: List[x509.GeneralName] = []
    for h in (hosts or ["127.0.0.1", "localhost"]):
        try:
            sans.append(x509.IPAddress(ipaddress.ip_address(h)))
        except ValueError:
            sans.append(x509.DNSName(h))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name(
                [x509.NameAttribute(NameOID.COMMON_NAME, name)]))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=days))
            .add_extension(x509.SubjectAlternativeName(sans), critical=False)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .sign(ca_key, hashes.SHA256()))
    os.makedirs(out_dir, exist_ok=True)
    cert_path = os.path.join(out_dir, f"{name}.crt")
    key_path = os.path.join(out_dir, f"{name}.key")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    os.chmod(key_path, 0o600)
    return {"cert": cert_path, "key": key_path}


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(prog="certutil")
    parser.add_argument("command", choices=["certutil"])
    parser.add_argument("--out", required=True)
    parser.add_argument("--name", default="node")
    parser.add_argument("--hosts", default="127.0.0.1,localhost")
    args = parser.parse_args(argv)
    ca = generate_ca(args.out)
    node = generate_node_cert(args.out, ca["cert"], ca["key"],
                              name=args.name,
                              hosts=args.hosts.split(","))
    print(f"wrote {ca['cert']}, {ca['key']}, {node['cert']}, {node['key']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
