"""Real TCP transport: asyncio sockets speaking the framed binary protocol.

The production counterpart of `testing.deterministic.DisruptableTransport`,
exposing the exact same `register`/`send` surface so `ClusterNode` and
`Coordinator` run unchanged over real sockets. Redesign of the reference's
transport stack (SURVEY.md §2.2):

- `TransportService` façade — handler registry, request/response
  correlation, timeouts, local direct dispatch when the target is this node
  (reference `TransportService.java:119-121`).
- `TcpTransport` — connection lifecycle, server bind, version handshake on
  connect (reference `TcpTransport.java:796`), inbound dispatch.
- Connection profile — per-purpose channels (recovery / bulk / state / reg,
  reference `ConnectionProfile.java`) so a long recovery file copy cannot
  head-of-line-block cluster-state publications.
- Keep-alive pings (reference `TransportKeepAlive.java`).

Design departure: the reference multiplexes blocking Java threads over
Netty; here each node is a single-threaded asyncio actor — all handler
callbacks run on the owning event loop, which is the same no-shared-memory
discipline the deterministic simulator enforces, so code validated under
simulation runs identically in production.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.transport.wire import (
    STATUS_ERROR, STATUS_HANDSHAKE, STATUS_REQUEST, WIRE_VERSION,
    decode_frames, encode_frame, encode_ping,
)

HANDSHAKE_ACTION = "internal:tcp/handshake"
PING_ACTION = "internal:tcp/ping"

# channel classes by action prefix (reference: ConnectionProfile channel
# types — recovery, bulk, reg, state, ping)
_CHANNEL_RULES = (
    ("internal:index/shard/recovery", "recovery"),
    ("indices:data/write", "bulk"),
    ("internal:cluster", "state"),
    ("cluster:", "state"),
)


def channel_type_for(action: str) -> str:
    for prefix, channel in _CHANNEL_RULES:
        if action.startswith(prefix):
            return channel
    return "reg"


class ConnectionProfile:
    """Connections per channel type (reference `ConnectionProfile.java`).

    A recovery file copy saturating its socket must not head-of-line-
    block a query fan-out: each channel type gets its OWN pool of TCP
    connections, and senders round-robin within a type so concurrent
    query legs spread across `reg` sockets instead of serializing behind
    one kernel send buffer."""

    DEFAULT_CONNECTIONS = {"reg": 2, "bulk": 1, "state": 1, "recovery": 1}

    def __init__(self, connections_per_type: Optional[Dict[str, int]] = None):
        self.connections_per_type = dict(self.DEFAULT_CONNECTIONS)
        for ctype, n in (connections_per_type or {}).items():
            self.connections_per_type[ctype] = max(1, int(n))

    def num_connections(self, channel_type: str) -> int:
        return self.connections_per_type.get(channel_type, 1)


class RemoteTransportError(SearchEngineError):
    """An exception raised on the remote node, rethrown locally."""


class ConnectTransportError(SearchEngineError):
    """Could not establish/keep a connection to the target node."""


class AsyncioScheduler:
    """Adapter giving asyncio the deterministic-queue scheduling surface
    (`schedule` / `schedule_in` / `now_ms` / `rng`) that Coordinator and
    ClusterNode are written against."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None,
                 seed: Optional[int] = None):
        self.loop = loop or asyncio.get_event_loop()
        self.rng = random.Random(seed)

    @property
    def now_ms(self) -> int:
        return int(self.loop.time() * 1000)

    def schedule(self, fn: Callable[[], None], label: str = "") -> None:
        self.loop.call_soon(fn)

    def schedule_in(self, delay_ms: int, fn: Callable[[], None],
                    label: str = "") -> None:
        self.loop.call_later(delay_ms / 1000.0, fn)

    def schedule_at(self, time_ms: int, fn: Callable[[], None],
                    label: str = "") -> None:
        self.schedule_in(max(0, time_ms - self.now_ms), fn, label)


class _Channel:
    """One TCP connection to a peer, with its read pump and write half."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.buf = bytearray()
        self.closed = False
        self.pending_rids: set = set()  # requests in flight on this channel

    def write_frame(self, frame: bytes) -> None:
        if not self.closed:
            self.writer.write(frame)

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.writer.close()
            except Exception:
                pass


class TcpTransportService:
    """Bound TCP endpoint + RPC façade for one node.

    API-compatible with DisruptableTransport: `register(node_id, action,
    handler)` (node_id must be this node's), and `send(sender, target,
    action, request, on_response, on_failure)`.
    """

    def __init__(self, node_id: str, host: str = "127.0.0.1", port: int = 0,
                 *, loop: Optional[asyncio.AbstractEventLoop] = None,
                 keepalive_interval_ms: int = 15_000,
                 default_timeout_ms: Optional[int] = 30_000,
                 tls=None, auth=None,
                 connection_profile: Optional[ConnectionProfile] = None):
        self.node_id = node_id
        self.host = host
        self.port = port  # 0 = ephemeral; real port known after bind()
        # TLS on the inter-node socket + per-envelope signed authn context
        # (transport/tls.py; SecurityServerTransportInterceptor.java:50)
        self.tls = tls
        self.auth = auth
        self.loop = loop or asyncio.get_event_loop()
        self.keepalive_interval_ms = keepalive_interval_ms
        self.default_timeout_ms = default_timeout_ms
        self._server: Optional[asyncio.AbstractServer] = None
        self._handlers: Dict[str, Callable] = {}
        self.connection_profile = connection_profile or ConnectionProfile()
        self._request_id = 0
        # request_id -> (on_response, on_failure, timeout_handle, action,
        #                target, sent_monotonic)
        self._pending: Dict[int, Tuple] = {}
        # peer node_id -> {channel slot ("reg#0", "recovery#0"): _Channel}
        self._channels: Dict[str, Dict[str, _Channel]] = {}
        # per-(peer, channel_type) round-robin cursor over profile slots
        self._channel_rr: Dict[Tuple[str, str], int] = {}
        self._addresses: Dict[str, Tuple[str, int]] = {}
        # per-peer request->response round-trip EWMA (ms): the transport
        # leg of the unified dispatch cost router (serving/router.py)
        self._rtt_ewma: Dict[str, float] = {}
        self._connecting: Dict[Tuple[str, str], asyncio.Future] = {}
        self._keepalive_task: Optional[asyncio.Task] = None
        self._pumps: List[asyncio.Task] = []
        self._inbound: List[_Channel] = []
        self.stats = {"tx_count": 0, "rx_count": 0, "tx_bytes": 0,
                      "rx_bytes": 0, "connections_opened": 0}
        self.closed = False

    # ------------------------------------------------------------- lifecycle
    async def bind(self) -> Tuple[str, int]:
        """Bind the server socket (reference `TcpTransport.java:376,648`)."""
        self._server = await asyncio.start_server(
            self._accept, self.host, self.port,
            ssl=self.tls.server_context() if self.tls else None)
        self.port = self._server.sockets[0].getsockname()[1]
        self._keepalive_task = self.loop.create_task(self._keepalive_pump())
        return self.host, self.port

    async def close(self) -> None:
        self.closed = True
        if self._keepalive_task:
            self._keepalive_task.cancel()
        for pump in self._pumps:
            pump.cancel()
        self._pumps.clear()
        for chans in list(self._channels.values()):
            for ch in list(chans.values()):
                ch.close()
        self._channels.clear()
        for ch in self._inbound:
            ch.close()
        self._inbound.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for rid in list(self._pending):
            self._fail_pending(rid, ConnectTransportError("transport closed"))

    def _client_connect(self, host: str, port: int):
        if self.tls is None:
            return asyncio.open_connection(host, port)
        return asyncio.open_connection(
            host, port, ssl=self.tls.client_context(),
            server_hostname=host if self.tls.verification_mode == "full"
            else None)

    async def probe_address(self, host: str, port: int) -> str:
        """Seed-host discovery (PeerFinder/SeedHostsResolver analog): dial a
        bare host:port, handshake to learn the peer's node id, record the
        address mapping, close the probe channel. Returns the node id."""
        reader, writer = await self._client_connect(host, port)
        channel = _Channel(reader, writer)
        pump = self.loop.create_task(self._read_pump(channel))
        self._pumps.append(pump)
        # probes are short-lived and periodic: drop the finished pump task
        # or the forever-running discovery loop grows _pumps without bound
        pump.add_done_callback(
            lambda t: self._pumps.remove(t) if t in self._pumps else None)
        ok = self.loop.create_future()
        self._request_id += 1
        rid = self._request_id
        self._pending[rid] = (
            lambda resp: ok.set_result(resp) if not ok.done() else None,
            lambda err: ok.set_exception(err) if not ok.done() else None,
            self.loop.call_later(10.0, self._on_request_timeout, rid,
                                 f"{host}:{port}"),
            HANDSHAKE_ACTION, None, time.monotonic())
        channel.pending_rids.add(rid)
        channel.write_frame(encode_frame(
            rid, STATUS_REQUEST | STATUS_HANDSHAKE, WIRE_VERSION,
            HANDSHAKE_ACTION,
            {"sender": self.node_id, "request": {
                "node_id": self.node_id, "version": WIRE_VERSION}}))
        try:
            resp = await ok
        finally:
            channel.close()
        node_id = resp.get("node_id")
        if not node_id:
            raise ConnectTransportError(f"no node id from {host}:{port}")
        if node_id != self.node_id:
            self.add_peer_address(node_id, host, port)
        return node_id

    def add_peer_address(self, node_id: str, host: str, port: int) -> None:
        self._addresses[node_id] = (host, port)

    @property
    def bound_address(self) -> Tuple[str, int]:
        return self.host, self.port

    # ------------------------------------------------------------ telemetry
    def rtt_ms(self, node_id: str) -> Optional[float]:
        """Request->response round-trip EWMA to `node_id` in ms, or None
        when unmeasured — the transport-leg term of the unified dispatch
        cost router."""
        return self._rtt_ewma.get(node_id)

    def _observe_rtt(self, node_id: Optional[str], sent_monotonic) -> None:
        if not node_id or sent_monotonic is None:
            return
        rtt = max((time.monotonic() - sent_monotonic) * 1000.0, 0.0)
        prev = self._rtt_ewma.get(node_id)
        self._rtt_ewma[node_id] = (rtt if prev is None
                                   else 0.7 * prev + 0.3 * rtt)

    # ------------------------------------------------------------- handlers
    def register(self, node_id: str, action: str, handler: Callable) -> None:
        """handler(sender, request, respond) — same shape as the simulator's."""
        if node_id != self.node_id:
            raise SearchEngineError(
                f"cannot register handler for foreign node [{node_id}] "
                f"on transport of [{self.node_id}]")
        self._handlers[action] = handler

    # ---------------------------------------------------------------- send
    def send(self, sender: str, target: str, action: str, request: Any,
             on_response: Optional[Callable[[Any], None]] = None,
             on_failure: Optional[Callable[[Exception], None]] = None,
             timeout_ms: Optional[int] = None) -> None:
        if target == self.node_id:
            # local optimization: direct dispatch, no serialization
            # (reference TransportService.java:119-121)
            self._dispatch_local(sender, action, request, on_response,
                                 on_failure)
            return
        self.loop.create_task(self._send_remote(
            target, action, request, on_response, on_failure,
            self.default_timeout_ms if timeout_ms is None else timeout_ms))

    def _dispatch_local(self, sender, action, request, on_response,
                        on_failure) -> None:
        handler = self._handlers.get(action)
        if handler is None:
            if on_failure:
                self.loop.call_soon(on_failure, SearchEngineError(
                    f"no handler for [{action}] on [{self.node_id}]"))
            return

        def respond(response: Any) -> None:
            if on_response is not None:
                self.loop.call_soon(on_response, response)

        def run():
            try:
                handler(sender, request, respond)
            except Exception as e:
                if on_failure:
                    on_failure(e)

        self.loop.call_soon(run)

    async def _send_remote(self, target, action, request, on_response,
                           on_failure, timeout_ms) -> None:
        try:
            channel = await self._get_channel(target, channel_type_for(action))
        except Exception as e:
            if on_failure:
                on_failure(ConnectTransportError(
                    f"[{target}][{action}] connect failed: {e}"))
            return
        self._request_id += 1
        rid = self._request_id
        timeout_handle = None
        if timeout_ms is not None:
            timeout_handle = self.loop.call_later(
                timeout_ms / 1000.0, self._on_request_timeout, rid, target)
        self._pending[rid] = (on_response, on_failure, timeout_handle,
                              action, target, time.monotonic())
        channel.pending_rids.add(rid)
        envelope = {"sender": self.node_id, "request": request}
        if self.auth is not None:
            # authn context propagates with the RPC and is validated before
            # dispatch on the receiver (SecurityServerTransportInterceptor);
            # the MAC binds rid + payload so a captured envelope cannot be
            # replayed onto a different request
            envelope["auth"] = self.auth.outbound_context(
                self.node_id, action, rid=rid, request=request)
        frame = encode_frame(rid, STATUS_REQUEST, WIRE_VERSION, action,
                             envelope)
        self.stats["tx_count"] += 1
        self.stats["tx_bytes"] += len(frame)
        channel.write_frame(frame)

    def _on_request_timeout(self, rid: int, target: str) -> None:
        self._fail_pending(rid, ConnectTransportError(
            f"request [{rid}] to [{target}] timed out"))

    def _fail_pending(self, rid: int, error: Exception) -> None:
        entry = self._pending.pop(rid, None)
        if entry is None:
            return
        _, on_failure, timeout_handle, _, _, _ = entry
        if timeout_handle:
            timeout_handle.cancel()
        if on_failure:
            on_failure(error)

    # --------------------------------------------------------- connections
    async def _get_channel(self, target: str, channel_type: str) -> _Channel:
        """One of the profile's sockets for (target, channel_type).

        Slots are independent TCP connections, so a saturated recovery
        stream and a query fan-out never share a kernel send buffer.
        Reuse policy: an IDLE open channel is always reused (a serial
        request stream stays on one socket); when every open channel of
        the type has requests in flight, the profile widens to its next
        unopened slot, and once the profile is exhausted concurrent
        requests round-robin across the open slots."""
        slots = self.connection_profile.num_connections(channel_type)
        chans = self._channels.get(target, {})
        busy = []
        connecting = []
        slot = None
        for i in range(slots):
            name = f"{channel_type}#{i}"
            ch = chans.get(name)
            if ch is not None and not ch.closed:
                if not ch.pending_rids:
                    return ch          # idle open channel: reuse
                busy.append(ch)
                continue
            fut = self._connecting.get((target, name))
            if fut is not None:
                # a slot mid-connect counts as busy: a concurrent request
                # widens to the NEXT slot instead of piling onto it
                connecting.append(fut)
            elif slot is None:
                slot = name            # first unopened/closed slot
        if slot is None:
            if connecting:
                # profile exhausted but sockets still opening: join the
                # soonest-to-open one rather than queueing behind an
                # already-busy channel
                return await asyncio.shield(connecting[0])
            # profile exhausted, all channels busy: round-robin
            rr_key = (target, channel_type)
            cursor = self._channel_rr.get(rr_key, 0)
            self._channel_rr[rr_key] = (cursor + 1) % len(busy)
            return busy[cursor % len(busy)]
        key = (target, slot)
        fut = self._connecting.get(key)
        if fut is None:
            fut = self.loop.create_future()
            self._connecting[key] = fut
            try:
                channel = await self._open_channel(target)
                self._channels.setdefault(target, {})[slot] = channel
                fut.set_result(channel)
            except Exception as e:
                fut.set_exception(e)
                # mark retrieved: with no concurrent waiter the future would
                # log "exception was never retrieved" at GC
                fut.exception()
                raise
            finally:
                del self._connecting[key]
            return channel
        return await asyncio.shield(fut)

    async def _open_channel(self, target: str) -> _Channel:
        addr = self._addresses.get(target)
        if addr is None:
            raise ConnectTransportError(f"no known address for [{target}]")
        reader, writer = await self._client_connect(*addr)
        channel = _Channel(reader, writer)
        self.stats["connections_opened"] += 1
        self._pumps.append(
            self.loop.create_task(self._read_pump(channel, outbound_to=target)))
        # version + identity handshake before any traffic
        # (reference TcpTransport.java:796 executeHandshake)
        try:
            ok = self.loop.create_future()
            self._request_id += 1
            rid = self._request_id
            self._pending[rid] = (
                lambda resp: ok.set_result(resp) if not ok.done() else None,
                lambda err: ok.set_exception(err) if not ok.done() else None,
                self.loop.call_later(10.0, self._on_request_timeout, rid, target),
                HANDSHAKE_ACTION, target, time.monotonic())
            channel.pending_rids.add(rid)
            channel.write_frame(encode_frame(
                rid, STATUS_REQUEST | STATUS_HANDSHAKE, WIRE_VERSION,
                HANDSHAKE_ACTION,
                {"sender": self.node_id, "request": {
                    "node_id": self.node_id, "version": WIRE_VERSION}}))
            resp = await ok
            remote_id = resp.get("node_id")
            if remote_id != target:
                raise ConnectTransportError(
                    f"handshake with {addr} expected node [{target}] "
                    f"but found [{remote_id}]")
            return channel
        except BaseException:
            # don't leak the socket/read pump on handshake timeout or error
            channel.close()
            raise

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        channel = _Channel(reader, writer)
        self._inbound.append(channel)
        try:
            await self._read_pump(channel)
        finally:
            if channel in self._inbound:
                self._inbound.remove(channel)

    async def _read_pump(self, channel: _Channel,
                         outbound_to: Optional[str] = None) -> None:
        try:
            while not channel.closed:
                data = await channel.reader.read(64 * 1024)
                if not data:
                    break
                self.stats["rx_bytes"] += len(data)
                channel.buf.extend(data)
                for (rid, status, version, action,
                     payload) in decode_frames(channel.buf):
                    self._on_frame(channel, rid, status, action, payload)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            channel.close()
            if outbound_to is not None:
                chans = self._channels.get(outbound_to, {})
                for ctype, ch in list(chans.items()):
                    if ch is channel:
                        del chans[ctype]
            # fail every request still in flight on this channel
            # (reference: TcpTransport notifies pending handlers on close)
            for rid in list(channel.pending_rids):
                self._fail_pending(rid, ConnectTransportError(
                    f"channel to [{outbound_to or 'peer'}] closed with "
                    f"request [{rid}] in flight"))
            channel.pending_rids.clear()

    # ------------------------------------------------------------- inbound
    def _on_frame(self, channel: _Channel, rid: int, status: int,
                  action: Optional[str], payload: Any) -> None:
        from elasticsearch_tpu.transport.wire import STATUS_PING
        if status & STATUS_PING:
            return
        self.stats["rx_count"] += 1
        if status & STATUS_REQUEST:
            self._handle_request(channel, rid, action, payload)
        else:
            # a response is only valid on the channel that carried the
            # request: without this, any connected peer could forge
            # responses to other channels' in-flight rids
            if rid not in channel.pending_rids:
                return
            entry = self._pending.pop(rid, None)
            channel.pending_rids.discard(rid)
            if entry is None:
                return  # late response after timeout
            (on_response, on_failure, timeout_handle, req_action,
             target, sent_at) = entry
            if timeout_handle:
                timeout_handle.cancel()
            # RTT samples come only from control exchanges whose remote
            # handler is O(1) — a data response would fold the remote's
            # service time into the wire estimate and double-count it
            # against the cost router's device-leg term
            if req_action in (HANDSHAKE_ACTION, PING_ACTION):
                self._observe_rtt(target, sent_at)
            if status & STATUS_ERROR:
                if on_failure:
                    err = RemoteTransportError(
                        f"[{req_action}] {payload.get('type', 'error')}: "
                        f"{payload.get('message', '')}")
                    # carry the remote exception's HTTP status so a 404/409
                    # raised on the primary's node does not degrade to a 500
                    # at the coordinating node (reference: the wire format
                    # serializes the full exception)
                    err.status = int(payload.get("status", 500))
                    err.remote_type = payload.get("type")
                    on_failure(err)
            elif on_response:
                on_response(payload)

    def _handle_request(self, channel: _Channel, rid: int, action: str,
                        envelope: Any) -> None:
        sender = envelope.get("sender", "?")
        request = envelope.get("request")
        if action == HANDSHAKE_ACTION:
            channel.write_frame(encode_frame(
                rid, STATUS_HANDSHAKE, WIRE_VERSION, None,
                {"node_id": self.node_id, "version": WIRE_VERSION}))
            return
        if action == PING_ACTION:
            # O(1) echo for the keepalive RTT probe: carries no state, so
            # (like the handshake) it answers before authn
            channel.write_frame(encode_frame(
                rid, 0, WIRE_VERSION, None, {"node_id": self.node_id}))
            return
        # authenticate BEFORE even the handler lookup: a peer that completed
        # the socket handshake may not invoke actions — nor enumerate which
        # exist — without a valid cluster-key MAC binding (sender, action,
        # rid, payload, identity)
        auth_ctx = None
        if self.auth is not None:
            try:
                auth_ctx = self.auth.validate(sender, action,
                                              envelope.get("auth"),
                                              rid=rid, request=request)
            except Exception as e:
                channel.write_frame(encode_frame(
                    rid, STATUS_ERROR, WIRE_VERSION, None,
                    {"type": "security_exception", "message": str(e)}))
                return
        handler = self._handlers.get(action)
        if handler is None:
            channel.write_frame(encode_frame(
                rid, STATUS_ERROR, WIRE_VERSION, None,
                {"type": "action_not_found",
                 "message": f"no handler for [{action}]"}))
            return

        def respond(response: Any) -> None:
            frame = encode_frame(rid, 0, WIRE_VERSION, None, response)
            self.stats["tx_count"] += 1
            self.stats["tx_bytes"] += len(frame)
            channel.write_frame(frame)

        from elasticsearch_tpu.transport.tls import current_auth
        token = current_auth.set(auth_ctx) if auth_ctx is not None else None
        try:
            handler(sender, request, respond)
        except Exception as e:
            channel.write_frame(encode_frame(
                rid, STATUS_ERROR, WIRE_VERSION, None,
                {"type": type(e).__name__, "message": str(e),
                 "status": int(getattr(e, "status", 500))}))
        finally:
            if token is not None:
                current_auth.reset(token)

    # ----------------------------------------------------------- keepalive
    async def _keepalive_pump(self) -> None:
        try:
            while not self.closed:
                await asyncio.sleep(self.keepalive_interval_ms / 1000.0)
                ping = encode_ping()
                for target, chans in list(self._channels.items()):
                    for ch in chans.values():
                        ch.write_frame(ping)
                    # request/response ping refreshes the per-peer RTT
                    # EWMA the dispatch cost router consumes; the raw
                    # wire ping above only defeats idle-connection reaping
                    self.send(self.node_id, target, PING_ACTION, {},
                              timeout_ms=min(self.keepalive_interval_ms,
                                             10_000))
        except asyncio.CancelledError:
            pass
