"""Scripting subsystem: ScriptService, stored scripts, mustache templates.

Reference layers: `server/.../script/` (ScriptService, Script, contexts),
`modules/lang-painless` (expression engine — here `search/script_score.py`),
`modules/lang-mustache` (search templates — here `script/mustache.py`).
"""

from elasticsearch_tpu.script.service import ScriptService, StoredScript

__all__ = ["ScriptService", "StoredScript"]
