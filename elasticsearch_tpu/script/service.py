"""ScriptService: stored scripts + language dispatch.

Reference: `server/src/main/java/org/elasticsearch/script/ScriptService.java:62`
— stored scripts live in cluster state (`StoredScriptSource`), are addressed
by id from any `"script": {"id": ...}` spec, and compile through per-language
engines (painless, mustache, expression). Here the two engines are the
painless-lite expression evaluator (`search/script_score.py`) and the
mustache renderer (`script/mustache.py`); `resolve()` is the single entry
that turns any script spec (inline `source` / stored `id`) into a concrete
source + params, which every call-site (script_score, script fields, ingest
script processor, update-by-script, search templates) funnels through.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError,
    ParsingError,
    ResourceNotFoundError,
)

#: languages the service accepts; "painless" is the default like the
#: reference's Script.DEFAULT_SCRIPT_LANG.
SUPPORTED_LANGS = ("painless", "mustache", "expression")


class StoredScript:
    def __init__(self, lang: str, source: str, options: Optional[dict] = None):
        if lang not in SUPPORTED_LANGS:
            raise IllegalArgumentError(f"unable to parse unsupported lang [{lang}]")
        self.lang = lang
        self.source = source
        self.options = options or {}

    def to_dict(self) -> dict:
        return {"lang": self.lang, "source": self.source}


class ScriptService:
    """Stored-script registry + spec resolution.

    The reference persists stored scripts in cluster-state metadata
    (`ScriptMetaData`), replicated to every node and written to the gateway
    state store. Here the registry is process-wide (the single-process analog
    of replicated cluster state) and persists to a JSON file under the data
    path of the most recently constructed node (every node attaches, so in a
    multi-node process each write lands in the latest node's state dir).
    """

    def __init__(self):
        self._stored: Dict[str, StoredScript] = {}
        self.compilations = 0
        self._path: Optional[str] = None

    def attach_storage(self, path: str) -> None:
        """Load persisted scripts and persist future changes to `path`.
        Mirrors GatewayMetaState recovering ScriptMetaData on node boot."""
        import json
        import os
        self._path = path
        if os.path.exists(path):
            with open(path) as f:
                for sid, spec in json.load(f).items():
                    self._stored.setdefault(
                        sid, StoredScript(spec["lang"], spec["source"]))

    def _persist(self) -> None:
        if self._path is None:
            return
        import json
        import os
        os.makedirs(os.path.dirname(self._path), exist_ok=True)
        with open(self._path, "w") as f:
            json.dump(self.list_stored(), f)

    def clear(self) -> None:
        """Drop all stored scripts (test isolation helper)."""
        self._stored.clear()

    # -- stored scripts API (`_scripts/{id}`) --------------------------------
    def put_stored(self, script_id: str, body: dict) -> None:
        spec = body.get("script")
        if not isinstance(spec, dict) or "source" not in spec:
            raise ParsingError("stored script must define [script.source]")
        lang = spec.get("lang", "painless")
        source = spec["source"]
        if not isinstance(source, str):
            import json
            source = json.dumps(source)
        script = StoredScript(lang, source)
        self._compile_check(script)
        self._stored[script_id] = script
        self._persist()

    def get_stored(self, script_id: str) -> StoredScript:
        if script_id not in self._stored:
            raise ResourceNotFoundError(f"stored script [{script_id}] not found")
        return self._stored[script_id]

    def delete_stored(self, script_id: str) -> None:
        if script_id not in self._stored:
            raise ResourceNotFoundError(f"stored script [{script_id}] not found")
        del self._stored[script_id]
        self._persist()

    def list_stored(self) -> Dict[str, dict]:
        return {k: v.to_dict() for k, v in self._stored.items()}

    def _compile_check(self, script: StoredScript) -> None:
        """Compile at store time, like the reference (`putStoredScript`
        compiles against every context to surface errors early)."""
        self.compilations += 1
        if script.lang == "mustache":
            from elasticsearch_tpu.script import mustache
            mustache._Parser(script.source).parse()
        else:
            import ast
            try:
                ast.parse(script.source, mode="eval")
            except SyntaxError:
                # multi-statement update/ingest scripts are exec-mode
                try:
                    ast.parse(_strip_semicolons(script.source), mode="exec")
                except SyntaxError as e:
                    raise ParsingError(f"compile error: {e}")

    # -- spec resolution ------------------------------------------------------
    def resolve(self, spec: Any) -> dict:
        """Turn any `"script"` value (str | {source}|{id}) into
        {"lang", "source", "params"}."""
        if isinstance(spec, str):
            return {"lang": "painless", "source": spec, "params": {}}
        if not isinstance(spec, dict):
            raise ParsingError("script must be a string or object")
        params = spec.get("params", {})
        if "id" in spec:
            stored = self.get_stored(spec["id"])
            return {"lang": stored.lang, "source": stored.source, "params": params}
        if "source" not in spec:
            raise ParsingError("script must define [source] or [id]")
        return {"lang": spec.get("lang", "painless"),
                "source": spec["source"], "params": params}

    # -- search templates -----------------------------------------------------
    def render_template(self, body: dict) -> dict:
        """`_render/template` / `_search/template`: resolve source (inline or
        stored id) and mustache-render with params into a search body."""
        from elasticsearch_tpu.script import mustache
        params = body.get("params", {})
        if "id" in body:
            stored = self.get_stored(body["id"])
            source = stored.source
        else:
            source = body.get("source")
            if source is None:
                raise ParsingError("search template must define [source] or [id]")
        return mustache.render_search_template(source, params)


def _strip_semicolons(source: str) -> str:
    """Painless statements end with `;` — normalize to Python exec form."""
    return "\n".join(s.strip() for s in source.split(";") if s.strip())


#: Cluster-wide stored-script registry. The reference keeps stored scripts in
#: cluster-state metadata replicated to every node; a process-global registry
#: is the single-process analog, shared by all in-process nodes of a cluster.
GLOBAL_SCRIPTS = ScriptService()
