"""A sandboxed Painless interpreter: lexer → recursive-descent parser →
tree-walking evaluator.

The reference compiles Painless (Java-like syntax) through an ANTLR grammar
to JVM bytecode with an allowlisted class/method surface
(`modules/lang-painless`, 34.8k LoC: `Compiler.java`, `ir/`, `api/`
whitelists). This re-design keeps the language surface and the sandbox
discipline but interprets the AST directly — scripts here steer control
flow around the engine, they are never the hot loop (vector scoring runs
batched on the accelerator; `search/script_score.py` keeps a vectorized
fast path for pure expressions).

Supported: statements (decl/assign with compound ops, if/else, for,
for-each, while, do-while, return, break, continue), user-defined
functions, ternary and elvis operators, list/map literals, `new ArrayList/
HashMap`, method calls from a fixed allowlist over str/list/map values,
`Math.*`/`Integer.parseInt`-style statics, and the script contexts' bound
variables (`params`, `doc`, `_score`, `ctx`).

Sandbox: unknown names/methods/constructors raise; loops carry an
iteration budget and calls a depth budget (the reference's loop counter
and stack guards, `LoopNode`/`FunctionNode` limits).
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError

MAX_LOOP_ITERATIONS = 1_000_000
MAX_CALL_DEPTH = 64


class PainlessError(ParsingError):
    pass


# ---------------------------------------------------------------------------
# lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|//[^\n]*|/\*.*?\*/)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?[fFdD]?|\.\d+(?:[eE][+-]?\d+)?[fFdD]?|\d+[lLfFdD]?)
  | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<id>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>\?\.|\?:|==|!=|<=|>=|&&|\|\||\+\+|--|\+=|-=|\*=|/=|%=|=|[-+*/%<>!?:;,.(){}\[\]])
""", re.VERBOSE | re.DOTALL)

_KEYWORDS = {"if", "else", "for", "while", "do", "return", "break",
             "continue", "def", "in", "new", "null", "true", "false",
             "instanceof", "void", "try", "catch", "throw"}

_TYPE_WORDS = {"def", "int", "long", "float", "double", "boolean", "byte",
               "short", "char", "String", "Map", "HashMap", "List",
               "ArrayList", "Object", "void"}


def tokenize(src: str) -> List[Tuple[str, str]]:
    out: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise PainlessError(f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        out.append((m.lastgroup, m.group()))
    out.append(("eof", ""))
    return out


# ---------------------------------------------------------------------------
# AST: tuples ("kind", ...)
# ---------------------------------------------------------------------------

class Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, k: int = 0) -> Tuple[str, str]:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Tuple[str, str]:
        if self.i >= len(self.toks) - 1:
            raise PainlessError("unexpected end of script")
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, value: str) -> bool:
        if self.peek()[1] == value and self.peek()[0] != "str":
            self.i += 1
            return True
        return False

    def expect(self, value: str) -> None:
        if not self.accept(value):
            raise PainlessError(
                f"expected {value!r} but found {self.peek()[1]!r}")

    # ------------------------------------------------------------- program
    def parse_program(self):
        functions = {}
        stmts = []
        while self.peek()[0] != "eof":
            fn = self._try_function()
            if fn is not None:
                functions[fn[0]] = fn
            else:
                stmts.append(self.statement())
        return ("program", functions, stmts)

    def _try_function(self):
        """`type name(type a, type b) { ... }` at top level."""
        save = self.i
        kind, val = self.peek()
        if kind == "id" and (val in _TYPE_WORDS) and self.peek(1)[0] == "id" \
                and self.peek(2)[1] == "(":
            self.next()
            name = self.next()[1]
            self.expect("(")
            params = []
            while not self.accept(")"):
                ptype = self.next()  # type word
                if self.peek()[0] == "id":
                    params.append(self.next()[1])
                else:  # untyped param: the "type" was the name
                    params.append(ptype[1])
                self.accept(",")
            if self.peek()[1] != "{":
                self.i = save
                return None
            body = self.block()
            return (name, params, body)
        return None

    # ----------------------------------------------------------- statements
    def block(self):
        self.expect("{")
        stmts = []
        while not self.accept("}"):
            stmts.append(self.statement())
        return ("block", stmts)

    def statement(self):
        kind, val = self.peek()
        if val == "{":
            return self.block()
        if val == ";":
            self.next()
            return ("block", [])
        if val == "if":
            self.next()
            self.expect("(")
            cond = self.expression()
            self.expect(")")
            then = self.statement()
            otherwise = None
            if self.accept("else"):
                otherwise = self.statement()
            return ("if", cond, then, otherwise)
        if val == "while":
            self.next()
            self.expect("(")
            cond = self.expression()
            self.expect(")")
            return ("while", cond, self.statement())
        if val == "do":
            self.next()
            body = self.statement()
            self.expect("while")
            self.expect("(")
            cond = self.expression()
            self.expect(")")
            self.accept(";")
            return ("dowhile", cond, body)
        if val == "for":
            return self._for()
        if val == "return":
            self.next()
            if self.peek()[1] == ";":
                self.next()
                return ("return", None)
            e = self.expression()
            self.accept(";")
            return ("return", e)
        if val == "break":
            self.next()
            self.accept(";")
            return ("break",)
        if val == "continue":
            self.next()
            self.accept(";")
            return ("continue",)
        if val == "throw":
            self.next()
            e = self.expression()
            self.accept(";")
            return ("throw", e)
        decl = self._try_declaration()
        if decl is not None:
            self.accept(";")
            return decl
        e = self.expression()
        self.accept(";")
        return ("expr", e)

    def _try_declaration(self):
        kind, val = self.peek()
        if kind == "id" and val in _TYPE_WORDS and \
                (self.peek(1)[0] == "id" or self.peek(1)[1] == "<"):
            save = self.i
            self.next()
            # generic parameters of the type are not modelled: skip <...>
            if self.peek()[1] == "<":
                depth = 0
                while True:
                    t = self.next()[1]
                    depth += t.count("<") - t.count(">")
                    if depth <= 0:
                        break
            if self.peek()[0] != "id":
                self.i = save
                return None
            entries = []
            while True:
                name = self.next()[1]
                init = None
                if self.accept("="):
                    init = self.expression()
                entries.append((name, init))
                if not self.accept(","):
                    break
            return ("decl", entries)
        return None

    def _for(self):
        self.next()  # for
        self.expect("(")
        # for-each: `for (def x : expr)` / `for (x in expr)`
        save = self.i
        kind, val = self.peek()
        if kind == "id":
            if val in _TYPE_WORDS and self.peek(1)[0] == "id" \
                    and self.peek(2)[1] in (":", "in"):
                self.next()
                var = self.next()[1]
                self.next()  # ':' or 'in'
                it = self.expression()
                self.expect(")")
                return ("foreach", var, it, self.statement())
            if self.peek(1)[1] in (":", "in"):
                var = self.next()[1]
                self.next()
                it = self.expression()
                self.expect(")")
                return ("foreach", var, it, self.statement())
        self.i = save
        init = None
        if not self.accept(";"):
            init = self._try_declaration()
            if init is None:
                init = ("expr", self.expression())
            self.expect(";")
        cond = None
        if not self.accept(";"):
            cond = self.expression()
            self.expect(";")
        step = None
        if self.peek()[1] != ")":
            step = ("expr", self.expression())
        self.expect(")")
        return ("for", init, cond, step, self.statement())

    # ---------------------------------------------------------- expressions
    def expression(self):
        return self.assignment()

    def assignment(self):
        target = self.ternary()
        for op in ("=", "+=", "-=", "*=", "/=", "%="):
            if self.accept(op):
                value = self.assignment()
                return ("assign", op, target, value)
        return target

    def ternary(self):
        cond = self.elvis()
        if self.accept("?"):
            then = self.expression()
            self.expect(":")
            other = self.expression()
            return ("ternary", cond, then, other)
        return cond

    def elvis(self):
        left = self.logic_or()
        while self.accept("?:"):
            right = self.logic_or()
            left = ("elvis", left, right)
        return left

    def logic_or(self):
        left = self.logic_and()
        while self.accept("||"):
            left = ("or", left, self.logic_and())
        return left

    def logic_and(self):
        left = self.equality()
        while self.accept("&&"):
            left = ("and", left, self.equality())
        return left

    def equality(self):
        left = self.relational()
        while self.peek()[1] in ("==", "!=") and self.peek()[0] == "op":
            op = self.next()[1]
            left = ("binop", op, left, self.relational())
        return left

    def relational(self):
        left = self.additive()
        while True:
            if self.peek()[0] == "op" and self.peek()[1] in ("<", "<=", ">", ">="):
                op = self.next()[1]
                left = ("binop", op, left, self.additive())
            elif self.peek()[1] == "instanceof":
                self.next()
                tname = self.next()[1]
                left = ("instanceof", left, tname)
            else:
                return left

    def additive(self):
        left = self.multiplicative()
        while self.peek()[0] == "op" and self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            left = ("binop", op, left, self.multiplicative())
        return left

    def multiplicative(self):
        left = self.unary()
        while self.peek()[0] == "op" and self.peek()[1] in ("*", "/", "%"):
            op = self.next()[1]
            left = ("binop", op, left, self.unary())
        return left

    def unary(self):
        kind, val = self.peek()
        if kind == "op" and val in ("-", "+", "!"):
            self.next()
            return ("unary", val, self.unary())
        if kind == "op" and val in ("++", "--"):
            self.next()
            target = self.unary()
            return ("preincr", val, target)
        # cast: (int) expr — a parenthesized single type word
        if val == "(" and self.peek(1)[0] == "id" \
                and self.peek(1)[1] in _TYPE_WORDS and self.peek(2)[1] == ")":
            self.next(); tname = self.next()[1]; self.next()
            return ("cast", tname, self.unary())
        return self.postfix()

    def postfix(self):
        node = self.primary()
        while True:
            if self.accept("."):
                name = self.next()[1]
                if self.accept("("):
                    args = []
                    while not self.accept(")"):
                        args.append(self.expression())
                        self.accept(",")
                    node = ("method", node, name, args)
                else:
                    node = ("field", node, name)
            elif self.accept("["):
                idx = self.expression()
                self.expect("]")
                node = ("index", node, idx)
            elif self.peek()[0] == "op" and self.peek()[1] in ("++", "--"):
                op = self.next()[1]
                node = ("postincr", op, node)
            else:
                return node

    def primary(self):
        kind, val = self.next()
        if kind == "num":
            text = val.rstrip("lLfFdD")
            return ("const", float(text) if ("." in text or "e" in text
                                             or "E" in text) else int(text))
        if kind == "str":
            # single-pass escape decode: chained str.replace would re-consume
            # the backslash an earlier replacement produced
            escapes = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                       "'": "'", '"': '"'}
            return ("const", re.sub(
                r"\\(.)", lambda m: escapes.get(m.group(1), m.group(0)),
                val[1:-1]))
        if val == "null":
            return ("const", None)
        if val == "true":
            return ("const", True)
        if val == "false":
            return ("const", False)
        if val == "new":
            tname = self.next()[1]
            if self.peek()[1] == "<":
                depth = 0
                while True:
                    t = self.next()[1]
                    depth += t.count("<") - t.count(">")
                    if depth <= 0:
                        break
            self.expect("(")
            args = []
            while not self.accept(")"):
                args.append(self.expression())
                self.accept(",")
            return ("new", tname, args)
        if val == "(":
            e = self.expression()
            self.expect(")")
            return e
        if val == "[":
            # list [a, b] / map [k: v, ...] / empty map [:]
            if self.accept(":"):
                self.expect("]")
                return ("maplit", [])
            if self.accept("]"):
                return ("listlit", [])
            first = self.expression()
            if self.accept(":"):
                pairs = [(first, self.expression())]
                while self.accept(","):
                    k = self.expression()
                    self.expect(":")
                    pairs.append((k, self.expression()))
                self.expect("]")
                return ("maplit", pairs)
            items = [first]
            while self.accept(","):
                items.append(self.expression())
            self.expect("]")
            return ("listlit", items)
        if kind == "id":
            if self.peek()[1] == "(" and self.peek()[0] == "op":
                self.next()
                args = []
                while not self.accept(")"):
                    args.append(self.expression())
                    self.accept(",")
                return ("call", val, args)
            return ("name", val)
        raise PainlessError(f"unexpected token {val!r}")


# ---------------------------------------------------------------------------
# interpreter
# ---------------------------------------------------------------------------

class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class _UserThrow(IllegalArgumentError):
    pass


_MATH_STATICS: Dict[str, Any] = {
    "abs": abs, "max": max, "min": min, "pow": math.pow, "sqrt": math.sqrt,
    "log": math.log, "log10": math.log10, "exp": math.exp,
    "floor": math.floor, "ceil": math.ceil, "round": round,
    "E": math.e, "PI": math.pi,
}

_STATIC_CALLS: Dict[Tuple[str, str], Callable] = {
    ("Integer", "parseInt"): lambda s: int(str(s)),
    ("Long", "parseLong"): lambda s: int(str(s)),
    ("Double", "parseDouble"): lambda s: float(str(s)),
    ("Float", "parseFloat"): lambda s: float(str(s)),
    ("Boolean", "parseBoolean"): lambda s: str(s).lower() == "true",
    ("String", "valueOf"): lambda v: _to_string(v),
    ("Integer", "toString"): lambda v: _to_string(v),
    ("Math", "abs"): abs, ("Math", "max"): max, ("Math", "min"): min,
    ("Math", "pow"): math.pow, ("Math", "sqrt"): math.sqrt,
    ("Math", "log"): math.log, ("Math", "log10"): math.log10,
    ("Math", "exp"): math.exp, ("Math", "floor"): math.floor,
    ("Math", "ceil"): math.ceil, ("Math", "round"): round,
}


def _to_string(v) -> str:
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(v)
    return str(v)


def _str_methods(s: str) -> Dict[str, Callable]:
    return {
        "length": lambda: len(s),
        "substring": lambda a, b=None: s[int(a):] if b is None else s[int(a):int(b)],
        "contains": lambda x: str(x) in s,
        "startsWith": lambda x: s.startswith(str(x)),
        "endsWith": lambda x: s.endswith(str(x)),
        "indexOf": lambda x, frm=0: s.find(str(x), int(frm)),
        "lastIndexOf": lambda x: s.rfind(str(x)),
        "toLowerCase": lambda: s.lower(),
        "toUpperCase": lambda: s.upper(),
        "trim": lambda: s.strip(),
        "replace": lambda a, b: s.replace(str(a), str(b)),
        "split": lambda sep: list(re.split(sep, s)),
        "equals": lambda x: s == x,
        "equalsIgnoreCase": lambda x: s.lower() == str(x).lower(),
        "charAt": lambda i: s[int(i)],
        "isEmpty": lambda: len(s) == 0,
        "compareTo": lambda x: (s > str(x)) - (s < str(x)),
        "hashCode": lambda: hash(s),
        "toString": lambda: s,
    }


def _list_methods(lst: list) -> Dict[str, Callable]:
    return {
        "add": lambda *a: (lst.insert(int(a[0]), a[1]) if len(a) == 2
                           else lst.append(a[0])) or True,
        "get": lambda i: lst[int(i)],
        "set": lambda i, v: lst.__setitem__(int(i), v),
        "size": lambda: len(lst),
        "isEmpty": lambda: len(lst) == 0,
        "contains": lambda x: x in lst,
        "indexOf": lambda x: lst.index(x) if x in lst else -1,
        "remove": lambda i: lst.pop(int(i)),
        "clear": lambda: lst.clear(),
        "addAll": lambda other: lst.extend(other) or True,
        "sort": lambda *a: lst.sort(),
        "toString": lambda: _to_string(lst),
        "hashCode": lambda: 0,
    }


class FrozenParams(dict):
    """Script `params` are read-only in the reference (mutation throws an
    UnsupportedOperationException); a mutable params dict shared across
    per-document executions would leak state between documents."""


def _map_methods(mp: dict) -> Dict[str, Callable]:
    if isinstance(mp, FrozenParams):
        return {
            "get": lambda k: mp.get(k),
            "getOrDefault": lambda k, d: mp.get(k, d),
            "containsKey": lambda k: k in mp,
            "containsValue": lambda v: v in mp.values(),
            "size": lambda: len(mp),
            "isEmpty": lambda: len(mp) == 0,
            "keySet": lambda: list(mp.keys()),
            "values": lambda: list(mp.values()),
            "entrySet": lambda: [{"key": k, "value": v}
                                 for k, v in mp.items()],
            "toString": lambda: _to_string(mp),
        }
    return {
        "put": lambda k, v: mp.__setitem__(k, v),
        "get": lambda k: mp.get(k),
        "getOrDefault": lambda k, d: mp.get(k, d),
        "containsKey": lambda k: k in mp,
        "containsValue": lambda v: v in mp.values(),
        "remove": lambda k: mp.pop(k, None),
        "size": lambda: len(mp),
        "isEmpty": lambda: len(mp) == 0,
        "keySet": lambda: list(mp.keys()),
        "values": lambda: list(mp.values()),
        "entrySet": lambda: [{"key": k, "value": v} for k, v in mp.items()],
        "clear": lambda: mp.clear(),
        "putAll": lambda other: mp.update(other),
        "toString": lambda: _to_string(mp),
    }


class Interpreter:
    """Executes a parsed program with the given bound variables."""

    def __init__(self, program, bindings: Dict[str, Any]):
        _, self.functions, self.stmts = program
        self.globals = dict(bindings)
        self.loop_budget = MAX_LOOP_ITERATIONS
        self.depth = 0

    # ------------------------------------------------------------------ run
    def run(self) -> Any:
        """Execute top-level statements; like the reference compiler, a
        trailing expression statement is the script's implicit return."""
        scope = [self.globals]
        last = None
        try:
            for stmt in self.stmts:
                if stmt[0] == "expr":
                    last = self.eval(stmt[1], scope)
                else:
                    last = None
                    self.exec_stmt(stmt, scope)
        except _Return as r:
            return r.value
        return last

    # ------------------------------------------------------------ statements
    def exec_stmt(self, node, scope) -> None:
        kind = node[0]
        if kind == "block":
            inner = scope + [{}]
            for s in node[1]:
                self.exec_stmt(s, inner)
        elif kind == "decl":
            for name, init in node[1]:
                scope[-1][name] = self.eval(init, scope) if init is not None else None
        elif kind == "expr":
            self.eval(node[1], scope)
        elif kind == "if":
            if self._truthy(self.eval(node[1], scope)):
                self.exec_stmt(node[2], scope)
            elif node[3] is not None:
                self.exec_stmt(node[3], scope)
        elif kind == "while":
            while self._truthy(self.eval(node[1], scope)):
                self._tick()
                try:
                    self.exec_stmt(node[2], scope)
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "dowhile":
            while True:
                self._tick()
                try:
                    self.exec_stmt(node[2], scope)
                except _Break:
                    break
                except _Continue:
                    pass
                if not self._truthy(self.eval(node[1], scope)):
                    break
        elif kind == "for":
            _, init, cond, step, body = node
            inner = scope + [{}]
            if init is not None:
                self.exec_stmt(init, inner)
            while cond is None or self._truthy(self.eval(cond, inner)):
                self._tick()
                try:
                    self.exec_stmt(body, inner)
                except _Break:
                    break
                except _Continue:
                    pass
                if step is not None:
                    self.exec_stmt(step, inner)
        elif kind == "foreach":
            _, var, it_expr, body = node
            seq = self.eval(it_expr, scope)
            if isinstance(seq, dict):
                seq = list(seq.keys())
            inner = scope + [{}]
            for item in list(seq or []):
                self._tick()
                inner[-1][var] = item
                try:
                    self.exec_stmt(body, inner)
                except _Break:
                    break
                except _Continue:
                    continue
        elif kind == "return":
            raise _Return(self.eval(node[1], scope) if node[1] is not None else None)
        elif kind == "break":
            raise _Break()
        elif kind == "continue":
            raise _Continue()
        elif kind == "throw":
            raise _UserThrow(_to_string(self.eval(node[1], scope)))
        else:
            raise PainlessError(f"unknown statement [{kind}]")

    def _tick(self) -> None:
        self.loop_budget -= 1
        if self.loop_budget <= 0:
            raise IllegalArgumentError(
                "script exceeded the allowed loop iteration budget "
                f"[{MAX_LOOP_ITERATIONS}] (possible infinite loop)")

    # ----------------------------------------------------------- expressions
    def eval(self, node, scope) -> Any:
        kind = node[0]
        if kind == "const":
            return node[1]
        if kind == "name":
            for frame in reversed(scope):
                if node[1] in frame:
                    return frame[node[1]]
            if node[1] == "Math":
                return dict(_MATH_STATICS)  # Math.PI / Math.E field reads
            raise IllegalArgumentError(f"unknown variable [{node[1]}]")
        if kind == "listlit":
            return [self.eval(e, scope) for e in node[1]]
        if kind == "maplit":
            return {self.eval(k, scope): self.eval(v, scope)
                    for k, v in node[1]}
        if kind == "new":
            tname = node[1]
            if tname in ("ArrayList", "List"):
                return list(self.eval(node[2][0], scope)) if node[2] else []
            if tname in ("HashMap", "Map"):
                return dict(self.eval(node[2][0], scope)) if node[2] else {}
            if tname == "StringBuilder":
                return []
            raise IllegalArgumentError(f"constructor [{tname}] is not allowed")
        if kind == "ternary":
            return self.eval(node[2], scope) if self._truthy(self.eval(node[1], scope)) \
                else self.eval(node[3], scope)
        if kind == "elvis":
            left = self.eval(node[1], scope)
            return left if left is not None else self.eval(node[2], scope)
        if kind == "or":
            return self._truthy(self.eval(node[1], scope)) or \
                self._truthy(self.eval(node[2], scope))
        if kind == "and":
            return self._truthy(self.eval(node[1], scope)) and \
                self._truthy(self.eval(node[2], scope))
        if kind == "binop":
            return self._binop(node[1], self.eval(node[2], scope),
                               self.eval(node[3], scope))
        if kind == "instanceof":
            value = self.eval(node[1], scope)
            checks = {"String": str, "List": list, "ArrayList": list,
                      "Map": dict, "HashMap": dict, "Integer": int,
                      "Long": int, "Double": float, "Float": float,
                      "Boolean": bool}
            t = checks.get(node[2])
            return isinstance(value, t) if t else False
        if kind == "unary":
            v = self.eval(node[2], scope)
            if node[1] == "-":
                return -v
            if node[1] == "+":
                return v
            return not self._truthy(v)
        if kind == "cast":
            v = self.eval(node[2], scope)
            if node[1] in ("int", "long", "short", "byte"):
                return int(v)
            if node[1] in ("double", "float"):
                return float(v)
            if node[1] == "String":
                return _to_string(v)
            if node[1] == "boolean":
                return self._truthy(v)
            return v
        if kind in ("preincr", "postincr"):
            old = self.eval(node[2], scope)
            new = (old or 0) + (1 if node[1] == "++" else -1)
            self._store(node[2], new, scope)
            return new if kind == "preincr" else old
        if kind == "assign":
            op, target, value_node = node[1], node[2], node[3]
            value = self.eval(value_node, scope)
            if op != "=":
                value = self._binop(op[0], self.eval(target, scope), value)
            self._store(target, value, scope)
            return value
        if kind == "field":
            return self._field(self.eval(node[1], scope), node[2])
        if kind == "index":
            base = self.eval(node[1], scope)
            key = self.eval(node[2], scope)
            if isinstance(base, list):
                return base[int(key)]
            if isinstance(base, dict):
                return base.get(key)
            if hasattr(base, "__getitem__"):
                return base[key]
            raise IllegalArgumentError("subscript on unsupported value")
        if kind == "method":
            return self._method(node, scope)
        if kind == "call":
            return self._call(node[1], [self.eval(a, scope) for a in node[2]],
                              scope)
        raise PainlessError(f"unknown expression [{kind}]")

    def _truthy(self, v) -> bool:
        return bool(v)

    def _binop(self, op: str, left, right):
        if (left is None or right is None) and op not in ("==", "!="):
            # the reference raises a script NullPointerException here; keep
            # it a SearchEngineError so REST maps it to a client error, not
            # a 500 (e.g. `ctx._source.missing += 1`)
            raise IllegalArgumentError(
                f"cannot apply [{op}] to a null value")
        if op == "+":
            if isinstance(left, str) or isinstance(right, str):
                return _to_string(left) + _to_string(right)
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            if isinstance(left, int) and isinstance(right, int) \
                    and not isinstance(left, bool) and not isinstance(right, bool):
                q = left // right  # Java int division truncates toward zero
                if q < 0 and q * right != left:
                    q += 1
                return q
            return left / right
        if op == "%":
            if isinstance(left, int) and isinstance(right, int) \
                    and not isinstance(left, bool) and not isinstance(right, bool):
                # Java long remainder truncates toward zero; keep it in
                # exact integer arithmetic (fmod loses exactness > 2^53)
                r = abs(left) % abs(right)
                return -r if left < 0 else r
            return math.fmod(left, right)
        if op == "==":
            return left == right
        if op == "!=":
            return left != right
        if op == "<":
            return left < right
        if op == "<=":
            return left <= right
        if op == ">":
            return left > right
        if op == ">=":
            return left >= right
        raise PainlessError(f"unknown operator [{op}]")

    def _store(self, target, value, scope) -> None:
        kind = target[0]
        if kind == "name":
            for frame in reversed(scope):
                if target[1] in frame:
                    frame[target[1]] = value
                    return
            scope[-1][target[1]] = value
            return
        if kind == "index":
            base = self.eval(target[1], scope)
            key = self.eval(target[2], scope)
            if isinstance(base, FrozenParams):
                raise IllegalArgumentError("params are read-only")
            if isinstance(base, list):
                base[int(key)] = value
            elif isinstance(base, dict):
                base[key] = value
            else:
                raise IllegalArgumentError("cannot assign into this value")
            return
        if kind == "field":
            base = self.eval(target[1], scope)
            if isinstance(base, FrozenParams):
                raise IllegalArgumentError("params are read-only")
            if isinstance(base, dict):
                base[target[2]] = value
                return
            raise IllegalArgumentError(
                f"cannot assign field [{target[2]}] on this value")
        raise IllegalArgumentError("invalid assignment target")

    def _field(self, base, name: str):
        if isinstance(base, dict):
            return base.get(name)
        if name == "length" and isinstance(base, (str, list)):
            return len(base)
        # script-context objects expose python properties (doc values)
        if base is not None and not isinstance(base, (int, float, str, bool,
                                                      list)):
            if name in getattr(base, "_painless_fields", ()):
                return getattr(base, name)
        raise IllegalArgumentError(f"field [{name}] not accessible")

    def _method(self, node, scope):
        name = node[2]
        # static allowlist FIRST: Math.max(...), Integer.parseInt(...) —
        # the class name is not a variable, so don't evaluate it
        if node[1][0] == "name":
            static = _STATIC_CALLS.get((node[1][1], name))
            if static is not None:
                return static(*(self.eval(a, scope) for a in node[3]))
        base = self.eval(node[1], scope)
        args = [self.eval(a, scope) for a in node[3]]
        if isinstance(base, str):
            table = _str_methods(base)
        elif isinstance(base, list):
            table = _list_methods(base)
        elif isinstance(base, dict):
            table = _map_methods(base)
        elif base is not None and hasattr(base, "_painless_methods"):
            table = base._painless_methods()
        else:
            table = {}
        fn = table.get(name)
        if fn is None:
            raise IllegalArgumentError(
                f"method [{name}] is not allowed on "
                f"[{type(base).__name__}]")
        return fn(*args)

    def _call(self, name: str, args: list, scope):
        fn = self.functions.get(name)
        if fn is None:
            # context-bound callables (e.g. the vector scoring kernels the
            # score context whitelists: cosineSimilarity, dotProduct, ...)
            bound = self.globals.get(name)
            if callable(bound):
                return bound(*args)
            raise IllegalArgumentError(f"unknown function [{name}]")
        _, params, body = fn
        if len(params) != len(args):
            raise IllegalArgumentError(
                f"function [{name}] expects {len(params)} args, got {len(args)}")
        self.depth += 1
        if self.depth > MAX_CALL_DEPTH:
            raise IllegalArgumentError(
                f"script call depth exceeded [{MAX_CALL_DEPTH}]")
        try:
            inner = [self.globals, dict(zip(params, args))]
            try:
                self.exec_stmt(body, inner)
            except _Return as r:
                return r.value
            return None
        finally:
            self.depth -= 1


def compile_painless(source: str):
    """Parse once; reuse across executions (Compiler.compile analog)."""
    return Parser(tokenize(source)).parse_program()


def execute(program, bindings: Dict[str, Any]) -> Any:
    try:
        return Interpreter(program, bindings).run()
    except (IllegalArgumentError, ParsingError):
        raise
    except RecursionError:
        raise IllegalArgumentError("script recursion too deep")
    except Exception as e:
        # interpreter-internal type errors etc. are the script author's
        # bug: a client error, never a 500
        raise IllegalArgumentError(
            f"runtime error in script: {type(e).__name__}: {e}")
