"""Mustache template rendering for search templates.

Reference: `modules/lang-mustache` (2.1k LoC) — Elasticsearch embeds the
Mustache engine to render `_search/template` bodies before parsing them as
query DSL. This is a self-contained renderer covering the subset the
reference's search templates exercise: `{{var}}` interpolation with
dotted-path lookup, triple-stash `{{{var}}}` (no escaping — ES renders into
JSON, not HTML, so both forms are unescaped here too), sections
`{{#x}}...{{/x}}` over lists / truthy values, inverted sections `{{^x}}`,
comments `{{! }}`, and the ES custom lambdas `{{#toJson}}field{{/toJson}}`,
`{{#join}}field{{/join}}` (`CustomMustacheFactory.java` in the reference
module registers toJson/join encoders).
"""

from __future__ import annotations

import json
import re
from typing import Any, List

from elasticsearch_tpu.common.errors import ParsingError

# Triple-stash must be matched as an alternative, not with optional braces —
# otherwise `{{n}}}` (a tag followed by the surrounding JSON's own `}`)
# greedily consumes three closing braces.
_TAG = re.compile(
    r"\{\{\{\s*([^}]*?)\s*\}\}\}"            # {{{ var }}}
    r"|\{\{\s*([#/^!&]?)\s*([^}]*?)\s*\}\}"  # {{ sigil name }}
)


def _lookup(context_stack: List[Any], path: str) -> Any:
    if path == ".":
        return context_stack[-1]
    parts = path.split(".")
    for ctx in reversed(context_stack):
        cur = ctx
        found = True
        for p in parts:
            if isinstance(cur, dict) and p in cur:
                cur = cur[p]
            else:
                found = False
                break
        if found:
            return cur
    return None


def _stringify(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    return str(v)


class _Parser:
    """Tokenizes a template into a tree of (text | var | section) nodes."""

    def __init__(self, template: str):
        self.template = template

    def parse(self) -> list:
        nodes, rest = self._parse_block(0, None)
        if rest != len(self.template):
            raise ParsingError("unbalanced mustache section close tag")
        return nodes

    def _parse_block(self, pos: int, open_name: str | None):
        nodes: list = []
        tmpl = self.template
        while pos < len(tmpl):
            m = _TAG.search(tmpl, pos)
            if m is None:
                nodes.append(("text", tmpl[pos:]))
                return nodes, len(tmpl)
            if m.start() > pos:
                nodes.append(("text", tmpl[pos:m.start()]))
            if m.group(1) is not None:          # triple-stash variable
                sigil, name = "", m.group(1)
            else:
                sigil, name = m.group(2), m.group(3)
            pos = m.end()
            if sigil == "!":
                continue
            if sigil in ("#", "^"):
                body, pos = self._parse_block(pos, name)
                nodes.append(("section" if sigil == "#" else "inverted",
                              name, body))
            elif sigil == "/":
                if name != open_name:
                    raise ParsingError(
                        f"mustache section mismatch: open [{open_name}] "
                        f"closed by [{name}]")
                return nodes, pos
            else:
                nodes.append(("var", name))
        if open_name is not None:
            raise ParsingError(f"unclosed mustache section [{open_name}]")
        return nodes, pos


def _render_nodes(nodes: list, stack: List[Any], out: List[str]) -> None:
    for node in nodes:
        kind = node[0]
        if kind == "text":
            out.append(node[1])
        elif kind == "var":
            out.append(_stringify(_lookup(stack, node[1])))
        elif kind == "section":
            name, body = node[1], node[2]
            if name == "toJson":
                inner: List[str] = []
                _render_nodes(body, stack, inner)
                out.append(json.dumps(_lookup(stack, "".join(inner).strip())))
                continue
            if name == "join":
                inner = []
                _render_nodes(body, stack, inner)
                val = _lookup(stack, "".join(inner).strip())
                if isinstance(val, list):
                    out.append(",".join(_stringify(v) for v in val))
                else:
                    out.append(_stringify(val))
                continue
            val = _lookup(stack, name)
            if isinstance(val, list):
                for item in val:
                    stack.append(item)
                    _render_nodes(body, stack, out)
                    stack.pop()
            elif isinstance(val, dict):
                stack.append(val)
                _render_nodes(body, stack, out)
                stack.pop()
            elif val:
                _render_nodes(body, stack, out)
        elif kind == "inverted":
            name, body = node[1], node[2]
            val = _lookup(stack, name)
            if not val or (isinstance(val, list) and not val):
                _render_nodes(body, stack, out)


def render(template: str, params: dict | None) -> str:
    """Render a mustache template with params; returns the raw string."""
    nodes = _Parser(template).parse()
    out: List[str] = []
    _render_nodes(nodes, [params or {}], out)
    return "".join(out)


def render_search_template(source: Any, params: dict | None) -> dict:
    """Render a search-template source (string or dict) into a request body.

    The reference serializes a dict source back to JSON before rendering
    (`TransportRenderSearchTemplateAction`), so both forms funnel through
    the string path.
    """
    if isinstance(source, dict):
        source = json.dumps(source)
    rendered = render(source, params)
    try:
        return json.loads(rendered)
    except ValueError as e:
        raise ParsingError(
            f"rendered search template is not valid JSON: {e}: {rendered[:200]}")
