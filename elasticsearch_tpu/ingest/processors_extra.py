"""Additional ingest processors: grok, csv, kv, json, urldecode, html_strip,
bytes, fingerprint, sort, uri_parts, dot_expander, foreach, user_agent,
geoip.

Reference: `modules/ingest-common` (3.9k LoC), `modules/ingest-user-agent`,
`plugins/ingest-geoip` (MaxMind-backed there; here an inline-database
variant since the GeoLite2 db doesn't ship in this build).
"""

from __future__ import annotations

import hashlib
import json as _json
import re
import urllib.parse
from typing import Any, Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.settings import parse_byte_size
from elasticsearch_tpu.ingest.grok import Grok
from elasticsearch_tpu.ingest.service import (
    IngestProcessorError,
    Processor,
    _del_path,
    _get_path,
    _set_path,
)


class GrokProcessor(Processor):
    kind = "grok"

    def __init__(self, spec):
        super().__init__(spec)
        patterns = spec.get("patterns")
        if not patterns:
            raise IllegalArgumentError("[grok] requires [patterns]")
        defs = spec.get("pattern_definitions")
        self.groks = [Grok(p, defs) for p in patterns]

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        for grok in self.groks:
            m = grok.match(str(v))
            if m is not None:
                for field, value in m.items():
                    _set_path(ctx, field, value)
                return
        raise IngestProcessorError(
            f"Provided Grok expressions do not match field value: [{v}]")


class CsvProcessor(Processor):
    kind = "csv"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        import csv as _csv
        import io
        sep = self.spec.get("separator", ",")
        quote = self.spec.get("quote", '"')
        row = next(_csv.reader(io.StringIO(str(v)), delimiter=sep,
                               quotechar=quote))
        targets = self.spec.get("target_fields", [])
        for name, value in zip(targets, row):
            if value == "" and not self.spec.get("empty_value"):
                continue
            _set_path(ctx, name, value if value != "" else
                      self.spec.get("empty_value"))


class KvProcessor(Processor):
    kind = "kv"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        field_split = self.spec.get("field_split", " ")
        value_split = self.spec.get("value_split", "=")
        prefix = self.spec.get("prefix", "")
        target = self.spec.get("target_field")
        include = set(self.spec.get("include_keys", []) or [])
        exclude = set(self.spec.get("exclude_keys", []) or [])
        out: Dict[str, Any] = {}
        for pair in re.split(field_split, str(v)):
            if not pair:
                continue
            key, sep, val = pair.partition(value_split)
            if not sep:
                continue
            if include and key not in include:
                continue
            if key in exclude:
                continue
            out[prefix + key] = val.strip('"') if self.spec.get(
                "strip_brackets") else val
        if target:
            _set_path(ctx, target, out)
        else:
            for k, val in out.items():
                _set_path(ctx, k, val)


class JsonProcessor(Processor):
    kind = "json"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        try:
            parsed = _json.loads(v)
        except (TypeError, ValueError) as e:
            raise IngestProcessorError(f"cannot parse JSON in [{self.field}]: {e}")
        target = self.spec.get("target_field")
        if self.spec.get("add_to_root") and isinstance(parsed, dict):
            for k, val in parsed.items():
                ctx[k] = val
        else:
            _set_path(ctx, target or self.field, parsed)


class UrlDecodeProcessor(Processor):
    kind = "urldecode"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  urllib.parse.unquote_plus(str(v)))


class HtmlStripProcessor(Processor):
    kind = "html_strip"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  re.sub(r"<[^>]*>", "", str(v)))


class BytesProcessor(Processor):
    kind = "bytes"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  parse_byte_size(str(v), self.field))


class FingerprintProcessor(Processor):
    kind = "fingerprint"

    def run(self, ctx):
        fields = self.spec.get("fields", [])
        method = self.spec.get("method", "SHA-1").lower().replace("-", "")
        h = hashlib.new({"sha1": "sha1", "sha256": "sha256", "md5": "md5",
                         "sha512": "sha512"}.get(method, "sha1"))
        for f in sorted(fields):
            v = _get_path(ctx, f)
            if v is None:
                if self.ignore_missing:
                    continue
                raise IngestProcessorError(f"field [{f}] is missing")
            h.update(f.encode())
            h.update(str(v).encode())
        _set_path(ctx, self.spec.get("target_field", "fingerprint"),
                  h.hexdigest())


class SortProcessor(Processor):
    kind = "sort"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        if not isinstance(v, list):
            raise IngestProcessorError(f"field [{self.field}] is not a list")
        out = sorted(v, reverse=self.spec.get("order", "asc") == "desc")
        _set_path(ctx, self.spec.get("target_field", self.field), out)


class UriPartsProcessor(Processor):
    kind = "uri_parts"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        u = urllib.parse.urlsplit(str(v))
        parts: Dict[str, Any] = {"original": str(v), "scheme": u.scheme,
                                 "domain": u.hostname, "path": u.path}
        if u.port:
            parts["port"] = u.port
        if u.query:
            parts["query"] = u.query
        if u.fragment:
            parts["fragment"] = u.fragment
        if u.username:
            parts["user_info"] = u.username + (":" + u.password if u.password else "")
        if "." in u.path.rsplit("/", 1)[-1]:
            parts["extension"] = u.path.rsplit(".", 1)[-1]
        _set_path(ctx, self.spec.get("target_field", "url"), parts)
        if not self.spec.get("keep_original", True):
            _del_path(ctx, self.field)


class DotExpanderProcessor(Processor):
    kind = "dot_expander"

    def run(self, ctx):
        field = self.field
        if field == "*":
            for k in [k for k in list(ctx) if "." in k and not k.startswith("_")]:
                self._expand(ctx, k)
            return
        self._expand(ctx, field)

    @staticmethod
    def _expand(ctx, key):
        if key not in ctx:
            return
        v = ctx.pop(key)
        _set_path(ctx, key, v)


class ForeachProcessor(Processor):
    kind = "foreach"

    def run(self, ctx):
        from elasticsearch_tpu.ingest.service import build_processor
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] is missing")
        if not isinstance(v, list):
            raise IngestProcessorError(f"field [{self.field}] is not a list")
        inner_spec = self.spec.get("processor")
        if not inner_spec:
            raise IllegalArgumentError("[foreach] requires [processor]")
        out = []
        for item in v:
            ctx["_ingest"] = ctx.get("_ingest", {})
            ctx["_ingest"]["_value"] = item
            build_processor(inner_spec).process(ctx, getattr(self, "_registry", None))
            out.append(ctx["_ingest"].pop("_value"))
        _set_path(ctx, self.field, out)


_UA_PATTERNS = [
    # (regex, name) — ordered, first match wins (reference bundles the
    # uap-core database; this is the high-traffic subset)
    (re.compile(r"Edg(?:e|A|iOS)?/(\d+)[.\d]*"), "Edge"),
    (re.compile(r"OPR/(\d+)[.\d]*"), "Opera"),
    (re.compile(r"Chrome/(\d+)[.\d]*"), "Chrome"),
    (re.compile(r"CriOS/(\d+)[.\d]*"), "Chrome Mobile iOS"),
    (re.compile(r"Firefox/(\d+)[.\d]*"), "Firefox"),
    (re.compile(r"Version/(\d+)[.\d]* .*Safari/"), "Safari"),
    (re.compile(r"MSIE (\d+)[.\d]*"), "IE"),
    (re.compile(r"Trident/.*rv:(\d+)"), "IE"),
    (re.compile(r"curl/(\d+)[.\d]*"), "curl"),
    (re.compile(r"python-requests/(\d+)[.\d]*"), "Python Requests"),
]

_OS_PATTERNS = [
    (re.compile(r"Windows NT 10"), "Windows", "10"),
    (re.compile(r"Windows NT 6\.3"), "Windows", "8.1"),
    (re.compile(r"Windows NT 6\.1"), "Windows", "7"),
    (re.compile(r"Mac OS X (\d+)[_.](\d+)"), "Mac OS X", None),
    (re.compile(r"Android (\d+)"), "Android", None),
    (re.compile(r"iPhone OS (\d+)"), "iOS", None),
    (re.compile(r"Linux"), "Linux", None),
]


class UserAgentProcessor(Processor):
    kind = "user_agent"

    def run(self, ctx):
        field = self.field or "user_agent"
        v = _get_path(ctx, field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] is missing")
        ua = str(v)
        out: Dict[str, Any] = {"original": ua, "name": "Other"}
        for pat, name in _UA_PATTERNS:
            m = pat.search(ua)
            if m:
                out["name"] = name
                out["version"] = m.group(1)
                break
        for pat, os_name, os_version in _OS_PATTERNS:
            m = pat.search(ua)
            if m:
                os_out = {"name": os_name}
                if os_version:
                    os_out["version"] = os_version
                elif m.groups():
                    os_out["version"] = ".".join(g for g in m.groups() if g)
                out["os"] = os_out
                break
        device = "Other"
        if "iPhone" in ua:
            device = "iPhone"
        elif "Android" in ua and "Mobile" in ua:
            device = "Generic Smartphone"
        out["device"] = {"name": device}
        _set_path(ctx, self.spec.get("target_field", "user_agent"), out)


class GeoIpProcessor(Processor):
    """`geoip` — the reference bundles GeoLite2 (`plugins/ingest-geoip`);
    that database can't ship here, so lookups resolve against an inline
    `database` param: a list of {cidr, ...geo fields} entries."""

    kind = "geoip"

    def run(self, ctx):
        import ipaddress
        field = self.field or "ip"
        v = _get_path(ctx, field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{field}] is missing")
        database = self.spec.get("database", [])
        try:
            addr = ipaddress.ip_address(str(v))
        except ValueError:
            raise IngestProcessorError(f"[{v}] is not a valid ip address")
        for entry in database:
            net = ipaddress.ip_network(entry.get("cidr", "0.0.0.0/0"))
            if addr in net:
                geo = {k: val for k, val in entry.items() if k != "cidr"}
                _set_path(ctx, self.spec.get("target_field", "geoip"), geo)
                return
        if not self.ignore_missing and database:
            return   # address not in database: no-op like the reference


def register_extra_processors() -> None:
    from elasticsearch_tpu.ingest.service import PROCESSORS
    for cls in (GrokProcessor, CsvProcessor, KvProcessor, JsonProcessor,
                UrlDecodeProcessor, HtmlStripProcessor, BytesProcessor,
                FingerprintProcessor, SortProcessor, UriPartsProcessor,
                DotExpanderProcessor, ForeachProcessor, UserAgentProcessor,
                GeoIpProcessor):
        PROCESSORS[cls.kind] = cls
