"""Ingest subsystem: pipelines + processors.

Reference: `ingest/IngestService.java`, `modules/ingest-common`,
`modules/ingest-user-agent`, `plugins/ingest-geoip`, `libs/grok`,
`libs/dissect`.
"""

from elasticsearch_tpu.ingest.processors_extra import register_extra_processors

register_extra_processors()
