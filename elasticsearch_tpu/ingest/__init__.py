"""Ingest subsystem: pipelines + processors.

Reference: `ingest/IngestService.java`, `modules/ingest-common`,
`modules/ingest-user-agent`, `plugins/ingest-geoip`,
`plugins/ingest-attachment`, `libs/grok`, `libs/dissect`.
"""

from elasticsearch_tpu.ingest.attachment import register_attachment_processor
from elasticsearch_tpu.ingest.processors_extra import register_extra_processors

register_extra_processors()
register_attachment_processor()
