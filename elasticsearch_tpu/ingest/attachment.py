"""Attachment ingest processor (Tika-lite).

Reference: `plugins/ingest-attachment` — the `attachment` processor runs
Apache Tika over a base64-encoded binary field and indexes the extracted
`content`, `content_type`, `content_length`, `language`, plus format
metadata (title/author/...). Tika is a JVM dependency; this environment
has no egress, so extraction is re-implemented for the formats that
matter in practice, pure-stdlib:

  * content-type sniffing by magic bytes (%PDF, PK zip/OOXML, {\\rtf,
    HTML markers, UTF BOMs)
  * text/plain (+ charset fallback utf-8 → latin-1)
  * text/html — tag strip with script/style suppression
  * DOCX (OOXML): `word/document.xml` <w:t> runs + `docProps/core.xml`
    title/author/dates
  * PDF — best-effort: FlateDecode stream inflation + Tj/TJ text-showing
    operators (covers simple generated PDFs; scanned/encrypted ones
    yield empty content, never an error)
  * RTF — control-word strip
  * language — trivial stopword vote over a handful of languages (the
    reference ships Tika's detector; same field, cruder signal)

Same spec surface: `field`, `target_field` (default "attachment"),
`indexed_chars` (default 100_000, -1 = unlimited), `indexed_chars_field`,
`properties` subset, `ignore_missing`, `remove_binary`.
"""

from __future__ import annotations

import base64
import html.parser
import io
import re
import zipfile
import zlib
from typing import List, Optional

from elasticsearch_tpu.ingest.service import (
    IngestProcessorError, Processor, _del_path, _get_path, _set_path,
)

DEFAULT_INDEXED_CHARS = 100_000

_STOPWORDS = {
    "en": {"the", "and", "of", "to", "in", "is", "that", "it", "for"},
    "de": {"der", "die", "das", "und", "ist", "nicht", "ein", "mit"},
    "fr": {"le", "la", "les", "et", "est", "une", "pour", "dans"},
    "es": {"el", "la", "los", "que", "es", "una", "por", "con"},
    "nl": {"de", "het", "een", "en", "van", "dat", "niet", "met"},
}


class _HtmlText(html.parser.HTMLParser):
    def __init__(self):
        super().__init__()
        self.parts: List[str] = []
        self._suppress = 0
        self.title: Optional[str] = None
        self._in_title = False

    def handle_starttag(self, tag, attrs):
        if tag in ("script", "style"):
            self._suppress += 1
        if tag == "title":
            self._in_title = True

    def handle_endtag(self, tag):
        if tag in ("script", "style") and self._suppress:
            self._suppress -= 1
        if tag == "title":
            self._in_title = False

    def handle_data(self, data):
        if self._in_title:
            self.title = (self.title or "") + data
            return
        if not self._suppress and data.strip():
            self.parts.append(data.strip())


def _decode_text(raw: bytes) -> str:
    # BOM-carrying UTF-16/UTF-8 first, then plain utf-8, then latin-1 —
    # a UTF-16 document must never be indexed as NUL-ridden mojibake
    if raw.startswith((b"\xff\xfe", b"\xfe\xff")):
        try:
            return raw.decode("utf-16")
        except UnicodeDecodeError:
            pass
    if raw.startswith(b"\xef\xbb\xbf"):
        raw = raw[3:]
    for enc in ("utf-8", "latin-1"):
        try:
            return raw.decode(enc)
        except UnicodeDecodeError:
            continue
    return raw.decode("utf-8", errors="replace")


def sniff_content_type(raw: bytes) -> str:
    head = raw[:512]
    if head.startswith(b"%PDF"):
        return "application/pdf"
    if head.startswith(b"{\\rtf"):
        return "application/rtf"
    if head.startswith(b"PK\x03\x04"):
        try:
            with zipfile.ZipFile(io.BytesIO(raw)) as z:
                names = set(z.namelist())
            if "word/document.xml" in names:
                return ("application/vnd.openxmlformats-officedocument"
                        ".wordprocessingml.document")
            return "application/zip"
        except zipfile.BadZipFile:
            return "application/zip"
    lowered = head.lstrip()[:64].lower()
    if lowered.startswith((b"<!doctype html", b"<html")) \
            or b"<html" in head.lower():
        return "text/html"
    if head.startswith((b"\xef\xbb\xbf", b"\xff\xfe", b"\xfe\xff")):
        return "text/plain"
    try:
        head.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


def _extract_html(raw: bytes):
    p = _HtmlText()
    p.feed(_decode_text(raw))
    meta = {}
    if p.title:
        meta["title"] = p.title.strip()
    return " ".join(p.parts), meta


_W_T = re.compile(r"<w:t(?:\s[^>]*)?>(.*?)</w:t>", re.S)
_W_P_END = re.compile(r"</w:p>")
_CORE = {
    "title": re.compile(r"<dc:title>(.*?)</dc:title>", re.S),
    "author": re.compile(r"<dc:creator>(.*?)</dc:creator>", re.S),
    "date": re.compile(
        r"<dcterms:created[^>]*>(.*?)</dcterms:created>", re.S),
    "keywords": re.compile(r"<cp:keywords>(.*?)</cp:keywords>", re.S),
}


def _extract_docx(raw: bytes):
    import xml.sax.saxutils as su
    with zipfile.ZipFile(io.BytesIO(raw)) as z:
        doc = z.read("word/document.xml").decode("utf-8", errors="replace")
        core = ""
        if "docProps/core.xml" in z.namelist():
            core = z.read("docProps/core.xml").decode("utf-8",
                                                      errors="replace")
    paragraphs = []
    for para in _W_P_END.split(doc):
        runs = [su.unescape(m) for m in _W_T.findall(para)]
        if runs:
            paragraphs.append("".join(runs))
    meta = {}
    for key, rx in _CORE.items():
        m = rx.search(core)
        if m and m.group(1).strip():
            meta[key] = su.unescape(m.group(1).strip())
    return "\n".join(paragraphs), meta


_PDF_STREAM = re.compile(rb"stream\r?\n(.*?)endstream", re.S)
_PDF_TEXT_OP = re.compile(rb"\(((?:[^()\\]|\\.)*)\)\s*Tj"
                          rb"|\[((?:[^\[\]\\]|\\.)*)\]\s*TJ", re.S)
_PDF_STR = re.compile(rb"\(((?:[^()\\]|\\.)*)\)")
_PDF_ESC = {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"(": b"(",
            b")": b")", b"\\": b"\\"}


def _pdf_unescape(s: bytes) -> bytes:
    out = bytearray()
    i = 0
    while i < len(s):
        c = s[i:i + 1]
        if c == b"\\" and i + 1 < len(s):
            nxt = s[i + 1:i + 2]
            out += _PDF_ESC.get(nxt, nxt)
            i += 2
        else:
            out += c
            i += 1
    return bytes(out)


def _extract_pdf(raw: bytes):
    chunks: List[bytes] = []
    for m in _PDF_STREAM.finditer(raw):
        data = m.group(1)
        try:
            data = zlib.decompress(data)
        except zlib.error:
            pass  # uncompressed content stream
        chunks.append(data)
    texts: List[str] = []
    for data in chunks:
        for tj, arr in _PDF_TEXT_OP.findall(data):
            if tj:
                texts.append(_pdf_unescape(tj).decode("latin-1"))
            elif arr:
                texts.append("".join(
                    _pdf_unescape(s).decode("latin-1")
                    for s in _PDF_STR.findall(arr)))
    return " ".join(t for t in texts if t.strip()), {}


_RTF_CTRL = re.compile(r"\\[a-zA-Z]+-?\d* ?|[{}]|\\'[0-9a-fA-F]{2}")


def _extract_rtf(raw: bytes):
    return _RTF_CTRL.sub("", _decode_text(raw)).strip(), {}


def detect_language(text: str) -> Optional[str]:
    words = set(re.findall(r"[a-zà-ÿ]+", text.lower())[:400])
    best, best_hits = None, 1  # require >= 2 stopword hits
    for lang, stops in _STOPWORDS.items():
        hits = len(words & stops)
        if hits > best_hits:
            best, best_hits = lang, hits
    return best


def extract(raw: bytes) -> dict:
    """bytes -> {content, content_type, content_length, language?, meta...}"""
    ctype = sniff_content_type(raw)
    meta: dict = {}
    if ctype == "application/pdf":
        content, meta = _extract_pdf(raw)
    elif ctype.endswith("wordprocessingml.document"):
        content, meta = _extract_docx(raw)
    elif ctype == "text/html":
        content, meta = _extract_html(raw)
    elif ctype == "application/rtf":
        content, meta = _extract_rtf(raw)
    elif ctype == "text/plain":
        content = _decode_text(raw)
    else:
        content = ""
    content = content.strip()
    out = {"content": content, "content_type": ctype,
           "content_length": len(content), **meta}
    lang = detect_language(content) if content else None
    if lang:
        out["language"] = lang
    return out


class AttachmentProcessor(Processor):
    kind = "attachment"

    def __init__(self, spec):
        super().__init__(spec)
        self.target_field = spec.get("target_field", "attachment")
        self.indexed_chars = int(spec.get("indexed_chars",
                                          DEFAULT_INDEXED_CHARS))
        self.indexed_chars_field = spec.get("indexed_chars_field")
        self.properties = spec.get("properties")
        self.remove_binary = bool(spec.get("remove_binary", False))

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(
                f"field [{self.field}] is missing")
        if isinstance(v, (bytes, bytearray)):
            raw = bytes(v)
        else:
            try:
                # whitespace is legal in transferred base64; anything else
                # outside the alphabet is a client error, not content
                cleaned = re.sub(r"\s+", "", str(v))
                raw = base64.b64decode(cleaned, validate=True)
            except Exception:
                raise IngestProcessorError(
                    f"field [{self.field}] is not valid base64")
        att = extract(raw)
        limit = self.indexed_chars
        if self.indexed_chars_field:
            per_doc = _get_path(ctx, self.indexed_chars_field)
            if per_doc is not None:
                try:
                    limit = int(per_doc)
                except (TypeError, ValueError):
                    raise IngestProcessorError(
                        f"field [{self.indexed_chars_field}] is not an "
                        f"integer: [{per_doc!r}]")
        if limit >= 0 and len(att.get("content", "")) > limit:
            att["content"] = att["content"][:limit]
            att["content_length"] = limit
        if self.properties:
            att = {k: v2 for k, v2 in att.items() if k in self.properties}
        _set_path(ctx, self.target_field, att)
        if self.remove_binary:
            _del_path(ctx, self.field)


def register_attachment_processor() -> None:
    from elasticsearch_tpu.ingest.service import PROCESSORS
    PROCESSORS[AttachmentProcessor.kind] = AttachmentProcessor
