"""Grok: named-pattern text extraction.

Reference: `libs/grok` (joni-based) + the pattern bank shipped in
`libs/grok/src/main/resources/patterns/` — `%{NAME:field}` /
`%{NAME:field:type}` syntax compiling recursively into one regex. This is a
pure-`re` implementation with the commonly-exercised subset of the bank.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

from elasticsearch_tpu.common.errors import IllegalArgumentError

# the slice of the reference pattern bank that covers the standard suites
BUILTIN_PATTERNS: Dict[str, str] = {
    "WORD": r"\b\w+\b",
    "NOTSPACE": r"\S+",
    "SPACE": r"\s*",
    "DATA": r".*?",
    "GREEDYDATA": r".*",
    "INT": r"[+-]?(?:[0-9]+)",
    "NUMBER": r"[+-]?(?:[0-9]+(?:\.[0-9]+)?)",
    "BASE10NUM": r"[+-]?(?:[0-9]+(?:\.[0-9]+)?)",
    "BASE16NUM": r"(?:0[xX])?[0-9a-fA-F]+",
    "POSINT": r"\b[1-9][0-9]*\b",
    "NONNEGINT": r"\b[0-9]+\b",
    "BOOLEAN": r"(?:true|false|TRUE|FALSE|True|False)",
    "QUOTEDSTRING": r'(?:"(?:[^"\\]|\\.)*"|\'(?:[^\'\\]|\\.)*\')',
    "UUID": r"[A-Fa-f0-9]{8}-(?:[A-Fa-f0-9]{4}-){3}[A-Fa-f0-9]{12}",
    "IPV4": r"(?:(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?)\.){3}"
            r"(?:25[0-5]|2[0-4][0-9]|[01]?[0-9][0-9]?)",
    "IPV6": r"[0-9A-Fa-f:.]{2,45}",
    "IP": r"(?:%{IPV6}|%{IPV4})",
    "HOSTNAME": r"\b(?:[0-9A-Za-z][0-9A-Za-z-]{0,62})"
                r"(?:\.(?:[0-9A-Za-z][0-9A-Za-z-]{0,62}))*\.?\b",
    "IPORHOST": r"(?:%{IP}|%{HOSTNAME})",
    "HOSTPORT": r"%{IPORHOST}:%{POSINT}",
    "USERNAME": r"[a-zA-Z0-9._-]+",
    "USER": r"%{USERNAME}",
    "EMAILLOCALPART": r"[a-zA-Z][a-zA-Z0-9_.+-=:]+",
    "EMAILADDRESS": r"%{EMAILLOCALPART}@%{HOSTNAME}",
    "PATH": r"(?:%{UNIXPATH}|%{WINPATH})",
    "UNIXPATH": r"(?:/[\w_%!$@:.,+~-]*)+",
    "WINPATH": r"(?:[A-Za-z]+:|\\)(?:\\[^\\?*]*)+",
    "URIPROTO": r"[A-Za-z]+(?:\+[A-Za-z+]+)?",
    "URIHOST": r"%{IPORHOST}(?::%{POSINT})?",
    "URIPATH": r"(?:/[A-Za-z0-9$.+!*'(){},~:;=@#%&_\-]*)+",
    "URIPARAM": r"\?[A-Za-z0-9$.+!*'|(){},~@#%&/=:;_?\-\[\]<>]*",
    "URIPATHPARAM": r"%{URIPATH}(?:%{URIPARAM})?",
    "URI": r"%{URIPROTO}://(?:%{USER}(?::[^@]*)?@)?(?:%{URIHOST})?"
           r"(?:%{URIPATHPARAM})?",
    "MONTH": r"\b(?:Jan(?:uary)?|Feb(?:ruary)?|Mar(?:ch)?|Apr(?:il)?|May|"
             r"Jun(?:e)?|Jul(?:y)?|Aug(?:ust)?|Sep(?:tember)?|Oct(?:ober)?|"
             r"Nov(?:ember)?|Dec(?:ember)?)\b",
    "MONTHNUM": r"(?:0?[1-9]|1[0-2])",
    "MONTHDAY": r"(?:(?:0[1-9])|(?:[12][0-9])|(?:3[01])|[1-9])",
    "DAY": r"(?:Mon(?:day)?|Tue(?:sday)?|Wed(?:nesday)?|Thu(?:rsday)?|"
           r"Fri(?:day)?|Sat(?:urday)?|Sun(?:day)?)",
    "YEAR": r"(?:\d\d){1,2}",
    "HOUR": r"(?:2[0123]|[01]?[0-9])",
    "MINUTE": r"(?:[0-5][0-9])",
    "SECOND": r"(?:(?:[0-5]?[0-9]|60)(?:[:.,][0-9]+)?)",
    "TIME": r"%{HOUR}:%{MINUTE}(?::%{SECOND})?",
    "DATE_US": r"%{MONTHNUM}[/-]%{MONTHDAY}[/-]%{YEAR}",
    "DATE_EU": r"%{MONTHDAY}[./-]%{MONTHNUM}[./-]%{YEAR}",
    "ISO8601_TIMEZONE": r"(?:Z|[+-]%{HOUR}(?::?%{MINUTE}))",
    "TIMESTAMP_ISO8601": r"%{YEAR}-%{MONTHNUM}-%{MONTHDAY}[T ]%{HOUR}:?"
                         r"%{MINUTE}(?::?%{SECOND})?%{ISO8601_TIMEZONE}?",
    "HTTPDATE": r"%{MONTHDAY}/%{MONTH}/%{YEAR}:%{TIME} %{INT}",
    "LOGLEVEL": r"(?:[Aa]lert|ALERT|[Tt]race|TRACE|[Dd]ebug|DEBUG|[Nn]otice|"
                r"NOTICE|[Ii]nfo(?:rmation)?|INFO(?:RMATION)?|[Ww]arn(?:ing)?|"
                r"WARN(?:ING)?|[Ee]rr(?:or)?|ERR(?:OR)?|[Cc]rit(?:ical)?|"
                r"CRIT(?:ICAL)?|[Ff]atal|FATAL|[Ss]evere|SEVERE|EMERG(?:ENCY)?|"
                r"[Ee]merg(?:ency)?)",
    "SYSLOGTIMESTAMP": r"%{MONTH} +%{MONTHDAY} %{TIME}",
    "PROG": r"[\x21-\x5a\x5c\x5e-\x7e]+",
    "SYSLOGPROG": r"%{PROG:process.name}(?:\[%{POSINT:process.pid:int}\])?",
    "COMMONAPACHELOG": r'%{IPORHOST:source.address} %{USER:apache.access.user.identity} '
                       r'%{USER:user.name} \[%{HTTPDATE:timestamp}\] '
                       r'"(?:%{WORD:http.request.method} %{NOTSPACE:url.original}'
                       r'(?: HTTP/%{NUMBER:http.version})?|%{DATA})" '
                       r'%{INT:http.response.status_code:int} '
                       r'(?:%{INT:http.response.body.bytes:int}|-)',
    "COMBINEDAPACHELOG": r'%{COMMONAPACHELOG} "%{DATA:http.request.referrer}" '
                         r'"%{DATA:user_agent.original}"',
}

_GROK_REF = re.compile(r"%\{(\w+)(?::([\w.\[\]@-]+))?(?::(\w+))?\}")


class Grok:
    def __init__(self, pattern: str,
                 pattern_definitions: Optional[Dict[str, str]] = None):
        self.bank = dict(BUILTIN_PATTERNS)
        if pattern_definitions:
            self.bank.update(pattern_definitions)
        self.types: Dict[str, str] = {}
        self._group_to_field: Dict[str, str] = {}
        regex = self._compile(pattern, depth=0)
        try:
            self.regex = re.compile(regex)
        except re.error as e:
            raise IllegalArgumentError(f"invalid grok pattern [{pattern}]: {e}")

    def _compile(self, pattern: str, depth: int) -> str:
        if depth > 20:
            raise IllegalArgumentError("circular grok pattern reference")

        def repl(m: "re.Match") -> str:
            name, field, typ = m.group(1), m.group(2), m.group(3)
            sub = self.bank.get(name)
            if sub is None:
                raise IllegalArgumentError(f"Unable to find pattern [{name}]")
            inner = self._compile(sub, depth + 1)
            if field:
                group = f"g{len(self._group_to_field)}"
                self._group_to_field[group] = field
                if typ:
                    self.types[field] = typ
                return f"(?P<{group}>{inner})"
            return f"(?:{inner})"

        return _GROK_REF.sub(repl, pattern)

    def match(self, text: str) -> Optional[Dict[str, Any]]:
        m = self.regex.search(text)
        if m is None:
            return None
        out: Dict[str, Any] = {}
        for group, field in self._group_to_field.items():
            v: Any = m.group(group)
            if v is None:
                continue
            typ = self.types.get(field)
            if typ == "int":
                v = int(v)
            elif typ in ("float", "double"):
                v = float(v)
            elif typ == "boolean":
                v = v.lower() == "true"
            out[field] = v
        return out
