"""Ingest pipelines: node-side document transforms before indexing.

Re-design of `ingest/IngestService.java` + `modules/ingest-common/`
(SURVEY.md §2.4): named pipelines of processors applied to documents on
index/bulk when `?pipeline=` or the index's `default_pipeline` setting says
so. Processor set covers the common core of ingest-common: set, remove,
rename, lowercase/uppercase/trim, split/join, convert, gsub, append, date,
drop, fail, script (painless-lite), dissect-lite, user_agent/geoip are
stubbed as unavailable (external databases).

Documents flow as a mutable ctx dict with `_source` plus metadata fields
(`_index`, `_id`), the same shape Painless ingest scripts see.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import (
    IllegalArgumentError, ParsingError, ResourceNotFoundError, SearchEngineError,
)
from elasticsearch_tpu.index.mapping import parse_date_millis


class DropDocument(Exception):
    """Raised by the drop processor: the document is silently discarded."""


class IngestProcessorError(SearchEngineError):
    status = 400


def _get_path(doc: dict, path: str):
    node = doc
    for part in path.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node


def _set_path(doc: dict, path: str, value) -> None:
    parts = path.split(".")
    node = doc
    for p in parts[:-1]:
        nxt = node.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            node[p] = nxt
        node = nxt
    node[parts[-1]] = value


def _del_path(doc: dict, path: str) -> bool:
    parts = path.split(".")
    node = doc
    for p in parts[:-1]:
        node = node.get(p)
        if not isinstance(node, dict):
            return False
    return node.pop(parts[-1], None) is not None


def _render(template: Any, ctx: dict):
    """Mustache-lite {{field}} substitution (reference: lang-mustache)."""
    if not isinstance(template, str):
        return template

    def sub(m):
        v = _get_path(ctx, m.group(1).strip())
        return "" if v is None else str(v)

    return re.sub(r"\{\{([^}]+)\}\}", sub, template)


class Processor:
    kind = "base"

    def __init__(self, spec: dict):
        self.spec = spec
        self.field = spec.get("field")
        self.ignore_missing = bool(spec.get("ignore_missing", False))
        self.condition = spec.get("if")
        self.on_failure = spec.get("on_failure")
        self.ignore_failure = bool(spec.get("ignore_failure", False))
        self.tag = spec.get("tag")

    def should_run(self, ctx: dict) -> bool:
        if self.condition is None:
            return True
        # condition is a painless-lite boolean over ctx
        import ast

        try:
            tree = ast.parse(self.condition.replace("ctx.", "__ctx__."), mode="eval")
        except SyntaxError:
            raise IngestProcessorError(f"invalid [if] condition [{self.condition}]")

        def ev(node):
            if isinstance(node, ast.Expression):
                return ev(node.body)
            if isinstance(node, ast.Constant):
                return node.value
            if isinstance(node, ast.Attribute):
                path = []
                n = node
                while isinstance(n, ast.Attribute):
                    path.append(n.attr)
                    n = n.value
                if isinstance(n, ast.Name) and n.id == "__ctx__":
                    return _get_path(ctx, ".".join(reversed(path)))
                raise IngestProcessorError("condition may only access ctx.*")
            if isinstance(node, ast.Compare):
                left = ev(node.left)
                right = ev(node.comparators[0])
                ops = {ast.Eq: left == right, ast.NotEq: left != right}
                import ast as _a
                if isinstance(node.ops[0], (_a.Lt, _a.LtE, _a.Gt, _a.GtE)):
                    try:
                        return {_a.Lt: left < right, _a.LtE: left <= right,
                                _a.Gt: left > right, _a.GtE: left >= right}[type(node.ops[0])]
                    except TypeError:
                        return False
                return ops.get(type(node.ops[0]), False)
            if isinstance(node, ast.BoolOp):
                vals = [ev(v) for v in node.values]
                return all(vals) if isinstance(node.op, ast.And) else any(vals)
            if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
                return not ev(node.operand)
            raise IngestProcessorError("unsupported condition construct")

        return bool(ev(tree))

    def run(self, ctx: dict) -> None:
        raise NotImplementedError

    def process(self, ctx: dict, pipeline_registry=None) -> None:
        if not self.should_run(ctx):
            return
        self._registry = pipeline_registry
        try:
            self.run(ctx)
        except DropDocument:
            raise
        except Exception as e:
            if self.ignore_failure:
                return
            if self.on_failure:
                for spec in self.on_failure:
                    build_processor(spec).process(ctx, pipeline_registry)
                return
            raise


class SetProcessor(Processor):
    kind = "set"

    def run(self, ctx):
        if not self.spec.get("override", True) and _get_path(ctx, self.field) is not None:
            return
        _set_path(ctx, self.field, _render(self.spec.get("value"), ctx)
                  if "value" in self.spec else _get_path(ctx, self.spec["copy_from"]))


class RemoveProcessor(Processor):
    kind = "remove"

    def run(self, ctx):
        fields = self.field if isinstance(self.field, list) else [self.field]
        for f in fields:
            if not _del_path(ctx, f) and not self.ignore_missing:
                raise IngestProcessorError(f"field [{f}] not present")


class RenameProcessor(Processor):
    kind = "rename"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        _del_path(ctx, self.field)
        _set_path(ctx, self.spec["target_field"], v)


class _StringProcessor(Processor):
    fn = staticmethod(lambda s: s)

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        target = self.spec.get("target_field", self.field)
        if isinstance(v, list):
            _set_path(ctx, target, [self.fn(str(x)) for x in v])
        else:
            _set_path(ctx, target, self.fn(str(v)))


class LowercaseProcessor(_StringProcessor):
    kind = "lowercase"
    fn = staticmethod(str.lower)


class UppercaseProcessor(_StringProcessor):
    kind = "uppercase"
    fn = staticmethod(str.upper)


class TrimProcessor(_StringProcessor):
    kind = "trim"
    fn = staticmethod(str.strip)


class SplitProcessor(Processor):
    kind = "split"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        sep = self.spec.get("separator", ",")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  re.split(sep, str(v)))


class JoinProcessor(Processor):
    kind = "join"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if not isinstance(v, list):
            raise IngestProcessorError(f"field [{self.field}] is not a list")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  self.spec.get("separator", ",").join(str(x) for x in v))


class ConvertProcessor(Processor):
    kind = "convert"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        t = self.spec.get("type")
        try:
            if t == "integer" or t == "long":
                out = int(v)
            elif t == "float" or t == "double":
                out = float(v)
            elif t == "boolean":
                out = str(v).lower() in ("true", "1")
            elif t == "string":
                out = str(v)
            elif t == "auto":
                s = str(v)
                try:
                    out = int(s)
                except ValueError:
                    try:
                        out = float(s)
                    except ValueError:
                        out = True if s.lower() == "true" else False if s.lower() == "false" else s
            else:
                raise IngestProcessorError(f"unknown convert type [{t}]")
        except (TypeError, ValueError):
            raise IngestProcessorError(f"cannot convert [{v}] to [{t}]")
        _set_path(ctx, self.spec.get("target_field", self.field), out)


class GsubProcessor(Processor):
    kind = "gsub"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        _set_path(ctx, self.spec.get("target_field", self.field),
                  re.sub(self.spec["pattern"], self.spec["replacement"], str(v)))


class AppendProcessor(Processor):
    kind = "append"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        add = self.spec.get("value")
        add = add if isinstance(add, list) else [add]
        add = [_render(a, ctx) for a in add]
        if v is None:
            _set_path(ctx, self.field, add)
        elif isinstance(v, list):
            v.extend(add)
        else:
            _set_path(ctx, self.field, [v] + add)


class DateProcessor(Processor):
    kind = "date"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            raise IngestProcessorError(f"field [{self.field}] not present")
        millis = parse_date_millis(v)
        import datetime as dt
        iso = dt.datetime.fromtimestamp(millis / 1000.0, tz=dt.timezone.utc
                                        ).strftime("%Y-%m-%dT%H:%M:%S.%f")[:-3] + "Z"
        _set_path(ctx, self.spec.get("target_field", "@timestamp"), iso)


class DropProcessor(Processor):
    kind = "drop"

    def run(self, ctx):
        raise DropDocument()


class FailProcessor(Processor):
    kind = "fail"

    def run(self, ctx):
        raise IngestProcessorError(_render(self.spec.get("message", "fail processor"), ctx))


class ScriptProcessor(Processor):
    kind = "script"

    def run(self, ctx):
        from elasticsearch_tpu.node import _apply_update_script

        if "source" in self.spec:
            spec = self.spec          # {"source": ..., "params": ...}
        else:
            spec = self.spec.get("script") or self.spec
        if isinstance(spec, str):
            spec = {"source": spec}
        src = spec.get("source", "")
        # ingest scripts address ctx.field directly; reuse the update-script
        # evaluator by mapping ctx.* -> ctx._source.*
        rewritten = re.sub(r"\bctx\.(?!_source)", "ctx._source.", src)
        _apply_update_script(ctx, {"source": rewritten,
                                   "params": spec.get("params", {})})


class DissectProcessor(Processor):
    kind = "dissect"

    def run(self, ctx):
        v = _get_path(ctx, self.field)
        if v is None:
            if self.ignore_missing:
                return
            raise IngestProcessorError(f"field [{self.field}] not present")
        pattern = self.spec["pattern"]
        # %{key} delimited extraction (reference: libs/dissect). Keys may be
        # dotted / duplicated — regex group names can't, so use positional
        # groups mapped back to keys.
        keys = re.findall(r"%\{([^}]*)\}", pattern)
        regex = re.escape(pattern)
        for key in keys:
            regex = regex.replace(re.escape("%{" + key + "}"),
                                  "(.*?)" if key else "(?:.*?)", 1)
        regex = "^" + regex + "$"
        try:
            m = re.match(regex, str(v))
        except re.error as e:
            raise IngestProcessorError(f"invalid dissect pattern [{pattern}]: {e}")
        if m is None:
            raise IngestProcessorError(
                f"dissect pattern [{pattern}] does not match [{v}]")
        named = [k for k in keys if k]
        for key, value in zip(named, m.groups()):
            if not key.startswith("?"):
                _set_path(ctx, key, value)


class PipelineProcessor(Processor):
    kind = "pipeline"

    def run(self, ctx):
        # base process() handles if/ignore_failure/on_failure and stashes the
        # registry on self._registry before calling run
        registry = getattr(self, "_registry", None)
        if registry is None:
            raise IngestProcessorError("pipeline processor requires a registry")
        registry.run(self.spec["name"], ctx)


PROCESSORS = {p.kind: p for p in (
    SetProcessor, RemoveProcessor, RenameProcessor, LowercaseProcessor,
    UppercaseProcessor, TrimProcessor, SplitProcessor, JoinProcessor,
    ConvertProcessor, GsubProcessor, AppendProcessor, DateProcessor,
    DropProcessor, FailProcessor, ScriptProcessor, DissectProcessor,
    PipelineProcessor,
)}


def build_processor(spec: dict) -> Processor:
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ParsingError("each processor must be an object with one key")
    ((kind, body),) = spec.items()
    cls = PROCESSORS.get(kind)
    if cls is None:
        raise ParsingError(f"No processor type exists with name [{kind}]")
    return cls(body or {})


class Pipeline:
    def __init__(self, pipeline_id: str, definition: dict):
        self.pipeline_id = pipeline_id
        self.description = definition.get("description", "")
        self.definition = definition
        self.processors = [build_processor(p) for p in definition.get("processors", [])]
        self.on_failure = [build_processor(p) for p in definition.get("on_failure", [])]

    def run(self, ctx: dict, registry=None) -> Optional[dict]:
        """Returns the transformed ctx, or None if the document was dropped."""
        try:
            for p in self.processors:
                p.process(ctx, registry)
        except DropDocument:
            return None
        except Exception:
            if self.on_failure:
                for p in self.on_failure:
                    p.process(ctx, registry)
                return ctx
            raise
        return ctx


class IngestService:
    """Pipeline registry (reference: IngestService.java:712)."""

    def __init__(self):
        self.pipelines: Dict[str, Pipeline] = {}
        import threading
        self._running = threading.local()

    def put_pipeline(self, pipeline_id: str, definition: dict) -> None:
        bad = [k for k in (definition or {})
               if k not in ("description", "processors", "on_failure",
                            "version", "_meta")]
        if bad:
            from elasticsearch_tpu.common.errors import ParseError
            raise ParseError(
                f"processor [{bad[0]}] doesn't support one or more provided "
                f"configuration parameters [{bad[0]}]")
        self.pipelines[pipeline_id] = Pipeline(pipeline_id, definition)

    def get_pipeline(self, pipeline_id: str) -> Pipeline:
        p = self.pipelines.get(pipeline_id)
        if p is None:
            raise ResourceNotFoundError(f"pipeline [{pipeline_id}] is missing")
        return p

    def delete_pipeline(self, pipeline_id: str) -> None:
        if pipeline_id not in self.pipelines:
            raise ResourceNotFoundError(f"pipeline [{pipeline_id}] is missing")
        del self.pipelines[pipeline_id]

    def run(self, pipeline_id: str, ctx: dict) -> Optional[dict]:
        stack = getattr(self._running, "stack", None)
        if stack is None:
            stack = self._running.stack = []
        if pipeline_id in stack:
            raise IngestProcessorError(
                f"Cycle detected for pipeline: {pipeline_id} "
                f"(execution chain: {' -> '.join(stack + [pipeline_id])})")
        stack.append(pipeline_id)
        try:
            return self.get_pipeline(pipeline_id).run(ctx, self)
        finally:
            stack.pop()

    def execute(self, pipeline_id: str, index: str, doc_id: Optional[str],
                source: dict) -> Optional[dict]:
        """Run a pipeline over one document source; returns the new source
        or None when dropped.

        Ingest ctx exposes source fields at TOP level (`ctx.field`) with
        metadata beside them (`ctx._index`, `ctx._id`) — the shape Painless
        ingest scripts see in the reference."""
        import copy as _copy
        # deep copy: engine.get hands out stored _source by reference; a
        # shallow copy would let nested/append mutations corrupt the stored
        # document of the SOURCE index (reindex-with-pipeline case)
        ctx = _copy.deepcopy(source)
        ctx["_index"] = index
        ctx["_id"] = doc_id
        out = self.run(pipeline_id, ctx)
        if out is None:
            return None
        return {k: v for k, v in out.items() if k not in ("_index", "_id")}

    def simulate(self, definition_or_id, docs: List[dict]) -> List[dict]:
        """_ingest/pipeline/_simulate."""
        if isinstance(definition_or_id, str):
            pipeline = self.get_pipeline(definition_or_id)
        else:
            pipeline = Pipeline("_simulate", definition_or_id)
        results = []
        for doc in docs:
            ctx = dict(doc.get("_source", {}))
            ctx["_index"] = doc.get("_index", "_index")
            ctx["_id"] = doc.get("_id", "_id")
            try:
                out = pipeline.run(ctx, self)
                if out is None:
                    results.append({"doc": None, "dropped": True})
                else:
                    results.append({"doc": {
                        "_index": out.get("_index"), "_id": out.get("_id"),
                        "_source": {k: v for k, v in out.items()
                                    if k not in ("_index", "_id")}}})
            except Exception as e:
                results.append({"error": {"type": "ingest_processor_exception",
                                          "reason": str(e)}})
        return results
