"""Format-agnostic structured-content layer ("x-content").

Re-design of `libs/x-content` (reference XContentParser/XContentBuilder +
json/smile/yaml/cbor subformats, SURVEY.md §2.1): a small registry of codecs
keyed by content type, plus an ObjectParser-style declarative mapper used by
request parsing (reference `ObjectParser.java` / `ConstructingObjectParser.java`).

JSON and CBOR are implemented natively (CBOR hand-rolled — no external dep);
YAML/SMILE are registered as unavailable and produce a clear error, gated the
way optional modules are.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError


class XContentType:
    JSON = "application/json"
    CBOR = "application/cbor"
    YAML = "application/yaml"
    SMILE = "application/smile"

    @staticmethod
    def from_media_type(media_type: Optional[str]) -> str:
        if not media_type:
            return XContentType.JSON
        mt = media_type.split(";")[0].strip().lower()
        aliases = {
            "application/json": XContentType.JSON,
            "application/x-ndjson": XContentType.JSON,
            "text/plain": XContentType.JSON,
            "application/cbor": XContentType.CBOR,
            "application/yaml": XContentType.YAML,
            "application/smile": XContentType.SMILE,
        }
        if mt not in aliases:
            raise IllegalArgumentError(f"unsupported Content-Type [{media_type}]")
        return aliases[mt]


# ---------------------------------------------------------------------------
# CBOR (RFC 8949 subset: the data model JSON covers + bytes)
# ---------------------------------------------------------------------------

def _cbor_encode(obj: Any, out: bytearray) -> None:
    def head(major: int, n: int) -> None:
        if n < 24:
            out.append((major << 5) | n)
        elif n < 0x100:
            out.append((major << 5) | 24); out.append(n)
        elif n < 0x10000:
            out.append((major << 5) | 25); out.extend(n.to_bytes(2, "big"))
        elif n < 0x100000000:
            out.append((major << 5) | 26); out.extend(n.to_bytes(4, "big"))
        else:
            out.append((major << 5) | 27); out.extend(n.to_bytes(8, "big"))

    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            head(0, obj)
        else:
            head(1, -1 - obj)
    elif isinstance(obj, float):
        out.append(0xFB); out.extend(struct.pack(">d", obj))
    elif isinstance(obj, bytes):
        head(2, len(obj)); out.extend(obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8"); head(3, len(b)); out.extend(b)
    elif isinstance(obj, (list, tuple)):
        head(4, len(obj))
        for item in obj:
            _cbor_encode(item, out)
    elif isinstance(obj, dict):
        head(5, len(obj))
        for k, v in obj.items():
            _cbor_encode(str(k), out)
            _cbor_encode(v, out)
    else:
        raise ParsingError(f"cannot CBOR-encode value of type {type(obj).__name__}")


def _cbor_decode(data: bytes, pos: int = 0):
    if pos >= len(data):
        raise ParsingError("truncated CBOR input")
    ib = data[pos]; pos += 1
    major, info = ib >> 5, ib & 0x1F

    def need(pos, n):
        if pos + n > len(data):
            raise ParsingError("truncated CBOR input")

    def read_uint(info, pos):
        if info < 24:
            return info, pos
        n = {24: 1, 25: 2, 26: 4, 27: 8}.get(info)
        if n is None:
            raise ParsingError(f"unsupported CBOR additional info {info}")
        need(pos, n)
        return int.from_bytes(data[pos:pos + n], "big"), pos + n

    if major == 0:
        return read_uint(info, pos)
    if major == 1:
        n, pos = read_uint(info, pos)
        return -1 - n, pos
    if major == 2:
        n, pos = read_uint(info, pos)
        need(pos, n)
        return data[pos:pos + n], pos + n
    if major == 3:
        n, pos = read_uint(info, pos)
        need(pos, n)
        return data[pos:pos + n].decode("utf-8"), pos + n
    if major == 4:
        n, pos = read_uint(info, pos)
        items = []
        for _ in range(n):
            v, pos = _cbor_decode(data, pos)
            items.append(v)
        return items, pos
    if major == 5:
        n, pos = read_uint(info, pos)
        d = {}
        for _ in range(n):
            k, pos = _cbor_decode(data, pos)
            v, pos = _cbor_decode(data, pos)
            d[k] = v
        return d, pos
    if major == 7:
        if ib == 0xF4:
            return False, pos
        if ib == 0xF5:
            return True, pos
        if ib == 0xF6 or ib == 0xF7:
            return None, pos
        if ib == 0xFA:
            need(pos, 4)
            return struct.unpack(">f", data[pos:pos + 4])[0], pos + 4
        if ib == 0xFB:
            need(pos, 8)
            return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    raise ParsingError(f"unsupported CBOR initial byte 0x{ib:02x}")


# ---------------------------------------------------------------------------
# Codec registry
# ---------------------------------------------------------------------------

class _Codec:
    def __init__(self, dumps: Callable[[Any], bytes], loads: Callable[[bytes], Any]):
        self.dumps = dumps
        self.loads = loads


def _json_loads(data: bytes) -> Any:
    try:
        return json.loads(data.decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ParsingError(f"failed to parse JSON: {e}") from None


_CODECS: Dict[str, _Codec] = {
    XContentType.JSON: _Codec(lambda o: json.dumps(o, separators=(",", ":")).encode("utf-8"), _json_loads),
    XContentType.CBOR: _Codec(
        lambda o: bytes(memoryview(_encode_cbor_root(o))),
        lambda d: _cbor_decode_root(d),
    ),
}


def _cbor_decode_root(data: bytes) -> Any:
    value, pos = _cbor_decode(data, 0)
    if pos != len(data):
        raise ParsingError(f"trailing bytes after CBOR value ({len(data) - pos} extra)")
    return value


def _encode_cbor_root(obj: Any) -> bytearray:
    out = bytearray()
    _cbor_encode(obj, out)
    return out


def dumps(obj: Any, content_type: str = XContentType.JSON) -> bytes:
    codec = _CODECS.get(content_type)
    if codec is None:
        raise IllegalArgumentError(f"content type [{content_type}] is not supported in this build")
    return codec.dumps(obj)


def loads(data: bytes, content_type: str = XContentType.JSON) -> Any:
    codec = _CODECS.get(content_type)
    if codec is None:
        raise IllegalArgumentError(f"content type [{content_type}] is not supported in this build")
    return codec.loads(data)


def loads_auto(data: bytes) -> Any:
    """Sniff JSON vs CBOR (reference: XContentFactory.xContentType).

    Any byte that can start a JSON document (object, array, string, number,
    literal, leading whitespace) routes to JSON; only bytes impossible as
    JSON starters fall through to CBOR. Note CBOR documents whose first byte
    is also a JSON starter (e.g. a bare CBOR int < 24) must be passed with an
    explicit content type — the same ambiguity the reference resolves via the
    Content-Type header.
    """
    first = data[:1]
    if first and (first in b'{["-tfn' or first.isdigit() or first.isspace()):
        return loads(data, XContentType.JSON)
    return loads(data, XContentType.CBOR)


# ---------------------------------------------------------------------------
# ObjectParser — declarative request parsing
# ---------------------------------------------------------------------------

class ObjectParser:
    """Declarative dict→object parser (reference: ObjectParser.java).

    Fields are declared with a setter and the parser walks a decoded dict,
    raising on unknown fields unless `ignore_unknown` is set — matching the
    strict parsing the reference applies to request bodies.
    """

    def __init__(self, name: str, ctor: Callable[[], Any], ignore_unknown: bool = False):
        self.name = name
        self._ctor = ctor
        self._fields: Dict[str, Callable[[Any, Any], None]] = {}
        self._ignore_unknown = ignore_unknown

    def declare_field(self, field: str, setter: Callable[[Any, Any], None]) -> "ObjectParser":
        self._fields[field] = setter
        return self

    def parse(self, source: Dict[str, Any]) -> Any:
        if not isinstance(source, dict):
            raise ParsingError(f"[{self.name}] expected an object, got {type(source).__name__}")
        obj = self._ctor()
        for key, value in source.items():
            setter = self._fields.get(key)
            if setter is None:
                if self._ignore_unknown:
                    continue
                raise ParsingError(f"[{self.name}] unknown field [{key}]")
            setter(obj, value)
        return obj
