"""Format-agnostic structured-content layer ("x-content").

Re-design of `libs/x-content` (reference XContentParser/XContentBuilder +
json/smile/yaml/cbor subformats, SURVEY.md §2.1): a small registry of codecs
keyed by content type, plus an ObjectParser-style declarative mapper used by
request parsing (reference `ObjectParser.java` / `ConstructingObjectParser.java`).

All four reference formats are full codecs: JSON (stdlib), CBOR and SMILE
hand-rolled (SMILE emits header flags 0 — no shared-name/value
back-references — which every SMILE parser accepts; inputs using
back-references are rejected upfront), YAML via PyYAML when present
(a clear unsupported-content-type error otherwise).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError, ParsingError


class XContentType:
    JSON = "application/json"
    CBOR = "application/cbor"
    YAML = "application/yaml"
    SMILE = "application/smile"

    @staticmethod
    def from_media_type(media_type: Optional[str]) -> str:
        if not media_type:
            return XContentType.JSON
        mt = media_type.split(";")[0].strip().lower()
        aliases = {
            "application/json": XContentType.JSON,
            "application/x-ndjson": XContentType.JSON,
            "text/plain": XContentType.JSON,
            "application/cbor": XContentType.CBOR,
            "application/yaml": XContentType.YAML,
            "application/smile": XContentType.SMILE,
        }
        if mt not in aliases:
            raise IllegalArgumentError(f"unsupported Content-Type [{media_type}]")
        return aliases[mt]


# ---------------------------------------------------------------------------
# CBOR (RFC 8949 subset: the data model JSON covers + bytes)
# ---------------------------------------------------------------------------

def _cbor_encode(obj: Any, out: bytearray) -> None:
    def head(major: int, n: int) -> None:
        if n < 24:
            out.append((major << 5) | n)
        elif n < 0x100:
            out.append((major << 5) | 24); out.append(n)
        elif n < 0x10000:
            out.append((major << 5) | 25); out.extend(n.to_bytes(2, "big"))
        elif n < 0x100000000:
            out.append((major << 5) | 26); out.extend(n.to_bytes(4, "big"))
        else:
            out.append((major << 5) | 27); out.extend(n.to_bytes(8, "big"))

    if obj is None:
        out.append(0xF6)
    elif obj is True:
        out.append(0xF5)
    elif obj is False:
        out.append(0xF4)
    elif isinstance(obj, int):
        if obj >= 0:
            head(0, obj)
        else:
            head(1, -1 - obj)
    elif isinstance(obj, float):
        out.append(0xFB); out.extend(struct.pack(">d", obj))
    elif isinstance(obj, bytes):
        head(2, len(obj)); out.extend(obj)
    elif isinstance(obj, str):
        b = obj.encode("utf-8"); head(3, len(b)); out.extend(b)
    elif isinstance(obj, (list, tuple)):
        head(4, len(obj))
        for item in obj:
            _cbor_encode(item, out)
    elif isinstance(obj, dict):
        head(5, len(obj))
        for k, v in obj.items():
            _cbor_encode(str(k), out)
            _cbor_encode(v, out)
    else:
        raise ParsingError(f"cannot CBOR-encode value of type {type(obj).__name__}")


def _cbor_decode(data: bytes, pos: int = 0):
    if pos >= len(data):
        raise ParsingError("truncated CBOR input")
    ib = data[pos]; pos += 1
    major, info = ib >> 5, ib & 0x1F

    def need(pos, n):
        if pos + n > len(data):
            raise ParsingError("truncated CBOR input")

    def read_uint(info, pos):
        if info < 24:
            return info, pos
        n = {24: 1, 25: 2, 26: 4, 27: 8}.get(info)
        if n is None:
            raise ParsingError(f"unsupported CBOR additional info {info}")
        need(pos, n)
        return int.from_bytes(data[pos:pos + n], "big"), pos + n

    if major == 0:
        return read_uint(info, pos)
    if major == 1:
        n, pos = read_uint(info, pos)
        return -1 - n, pos
    if major == 2:
        n, pos = read_uint(info, pos)
        need(pos, n)
        return data[pos:pos + n], pos + n
    if major == 3:
        n, pos = read_uint(info, pos)
        need(pos, n)
        return data[pos:pos + n].decode("utf-8"), pos + n
    if major == 4:
        n, pos = read_uint(info, pos)
        items = []
        for _ in range(n):
            v, pos = _cbor_decode(data, pos)
            items.append(v)
        return items, pos
    if major == 5:
        n, pos = read_uint(info, pos)
        d = {}
        for _ in range(n):
            k, pos = _cbor_decode(data, pos)
            v, pos = _cbor_decode(data, pos)
            d[k] = v
        return d, pos
    if major == 7:
        if ib == 0xF4:
            return False, pos
        if ib == 0xF5:
            return True, pos
        if ib == 0xF6 or ib == 0xF7:
            return None, pos
        if ib == 0xFA:
            need(pos, 4)
            return struct.unpack(">f", data[pos:pos + 4])[0], pos + 4
        if ib == 0xFB:
            need(pos, 8)
            return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
    raise ParsingError(f"unsupported CBOR initial byte 0x{ib:02x}")


# ---------------------------------------------------------------------------
# Codec registry
# ---------------------------------------------------------------------------

class _Codec:
    def __init__(self, dumps: Callable[[Any], bytes], loads: Callable[[bytes], Any]):
        self.dumps = dumps
        self.loads = loads


def _json_loads(data: bytes) -> Any:
    try:
        return json.loads(data.decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise ParsingError(f"failed to parse JSON: {e}") from None


# ---------------------------------------------------------------------------
# SMILE (Jackson's binary JSON; reference: libs/x-content smile/ package).
# Hand-rolled subset: no shared-name/value back-references (header flags 0),
# which every SMILE parser must accept.
# ---------------------------------------------------------------------------

_SMILE_HEADER = b":)\n\x00"


def _smile_vint(n: int, out: bytearray) -> None:
    """SMILE unsigned vint: 7 bits/byte, LAST byte carries 6 bits + 0x80."""
    last = n & 0x3F
    n >>= 6
    rest = []
    while n:
        rest.append(n & 0x7F)
        n >>= 7
    out.extend(reversed(rest))
    out.append(0x80 | last)


def _smile_read_vint(data: bytes, pos: int):
    n = 0
    while True:
        if pos >= len(data):
            raise ParsingError("truncated SMILE vint")
        b = data[pos]
        pos += 1
        if b & 0x80:
            return (n << 6) | (b & 0x3F), pos
        n = (n << 7) | b


def _zigzag(n: int) -> int:
    # arbitrary-precision form (a fixed 63-bit shift corrupts ints < -2^63)
    return -2 * n - 1 if n < 0 else 2 * n


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _smile_7bit(raw: bytes, out: bytearray) -> None:
    """Big-endian 7-bits-per-byte packing (floats in SMILE)."""
    bits = int.from_bytes(raw, "big")
    total = len(raw) * 8
    n_out = (total + 6) // 7
    for i in range(n_out - 1, -1, -1):
        out.append((bits >> (7 * i)) & 0x7F)


def _smile_un7bit(data: bytes, pos: int, raw_len: int):
    n_in = (raw_len * 8 + 6) // 7
    if pos + n_in > len(data):
        raise ParsingError("truncated SMILE float")
    bits = 0
    for i in range(n_in):
        bits = (bits << 7) | (data[pos + i] & 0x7F)
    return bits.to_bytes((n_in * 7 + 7) // 8, "big")[-raw_len:], pos + n_in


def _smile_encode(obj: Any, out: bytearray) -> None:
    import struct as _struct
    if obj is None:
        out.append(0x21)
    elif obj is True:
        out.append(0x23)
    elif obj is False:
        out.append(0x22)
    elif isinstance(obj, int):
        if -16 <= obj <= 15:
            out.append(0xC0 + _zigzag(obj))
        elif -(1 << 31) <= obj < (1 << 31):
            out.append(0x24)
            _smile_vint(_zigzag(obj), out)
        elif -(1 << 63) <= obj < (1 << 63):
            out.append(0x25)
            _smile_vint(_zigzag(obj), out)
        else:
            # BigInteger (0x26): vint byte-length + 7-bit packed big-endian
            # two's complement, Jackson's safe-binary layout — a 64-bit
            # token here would overflow conformant parsers
            raw_len = (obj.bit_length() + 8) // 8  # +1 bit for sign
            raw = obj.to_bytes(raw_len, "big", signed=True)
            out.append(0x26)
            _smile_vint(len(raw), out)
            _smile_7bit(raw, out)
    elif isinstance(obj, float):
        out.append(0x29)
        _smile_7bit(_struct.pack(">d", obj), out)
    elif isinstance(obj, str):
        if obj == "":
            out.append(0x20)
            return
        raw = obj.encode("utf-8")
        if len(raw) == len(obj):  # pure ASCII
            if 1 <= len(raw) <= 32:
                out.append(0x40 + len(raw) - 1)
                out.extend(raw)
            elif len(raw) <= 64:
                out.append(0x60 + len(raw) - 33)
                out.extend(raw)
            else:
                out.append(0xE0)
                out.extend(raw)
                out.append(0xFC)
        else:
            if 2 <= len(raw) <= 33:
                out.append(0x80 + len(raw) - 2)
                out.extend(raw)
            elif len(raw) <= 65:
                out.append(0xA0 + len(raw) - 34)
                out.extend(raw)
            else:
                out.append(0xE4)
                out.extend(raw)
                out.append(0xFC)
    elif isinstance(obj, (list, tuple)):
        out.append(0xF8)
        for item in obj:
            _smile_encode(item, out)
        out.append(0xF9)
    elif isinstance(obj, dict):
        out.append(0xFA)
        for k, v in obj.items():
            _smile_encode_key(str(k), out)
            _smile_encode(v, out)
        out.append(0xFB)
    else:
        raise ParsingError(
            f"cannot SMILE-encode object of type {type(obj).__name__}")


def _smile_encode_key(key: str, out: bytearray) -> None:
    if key == "":
        out.append(0x20)
        return
    raw = key.encode("utf-8")
    if len(raw) == len(key) and 1 <= len(raw) <= 64:  # short ASCII name
        out.append(0x80 + len(raw) - 1)
        out.extend(raw)
    elif len(raw) != len(key) and 2 <= len(raw) <= 57:  # short Unicode name
        out.append(0xC0 + len(raw) - 2)
        out.extend(raw)
    else:
        out.append(0x34)  # long name
        out.extend(raw)
        out.append(0xFC)


def _smile_take(data: bytes, pos: int, n: int) -> bytes:
    if pos + n > len(data):
        raise ParsingError("truncated SMILE document")
    return data[pos:pos + n]


def _smile_str_end(data: bytes, pos: int) -> int:
    end = data.find(0xFC, pos)
    if end < 0:
        raise ParsingError("unterminated SMILE long string")
    return end


def _smile_decode_value(data: bytes, pos: int):
    import struct as _struct
    if pos >= len(data):
        raise ParsingError("truncated SMILE document")
    t = data[pos]
    pos += 1
    if t == 0x20:
        return "", pos
    if t == 0x21:
        return None, pos
    if t == 0x22:
        return False, pos
    if t == 0x23:
        return True, pos
    if t in (0x24, 0x25):
        n, pos = _smile_read_vint(data, pos)
        return _unzigzag(n), pos
    if t == 0x26:
        raw_len, pos = _smile_read_vint(data, pos)
        raw, pos = _smile_un7bit(data, pos, raw_len)
        return int.from_bytes(raw, "big", signed=True), pos
    if t == 0x28:
        raw, pos = _smile_un7bit(data, pos, 4)
        return float(_struct.unpack(">f", raw)[0]), pos
    if t == 0x29:
        raw, pos = _smile_un7bit(data, pos, 8)
        return _struct.unpack(">d", raw)[0], pos
    if 0x40 <= t <= 0x5F:
        n = t - 0x40 + 1
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    if 0x60 <= t <= 0x7F:
        n = t - 0x60 + 33
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    if 0x80 <= t <= 0x9F:
        n = t - 0x80 + 2
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    if 0xA0 <= t <= 0xBF:
        n = t - 0xA0 + 34
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    if 0xC0 <= t <= 0xDF:
        return _unzigzag(t - 0xC0), pos
    if t in (0xE0, 0xE4):
        end = _smile_str_end(data, pos)
        return data[pos:end].decode("utf-8"), end + 1
    if t == 0xF8:
        arr = []
        while True:
            if pos >= len(data):
                raise ParsingError("unterminated SMILE array")
            if data[pos] == 0xF9:
                return arr, pos + 1
            v, pos = _smile_decode_value(data, pos)
            arr.append(v)
    if t == 0xFA:
        obj = {}
        while True:
            if pos >= len(data):
                raise ParsingError("unterminated SMILE object")
            if data[pos] == 0xFB:
                return obj, pos + 1
            k, pos = _smile_decode_key(data, pos)
            v, pos = _smile_decode_value(data, pos)
            obj[k] = v
    raise ParsingError(f"unsupported SMILE value token 0x{t:02x}")


def _smile_decode_key(data: bytes, pos: int):
    t = data[pos]
    pos += 1
    if t == 0x20:
        return "", pos
    if t == 0x34:
        end = _smile_str_end(data, pos)
        return data[pos:end].decode("utf-8"), end + 1
    if 0x80 <= t <= 0xBF:
        n = t - 0x80 + 1
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    if 0xC0 <= t <= 0xF7:
        n = t - 0xC0 + 2
        return _smile_take(data, pos, n).decode("utf-8"), pos + n
    raise ParsingError(f"unsupported SMILE key token 0x{t:02x}")


def _smile_dumps(obj: Any) -> bytes:
    out = bytearray(_SMILE_HEADER)
    _smile_encode(obj, out)
    return bytes(out)


def _smile_loads(data: bytes) -> Any:
    if not data.startswith(b":)\n") or len(data) < 4:
        raise ParsingError("not a SMILE document (missing :)\\n header)")
    if data[3] & 0x03:
        raise ParsingError(
            "SMILE shared-name/value back-references are not supported; "
            "encode with shared references disabled (header flags 0)")
    try:
        value, pos = _smile_decode_value(data, 4)
    except ParsingError:
        raise
    except (UnicodeDecodeError, IndexError, ValueError) as e:
        raise ParsingError(f"malformed SMILE document: {e}") from None
    if pos != len(data) and not (pos == len(data) - 1 and data[pos] == 0xFF):
        raise ParsingError(
            f"trailing bytes after SMILE value ({len(data) - pos} extra)")
    return value


# ---------------------------------------------------------------------------
# YAML (PyYAML; reference: libs/x-content yaml/ package via SnakeYAML)
# ---------------------------------------------------------------------------

def _yaml_module():
    try:
        import yaml
        return yaml
    except ImportError:
        raise IllegalArgumentError(
            "content type [application/yaml] is not supported in this "
            "build (PyYAML not installed)") from None


def _yaml_dumps(obj: Any) -> bytes:
    yaml = _yaml_module()
    return yaml.safe_dump(obj, sort_keys=False,
                          default_flow_style=False).encode("utf-8")


def _yaml_loads(data: bytes) -> Any:
    yaml = _yaml_module()
    try:
        return yaml.safe_load(data.decode("utf-8"))
    except (yaml.YAMLError, UnicodeDecodeError) as e:
        raise ParsingError(f"failed to parse YAML: {e}") from None


_CODECS: Dict[str, _Codec] = {
    XContentType.JSON: _Codec(lambda o: json.dumps(o, separators=(",", ":")).encode("utf-8"), _json_loads),
    XContentType.CBOR: _Codec(
        lambda o: bytes(memoryview(_encode_cbor_root(o))),
        lambda d: _cbor_decode_root(d),
    ),
    XContentType.SMILE: _Codec(_smile_dumps, _smile_loads),
    XContentType.YAML: _Codec(_yaml_dumps, _yaml_loads),
}


def _cbor_decode_root(data: bytes) -> Any:
    value, pos = _cbor_decode(data, 0)
    if pos != len(data):
        raise ParsingError(f"trailing bytes after CBOR value ({len(data) - pos} extra)")
    return value


def _encode_cbor_root(obj: Any) -> bytearray:
    out = bytearray()
    _cbor_encode(obj, out)
    return out


def dumps(obj: Any, content_type: str = XContentType.JSON) -> bytes:
    codec = _CODECS.get(content_type)
    if codec is None:
        raise IllegalArgumentError(f"content type [{content_type}] is not supported in this build")
    return codec.dumps(obj)


def loads(data: bytes, content_type: str = XContentType.JSON) -> Any:
    codec = _CODECS.get(content_type)
    if codec is None:
        raise IllegalArgumentError(f"content type [{content_type}] is not supported in this build")
    return codec.loads(data)


def loads_auto(data: bytes) -> Any:
    """Sniff JSON vs CBOR (reference: XContentFactory.xContentType).

    Any byte that can start a JSON document (object, array, string, number,
    literal, leading whitespace) routes to JSON; only bytes impossible as
    JSON starters fall through to CBOR. Note CBOR documents whose first byte
    is also a JSON starter (e.g. a bare CBOR int < 24) must be passed with an
    explicit content type — the same ambiguity the reference resolves via the
    Content-Type header.
    """
    if data.startswith(b":)\n"):  # SMILE magic (XContentFactory checks it)
        return loads(data, XContentType.SMILE)
    if data.startswith(b"---"):   # YAML document marker
        return loads(data, XContentType.YAML)
    first = data[:1]
    if first and (first in b'{["-tfn' or first.isdigit() or first.isspace()):
        return loads(data, XContentType.JSON)
    return loads(data, XContentType.CBOR)


# ---------------------------------------------------------------------------
# ObjectParser — declarative request parsing
# ---------------------------------------------------------------------------

class ObjectParser:
    """Declarative dict→object parser (reference: ObjectParser.java).

    Fields are declared with a setter and the parser walks a decoded dict,
    raising on unknown fields unless `ignore_unknown` is set — matching the
    strict parsing the reference applies to request bodies.
    """

    def __init__(self, name: str, ctor: Callable[[], Any], ignore_unknown: bool = False):
        self.name = name
        self._ctor = ctor
        self._fields: Dict[str, Callable[[Any, Any], None]] = {}
        self._ignore_unknown = ignore_unknown

    def declare_field(self, field: str, setter: Callable[[Any, Any], None]) -> "ObjectParser":
        self._fields[field] = setter
        return self

    def parse(self, source: Dict[str, Any]) -> Any:
        if not isinstance(source, dict):
            raise ParsingError(f"[{self.name}] expected an object, got {type(source).__name__}")
        obj = self._ctor()
        for key, value in source.items():
            setter = self._fields.get(key)
            if setter is None:
                if self._ignore_unknown:
                    continue
                raise ParsingError(f"[{self.name}] unknown field [{key}]")
            setter(obj, value)
        return obj
