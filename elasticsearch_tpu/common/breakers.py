"""Hierarchical circuit breakers.

Reference: `indices/breaker/HierarchyCircuitBreakerService.java:47` — child
breakers (request, fielddata, in_flight_requests, accounting) account
estimated memory against per-breaker limits, and every child addition also
checks the parent's total. Tripping raises a 429 CircuitBreakingException.
"""

from __future__ import annotations

import threading
from typing import Dict

from elasticsearch_tpu.common.errors import SearchEngineError


class CircuitBreakingError(SearchEngineError):
    status = 429

    @property
    def error_type(self) -> str:
        return "circuit_breaking_exception"


class ChildBreaker:
    def __init__(self, name: str, limit_bytes: int, overhead: float = 1.0):
        self.name = name
        self.limit = limit_bytes
        self.overhead = overhead
        self.used = 0
        self.trip_count = 0

    def stats(self) -> dict:
        return {"limit_size_in_bytes": self.limit,
                "estimated_size_in_bytes": self.used,
                "overhead": self.overhead,
                "tripped": self.trip_count}


class HierarchyCircuitBreakerService:
    """Parent limit defaults to 95% of a nominal heap; children as in
    `HierarchyCircuitBreakerService` defaults (request 60%, fielddata 40%,
    in_flight 100%, accounting 100%)."""

    def __init__(self, total_limit: int = 1 << 31):  # nominal 2 GB "heap"
        self.parent_limit = int(total_limit * 0.95)
        self.parent_trip_count = 0
        self._lock = threading.Lock()
        self.breakers: Dict[str, ChildBreaker] = {
            "request": ChildBreaker("request", int(total_limit * 0.6)),
            "fielddata": ChildBreaker("fielddata", int(total_limit * 0.4),
                                      overhead=1.03),
            "in_flight_requests": ChildBreaker("in_flight_requests",
                                               total_limit, overhead=2.0),
            "accounting": ChildBreaker("accounting", total_limit),
        }

    def add_estimate(self, breaker: str, bytes_: int, label: str = "") -> None:
        with self._lock:
            child = self.breakers[breaker]
            new_used = child.used + int(bytes_ * child.overhead)
            if bytes_ > 0 and new_used > child.limit:
                child.trip_count += 1
                raise CircuitBreakingError(
                    f"[{breaker}] Data too large, data for [{label}] would be "
                    f"[{new_used}/{new_used}b], which is larger than the limit "
                    f"of [{child.limit}/{child.limit}b]",
                    bytes_wanted=new_used, bytes_limit=child.limit,
                    durability="TRANSIENT")
            total = sum(b.used for b in self.breakers.values()) + \
                int(bytes_ * child.overhead)
            if bytes_ > 0 and total > self.parent_limit:
                self.parent_trip_count += 1
                raise CircuitBreakingError(
                    f"[parent] Data too large, data for [{label}] would be "
                    f"[{total}b], which is larger than the limit of "
                    f"[{self.parent_limit}b]",
                    bytes_wanted=total, bytes_limit=self.parent_limit,
                    durability="TRANSIENT")
            child.used = max(0, new_used)

    def release(self, breaker: str, bytes_: int) -> None:
        with self._lock:
            child = self.breakers[breaker]
            child.used = max(0, child.used - int(bytes_ * child.overhead))

    def stats(self) -> dict:
        out = {name: b.stats() for name, b in self.breakers.items()}
        out["parent"] = {"limit_size_in_bytes": self.parent_limit,
                         "estimated_size_in_bytes":
                         sum(b.used for b in self.breakers.values()),
                         "overhead": 1.0, "tripped": self.parent_trip_count}
        return out
