"""Named per-workload thread pools with bounded queues and rejection.

Re-design of `threadpool/ThreadPool.java:115-180` + `EsThreadPoolExecutor`:
every workload class gets its own executor so a flood of one request type
cannot starve the others — searches queue behind searches, never behind
bulk indexing. Fixed pools have a hard queue bound and REJECT above it
(the request-level backpressure that keeps an overloaded node answering
429s instead of melting); scaling pools grow to a cap and queue unbounded
(management work must never be dropped).

The compute hot path runs on the accelerator regardless — these pools
schedule the host-side request work (engine writes, postings scoring,
fetches), exactly the role the reference's executors play around Lucene.

| pool             | type    | size                | queue |
|------------------|---------|---------------------|-------|
| search           | fixed   | 1.5*cores + 1       | 1000  |
| write            | fixed   | cores               | 10000 |
| get              | fixed   | cores               | 1000  |
| analyze          | fixed   | 1                   | 16    |
| search_throttled | fixed   | 1                   | 100   |
| force_merge      | fixed   | 1                   | unbounded |
| generic          | scaling | 4..max(128, cores*4)| -     |
| management       | scaling | 1..5                | -     |
| flush/refresh    | scaling | 1..cores/2          | -     |
| snapshot         | scaling | 1..cores/2          | -     |
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
from typing import Any, Callable, Dict, Optional

from elasticsearch_tpu.common.errors import SearchEngineError


class EsRejectedExecutionError(SearchEngineError):
    """Queue full: the caller gets backpressure (HTTP 429)."""

    status = 429

    def to_dict(self):
        return {"type": "es_rejected_execution_exception",
                "reason": str(self)}


def _cores() -> int:
    return os.cpu_count() or 4


_UNBOUNDED = -1


class NamedExecutor:
    """One workload's executor with explicit queue accounting: the backing
    stdlib executor queues unboundedly, so the bound is enforced by counting
    submitted-but-unfinished tasks (EsThreadPoolExecutor + SizeBlockingQueue
    semantics)."""

    def __init__(self, name: str, threads: int, queue_size: int,
                 pool_type: str = "fixed"):
        self.name = name
        self.threads = threads
        self.queue_size = queue_size
        self.pool_type = pool_type
        self._lock = threading.Lock()
        self.active = 0
        self.queued = 0
        self.completed = 0
        self.rejected = 0
        self.largest = 0
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix=f"es[{name}]")

    def submit(self, fn: Callable, *args, **kwargs) -> concurrent.futures.Future:
        with self._lock:
            if self.queue_size != _UNBOUNDED and self.queued >= self.queue_size:
                self.rejected += 1
                raise EsRejectedExecutionError(
                    f"rejected execution on [{self.name}]: queue capacity "
                    f"[{self.queue_size}] is full")
            self.queued += 1

        def run():
            with self._lock:
                self.queued -= 1
                self.active += 1
                self.largest = max(self.largest, self.active)
            try:
                return fn(*args, **kwargs)
            finally:
                with self._lock:
                    self.active -= 1
                    self.completed += 1

        try:
            return self._executor.submit(run)
        except RuntimeError:
            with self._lock:
                self.queued -= 1
                self.rejected += 1
            raise EsRejectedExecutionError(
                f"[{self.name}] executor is shut down")

    def stats(self) -> dict:
        with self._lock:
            return {"threads": self.threads,
                    "queue": self.queued,
                    "active": self.active,
                    "rejected": self.rejected,
                    "largest": self.largest,
                    "completed": self.completed}

    def shutdown(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)


def _default_pools() -> Dict[str, tuple]:
    c = _cores()
    half = max(1, c // 2)
    return {
        # name: (threads, queue_size, type) — ThreadPool.java:164-180 sizes
        "search": (int(c * 1.5) + 1, 1000, "fixed"),
        "write": (c, 10000, "fixed"),
        "get": (c, 1000, "fixed"),
        "analyze": (1, 16, "fixed"),
        "search_throttled": (1, 100, "fixed"),
        "force_merge": (1, _UNBOUNDED, "fixed"),
        "generic": (max(128, c * 4), _UNBOUNDED, "scaling"),
        "management": (5, _UNBOUNDED, "scaling"),
        "flush": (half, _UNBOUNDED, "scaling"),
        "refresh": (half, _UNBOUNDED, "scaling"),
        "snapshot": (half, _UNBOUNDED, "scaling"),
        "fetch_shard_started": (2 * c, _UNBOUNDED, "scaling"),
        "fetch_shard_store": (2 * c, _UNBOUNDED, "scaling"),
        "listener": (half, _UNBOUNDED, "scaling"),
    }


class ThreadPool:
    """The node's executor registry (`threadpool/ThreadPool.java`).

    Executors spin up lazily — an idle node holds no worker threads for
    pools it never uses. `settings` may override sizes via
    `thread_pool.<name>.{size,queue_size}`.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._specs = _default_pools()
        settings = settings or {}
        for name in list(self._specs):
            threads, queue, ptype = self._specs[name]
            threads = int(settings.get(f"thread_pool.{name}.size", threads))
            queue = int(settings.get(f"thread_pool.{name}.queue_size", queue))
            self._specs[name] = (threads, queue, ptype)
        self._pools: Dict[str, NamedExecutor] = {}
        self._lock = threading.Lock()

    def executor(self, name: str) -> NamedExecutor:
        pool = self._pools.get(name)
        if pool is not None:
            return pool
        with self._lock:
            pool = self._pools.get(name)
            if pool is None:
                spec = self._specs.get(name)
                if spec is None:
                    raise SearchEngineError(f"no thread pool named [{name}]")
                pool = NamedExecutor(name, spec[0], spec[1], spec[2])
                self._pools[name] = pool
            return pool

    def submit(self, name: str, fn: Callable, *args, **kwargs):
        return self.executor(name).submit(fn, *args, **kwargs)

    def stats(self) -> Dict[str, dict]:
        out = {}
        for name in sorted(self._specs):
            pool = self._pools.get(name)
            if pool is not None:
                out[name] = pool.stats()
            else:
                threads, queue, _ = self._specs[name]
                out[name] = {"threads": 0, "queue": 0, "active": 0,
                             "rejected": 0, "largest": 0, "completed": 0}
        return out

    def info(self) -> Dict[str, dict]:
        return {name: {"type": ptype, "size": threads,
                       "queue_size": queue if queue != _UNBOUNDED else -1}
                for name, (threads, queue, ptype) in sorted(self._specs.items())}

    def shutdown(self) -> None:
        for pool in self._pools.values():
            pool.shutdown()


# route → workload classification (the reference maps each TransportAction
# to its executor; here the REST route prefix decides)
def pool_for_route(method: str, path: str) -> str:
    p = path.split("?")[0]
    if "/_search" in p or "/_count" in p or "/_msearch" in p \
            or "/_knn_search" in p or "/_async_search" in p \
            or "/_field_caps" in p or "/_validate" in p or "/_explain" in p:
        return "search"
    if "/_analyze" in p:
        return "analyze"
    if "/_bulk" in p or "/_update" in p or "/_delete_by_query" in p \
            or "/_update_by_query" in p or "/_reindex" in p:
        return "write"
    if "/_doc" in p or "/_create" in p or "/_source" in p:
        return "write" if method in ("PUT", "POST", "DELETE") else "get"
    if "/_mget" in p or "/_termvectors" in p:
        return "get"
    if "/_cat" in p or "/_cluster" in p or "/_nodes" in p or "/_tasks" in p:
        return "management"
    if "/_snapshot" in p:
        return "snapshot"
    if "/_flush" in p:
        return "flush"
    if "/_refresh" in p:
        return "refresh"
    if "/_forcemerge" in p:
        return "force_merge"
    return "generic"
