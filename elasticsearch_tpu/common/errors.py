"""Exception hierarchy.

Parallels the reference's ElasticsearchException tree
(`server/src/main/java/org/elasticsearch/ElasticsearchException.java`) with the
subset of status-carrying exceptions the REST layer needs. Each exception maps
to an HTTP status so RestController can render structured error bodies.
"""

from __future__ import annotations


class SearchEngineError(Exception):
    """Base of all framework errors. Carries an HTTP status for the REST layer."""

    status = 500

    def __init__(self, message: str = "", **metadata):
        super().__init__(message)
        self.message = message
        self.metadata = metadata

    @property
    def error_type(self) -> str:
        # e.g. IndexNotFoundError -> index_not_found_exception, matching the
        # reference's snake_cased exception names in REST error bodies.
        name = type(self).__name__
        if name.endswith("Error"):
            name = name[: -len("Error")]
        out = []
        for i, ch in enumerate(name):
            if ch.isupper() and i > 0:
                out.append("_")
            out.append(ch.lower())
        return "".join(out) + "_exception"

    def to_dict(self) -> dict:
        d = {"type": self.error_type, "reason": self.message}
        d.update(self.metadata)
        return d

    def to_wrapped_dict(self) -> dict:
        """Top-level error shape with the root_cause chain (the REST layer
        and per-response msearch errors use this; per-ITEM bulk/mget errors
        stay bare, matching the reference)."""
        inner = self.to_dict()
        return {**inner, "root_cause": [dict(inner)]}


class IllegalArgumentError(SearchEngineError):
    status = 400


class SnapshotMissingError(SearchEngineError):
    status = 404


class ActionRequestValidationError(SearchEngineError):
    status = 400


class InvalidIndexNameError(SearchEngineError):
    status = 400


class IllegalStateError(SearchEngineError):
    status = 500


class ParseError(SearchEngineError):
    status = 400


class ParsingError(SearchEngineError):
    status = 400


class MapperParsingError(SearchEngineError):
    status = 400


class ValidationError(SearchEngineError):
    status = 400


class ActionRequestValidationError(SearchEngineError):
    """Aggregated request validation failures (reference:
    ActionRequestValidationException — "Validation Failed: 1: ...;")."""
    status = 400

    @classmethod
    def of(cls, failures) -> "ActionRequestValidationError":
        msg = "Validation Failed: " + " ".join(
            f"{i + 1}: {m};" for i, m in enumerate(failures))
        return cls(msg)


class ResourceNotFoundError(SearchEngineError):
    status = 404


class SearchContextMissingError(SearchEngineError):
    """Expired/unknown scroll or PIT context
    (SearchContextMissingException)."""
    status = 404


class IndexNotFoundError(ResourceNotFoundError):
    status = 404

    def __init__(self, index: str):
        super().__init__(f"no such index [{index}]", index=index)
        self.index = index


class DocumentMissingError(ResourceNotFoundError):
    status = 404


class ResourceAlreadyExistsError(SearchEngineError):
    status = 400


class VersionConflictError(SearchEngineError):
    """Optimistic concurrency failure (seq_no/primary_term or version mismatch).

    Reference: `index/engine/VersionConflictEngineException.java`.
    """

    @property
    def error_type(self) -> str:
        # the engine-layer name the REST layer exposes
        return "version_conflict_engine_exception"

    status = 409


class TooManyBucketsError(SearchEngineError):
    """search.max_buckets exceeded (MultiBucketConsumerService)."""
    status = 503


class CircuitBreakingError(SearchEngineError):
    status = 429


class NodeNotConnectedError(SearchEngineError):
    status = 503


class MasterNotDiscoveredError(SearchEngineError):
    status = 503


class ClusterBlockError(SearchEngineError):
    status = 503


class IndexClosedError(SearchEngineError):
    """Operation against a closed index (IndexClosedException)."""

    status = 400


class TaskCancelledError(SearchEngineError):
    status = 400


class QueryShardError(SearchEngineError):
    """Query cannot execute against this shard's mappings (reference:
    QueryShardException — e.g. `exists` on [_source])."""

    status = 400


class ArrayIndexOutOfBoundsError(SearchEngineError):
    """Shard-level execution failure inside an aggregator — notably HDR
    percentiles collecting a negative value (the reference's DoubleHistogram
    throws ArrayIndexOutOfBoundsException and fails the shard, Ref
    `HDRPercentilesAggregator`). Execution-class: coordinators record it as
    a per-shard failure instead of failing the whole request."""

    status = 500


class SearchPhaseExecutionError(SearchEngineError):
    status = 503

    def __init__(self, phase: str, message: str, shard_failures=()):
        super().__init__(message, phase=phase)
        self.phase = phase
        self.shard_failures = list(shard_failures)
