"""Versioned binary wire format.

Re-design of the reference's hand-rolled serialization
(`common/io/stream/StreamOutput.java:87`, `StreamInput.java`,
`NamedWriteableRegistry`): variable-length ints, length-prefixed UTF-8
strings, typed generic values, and named-writeable polymorphism. Every
stream carries the wire version negotiated at handshake so readers can
branch on `version` for backwards compatibility.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional

from elasticsearch_tpu.common.errors import SearchEngineError
from elasticsearch_tpu.version import WIRE_VERSION


class StreamOutput:
    def __init__(self, version: int = WIRE_VERSION):
        self.version = version
        self._buf = bytearray()

    def bytes(self) -> bytes:
        return bytes(self._buf)

    def __len__(self):
        return len(self._buf)

    # -- primitives ----------------------------------------------------------
    def write_byte(self, b: int) -> None:
        self._buf.append(b & 0xFF)

    def write_bytes(self, data: bytes) -> None:
        self._buf.extend(data)

    def write_boolean(self, v: bool) -> None:
        self._buf.append(1 if v else 0)

    def write_int(self, v: int) -> None:
        self._buf.extend(struct.pack(">i", v))

    def write_long(self, v: int) -> None:
        self._buf.extend(struct.pack(">q", v))

    def write_float(self, v: float) -> None:
        self._buf.extend(struct.pack(">f", v))

    def write_double(self, v: float) -> None:
        self._buf.extend(struct.pack(">d", v))

    def write_vint(self, v: int) -> None:
        # LEB128-style varint over zig-zagged negatives kept out: reference
        # writeVInt requires non-negative; use write_zlong for signed.
        if v < 0:
            raise SearchEngineError(f"negative vint {v}")
        while v >= 0x80:
            self._buf.append((v & 0x7F) | 0x80)
            v >>= 7
        self._buf.append(v)

    def write_vlong(self, v: int) -> None:
        self.write_vint(v)

    def write_zlong(self, v: int) -> None:
        self.write_vint((v << 1) ^ (v >> 63) if v >= 0 else ((-v) << 1) - 1 | 1)

    def write_string(self, s: str) -> None:
        b = s.encode("utf-8")
        self.write_vint(len(b))
        self._buf.extend(b)

    def write_optional_string(self, s: Optional[str]) -> None:
        self.write_boolean(s is not None)
        if s is not None:
            self.write_string(s)

    def write_byte_array(self, data: bytes) -> None:
        self.write_vint(len(data))
        self._buf.extend(data)

    def write_string_list(self, items: List[str]) -> None:
        self.write_vint(len(items))
        for s in items:
            self.write_string(s)

    # -- generic (tagged) values --------------------------------------------
    def write_generic(self, v: Any) -> None:
        if v is None:
            self.write_byte(0)
        elif isinstance(v, bool):
            self.write_byte(1); self.write_boolean(v)
        elif isinstance(v, int):
            self.write_byte(2); self.write_zlong(v)
        elif isinstance(v, float):
            self.write_byte(3); self.write_double(v)
        elif isinstance(v, str):
            self.write_byte(4); self.write_string(v)
        elif isinstance(v, bytes):
            self.write_byte(5); self.write_byte_array(v)
        elif isinstance(v, (list, tuple)):
            self.write_byte(6); self.write_vint(len(v))
            for item in v:
                self.write_generic(item)
        elif isinstance(v, dict):
            self.write_byte(7); self.write_vint(len(v))
            for k, item in v.items():
                self.write_string(str(k))
                self.write_generic(item)
        else:
            raise SearchEngineError(f"cannot serialize type [{type(v).__name__}]")

    def write_named_writeable(self, obj: "NamedWriteable") -> None:
        self.write_string(obj.writeable_name())
        obj.write_to(self)


class StreamInput:
    def __init__(self, data: bytes, version: int = WIRE_VERSION,
                 registry: Optional["NamedWriteableRegistry"] = None):
        self.version = version
        self._data = memoryview(data)
        self._pos = 0
        self._registry = registry

    def remaining(self) -> int:
        return len(self._data) - self._pos

    def _take(self, n: int) -> memoryview:
        if self._pos + n > len(self._data):
            raise SearchEngineError("stream truncated")
        out = self._data[self._pos:self._pos + n]
        self._pos += n
        return out

    def read_byte(self) -> int:
        return self._take(1)[0]

    def read_bytes(self, n: int) -> bytes:
        return bytes(self._take(n))

    def read_boolean(self) -> bool:
        return self.read_byte() != 0

    def read_int(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def read_long(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def read_float(self) -> float:
        return struct.unpack(">f", self._take(4))[0]

    def read_double(self) -> float:
        return struct.unpack(">d", self._take(8))[0]

    def read_vint(self) -> int:
        shift = 0
        result = 0
        while True:
            b = self.read_byte()
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result
            shift += 7
            if shift > 63:
                # reference StreamInput caps vint/vlong width; unbounded
                # varints from untrusted input become giant allocations
                raise SearchEngineError("variable-length int is too long")

    def read_vlong(self) -> int:
        return self.read_vint()

    def read_zlong(self) -> int:
        v = self.read_vint()
        return (v >> 1) ^ -(v & 1)

    def read_string(self) -> str:
        n = self.read_vint()
        try:
            return bytes(self._take(n)).decode("utf-8")
        except UnicodeDecodeError as e:
            raise SearchEngineError(f"malformed UTF-8 string on stream: {e}") from None

    def read_optional_string(self) -> Optional[str]:
        return self.read_string() if self.read_boolean() else None

    def read_byte_array(self) -> bytes:
        return self.read_bytes(self.read_vint())

    def read_string_list(self) -> List[str]:
        return [self.read_string() for _ in range(self.read_vint())]

    def read_generic(self) -> Any:
        tag = self.read_byte()
        if tag == 0:
            return None
        if tag == 1:
            return self.read_boolean()
        if tag == 2:
            return self.read_zlong()
        if tag == 3:
            return self.read_double()
        if tag == 4:
            return self.read_string()
        if tag == 5:
            return self.read_byte_array()
        if tag == 6:
            return [self.read_generic() for _ in range(self.read_vint())]
        if tag == 7:
            return {self.read_string(): self.read_generic() for _ in range(self.read_vint())}
        raise SearchEngineError(f"unknown generic tag [{tag}]")

    def read_named_writeable(self, category: type) -> Any:
        if self._registry is None:
            raise SearchEngineError("no NamedWriteableRegistry attached to stream")
        name = self.read_string()
        reader = self._registry.get_reader(category, name)
        return reader(self)


class NamedWriteable:
    """Polymorphic wire object (reference: NamedWriteable.java)."""

    def writeable_name(self) -> str:
        raise NotImplementedError

    def write_to(self, out: StreamOutput) -> None:
        raise NotImplementedError


class NamedWriteableRegistry:
    def __init__(self):
        self._readers: Dict[tuple, Callable[[StreamInput], Any]] = {}

    def register(self, category: type, name: str, reader: Callable[[StreamInput], Any]) -> None:
        key = (category, name)
        if key in self._readers:
            raise SearchEngineError(f"duplicate named writeable [{category.__name__}/{name}]")
        self._readers[key] = reader

    def get_reader(self, category: type, name: str) -> Callable[[StreamInput], Any]:
        reader = self._readers.get((category, name))
        if reader is None:
            raise SearchEngineError(f"unknown named writeable [{category.__name__}/{name}]")
        return reader
