"""Typed, validated, dynamic settings registry.

Re-design of the reference's settings system (§5.6 of SURVEY.md):
`common/settings/Setting.java` (typed Setting<T> with NodeScope/IndexScope/
Dynamic properties), `Settings.java` (flat string map), and
`AbstractScopedSettings` (dynamic-update appliers). Kept deliberately small:
a Setting knows how to parse + validate its value from a flat map; scoped
registries (ClusterSettings / IndexScopedSettings) validate maps and dispatch
update consumers on dynamic changes.
"""

from __future__ import annotations

import enum
import re
from typing import Any, Callable, Dict, Generic, Iterable, Optional, TypeVar

from elasticsearch_tpu.common.errors import IllegalArgumentError

T = TypeVar("T")

_TIME_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(nanos|micros|ms|s|m|h|d)$")
_BYTES_RE = re.compile(r"^(-?\d+(?:\.\d+)?)(b|kb|mb|gb|tb|pb)?$")
_TIME_FACTORS = {"nanos": 1e-9, "micros": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}
_BYTE_FACTORS = {"b": 1, "kb": 1024, "mb": 1024**2, "gb": 1024**3, "tb": 1024**4, "pb": 1024**5}


def setting_bool(value: Any, default: bool = False) -> bool:
    """Boolean coercion with yml-style strings ("false" is False)."""
    if value is None:
        return default
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() in ("true", "1", "yes", "on")


def parse_time_value(value: Any, setting_name: str = "") -> float:
    """Parse '30s' / '500ms' / '-1' into seconds (reference: TimeValue.java)."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip().lower()
    if s in ("-1", "0"):
        return float(s)
    m = _TIME_RE.match(s)
    if not m or float(m.group(1)) < 0:
        # only the -1 sentinel may be negative (reference: TimeValue)
        raise IllegalArgumentError(f"failed to parse setting [{setting_name}] with value [{value}] as a time value")
    return float(m.group(1)) * _TIME_FACTORS[m.group(2)]


def parse_byte_size(value: Any, setting_name: str = "") -> int:
    """Parse '512mb' / '2gb' into bytes (reference: ByteSizeValue.java)."""
    if isinstance(value, int):
        return value
    s = str(value).strip().lower()
    m = _BYTES_RE.match(s)
    if not m:
        raise IllegalArgumentError(f"failed to parse setting [{setting_name}] with value [{value}] as a byte size")
    return int(float(m.group(1)) * _BYTE_FACTORS[m.group(2) or "b"])


class Property(enum.Flag):
    NODE_SCOPE = enum.auto()
    INDEX_SCOPE = enum.auto()
    DYNAMIC = enum.auto()
    FINAL = enum.auto()
    DEPRECATED = enum.auto()
    FILTERED = enum.auto()  # hidden from APIs (secrets)


class Setting(Generic[T]):
    def __init__(
        self,
        key: str,
        default: Any,
        parser: Callable[[Any], T],
        *properties: Property,
        validator: Optional[Callable[[T], None]] = None,
    ):
        self.key = key
        self._default = default
        self._parser = parser
        self.properties = Property(0)
        for p in properties:
            self.properties |= p
        self._validator = validator
        if (self.properties & Property.DYNAMIC) and (self.properties & Property.FINAL):
            raise IllegalArgumentError(f"setting [{key}] cannot be both dynamic and final")

    # -- factory helpers mirroring Setting.intSetting / boolSetting / etc. ----
    @staticmethod
    def bool_setting(key: str, default: bool, *props: Property) -> "Setting[bool]":
        def parse(v):
            if isinstance(v, bool):
                return v
            s = str(v).lower()
            if s in ("true", "1"):
                return True
            if s in ("false", "0"):
                return False
            raise IllegalArgumentError(f"cannot parse boolean [{v}] for setting [{key}]")

        return Setting(key, default, parse, *props)

    @staticmethod
    def int_setting(key: str, default: int, *props: Property, min_value: Optional[int] = None,
                    max_value: Optional[int] = None) -> "Setting[int]":
        def validate(v: int):
            if min_value is not None and v < min_value:
                raise IllegalArgumentError(f"failed to parse value [{v}] for setting [{key}] must be >= {min_value}")
            if max_value is not None and v > max_value:
                raise IllegalArgumentError(f"failed to parse value [{v}] for setting [{key}] must be <= {max_value}")

        return Setting(key, default, lambda v: int(v), *props, validator=validate)

    @staticmethod
    def float_setting(key: str, default: float, *props: Property) -> "Setting[float]":
        return Setting(key, default, lambda v: float(v), *props)

    @staticmethod
    def string_setting(key: str, default: str = "", *props: Property) -> "Setting[str]":
        return Setting(key, default, str, *props)

    @staticmethod
    def time_setting(key: str, default: str, *props: Property) -> "Setting[float]":
        return Setting(key, default, lambda v: parse_time_value(v, key), *props)

    @staticmethod
    def byte_size_setting(key: str, default: str, *props: Property) -> "Setting[int]":
        return Setting(key, default, lambda v: parse_byte_size(v, key), *props)

    @staticmethod
    def list_setting(key: str, default: Iterable[str] = (), *props: Property) -> "Setting[list]":
        def parse(v):
            if isinstance(v, (list, tuple)):
                return list(v)
            return [p.strip() for p in str(v).split(",") if p.strip()]

        return Setting(key, list(default), parse, *props)

    @staticmethod
    def enum_setting(key: str, default: str, choices: Iterable[str], *props: Property) -> "Setting[str]":
        choice_set = set(choices)

        def validate(v: str):
            if v not in choice_set:
                raise IllegalArgumentError(f"unknown value [{v}] for setting [{key}], expected one of {sorted(choice_set)}")

        return Setting(key, default, str, *props, validator=validate)

    # -------------------------------------------------------------------------
    @property
    def dynamic(self) -> bool:
        return bool(self.properties & Property.DYNAMIC)

    def default(self, settings: "Settings") -> T:
        d = self._default(settings) if callable(self._default) else self._default
        return self._parser(d)

    def exists(self, settings: "Settings") -> bool:
        return self.key in settings

    def get(self, settings: "Settings") -> T:
        raw = settings.get(self.key)
        if raw is None:
            value = self.default(settings)
        else:
            value = self._parser(raw)
        if self._validator is not None:
            self._validator(value)
        return value


class Settings:
    """Immutable flat key→value map (reference: common/settings/Settings.java).

    Values may be scalars or lists; nested dicts flatten with dotted keys the
    way elasticsearch.yml does.
    """

    EMPTY: "Settings"

    def __init__(self, flat: Optional[Dict[str, Any]] = None):
        self._map: Dict[str, Any] = dict(flat or {})

    @staticmethod
    def of(obj: Optional[Dict[str, Any]] = None, **kwargs) -> "Settings":
        b = Settings.builder()
        if obj:
            b.put_dict(obj)
        for k, v in kwargs.items():
            b.put(k.replace("__", "."), v)
        return b.build()

    @staticmethod
    def builder() -> "SettingsBuilder":
        return SettingsBuilder()

    def get(self, key: str, default: Any = None) -> Any:
        return self._map.get(key, default)

    def keys(self):
        return self._map.keys()

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def __len__(self):
        return len(self._map)

    def __eq__(self, other):
        return isinstance(other, Settings) and self._map == other._map

    def __repr__(self):
        return f"Settings({self._map!r})"

    def as_flat_dict(self) -> Dict[str, Any]:
        return dict(self._map)

    def as_nested_dict(self) -> Dict[str, Any]:
        root: Dict[str, Any] = {}
        for key in sorted(self._map):
            parts = key.split(".")
            node = root
            ok = True
            for p in parts[:-1]:
                nxt = node.setdefault(p, {})
                if not isinstance(nxt, dict):
                    ok = False
                    break
                node = nxt
            if ok and isinstance(node, dict):
                node[parts[-1]] = self._map[key]
        return root

    def by_prefix(self, prefix: str) -> "Settings":
        return Settings({k[len(prefix):]: v for k, v in self._map.items() if k.startswith(prefix)})

    def filtered(self, predicate: Callable[[str], bool]) -> "Settings":
        return Settings({k: v for k, v in self._map.items() if predicate(k)})

    def merge(self, other: "Settings") -> "Settings":
        m = dict(self._map)
        m.update(other._map)
        return Settings(m)


class SettingsBuilder:
    def __init__(self):
        self._map: Dict[str, Any] = {}

    def put(self, key: str, value: Any) -> "SettingsBuilder":
        self._map[key] = value
        return self

    def put_dict(self, obj: Dict[str, Any], prefix: str = "") -> "SettingsBuilder":
        for k, v in obj.items():
            full = f"{prefix}{k}"
            if isinstance(v, dict):
                self.put_dict(v, prefix=full + ".")
            else:
                self._map[full] = v
        return self

    def put_settings(self, settings: Settings) -> "SettingsBuilder":
        self._map.update(settings.as_flat_dict())
        return self

    def remove(self, key: str) -> "SettingsBuilder":
        self._map.pop(key, None)
        return self

    def build(self) -> Settings:
        return Settings(self._map)


Settings.EMPTY = Settings()


class ScopedSettings:
    """Registry of known settings for a scope + dynamic-update dispatch.

    Reference: `common/settings/AbstractScopedSettings.java` — validates maps
    against registered settings and runs update consumers when dynamic values
    change (`ClusterSettings` for node scope, `IndexScopedSettings` for index
    scope).
    """

    def __init__(self, settings: Settings, registered: Iterable[Setting], scope: Property):
        self.scope = scope
        self._settings = settings
        self._registry: Dict[str, Setting] = {}
        for s in registered:
            if not (s.properties & scope):
                raise IllegalArgumentError(f"setting [{s.key}] is not registered for scope [{scope}]")
            if s.key in self._registry:
                raise IllegalArgumentError(f"duplicate setting [{s.key}]")
            self._registry[s.key] = s
        self._consumers: list = []  # (setting, callback)
        self._applied = Settings.EMPTY

    def register(self, setting: Setting) -> None:
        if not (setting.properties & self.scope):
            raise IllegalArgumentError(
                f"setting [{setting.key}] is not registered for scope [{self.scope}]")
        if setting.key in self._registry:
            raise IllegalArgumentError(f"duplicate setting [{setting.key}]")
        self._registry[setting.key] = setting

    def get_setting(self, key: str) -> Optional[Setting]:
        return self._registry.get(key)

    def get(self, setting: Setting):
        current = self._settings.merge(self._applied)
        return setting.get(current)

    def add_settings_update_consumer(self, setting: Setting, consumer: Callable[[Any], None]) -> None:
        if not setting.dynamic:
            raise IllegalArgumentError(f"setting [{setting.key}] is not dynamic")
        self._consumers.append((setting, consumer))

    def validate(self, settings: Settings, *, for_update: bool = False) -> None:
        for key in settings.keys():
            s = self._registry.get(key)
            if s is None:
                # archived/unknown settings are rejected, matching
                # AbstractScopedSettings#validate's unknown-setting error.
                raise IllegalArgumentError(f"unknown setting [{key}]")
            if for_update and not s.dynamic:
                raise IllegalArgumentError(f"setting [{key}], not dynamically updateable")
            s.get(settings)  # parse + validate value

    def apply_settings(self, update: Settings) -> Settings:
        """Apply a dynamic settings update, firing consumers whose value changed."""
        self.validate(update, for_update=True)
        before = self._settings.merge(self._applied)
        self._applied = self._applied.merge(update)
        after = self._settings.merge(self._applied)
        for setting, consumer in self._consumers:
            old, new = setting.get(before), setting.get(after)
            if old != new:
                consumer(new)
        return after
