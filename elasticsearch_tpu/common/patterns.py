"""Comma-separated wildcard matching (the `a*,b?,c` request-parameter idiom
used by cat filters, snapshot expressions, and index selectors)."""

from __future__ import annotations

import fnmatch


def matches_csv_patterns(name: str, patterns) -> bool:
    """True when `name` matches any pattern. `patterns` may be None/empty
    (match everything), a comma-separated string, or a list of patterns."""
    if patterns in (None, "", "_all", "*"):
        return True
    if isinstance(patterns, str):
        patterns = patterns.split(",")
    return any(fnmatch.fnmatch(name, str(p).strip()) for p in patterns)
