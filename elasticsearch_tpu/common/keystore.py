"""Secure settings keystore.

Re-design of `common/settings/KeyStoreWrapper.java` + the `keystore-cli`
tool (SURVEY.md §5.6): an on-disk store of secret settings (passwords,
repository credentials) kept out of yml/env config, optionally protected
by a passphrase, loaded into the node's settings at boot under their
setting names.

Cipher construction (stdlib-only — no AES in the standard library):
PBKDF2-HMAC-SHA256 key derivation (200k iterations, random 16-byte salt),
a counter-mode keystream of HMAC-SHA256(key, nonce || counter) blocks
XORed over the JSON payload, and an encrypt-then-MAC HMAC-SHA256 integrity
tag over header+ciphertext. Like the reference's default, an empty
passphrase still encrypts (obfuscation + tamper detection) so secrets
never sit in plaintext on disk.

File layout: magic "TPKS" | version u8 | salt 16 | nonce 16 | mac 32 |
ciphertext.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
from typing import Dict, List, Optional

from elasticsearch_tpu.common.errors import IllegalArgumentError

_MAGIC = b"TPKS"
_VERSION = 2  # v2: separate encryption / MAC subkeys (encrypt-then-MAC)
_ITERATIONS = 200_000


def _derive_keys(password: str, salt: bytes) -> tuple:
    """(enc_key, mac_key): one PBKDF2 pass, then domain-separated subkeys —
    the keystream and the integrity tag must never share a key."""
    master = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"), salt,
                                 _ITERATIONS, dklen=32)
    enc = hmac.new(master, b"enc", hashlib.sha256).digest()
    mac = hmac.new(master, b"mac", hashlib.sha256).digest()
    return enc, mac


def _keystream_xor(key: bytes, nonce: bytes, data: bytes) -> bytes:
    out = bytearray()
    counter = 0
    for i in range(0, len(data), 32):
        block = hmac.new(key, nonce + counter.to_bytes(8, "big"),
                         hashlib.sha256).digest()
        chunk = data[i:i + 32]
        out.extend(b ^ k for b, k in zip(chunk, block))
        counter += 1
    return bytes(out)


class KeyStore:
    """In-memory secrets map with encrypted load/save."""

    def __init__(self, path: str, password: str = ""):
        self.path = path
        self._password = password
        self._secrets: Dict[str, str] = {}

    # --------------------------------------------------------------- file IO
    @classmethod
    def create(cls, path: str, password: str = "") -> "KeyStore":
        ks = cls(path, password)
        ks.save()
        return ks

    @classmethod
    def load(cls, path: str, password: str = "") -> "KeyStore":
        ks = cls(path, password)
        with open(path, "rb") as f:
            blob = f.read()
        if len(blob) < 4 + 1 + 16 + 16 + 32 or blob[:4] != _MAGIC:
            raise IllegalArgumentError(f"[{path}] is not a keystore file")
        version = blob[4]
        if version not in (1, _VERSION):
            raise IllegalArgumentError(
                f"unsupported keystore version [{version}]")
        salt = blob[5:21]
        nonce = blob[21:37]
        mac = blob[37:69]
        ciphertext = blob[69:]
        if version == 1:
            # legacy format: one PBKDF2 key for both keystream and MAC;
            # readable for migration — the next save() rewrites as v2
            master = hashlib.pbkdf2_hmac("sha256", password.encode("utf-8"),
                                         salt, _ITERATIONS, dklen=32)
            enc_key = mac_key = master
        else:
            enc_key, mac_key = _derive_keys(password, salt)
        expect = hmac.new(mac_key, blob[:37] + ciphertext,
                          hashlib.sha256).digest()
        if not hmac.compare_digest(mac, expect):
            raise IllegalArgumentError(
                "keystore password is incorrect or the file is corrupted")
        payload = _keystream_xor(enc_key, nonce, ciphertext)
        ks._secrets = json.loads(payload.decode("utf-8"))
        return ks

    @classmethod
    def load_or_create(cls, path: str, password: str = "") -> "KeyStore":
        if os.path.exists(path):
            return cls.load(path, password)
        return cls.create(path, password)

    def save(self) -> None:
        salt = secrets.token_bytes(16)
        nonce = secrets.token_bytes(16)
        enc_key, mac_key = _derive_keys(self._password, salt)
        payload = json.dumps(self._secrets).encode("utf-8")
        ciphertext = _keystream_xor(enc_key, nonce, payload)
        header = _MAGIC + bytes([_VERSION]) + salt + nonce
        mac = hmac.new(mac_key, header + ciphertext, hashlib.sha256).digest()
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(header + mac + ciphertext)
        os.replace(tmp, self.path)
        try:
            os.chmod(self.path, 0o600)
        except OSError:
            pass

    # --------------------------------------------------------------- secrets
    def set(self, name: str, value: str) -> None:
        _validate_setting_name(name)
        self._secrets[name] = str(value)

    def get(self, name: str) -> Optional[str]:
        return self._secrets.get(name)

    def remove(self, name: str) -> None:
        if name not in self._secrets:
            raise IllegalArgumentError(
                f"setting [{name}] does not exist in the keystore")
        del self._secrets[name]

    def list(self) -> List[str]:
        return sorted(self._secrets)

    def as_settings(self) -> Dict[str, str]:
        return dict(self._secrets)

    def change_password(self, new_password: str) -> None:
        self._password = new_password


def _validate_setting_name(name: str) -> None:
    if not name or not all(c.isalnum() or c in "._-" for c in name):
        raise IllegalArgumentError(
            f"invalid setting name [{name}]: only alphanumerics, '.', '_' "
            f"and '-' are allowed")


def load_node_keystore(settings: dict, data_path: str):
    """Resolve and load the node keystore by the standard conventions
    (path.keystore setting, else <data>/config/tpu_search.keystore;
    password from keystore.password setting or $KEYSTORE_PASSWORD).

    Returns None when no keystore file exists. Raises on load failure
    (wrong password, corrupt file): security configuration must fail
    CLOSED — booting without the secrets the operator stored would
    silently disable whatever they protect.
    """
    import os
    path = settings.get("path.keystore",
                        os.path.join(data_path, "config",
                                     "tpu_search.keystore"))
    if not os.path.exists(path):
        return None
    return KeyStore.load(path, str(settings.get(
        "keystore.password", os.environ.get("KEYSTORE_PASSWORD", ""))))
