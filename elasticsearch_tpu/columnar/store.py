"""Process-wide segment block store: cache, composition, row sources.

ONE per-segment, block-addressed columnar store under every columnar
consumer (the Lucene doc-values/codec layer ported host-side). Blocks
(`columnar/blocks.py`) are immutable per-(segment, field) extractions
keyed by segment fingerprint:

* extracted LAZILY once, on first use by ANY consumer — an append-only
  refresh therefore extracts only the delta segments, for the vector
  store, the agg columns, and the BM25 CSR alike (O(delta) end to end);
* cached against the Segment OBJECT through a weak reference, so a
  block is evicted exactly when the engine drops its segment (an engine
  merge/rewrite releases the old blocks with the old segments — no
  epoch bookkeeping, no leak);
* composed into reader-wide views by concatenation of block REFERENCES
  (`FieldRowsView`, `RowSource`) rather than eager memcpy — merges and
  device generations re-read live rows through the shared blocks
  instead of pinning private corpus-sized copies.

Every composition is classified (`cached` / `delta` / `full`) and
counted per field, which is what makes the O(delta) refresh claim a
counter (`_nodes/stats indices.columnar`, `profile.knn`/`profile.aggs`
`columnar` annotations, bench 9's `gate_delta_refresh`) instead of a
comment.

Thread contract: `_lock` guards the block index and all counters.
Extraction runs OUTSIDE the lock (it is host-heavy Python; holding the
lock would serialize unrelated consumers) with a last-wins install —
two racing extractors of the same block waste one extraction, never
serve torn data (blocks are immutable).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.columnar.blocks import (
    EncodedVectorBlock,
    PostingsBlock,
    SparsePostingsBlock,
    TokenVectorBlock,
    ValuesBlock,
    VectorBlock,
    extract_encoded_vector_block,
    extract_postings_block,
    extract_sparse_postings_block,
    extract_token_vector_block,
    extract_values_block,
    extract_vector_block,
    fingerprint,
)

_EXTRACTORS = {
    "vector": lambda view, field, variant: extract_vector_block(view, field),
    "values": extract_values_block,
    "postings": lambda view, field, variant: extract_postings_block(
        view, field),
    "sparse_postings": lambda view, field, variant:
        extract_sparse_postings_block(view, field),
    "tokens": extract_token_vector_block,
}


class _Absent:
    """Cached marker for a (segment, field) the segment does not carry
    (a vector field absent from this segment): without it every sync
    would re-walk the segment and re-count an extraction for a block
    that can never exist, inflating the extracts ledger in fully-cached
    steady state."""

    __slots__ = ("fingerprint",)
    nbytes = 0

    def __init__(self, fp: tuple):
        self.fingerprint = fp


class SegmentBlockStore:
    """The shared block cache + its accounting."""

    def __init__(self):
        self._lock = threading.RLock()
        # weakref.ref(segment) -> {(kind, field): block}; the ref's
        # callback evicts the whole entry when the engine drops the
        # segment (refs hash/compare by referent identity while alive)
        self._entries: Dict[weakref.ref, Dict[tuple, object]] = {}
        self._counters = {
            "hits": 0, "extracts": 0, "seeds": 0, "evictions": 0,
            "extract_nanos": 0, "evicted_bytes": 0,
            # reader-wide composition classification: every block cached
            # / some extracted (the append-only refresh shape) / all
            # extracted (first build or a full re-extraction)
            "compositions": {"cached": 0, "delta": 0, "full": 0},
        }
        # per-(field, kind) breakdown for _nodes/stats indices.columnar
        self._fields: Dict[Tuple[str, str], Dict[str, int]] = {}

    # ------------------------------------------------------------- blocks
    def block(self, view, field: str, kind: str, variant=None):
        """The (cached) block of `kind` for one SegmentView + field.
        Returns (block, cached) — block is None only for a vector kind
        on a segment without that field."""
        seg = view.segment
        fp = fingerprint(view, () if variant is None else (variant,))
        key = (kind, field)
        with self._lock:
            entry = self._entries.get(weakref.ref(seg))
            blk = entry.get(key) if entry is not None else None
            if blk is not None and blk.fingerprint == fp:
                self._count(field, kind, "hits")
                return (None if isinstance(blk, _Absent) else blk), True
        t0 = time.perf_counter_ns()
        blk = _EXTRACTORS[kind](view, field, variant)
        nanos = time.perf_counter_ns() - t0
        with self._lock:
            self._count(field, kind, "extracts")
            self._counters["extract_nanos"] += nanos
            self._fields.setdefault(
                (field, kind), _field_slot())["extract_nanos"] += nanos
            ref = weakref.ref(seg, self._evicted)
            # absent results cache too (as a fingerprinted marker), so
            # steady-state syncs hit instead of re-counting extractions
            self._entries.setdefault(ref, {})[key] = \
                blk if blk is not None else _Absent(fp)
        return blk, False

    def _count(self, field: str, kind: str, counter: str) -> None:
        self._counters[counter] += 1
        self._fields.setdefault((field, kind), _field_slot())[counter] += 1

    # ---------------------------------------------------- durable blocks
    def cached_blocks(self, seg) -> Dict[tuple, object]:
        """Every block currently cached for one segment, keyed by the
        store's (kind, field, ...) entry key — the recovery subsystem
        snapshots THESE so a restored shard seeds its caches instead of
        re-extracting/re-encoding. Absent markers are skipped (nothing
        to ship for a field the segment does not carry)."""
        with self._lock:
            entry = self._entries.get(weakref.ref(seg))
            if not entry:
                return {}
            return {key: blk for key, blk in entry.items()
                    if not isinstance(blk, _Absent)}

    def install(self, view, key: tuple, blk) -> bool:
        """Install one restored block for a live SegmentView under its
        original entry key, VERIFIED against the view: the block's
        fingerprint must name this exact segment state (seg_id, size,
        live count) or the install is refused — restored derived state
        never outranks the restored source of truth. Returns True when
        installed (counted as a `seeds`, not an extract)."""
        seg = view.segment
        fp = getattr(blk, "fingerprint", None)
        if fp is None or tuple(fp[:3]) != fingerprint(view, ()):
            return False
        kind, field = key[0], key[1]
        with self._lock:
            self._count(field, kind, "seeds")
            ref = weakref.ref(seg, self._evicted)
            self._entries.setdefault(ref, {})[tuple(key)] = blk
        return True

    def _evicted(self, ref) -> None:
        """Weakref callback: the engine dropped a segment — release its
        blocks and count them (the eviction half of 'extracted lazily
        once, evicted with the segment')."""
        with self._lock:
            entry = self._entries.pop(ref, None)
            if not entry:
                return
            self._counters["evictions"] += len(entry)
            self._counters["evicted_bytes"] += sum(
                b.nbytes for b in entry.values())

    def note_composition(self, field: str, kind: str, n_cached: int,
                         n_extracted: int) -> str:
        """Classify one reader-wide composition for the delta-refresh
        ledger; returns the mode ("cached" / "delta" / "full") — the
        consumers put it in their `columnar_refresh` profile summaries.
        Zero-block compositions (empty reader) count as cached —
        nothing was extracted."""
        if n_extracted == 0:
            mode = "cached"
        elif n_cached > 0:
            mode = "delta"
        else:
            mode = "full"
        with self._lock:
            self._counters["compositions"][mode] += 1
            slot = self._fields.setdefault((field, kind), _field_slot())
            slot["compositions"][mode] += 1
        return mode

    # ------------------------------------------------------ compositions
    def vector_view(self, reader, field: str) -> "FieldRowsView":
        """Reader-wide view over one vector field: per-segment blocks
        (delta-extracted), composed by reference — the replacement for
        the retired O(corpus)-memcpy `extract_field_rows` loop. The
        row map is eagerly concatenated (8 B/row — the cheap half); the
        f32 matrix materializes only on demand (`matrix()` / `rows()` /
        `gather()`), which is what makes an append-only generational
        refresh O(delta) end to end."""
        blocks: List[VectorBlock] = []
        n_cached = n_extracted = 0
        for view in reader.views:
            blk, cached = self.block(view, field, "vector")
            # tally BEFORE skipping absent/empty blocks: the cached-vs-
            # extracted classification must reflect the extraction work
            # actually done, or an all-empty first composition would
            # misreport as "cached"
            if cached:
                n_cached += 1
            else:
                n_extracted += 1
            if blk is None or blk.n_rows == 0:
                continue
            blocks.append(blk)
        mode = self.note_composition(field, "vector", n_cached, n_extracted)
        return FieldRowsView(tuple(blocks), {
            "blocks": len(blocks), "cached": n_cached,
            "extracted": n_extracted, "mode": mode})

    def encoded_block(self, view, field: str, encoding: str, metric: str
                      ) -> Tuple[Optional[EncodedVectorBlock], bool]:
        """The codec-encoded block of one (segment, field) at one
        encoding variant — cached exactly like the f32 vector blocks
        (per segment fingerprint, evicted with the segment), so only
        delta segments re-encode on refresh and a dtype re-encode merge
        re-reads already-encoded tails for free. Feeds off the cached
        f32 block; returns (block | None, cached)."""
        seg = view.segment
        fp = fingerprint(view, (encoding, metric))
        key = ("vector_enc", field, encoding, metric)
        with self._lock:
            entry = self._entries.get(weakref.ref(seg))
            blk = entry.get(key) if entry is not None else None
            if blk is not None and blk.fingerprint == fp:
                self._count(field, "vector_enc", "hits")
                return (None if isinstance(blk, _Absent) else blk), True
        f32_block, _ = self.block(view, field, "vector")
        t0 = time.perf_counter_ns()
        blk = extract_encoded_vector_block(view, field, encoding, metric,
                                           f32_block)
        nanos = time.perf_counter_ns() - t0
        with self._lock:
            self._count(field, "vector_enc", "extracts")
            self._counters["extract_nanos"] += nanos
            self._fields.setdefault(
                (field, "vector_enc"), _field_slot())["extract_nanos"] \
                += nanos
            ref = weakref.ref(seg, self._evicted)
            self._entries.setdefault(ref, {})[key] = \
                blk if blk is not None else _Absent(fp)
        return blk, False

    def encoded_rows(self, reader, field: str, encoding: str, metric: str
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, str]:
        """Reader-wide codec-encoded rows for one vector field:
        (data [n, W] packed, scales [n] f32, row_map [n] engine rows,
        mode). Per-segment encoded blocks are delta-cached; rows encode
        independently, so this concatenation is byte-identical to
        encoding the whole matrix at once."""
        from elasticsearch_tpu.quant import codec as quant_codec
        blocks: List[EncodedVectorBlock] = []
        n_cached = n_extracted = 0
        for view in reader.views:
            blk, cached = self.encoded_block(view, field, encoding, metric)
            if cached:
                n_cached += 1
            else:
                n_extracted += 1
            if blk is None or blk.n_rows == 0:
                continue
            blocks.append(blk)
        mode = self.note_composition(field, "vector_enc", n_cached,
                                     n_extracted)
        if not blocks:
            codec = quant_codec.get(encoding)
            return (np.zeros((0, 0), dtype=codec.packed_np_dtype),
                    np.zeros(0, dtype=np.float32),
                    np.zeros(0, dtype=np.int64), mode)
        return (np.concatenate([b.data for b in blocks]),
                np.concatenate([b.scales for b in blocks]),
                np.concatenate([b.rows for b in blocks]), mode)

    def values_block(self, view, field: str, want_objs: bool
                     ) -> Tuple[ValuesBlock, bool]:
        return self.block(view, field, "values", variant=bool(want_objs))

    def postings_block(self, view, field: str
                       ) -> Tuple[PostingsBlock, bool]:
        return self.block(view, field, "postings")

    def sparse_postings_block(self, view, field: str
                              ) -> Tuple[SparsePostingsBlock, bool]:
        return self.block(view, field, "sparse_postings")

    def token_block(self, view, field: str, encoding: str, metric: str,
                    dims: int) -> Tuple[Optional[TokenVectorBlock], bool]:
        """The encoded token block of one (segment, field) at one
        (encoding, metric, dims) variant — delta-cached like the
        single-vector encoded blocks, evicted with the segment."""
        return self.block(view, field, "tokens",
                          variant=(encoding, metric, dims))

    # -------------------------------------------------------------- stats
    def stats(self) -> dict:
        """`_nodes/stats indices.columnar`: live block counts/bytes,
        cache hits / extractions (+ nanos) / evictions, and the
        delta-vs-full composition ledger — globally and per field."""
        with self._lock:
            live_blocks = 0
            live_bytes = 0
            zero_copy = 0
            per_key_live: Dict[Tuple[str, str], Tuple[int, int]] = {}
            for entry in self._entries.values():
                for key, blk in entry.items():
                    live_blocks += 1
                    live_bytes += blk.nbytes
                    if getattr(blk, "zero_copy", False):
                        zero_copy += 1
                    k = (key[1], key[0])
                    n, b = per_key_live.get(k, (0, 0))
                    per_key_live[k] = (n + 1, b + blk.nbytes)
            fields = {}
            for (field, kind), slot in sorted(self._fields.items()):
                n, b = per_key_live.get((field, kind), (0, 0))
                fields[f"{field}:{kind}"] = {
                    "blocks": n, "bytes": b, **{
                        k: (dict(v) if isinstance(v, dict) else v)
                        for k, v in slot.items()}}
            return {
                "blocks": live_blocks,
                "bytes": live_bytes,
                "zero_copy_blocks": zero_copy,
                "hits": self._counters["hits"],
                "extracts": self._counters["extracts"],
                "seeds": self._counters["seeds"],
                "extract_nanos": self._counters["extract_nanos"],
                "evictions": self._counters["evictions"],
                "evicted_bytes": self._counters["evicted_bytes"],
                "compositions": dict(self._counters["compositions"]),
                "fields": fields,
            }

    def reset(self) -> None:
        """Drop every cached block and zero the counters (tests)."""
        with self._lock:
            self._entries.clear()
            self._fields.clear()
            self._counters.update({
                "hits": 0, "extracts": 0, "seeds": 0, "evictions": 0,
                "extract_nanos": 0, "evicted_bytes": 0,
                "compositions": {"cached": 0, "delta": 0, "full": 0}})


def _field_slot() -> dict:
    return {"hits": 0, "extracts": 0, "seeds": 0, "extract_nanos": 0,
            "compositions": {"cached": 0, "delta": 0, "full": 0}}


# the process-wide store — one block per (segment, field, kind) serves
# every consumer on the node, like ops/dispatch.DISPATCH serves every
# kernel (all mutation inside SegmentBlockStore under its _lock)
STORE = SegmentBlockStore()


# ---------------------------------------------------------------------------
# row sources: shared-block host row providers
# ---------------------------------------------------------------------------


class _Part:
    """One contiguous source slice: rows `idx` of `matrix` (idx=None =
    the whole matrix). `shared` marks matrices owned by the block store
    / engine segments (NOT pinned by the holder) vs private arrays."""

    __slots__ = ("matrix", "idx", "shared")

    def __init__(self, matrix: np.ndarray, idx: Optional[np.ndarray],
                 shared: bool):
        self.matrix = matrix
        self.idx = idx
        self.shared = shared

    @property
    def n_rows(self) -> int:
        return len(self.matrix) if self.idx is None else len(self.idx)

    def take(self, local: np.ndarray) -> "_Part":
        """Narrow to `local` positions of THIS part (int64 ascending)."""
        idx = local if self.idx is None else self.idx[local]
        return _Part(self.matrix, idx, self.shared)

    def materialize(self) -> np.ndarray:
        m = self.matrix if self.idx is None else self.matrix[self.idx]
        return np.asarray(m, dtype=np.float32)


class RowSource:
    """Host vector rows resolved through shared column blocks instead of
    a pinned private copy — the merge scheduler's input shape. A device
    generation holds a RowSource; victim-gather / IVF retrain / mesh
    graduation `gather()` live rows on demand (transient, O(rows
    gathered)), so no generation ever retains a corpus-sized private
    `host_vectors` array for its lifetime."""

    __slots__ = ("parts", "n_rows", "dims")

    def __init__(self, parts: Sequence[_Part], dims: int):
        self.parts = tuple(p for p in parts if p.n_rows)
        self.n_rows = sum(p.n_rows for p in self.parts)
        self.dims = dims

    # ------------------------------------------------------- constructors
    @staticmethod
    def from_array(vectors: np.ndarray) -> "RowSource":
        """Private (pinning) source over a raw array — the fallback for
        direct construction in tests; production paths build sources
        from store blocks and stay pin-free."""
        vectors = np.asarray(vectors, dtype=np.float32)
        d = vectors.shape[1] if vectors.ndim == 2 else 0
        return RowSource((_Part(vectors, None, shared=False),), d)

    @staticmethod
    def concat(sources: Sequence["RowSource"]) -> "RowSource":
        parts: List[_Part] = []
        dims = 0
        for s in sources:
            parts.extend(s.parts)
            dims = dims or s.dims
        return RowSource(parts, dims)

    # ------------------------------------------------------------ queries
    def gather(self, sel: Optional[np.ndarray] = None) -> np.ndarray:
        """Materialize rows as f32 [m, d]: all rows (sel None), a bool
        mask over [0, n_rows), or ascending positions."""
        if sel is None:
            mats = [p.materialize() for p in self.parts]
            return (np.concatenate(mats, axis=0) if mats
                    else np.zeros((0, self.dims), dtype=np.float32))
        return self.select(sel).gather()

    def select(self, sel: np.ndarray) -> "RowSource":
        """Narrowed source: bool mask over [0, n_rows) or ascending
        int positions. Shares the underlying matrices."""
        sel = np.asarray(sel)
        if sel.dtype == bool:
            sel = np.nonzero(sel)[0]
        parts: List[_Part] = []
        off = 0
        for p in self.parts:
            n = p.n_rows
            local = sel[(sel >= off) & (sel < off + n)] - off
            if len(local):
                parts.append(p.take(local.astype(np.int64)))
            off += n
        return RowSource(parts, self.dims)

    def slice(self, start: int, stop: Optional[int] = None) -> "RowSource":
        """Contiguous range [start, stop) — the pure-append delta."""
        stop = self.n_rows if stop is None else stop
        parts: List[_Part] = []
        off = 0
        for p in self.parts:
            n = p.n_rows
            lo, hi = max(start - off, 0), min(stop - off, n)
            if lo < hi:
                if lo == 0 and hi == n:
                    parts.append(p)
                else:
                    parts.append(p.take(
                        np.arange(lo, hi, dtype=np.int64)))
            off += n
        return RowSource(parts, self.dims)

    def private_nbytes(self) -> int:
        """Host bytes this source PINS beyond the shared block store —
        0 for every store-backed source (the merge-does-not-pin
        invariant the tests assert)."""
        seen = set()
        total = 0
        for p in self.parts:
            if p.shared:
                continue
            marker = (p.matrix.__array_interface__["data"][0],
                      p.matrix.shape)
            if marker in seen:
                continue
            seen.add(marker)
            total += p.matrix.nbytes
        return total


class FieldRowsView:
    """Reader-wide composition of one vector field's blocks: row map
    eager (int64), matrix lazy. `refresh` carries the composition
    classification for the profile annotation."""

    __slots__ = ("blocks", "offsets", "row_map", "n_rows", "dims",
                 "refresh")

    def __init__(self, blocks: Tuple[VectorBlock, ...], refresh: dict):
        self.blocks = blocks
        sizes = [b.n_rows for b in blocks]
        self.offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(
            np.int64) if sizes else np.zeros(1, dtype=np.int64)
        self.row_map = (np.concatenate([b.rows for b in blocks])
                        if blocks else np.zeros(0, dtype=np.int64))
        self.n_rows = int(self.offsets[-1])
        self.dims = blocks[0].matrix.shape[1] if blocks else 0
        self.refresh = refresh

    def as_source(self) -> RowSource:
        return RowSource(tuple(_Part(b.matrix, None, shared=True)
                               for b in self.blocks), self.dims)

    def source_slice(self, start: int,
                     stop: Optional[int] = None) -> RowSource:
        return self.as_source().slice(start, stop)

    def source_select(self, sel: np.ndarray) -> RowSource:
        return self.as_source().select(sel)

    def rows(self, start: int, stop: Optional[int] = None) -> np.ndarray:
        """Materialize rows [start, stop) — the O(delta) refresh read."""
        return self.source_slice(start, stop).gather()

    def matrix(self) -> np.ndarray:
        """Materialize the WHOLE field matrix (monolithic rebuilds and
        the multi-shard mesh layout only — never the append-only
        refresh path). Shape matches the retired extractor exactly,
        including the (0, 0) empty case."""
        if not self.blocks:
            return np.zeros((0, 0), dtype=np.float32)
        return self.as_source().gather()
