"""Immutable per-(segment, field) column blocks.

The host-side port of Lucene's doc-values/codec layer (PAPER.md §
index/codec): every columnar consumer — the device vector store
(`vectors/store.py`), the agg engine (`ops/aggs.py`), the BM25 impact
layout (`ops/bm25.py`) — reads segment data through ONE block shape per
field kind instead of a private extractor with a private cache. A block
is extracted ONCE per (segment, field, live-set) and shared by every
consumer and every device generation derived from it; the store
(`columnar/store.py`) owns caching, fingerprints, and eviction.

Block kinds:

* ``VectorBlock``  — live f32 vector rows + engine global row ids. When
  the segment has no tombstones and every doc carries the field, the
  matrix is a ZERO-COPY reference to the engine segment's own
  ``[num_docs, d]`` array — the corpus-sized host RAM exists once, in
  the engine, and everything else holds references.
* ``ValuesBlock``  — the agg engine's f64 value/presence columns (+
  optional raw-object column for global ordinals), the exact
  `aggregations.numeric_values` coercion.
* ``PostingsBlock`` — one segment's live postings in dense live-slot
  space (the BM25 CSR input), via `SegmentView.live_postings`.
* ``SparsePostingsBlock`` — one segment's live `rank_features` maps
  inverted to feature-major (slots, weights) runs — the SAME CSR input
  shape as ``PostingsBlock``, with stored weights where BM25 has term
  freqs (the learned-sparse `ops/sparse.py` layout reads these).
* ``TokenVectorBlock`` — one segment's live `rank_vectors` token
  matrices, codec-encoded ragged (per-token rows + per-doc counts) plus
  the f32 pooled centroid per doc that feeds the coarse single-vector
  retrieval phase (`vectors/late_interaction.py`).

Extraction math is byte-identical to the three retired extractors (the
parity suite in `tests/test_columnar.py` pins it).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def fingerprint(view, extra: Tuple = ()) -> tuple:
    """The block cache key half that changes when a segment's content
    would: (seg_id, num_docs, live_count). Within one engine a segment's
    live count only shrinks (tombstones accumulate), so the triple is
    unique per live-set over the segment's lifetime."""
    seg = view.segment
    return (seg.seg_id, seg.num_docs, int(view.live.sum())) + tuple(extra)


class VectorBlock:
    """One segment's live rows of one dense_vector field.

    ``matrix`` is [n_live, d] f32; ``rows`` the matching engine global
    row ids. ``zero_copy`` marks the no-tombstone/all-present fast path
    where ``matrix`` IS the engine segment's array (no second corpus
    copy on host); ``nbytes`` counts only RAM this block ADDS beyond
    what the engine segment already holds."""

    __slots__ = ("fingerprint", "matrix", "rows", "zero_copy", "nbytes")

    def __init__(self, fp: tuple, matrix: np.ndarray, rows: np.ndarray,
                 zero_copy: bool):
        self.fingerprint = fp
        self.matrix = matrix
        self.rows = rows
        self.zero_copy = zero_copy
        self.nbytes = rows.nbytes + (0 if zero_copy else matrix.nbytes)

    @property
    def n_rows(self) -> int:
        return len(self.rows)


def extract_vector_block(view, field: str) -> Optional[VectorBlock]:
    """Live vector rows of one segment (None when the segment has no
    such field) — the per-segment half of the retired
    `vectors/store.extract_field_rows` loop, byte-identical."""
    seg = view.segment
    if field not in seg.vectors:
        return None
    fp = fingerprint(view)
    mat, present = seg.vectors[field]
    keep = present & view.live
    if keep.all():
        # zero-copy: the engine segment's matrix already IS the live f32
        # block (SegmentBuilder.seal materializes f32); rows are the
        # dense range
        rows = np.arange(seg.num_docs, dtype=np.int64) + seg.base
        return VectorBlock(fp, np.asarray(mat, dtype=np.float32), rows,
                           zero_copy=True)
    locs = np.nonzero(keep)[0]
    rows = locs.astype(np.int64) + seg.base
    return VectorBlock(fp, np.asarray(mat[locs], dtype=np.float32), rows,
                       zero_copy=False)


class EncodedVectorBlock:
    """One segment's live rows of one dense_vector field, codec-encoded
    (`quant/codec.py`) — the packed-ladder VARIANT of ``VectorBlock``.

    Cached per (segment, field, encoding, metric) exactly like the f32
    blocks, so a refresh re-encodes only delta segments and a dtype
    re-encode merge reads already-encoded tails for free. ``data`` is
    the packed rows [n_live, W], ``scales`` the per-row aux; rows encode
    independently, so concatenating blocks is byte-identical to
    encoding the concatenation."""

    __slots__ = ("fingerprint", "data", "scales", "rows", "nbytes")

    def __init__(self, fp: tuple, data: np.ndarray, scales: np.ndarray,
                 rows: np.ndarray):
        self.fingerprint = fp
        self.data = data
        self.scales = scales
        self.rows = rows
        self.nbytes = data.nbytes + scales.nbytes

    @property
    def n_rows(self) -> int:
        return len(self.rows)


def extract_encoded_vector_block(view, field: str, encoding: str,
                                 metric: str,
                                 f32_block: Optional[VectorBlock]
                                 ) -> Optional[EncodedVectorBlock]:
    """Codec-encode one segment's live rows (metric-prepped first:
    cosine rows normalize per row, so per-segment encoding agrees with
    whole-corpus encoding byte for byte). `f32_block` is the segment's
    cached ``VectorBlock`` — the store passes it so the f32 extraction
    is never repeated here."""
    from elasticsearch_tpu.quant import codec as quant_codec
    if f32_block is None:
        return None
    fp = fingerprint(view, (encoding, metric))
    mat = np.asarray(f32_block.matrix, dtype=np.float32)
    if metric == "cosine":
        norms = np.linalg.norm(mat, axis=-1, keepdims=True)
        mat = mat / np.maximum(norms, 1e-30)
    enc = quant_codec.get(encoding).encode_np(mat)
    return EncodedVectorBlock(fp, enc.data, enc.scales, f32_block.rows)


class ValuesBlock:
    """One segment's live-row doc-values extraction for one field — the
    agg engine's per-segment column (f64 numeric view + presence, raw
    objects when global ordinals are wanted, multi-valuedness flag)."""

    __slots__ = ("fingerprint", "vals", "present", "objs", "multi_valued",
                 "nbytes")

    def __init__(self, fp: tuple, vals, present, objs, multi_valued):
        self.fingerprint = fp
        self.vals = vals            # f64[n_live] (nan where absent)
        self.present = present      # bool[n_live]
        self.objs = objs            # object[n_live] raw doc values (or None)
        self.multi_valued = multi_valued
        self.nbytes = vals.nbytes + present.nbytes \
            + (objs.nbytes if objs is not None else 0)


def extract_values_block(view, field: str, want_objs: bool) -> ValuesBlock:
    """Port of the retired `ops/aggs._extract_segment_column` — EXACTLY
    the `aggregations.numeric_values` coercion: bools → 1/0, numerics →
    float, first element of lists, strings/geo absent."""
    seg = view.segment
    n_live = int(view.live.sum())
    fp = fingerprint(view, (want_objs,))
    col = seg.doc_values.get(field)
    vals = np.full(n_live, np.nan, dtype=np.float64)
    present = np.zeros(n_live, dtype=bool)
    objs = np.empty(n_live, dtype=object) if want_objs else None
    multi = False
    if col is not None and n_live:
        live_idx = np.nonzero(view.live)[0]
        raw = None
        if want_objs or col.numeric is None:
            raw = np.empty(n_live, dtype=object)
            for i, loc in enumerate(live_idx):
                v = col.values[int(loc)]
                raw[i] = v
                if isinstance(v, list):
                    multi = True
            if want_objs:
                objs = raw
        else:
            # multi-valuedness must be known even for pure-numeric
            # columns: the f64 view keeps only a doc's FIRST value, which
            # matches numeric_values but NOT all_values — value_count
            # (and terms) bind-checks depend on this flag being real
            multi = any(isinstance(col.values[int(loc)], list)
                        for loc in live_idx)
        if col.numeric is not None:
            vals[:] = col.numeric[live_idx]
            present[:] = col.present[live_idx]
            vals[~present] = np.nan
        else:
            for i in range(n_live):
                v = raw[i]
                if isinstance(v, list):
                    v = v[0] if v else None
                if v is None:
                    continue
                if isinstance(v, bool):
                    vals[i] = 1.0 if v else 0.0
                    present[i] = True
                elif isinstance(v, (int, float)):
                    vals[i] = float(v)
                    present[i] = True
    return ValuesBlock(fp, vals, present, objs, multi)


class PostingsBlock:
    """One segment's live postings of one text field in dense live-slot
    space — the BM25 CSR extraction (`SegmentView.live_postings`)."""

    __slots__ = ("fingerprint", "terms", "lengths", "n_live", "nbytes")

    def __init__(self, fp: tuple, terms, lengths, n_live):
        self.fingerprint = fp
        self.terms = terms      # term -> (live slots ascending, freqs)
        self.lengths = lengths  # f32[n_live] field length per live slot
        self.n_live = n_live
        self.nbytes = lengths.nbytes + sum(
            s.nbytes + f.nbytes for s, f in terms.values())


def extract_postings_block(view, field: str) -> PostingsBlock:
    terms, lengths, n_live = view.live_postings(field)
    return PostingsBlock(fingerprint(view), terms, lengths, n_live)


class SparsePostingsBlock:
    """One segment's live `rank_features` maps inverted to feature-major
    runs in dense live-slot space — the learned-sparse CSR input.

    ``features`` maps feature name -> (live slots ascending int32,
    stored weights f32): exactly ``PostingsBlock.terms`` with weights in
    the freq position, so `ops/sparse.py` tile-pads it with the same
    code BM25 uses (weights ARE the impacts — no idf/length math).
    ``n_live`` spans ALL live docs of the segment (docs without the
    field simply appear in no feature's run), keeping the slot space
    identical to the lexical layout's."""

    __slots__ = ("fingerprint", "features", "n_live", "nbytes")

    def __init__(self, fp: tuple, features, n_live: int):
        self.fingerprint = fp
        self.features = features
        self.n_live = n_live
        self.nbytes = sum(s.nbytes + w.nbytes
                          for s, w in features.values())


def extract_sparse_postings_block(view, field: str) -> SparsePostingsBlock:
    seg = view.segment
    col = seg.doc_values.get(field)
    live_idx = np.nonzero(view.live)[0]
    acc: dict = {}
    if col is not None:
        for slot, loc in enumerate(live_idx):
            v = col.values[int(loc)]
            if not isinstance(v, dict):
                continue
            for feat, w in v.items():
                lists = acc.get(feat)
                if lists is None:
                    lists = acc[feat] = ([], [])
                lists[0].append(slot)
                lists[1].append(w)
    features = {
        feat: (np.asarray(slots, dtype=np.int32),
               np.asarray(weights, dtype=np.float32))
        for feat, (slots, weights) in acc.items()}
    return SparsePostingsBlock(fingerprint(view), features, len(live_idx))


class TokenVectorBlock:
    """One segment's live `rank_vectors` token matrices, codec-encoded
    ragged: ``data`` [total_tokens, W] packed token rows (lane-padded
    width), ``scales`` [total_tokens] per-token codec aux, ``counts``
    [n] tokens per doc, ``pooled`` [n, dims] f32 coarse centroids,
    ``rows`` [n] engine global row ids. Only docs carrying at least one
    token appear. Cached per (segment, field, encoding, metric, dims)
    like the encoded single-vector blocks, so refresh re-encodes only
    delta segments."""

    __slots__ = ("fingerprint", "data", "scales", "counts", "pooled",
                 "rows", "dims", "nbytes")

    def __init__(self, fp: tuple, data, scales, counts, pooled, rows,
                 dims: int):
        self.fingerprint = fp
        self.data = data
        self.scales = scales
        self.counts = counts
        self.pooled = pooled
        self.rows = rows
        self.dims = dims
        self.nbytes = (data.nbytes + scales.nbytes + counts.nbytes
                       + pooled.nbytes + rows.nbytes)

    @property
    def n_rows(self) -> int:
        return len(self.rows)


def extract_token_vector_block(view, field: str, variant: tuple
                               ) -> Optional[TokenVectorBlock]:
    """Gather, metric-prep, and codec-encode one segment's live token
    matrices (all packing math in `quant/tokens.py` — the token twin of
    `extract_encoded_vector_block`). variant = (encoding, metric, dims);
    None when the segment carries no such field."""
    from elasticsearch_tpu.quant import tokens as quant_tokens
    encoding, metric, dims = variant
    seg = view.segment
    col = seg.doc_values.get(field)
    if col is None:
        return None
    fp = fingerprint(view, (variant,))
    live_idx = np.nonzero(view.live)[0]
    tok_parts, pooled_parts, counts, rows = [], [], [], []
    for loc in live_idx:
        v = col.values[int(loc)]
        if v is None:
            continue
        toks = quant_tokens.prep_tokens(
            np.asarray(v, dtype=np.float32).reshape(-1, dims), metric)
        if not len(toks):
            continue
        tok_parts.append(toks)
        pooled_parts.append(quant_tokens.pool_doc(toks, metric))
        counts.append(len(toks))
        rows.append(int(loc) + seg.base)
    if not tok_parts:
        return TokenVectorBlock(
            fp,
            np.zeros((0, quant_tokens.packed_width(encoding, dims)),
                     dtype=np.uint8),
            np.zeros(0, dtype=np.float32),
            np.zeros(0, dtype=np.int32),
            np.zeros((0, dims), dtype=np.float32),
            np.zeros(0, dtype=np.int64), dims)
    data, scales = quant_tokens.encode_tokens(
        np.concatenate(tok_parts), encoding, dims)
    return TokenVectorBlock(
        fp, data, scales, np.asarray(counts, dtype=np.int32),
        np.stack(pooled_parts), np.asarray(rows, dtype=np.int64), dims)
