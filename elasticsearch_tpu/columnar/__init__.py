"""Columnar segment block store (the Lucene doc-values/codec layer,
host-side): one per-(segment, field) immutable block cache under the
vector store, the agg engine, and the BM25 impact layout.

See `columnar/store.py` for the contract; `columnar/blocks.py` for the
block shapes. The process-wide instance is `columnar.STORE`."""

from elasticsearch_tpu.columnar.blocks import (
    PostingsBlock,
    SparsePostingsBlock,
    TokenVectorBlock,
    ValuesBlock,
    VectorBlock,
    extract_postings_block,
    extract_sparse_postings_block,
    extract_token_vector_block,
    extract_values_block,
    extract_vector_block,
    fingerprint,
)
from elasticsearch_tpu.columnar.store import (
    STORE,
    FieldRowsView,
    RowSource,
    SegmentBlockStore,
)

__all__ = [
    "STORE", "SegmentBlockStore", "FieldRowsView", "RowSource",
    "VectorBlock", "ValuesBlock", "PostingsBlock", "SparsePostingsBlock",
    "TokenVectorBlock", "extract_vector_block", "extract_values_block",
    "extract_postings_block", "extract_sparse_postings_block",
    "extract_token_vector_block", "fingerprint",
]
