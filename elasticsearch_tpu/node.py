"""Node: the composition root and API facade.

Re-design of `node/Node.java:275` (layer 3) for a single node: wires
IndicesService, the search coordinator, and the document APIs the REST layer
exposes. The cluster layer (coordination/replication over the transport)
mounts on top of these same internal APIs, mirroring how the reference's
TransportActions call into the node's services.
"""

from __future__ import annotations

import copy
import threading as _threading
import time
import uuid as _uuid
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.common.errors import (
    ArrayIndexOutOfBoundsError, DocumentMissingError, IllegalArgumentError,
    IndexNotFoundError, ParsingError, SearchEngineError, VersionConflictError,
)
from elasticsearch_tpu.index.analysis import DEFAULT_REGISTRY
from elasticsearch_tpu.indices.service import (
    SHARD_ROW_SPACE, IndexService, IndicesService,
)
from elasticsearch_tpu.search.service import (
    execute_fetch_phase, execute_query_phase,
)
from elasticsearch_tpu.common.settings import parse_time_value
from elasticsearch_tpu.telemetry import metrics as _telemetrics
from elasticsearch_tpu.telemetry import trace as _teletrace
from elasticsearch_tpu.version import __version__

MAX_RESULT_WINDOW_SCROLL = 10_000


class _ShardScopedStore:
    """Vector-store wrapper that drops result rows outside `allowed`
    internal shards — the shard-failure retry path, where the reader omits
    failed shards and a knn clause must not hand back rows the reader
    cannot resolve (a failed shard's hits are simply gone, per the
    reference's partial-results contract)."""

    def __init__(self, inner, allowed: frozenset):
        self._inner = inner
        self._allowed = np.asarray(sorted(allowed), dtype=np.int64)

    def field(self, name):
        return self._inner.field(name)

    def search(self, field, query_vector, k, filter_rows=None,
               precision: str = "bf16", num_candidates=None,
               deadline_at=None):
        rows, scores = self._inner.search(field, query_vector, k,
                                          filter_rows=filter_rows,
                                          precision=precision,
                                          num_candidates=num_candidates,
                                          deadline_at=deadline_at)
        keep = np.isin(rows // SHARD_ROW_SPACE, self._allowed)
        return rows[keep], scores[keep]

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _MultiShardVectorStore:
    """Scatter-gather adapter for multi-shard kNN.

    When the local device mesh can host one column per shard (device
    count >= shard count > 1), searches run as ONE compiled SPMD program:
    each mesh column scores its shard slice and the global top-k merges
    over ICI all_gather (`parallel/sharded_knn.py`) — the compiled
    collapse of `SearchPhaseController.mergeTopDocs:221`. Otherwise the
    host-coordinated fallback runs per-shard device kNN + host merge."""

    def __init__(self, svc: IndexService):
        self.svc = svc
        self._phases: dict = {}

    def field(self, name: str):
        for shard in self.svc.shards:
            fc = shard.vector_store.field(name)
            if fc is not None:
                return fc
        return None

    # -- mesh fast path -----------------------------------------------------
    def _mesh_state(self, field: str):
        """Build (and cache by segment fingerprints) the mesh-sharded
        corpus + row maps for one vector field; None when the mesh path
        does not apply."""
        import jax

        n_shards = len(self.svc.shards)
        if n_shards < 2 or len(jax.devices()) < n_shards:
            return None
        from elasticsearch_tpu.vectors.store import (
            VectorStoreShard, extract_field_rows)
        # one reader snapshot per shard: fingerprints (for cache
        # invalidation), matrices, and row maps all come from the SAME
        # snapshot, so rows can never misalign with doc ids
        readers = [s.engine.acquire_searcher() for s in self.svc.shards]
        version = tuple(VectorStoreShard._fingerprint(r, field)
                        for r in readers)
        cache = self.svc.__dict__.setdefault("_mesh_knn_cache", {})
        cached = cache.get(field)
        if cached is not None and cached["version"] == version:
            return cached
        import jax.numpy as jnp

        from elasticsearch_tpu.index.mapping import DenseVectorFieldMapper
        from elasticsearch_tpu.ops import similarity as sim
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import ShardedCorpus
        from elasticsearch_tpu.vectors.store import _METRIC_MAP

        mapper = self.svc.mapper_service.get(field)
        if not isinstance(mapper, DenseVectorFieldMapper):
            return None
        metric = _METRIC_MAP[mapper.similarity]

        # host-side extraction per shard, laid out one shard per mesh
        # column. NOTE: the per-shard device corpora stay resident as the
        # fallback path — on a multi-chip host they all sit on device 0
        # while the mesh copy spreads across chips, so the overlap on any
        # one chip is 1/n_shards of the corpus, not a full double.
        blocks, row_maps = [], []
        for shard, reader in zip(self.svc.shards, readers):
            block, rows = extract_field_rows(reader, field)
            if len(rows) == 0:
                block = np.zeros((0, mapper.dims), dtype=np.float32)
            blocks.append(block)
            row_maps.append(rows + shard.shard_id * SHARD_ROW_SPACE)
        if all(len(b) == 0 for b in blocks):
            return None
        # ONE policy-owned mesh build path (parallel/policy.py): the
        # shard axis is fixed by the engine shard count, but the dp
        # setting and device budget apply exactly as they do for the
        # serving mesh — a `search.mesh.dp` setting can't half-apply
        from elasticsearch_tpu.parallel import policy as mesh_policy
        mesh = mesh_policy.mesh_for_shards(n_shards)
        if mesh is None:
            return None
        from elasticsearch_tpu.ops import knn as knn_ops
        from elasticsearch_tpu.parallel import layout
        per = knn_ops.pad_rows(max(max(len(b) for b in blocks), 1))
        d = mapper.dims
        # dp-aware HBM budget: this upload replicates across every dp
        # group, so it must clear the same search.mesh.hbm_budget_bytes
        # gate the per-shard serving corpus clears (the host-coordinated
        # per-shard fallback below serves instead when it doesn't)
        from elasticsearch_tpu.vectors.store import device_corpus_nbytes
        if not mesh_policy.hbm_allows(
                device_corpus_nbytes(n_shards * per, d, "bf16"), mesh):
            return None
        matrix_host = np.zeros((n_shards * per, d), dtype=np.float32)
        sq_host = np.zeros(n_shards * per, dtype=np.float32)
        num_valid = np.zeros(n_shards, dtype=np.int32)
        for s, block in enumerate(blocks):
            if metric == sim.COSINE and len(block):
                norms = np.linalg.norm(block, axis=-1, keepdims=True)
                block = block / np.maximum(norms, 1e-30)
            matrix_host[s * per: s * per + len(block)] = block
            sq_host[s * per: s * per + len(block)] = \
                (block * block).sum(axis=-1)
            num_valid[s] = len(block)
        import ml_dtypes
        corpus = layout.shard_put(ShardedCorpus(
            matrix=matrix_host.astype(ml_dtypes.bfloat16),
            sq_norms=sq_host,
            scales=np.ones(n_shards * per, dtype=np.float32),
            num_valid=num_valid), mesh)
        state = {"version": version, "mesh": mesh, "corpus": corpus,
                 "row_maps": row_maps, "per": per, "metric": metric,
                 "n_rows": n_shards * per}
        cache[field] = state
        return state

    def _mesh_search(self, state, query_vector, k: int, filter_rows,
                     precision: str):
        import jax
        import jax.numpy as jnp

        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel.sharded_knn import (
            distributed_knn_search)

        per = state["per"]
        row_maps = state["row_maps"]
        mask = None
        if filter_rows is not None:
            m = np.zeros(state["n_rows"], dtype=bool)
            for s, rm in enumerate(row_maps):
                allowed = np.isin(rm, filter_rows)
                m[s * per: s * per + len(rm)] = allowed
            mask = jax.device_put(
                jnp.asarray(m),
                mesh_lib.per_shard_sharding(state["mesh"]))
        # the full-mesh program splits queries along dp, so a single
        # query pads up to a dp-divisible bucket (8 covers every pow-2
        # dp on this host); pad rows slice away below
        dp = mesh_lib.dp_size(state["mesh"])
        q_host = np.asarray(query_vector, dtype=np.float32)[None, :]
        if dp > 1:
            q_pad = _dispatch.bucket_queries(max(1, dp))
            q_host = np.concatenate(
                [q_host, np.zeros((q_pad - 1, q_host.shape[1]),
                                  dtype=np.float32)])
        q = jax.device_put(
            jnp.asarray(q_host),
            mesh_lib.query_sharding(state["mesh"]))
        # k rounds up the dispatch ladder so request streams sweeping k
        # reuse one compiled SPMD program per rung (prefixes are exact)
        k_b = _dispatch.bucket_k(min(k, per), limit=per)
        scores, gids = distributed_knn_search(
            q, state["corpus"], k_b, state["mesh"],
            metric=state["metric"], filter_mask=mask, precision=precision)
        scores = np.asarray(scores[0])[:k]
        gids = np.asarray(gids[0])[:k]
        # padding/filtered slots come back (-inf, -1) — masked out
        # before the ICI gather, so no aliased ids can reach this join
        valid = (scores > -1e37) & (gids >= 0)
        scores, gids = scores[valid], gids[valid]
        out_rows = np.empty(len(gids), dtype=np.int64)
        keep = np.ones(len(gids), dtype=bool)
        for i, g in enumerate(gids):
            s, local = int(g) // per, int(g) % per
            if local < len(row_maps[s]):
                out_rows[i] = row_maps[s][local]
            else:
                keep[i] = False
        return out_rows[keep], scores[keep]

    def _prefer_host(self, field: str) -> bool:
        """True when every shard has a host VNNI mirror and the cost model
        says a host pass beats a device round-trip for this corpus size
        (serving/batcher.py) — then the per-shard path (whose shard stores
        route host-side) wins over the fused mesh program."""
        from elasticsearch_tpu.serving.batcher import CostModel

        total, dims, pending = 0, 0, 0
        for shard in self.svc.shards:
            store = shard.vector_store
            fc = store.field(field) if hasattr(store, "field") else None
            if fc is None or fc.host is None:
                return False
            total += len(fc.row_map)
            dims = fc.dims
            if hasattr(store, "pending_requests"):
                pending += store.pending_requests(field)
        # this request plus whatever is already queued behind the shard
        # batchers: under concurrent load the coalesced batch amortizes the
        # device dispatch, so the fused mesh program wins earlier
        return total > 0 and CostModel.prefer_host(1 + pending, total, dims)

    def search(self, field: str, query_vector, k: int, filter_rows=None,
               precision: str = "bf16", num_candidates=None,
               deadline_at=None):
        state = self._mesh_state(field)
        self._phases = {}
        # k beyond the per-shard padded row count cannot merge losslessly
        # in the fused program; such deep k falls back to the host merge
        if state is not None and k <= state["per"] \
                and not self._prefer_host(field):
            # the fused mesh program has no per-phase split to report
            return self._mesh_search(state, query_vector, k, filter_rows,
                                     precision)
        all_rows, all_scores = [], []
        for shard in self.svc.shards:
            offset = shard.shard_id * SHARD_ROW_SPACE
            frows = None
            if filter_rows is not None:
                local = filter_rows[(filter_rows >= offset)
                                    & (filter_rows < offset + SHARD_ROW_SPACE)] - offset
                frows = local
            rows, scores = shard.vector_store.search(
                field, query_vector, k, filter_rows=frows,
                precision=precision, num_candidates=num_candidates,
                deadline_at=deadline_at)
            if not self._phases:
                # captured per dispatch, NOT scanned lazily later — a
                # later mesh-path query must not inherit these timings
                self._phases = dict(getattr(
                    shard.vector_store, "last_knn_phases", None) or {})
            all_rows.append(rows + offset)
            all_scores.append(scores)
        if not all_rows:
            return np.zeros(0, dtype=np.int64), np.zeros(0, dtype=np.float32)
        rows = np.concatenate(all_rows)
        scores = np.concatenate(all_scores)
        # global top-k with shard-order tie-break (stable sort over concat)
        order = np.argsort(-scores, kind="stable")[:k]
        return rows[order], scores[order]

    def search_many(self, field: str, requests, k: int,
                    precision: str = "bf16", num_candidates=None) -> list:
        """Batched kNN for the hybrid executor: the whole request batch
        crosses to the device in ONE dispatch per shard (single-shard
        indices — the common case — pay exactly one round-trip for N
        queries). The mesh fast path stays per-query; it is already one
        compiled program per search."""
        shards = self.svc.shards
        if len(shards) == 1:
            shard = shards[0]
            offset = shard.shard_id * SHARD_ROW_SPACE
            out = shard.vector_store.search_many(
                field, requests, k, precision=precision,
                num_candidates=num_candidates)
            self._phases = dict(getattr(
                shard.vector_store, "last_knn_phases", None) or {})
            return [(rows + offset, scores) for rows, scores in out]
        per_shard = []
        for shard in shards:
            offset = shard.shard_id * SHARD_ROW_SPACE
            reqs = []
            for q, filter_rows in requests:
                frows = None
                if filter_rows is not None:
                    frows = filter_rows[
                        (filter_rows >= offset)
                        & (filter_rows < offset + SHARD_ROW_SPACE)] - offset
                reqs.append((q, frows))
            out = shard.vector_store.search_many(
                field, reqs, k, precision=precision,
                num_candidates=num_candidates)
            per_shard.append([(rows + offset, scores)
                              for rows, scores in out])
        merged = []
        for qi in range(len(requests)):
            rows = np.concatenate([ps[qi][0] for ps in per_shard])
            scores = np.concatenate([ps[qi][1] for ps in per_shard])
            order = np.argsort(-scores, kind="stable")[:k]
            merged.append((rows[order], scores[order]))
        return merged

    def search_many_async(self, field: str, requests, k: int,
                          precision: str = "bf16", num_candidates=None):
        """Pipelined half of `search_many`: launch the batch's device
        dispatch without syncing (single-shard fast path); `finalize_many`
        lands it at response-assembly time. Multi-shard indices fall back
        to the synchronous scatter-gather inside the dispatch stage (the
        host merge needs every shard's results anyway)."""
        shards = self.svc.shards
        if len(shards) == 1:
            shard = shards[0]
            offset = shard.shard_id * SHARD_ROW_SPACE
            handle = shard.vector_store.search_many_async(
                field, requests, k, precision=precision,
                num_candidates=num_candidates)
            self._phases = dict(getattr(
                shard.vector_store, "last_knn_phases", None) or {})
            return ("shard", shard, offset, handle)
        return ("merged", None, 0,
                self.search_many(field, requests, k, precision=precision,
                                 num_candidates=num_candidates))

    def finalize_many(self, handle) -> list:
        kind, shard, offset, payload = handle
        if kind == "merged":
            return payload
        out = shard.vector_store.finalize_many(payload)
        return [(rows + offset, scores) for rows, scores in out]

    @property
    def last_knn_phases(self) -> dict:
        """Engine phase timings captured by this wrapper's most recent
        dispatch (empty for mesh fast-path searches, which have no
        per-phase split)."""
        return self._phases

    @property
    def columnar_refresh(self) -> dict:
        """Per-field segment-block-store refresh ledger, first shard
        that synced the field wins (the `columnar` annotation
        `profile.knn` attaches — see VectorStoreShard.columnar_refresh)."""
        out: dict = {}
        for shard in self.svc.shards:
            for f, info in getattr(shard.vector_store,
                                   "columnar_refresh", {}).items():
                out.setdefault(f, info)
        return out


class Node:
    def __init__(self, data_path: str, node_name: str = "node-0",
                 cluster_name: str = "tpu-search",
                 settings: Optional[dict] = None):
        from elasticsearch_tpu.ingest.service import IngestService
        from elasticsearch_tpu.node_admin import (
            AsyncSearchService, ScrollService, TaskManager, TemplateService,
        )

        self.node_id = _uuid.uuid4().hex[:20]
        self.node_name = node_name
        self.cluster_name = cluster_name
        self.data_path = data_path
        self.indices = IndicesService(data_path)
        self.ingest = IngestService()
        self.scrolls = ScrollService()
        self.async_search = AsyncSearchService()
        self.component_templates: Dict[str, dict] = {}
        self.data_streams: Dict[str, dict] = {}
        self.tasks = TaskManager(self.node_id)
        self.templates = TemplateService()
        from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
        self.scripts = GLOBAL_SCRIPTS
        import os as _os
        self.scripts.attach_storage(_os.path.join(data_path, "_state",
                                                  "stored_scripts.json"))
        from elasticsearch_tpu.xpack.ilm import IlmService, SlmService
        self.ilm = IlmService(self)
        self.slm = SlmService(self)
        from elasticsearch_tpu.xpack.transform import RollupService, TransformService
        from elasticsearch_tpu.xpack.watcher import WatcherService
        self.watcher = WatcherService(self)
        self.transform = TransformService(self)
        self.rollup = RollupService(self)
        from elasticsearch_tpu.xpack.ccr import CcrService, RemoteClusterService
        self.remotes = RemoteClusterService(self)
        self.ccr = CcrService(self)
        from elasticsearch_tpu.common.breakers import HierarchyCircuitBreakerService
        from elasticsearch_tpu.monitor import SlowLog
        from elasticsearch_tpu.search.caches import NodeCaches
        self.breakers = HierarchyCircuitBreakerService()
        # shard request cache + node query cache (IndicesRequestCache /
        # IndicesQueryCache analogs), shared across this node's shards
        self.caches = NodeCaches()
        from elasticsearch_tpu.common.threadpool import ThreadPool
        self.thread_pool = ThreadPool(settings or {})
        self.search_slow_log = SlowLog("search")
        self.indexing_slow_log = SlowLog("indexing")
        # per-group search counters (SearchRequest `stats` tags ->
        # SearchStats groupStats)
        self._search_groups: Dict[str, int] = {}
        # per-index fused hybrid executors (search/hybrid_plan.py)
        self._hybrid: Dict[str, Any] = {}
        # per-index device aggregation engines (search/agg_plan.py); the
        # lock serializes creation — engines register per-shard refresh
        # listeners, so a lost create-race would leak a permanently
        # resyncing duplicate engine
        self._aggs: Dict[str, Any] = {}
        self._aggs_lock = _threading.Lock()
        self.counters: Dict[str, int] = {"search": 0, "index": 0, "get": 0,
                                         "bulk": 0, "delete": 0}
        # per-index get counts for indices-stats `get` section (GetStats)
        self._index_get_counts: Dict[str, int] = {}
        # cluster-level persistent/transient settings (_cluster/settings API)
        self.cluster_settings: Dict[str, dict] = {"persistent": {},
                                                  "transient": {}}
        # copy: merging keystore secrets into a caller-shared dict would
        # leak plaintext secrets into the caller's object
        self.settings = dict(settings or {})
        # secure settings FIRST: keystore secrets merge under their names
        # without overriding explicit settings, before any service reads
        # them (reference: KeyStoreWrapper loaded in Bootstrap, exposed via
        # Settings#getSecureSettings)
        from elasticsearch_tpu.common.keystore import load_node_keystore
        self.keystore = load_node_keystore(self.settings, data_path)
        if self.keystore is not None:
            for name, value in self.keystore.as_settings().items():
                self.settings.setdefault(name, value)
        # wire remotes from boot settings (cluster.remote.<alias>.seeds);
        # apply_settings isolates + logs per-alias failures itself
        self.remotes.apply_settings(self.settings)
        from elasticsearch_tpu.security import SecurityService, SecurityStore
        from elasticsearch_tpu.security.realms import build_realm_chain
        _sec_store = SecurityStore(
            _os.path.join(data_path, "_state", "security.json"))
        _anon = self.settings.get("xpack.security.authc.anonymous.roles")
        if isinstance(_anon, str):
            _anon = [r.strip() for r in _anon.split(",") if r.strip()]
        self.security = SecurityService(
            _sec_store,
            enabled=bool(self.settings.get("xpack.security.enabled", False)),
            bootstrap_password=str(
                self.settings.get("bootstrap.password", "changeme")),
            realms=build_realm_chain(self.settings, _sec_store, data_path),
            anonymous_roles=_anon)
        from elasticsearch_tpu.xpack.license import LicenseService
        self.license = LicenseService(str(self.settings.get(
            "xpack.license.self_generated.type", "trial")))
        from elasticsearch_tpu.snapshots.service import SnapshotService
        self.snapshots = SnapshotService(self)
        from elasticsearch_tpu.ml import DatafeedService, MlService
        self.ml = MlService(self)
        self.datafeeds = DatafeedService(self)
        from elasticsearch_tpu.xpack.enrich import attach_enrich
        from elasticsearch_tpu.xpack.graph import GraphService
        self.enrich = attach_enrich(self)
        self.graph = GraphService(self)
        from elasticsearch_tpu.xpack.monitoring import MonitoringService
        self.monitoring = MonitoringService(self)
        from elasticsearch_tpu.plugins import PluginsService
        self.plugins = PluginsService(
            self.settings.get("path.plugins",
                              _os.path.join(data_path, "plugins")))
        self.plugins.load_all()
        self.plugins.apply_extensions()
        self.plugins.start_node(self)
        # shape-bucketed kernel dispatch (ops/dispatch.py): wire JAX's
        # persistent compilation cache so a node restart re-loads compiled
        # executables from disk instead of re-paying XLA compiles
        # (settings: search.dispatch.persistent_cache_dir, default
        # <data>/_state/xla_cache when search.dispatch.persistent_cache
        # is truthy; search.dispatch.warmup overrides the warmup policy)
        from elasticsearch_tpu.common.settings import setting_bool
        from elasticsearch_tpu.ops import dispatch as _dispatch
        cache_dir = self.settings.get("search.dispatch.persistent_cache_dir")
        if not cache_dir and setting_bool(
                self.settings.get("search.dispatch.persistent_cache")):
            cache_dir = _os.path.join(data_path, "_state", "xla_cache")
        if cache_dir:
            _dispatch.configure_persistent_cache(str(cache_dir))
        warm = self.settings.get("search.dispatch.warmup")
        self._dispatch_warmup = setting_bool(warm) if warm is not None \
            else None
        if self._dispatch_warmup is not None:
            # the dispatcher (and its warmup policy) is process-wide; a
            # node with no explicit setting must not clobber a policy an
            # earlier in-process node configured
            _dispatch.set_default_warmup(self._dispatch_warmup)
        # mesh serving policy (parallel/policy.py): search.mesh.* settings
        # pick the SPMD shard count and the per-corpus row floor the
        # host-side router applies. Process-wide like the dispatcher —
        # only an explicit setting reconfigures it (same clobber rule as
        # warmup above).
        mesh_keys = ("search.mesh.enabled", "search.mesh.num_shards",
                     "search.mesh.min_rows", "search.mesh.dp",
                     "search.mesh.hbm_budget_bytes")
        if any(self.settings.get(key) is not None for key in mesh_keys):
            from elasticsearch_tpu.parallel import policy as _mesh_policy
            enabled = self.settings.get("search.mesh.enabled")
            num_shards = self.settings.get("search.mesh.num_shards")
            min_rows = self.settings.get("search.mesh.min_rows")
            dp = self.settings.get("search.mesh.dp")
            hbm_budget = self.settings.get("search.mesh.hbm_budget_bytes")
            kwargs = {}
            if enabled is not None:
                kwargs["enabled"] = setting_bool(enabled)
            if num_shards is not None:
                kwargs["num_shards"] = int(num_shards)
            if min_rows is not None:
                kwargs["min_rows"] = int(min_rows)
            if dp is not None:
                kwargs["dp"] = int(dp)
            if hbm_budget is not None:
                kwargs["hbm_budget_bytes"] = int(hbm_budget)
            _mesh_policy.configure(**kwargs)
        # end-to-end telemetry (elasticsearch_tpu/telemetry/): tracer
        # sampling + trace-ring sizing. Process-wide like the dispatcher
        # — only an explicit setting reconfigures (same clobber rule as
        # warmup above).
        from elasticsearch_tpu import telemetry as _telemetry
        _telemetry.configure_from_settings(self.settings)
        # set by the server bootstrap after native hardening runs; embedded
        # nodes have no hardening (reference: JNANatives.LOCAL_MLOCKALL)
        self.natives = None
        self.start_time = time.time()

    # ------------------------------------------------------------- documents
    def index_doc(self, index: str, doc_id: Optional[str], body: dict,
                  op_type: str = "index", refresh: Optional[str] = None,
                  routing: Optional[str] = None,
                  if_seq_no: Optional[int] = None,
                  if_primary_term: Optional[int] = None,
                  version: Optional[int] = None,
                  version_type: str = "internal",
                  pipeline: Optional[str] = None) -> dict:
        svc = self.indices.check_open(self._index_or_autocreate(index))
        if pipeline is None:
            pipeline = svc.settings.get("index.default_pipeline")
        if pipeline and pipeline != "_none":
            body = self.ingest.execute(pipeline, svc.name, doc_id, body)
            if body is None:  # dropped by the pipeline
                return {"_index": svc.name, "_id": doc_id, "result": "noop",
                        "_version": -1, "_seq_no": -1, "_primary_term": 0,
                        "_shards": {"total": 0, "successful": 0, "failed": 0}}
        if doc_id is None:
            doc_id = _uuid.uuid4().hex[:20]
            op_type = "create"
        if len(str(doc_id).encode("utf-8")) > 512:
            raise IllegalArgumentError(
                f"id [{doc_id}] is too long, must be no longer than 512 "
                f"bytes but was: {len(str(doc_id).encode('utf-8'))}")
        if op_type == "create" and version_type != "internal":
            raise IllegalArgumentError(
                "create operations only support internal versioning. use "
                "index instead")
        shard = svc.route(doc_id, routing)
        t0 = time.monotonic()
        result = shard.engine.index(
            doc_id, body, op_type=op_type, if_seq_no=if_seq_no,
            if_primary_term=if_primary_term, version=version,
            version_type=version_type, routing=routing)
        self.counters["index"] += 1
        self.indexing_slow_log.maybe_log(
            svc.settings, svc.name, time.monotonic() - t0, source=body)
        self._maybe_refresh(svc, refresh, shard=shard)
        if svc.mapper_service.dirty:
            # persist only on real dynamic-mapping changes, not per document
            self.indices._persist_meta(svc)
            svc.mapper_service.dirty = False
        out = {
            "_index": svc.name, "_id": doc_id, "_version": result.version,
            "result": result.result, "_seq_no": result.seq_no,
            "_primary_term": result.primary_term,
            "_shards": {"total": 1, "successful": 1, "failed": 0},
        }
        if refresh in ("true", "", True):
            # the write itself made changes visible (RestActions
            # forced_refresh flag; wait_for is not "forced")
            out["forced_refresh"] = True
        return out

    def get_doc(self, index: str, doc_id: str, routing: Optional[str] = None,
                source_includes=None, realtime: bool = True) -> dict:
        svc = self.indices.check_open(self.indices.get(index))
        shard = svc.route(doc_id, routing)
        self.counters["get"] += 1
        self._index_get_counts[svc.name] = \
            self._index_get_counts.get(svc.name, 0) + 1
        doc = shard.engine.get(doc_id, realtime=realtime)
        if doc is None:
            return {"_index": svc.name, "_id": doc_id, "found": False}
        out = {"_index": svc.name, "_id": doc_id, "_version": doc["_version"],
               "_seq_no": doc["_seq_no"], "_primary_term": doc["_primary_term"],
               "found": True}
        if svc.mapper_service.source_enabled:
            out["_source"] = doc["_source"]
        if doc.get("_routing") is not None:
            out["_routing"] = doc["_routing"]
        return out

    def delete_doc(self, index: str, doc_id: str, refresh: Optional[str] = None,
                   routing: Optional[str] = None,
                   if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None,
                   version: Optional[int] = None,
                   version_type: str = "internal") -> dict:
        svc = self.indices.check_open(self.indices.get(index))
        shard = svc.route(doc_id, routing)
        self.counters["delete"] += 1
        result = shard.engine.delete(doc_id, if_seq_no=if_seq_no,
                                     if_primary_term=if_primary_term,
                                     version=version,
                                     version_type=version_type)
        self._maybe_refresh(svc, refresh, shard=shard)
        out = {"_index": svc.name, "_id": doc_id, "_version": result.version,
               "result": "deleted", "_seq_no": result.seq_no,
               "_primary_term": result.primary_term,
               "_shards": {"total": 1, "successful": 1, "failed": 0}}
        if refresh in ("true", "", True):
            out["forced_refresh"] = True
        return out

    _UPDATE_FIELDS = ["doc", "script", "upsert", "doc_as_upsert",
                      "scripted_upsert", "detect_noop", "_source",
                      "if_seq_no", "if_primary_term", "lang"]

    @classmethod
    def _validate_update_body(cls, body: Optional[dict]) -> None:
        import difflib as _difflib
        for k in body or {}:
            if k not in cls._UPDATE_FIELDS:
                close = _difflib.get_close_matches(k, cls._UPDATE_FIELDS,
                                                   n=1)
                hint = f" did you mean [{close[0]}]?" if close else ""
                raise ParsingError(
                    f"[UpdateRequest] unknown field [{k}]{hint}")

    def update_doc(self, index: str, doc_id: str, body: dict,
                   refresh: Optional[str] = None,
                   routing: Optional[str] = None,
                   if_seq_no: Optional[int] = None,
                   if_primary_term: Optional[int] = None,
                   source_filter=None) -> dict:
        """_update API: partial doc merge, script update, upsert.

        Reference: `action/update/UpdateHelper.java`.
        """
        self._validate_update_body(body)
        if source_filter is None and body and "_source" in body:
            # body-level _source is the documented alternative to the
            # query param (UpdateRequest fetchSource)
            source_filter = body["_source"]
        # update auto-creates its index like the index API
        # (TransportUpdateAction routes through auto-create)
        svc = self.indices.check_open(self._index_or_autocreate(index))
        shard = svc.route(doc_id, routing)
        existing = shard.engine.get(doc_id)

        def _with_get(out, src):
            if source_filter is not None and source_filter is not False:
                doc = {"_source": copy.deepcopy(src)}
                self._apply_mget_projection(doc, {}, None, svc.name,
                                            source_filter)
                out["get"] = {"_source": doc.get("_source", {}),
                              "found": True}
            return out

        if existing is None:
            if "upsert" in body:
                out = self.index_doc(svc.name, doc_id, body["upsert"],
                                     refresh=refresh, routing=routing)
                return _with_get(out, body["upsert"])
            if body.get("doc_as_upsert") and "doc" in body:
                out = self.index_doc(svc.name, doc_id, body["doc"],
                                     refresh=refresh, routing=routing)
                return _with_get(out, body["doc"])
            raise DocumentMissingError(f"[{doc_id}]: document missing")
        if if_seq_no is not None and existing["_seq_no"] != if_seq_no or \
                if_primary_term is not None \
                and existing["_primary_term"] != if_primary_term:
            raise VersionConflictError(
                f"[{doc_id}]: version conflict, required seqNo "
                f"[{if_seq_no}], primary term [{if_primary_term}], "
                f"current document has seqNo [{existing['_seq_no']}] and "
                f"primary term [{existing['_primary_term']}]")
        source = copy.deepcopy(existing["_source"])
        if "doc" in body:
            _deep_merge(source, body["doc"])
            if body.get("detect_noop", True) \
                    and source == existing["_source"]:
                return _with_get({
                    "_index": svc.name, "_id": doc_id,
                    "_version": existing["_version"], "result": "noop",
                    "_seq_no": existing["_seq_no"],
                    "_primary_term": existing["_primary_term"],
                    "_shards": {"total": 0, "successful": 0,
                                "failed": 0}}, source)
        elif "script" in body:
            verdict: Dict[str, Any] = {}
            source = _apply_update_script(source, body["script"],
                                          ctx_extra=verdict)
            op = verdict.get("op", "index")
            if op == "none":
                # script vetoed the update (UpdateHelper: ctx.op = 'none')
                return {"_index": index, "_id": doc_id,
                        "_version": existing["_version"],
                        "result": "noop",
                        "_seq_no": existing["_seq_no"],
                        "_primary_term": existing["_primary_term"],
                        "_shards": {"total": 0, "successful": 0, "failed": 0}}
            if op == "delete":
                out = self.delete_doc(index, doc_id, refresh=refresh,
                                      routing=routing)
                out["result"] = "deleted"
                return out
        else:
            raise IllegalArgumentError("update requires [doc] or [script]")
        out = self.index_doc(svc.name, doc_id, source, refresh=refresh,
                             routing=routing,
                             if_seq_no=existing["_seq_no"],
                             if_primary_term=existing["_primary_term"])
        out["result"] = "updated"
        return _with_get(out, source)

    def mget(self, body: dict, default_index: Optional[str] = None,
             stored_fields=None, realtime: bool = True,
             refresh: bool = False, source_filter=None) -> dict:
        """_mget (reference: TransportMultiGetAction / MultiGetRequest).

        Validation aggregates per-item failures into one
        action_request_validation_exception; a missing index or document
        yields {found: false}, while a multi-index alias yields a per-doc
        error with root_cause (`MultiGetRequest.java` add() validation +
        TransportMultiGetAction per-item failure handling)."""
        from elasticsearch_tpu.common.errors import (
            ActionRequestValidationError, IllegalArgumentError,
            IndexNotFoundError)
        body = body or {}
        items: List[dict] = []
        verrs: List[str] = []
        for spec in body.get("docs") or []:
            index = spec.get("_index", default_index)
            if not index:
                verrs.append("index is missing")
            if "_id" not in spec:
                verrs.append("id is missing")
            if index and "_id" in spec:
                items.append({**spec, "_index": index})
        for doc_id in body.get("ids") or []:
            if not default_index:
                verrs.append("index is missing")
            else:
                items.append({"_index": default_index, "_id": doc_id})
        if not items and not verrs:
            verrs.append("no documents to get")
        if verrs:
            raise ActionRequestValidationError.of(verrs)

        docs = []
        refreshed = set()
        for spec in items:
            index = spec["_index"]
            doc_id = str(spec["_id"])
            routing = spec.get("routing")
            routing = str(routing) if routing is not None else None
            try:
                if refresh and index not in refreshed:
                    self.indices.get(index).refresh()
                    refreshed.add(index)
                doc = self.get_doc(index, doc_id, routing=routing,
                                   realtime=realtime)
            except IndexNotFoundError:
                docs.append({"_index": index, "_id": doc_id, "found": False})
                continue
            except IllegalArgumentError as e:
                docs.append({"_index": index, "_id": doc_id,
                             "error": e.to_wrapped_dict()})
                continue
            except SearchEngineError as e:
                docs.append({"_index": index, "_id": doc_id,
                             "error": e.to_dict()})
                continue
            self._apply_mget_projection(doc, spec, stored_fields, index,
                                        source_filter)
            docs.append(doc)
        return {"docs": docs}

    def _apply_mget_projection(self, doc: dict, spec: dict, req_stored_fields,
                               index: str, req_source=None) -> None:
        """stored_fields + per-doc _source filtering on a fetched doc."""
        from elasticsearch_tpu.search.service import _filter_source, _get_path
        if "_source" not in spec and req_source is not None:
            spec = {**spec, "_source": req_source}
        sf = spec.get("stored_fields", req_stored_fields)
        if sf:
            sf = [sf] if isinstance(sf, str) else list(sf)
            svc = self.indices.get(index)
            fields = {}
            for fname in sf:
                if fname.startswith("_"):
                    continue  # metadata fields ride at the top level
                mapper = svc.mapper_service.get(fname)
                if mapper is None or not mapper.params.get("store"):
                    continue
                val = _get_path(doc.get("_source") or {}, fname)
                if val is not None:
                    fields[fname] = val if isinstance(val, list) else [val]
            if fields:
                doc["fields"] = fields
            # stored_fields suppress _source unless the caller asked for
            # it explicitly (via the list or a truthy _source param)
            if "_source" not in sf and spec.get("_source") in (None, False):
                doc.pop("_source", None)
        src_spec = spec.get("_source")
        if src_spec is False:
            doc.pop("_source", None)
        elif isinstance(src_spec, (list, str)):
            inc = [src_spec] if isinstance(src_spec, str) else src_spec
            if doc.get("_source") is not None:
                doc["_source"] = _filter_source(doc["_source"], inc, [])
        elif isinstance(src_spec, dict):
            inc = src_spec.get("include", src_spec.get("includes", [])) or []
            exc = src_spec.get("exclude", src_spec.get("excludes", [])) or []
            inc = [inc] if isinstance(inc, str) else inc
            exc = [exc] if isinstance(exc, str) else exc
            if doc.get("_source") is not None:
                doc["_source"] = _filter_source(doc["_source"], inc, exc)

    def bulk(self, operations: List[dict], default_index: Optional[str] = None,
             refresh: Optional[str] = None, source_filter=None) -> dict:
        """_bulk: list of {action: meta} / source pairs already decoded.

        Reference: `TransportBulkAction` §3.3 — here single-node, grouped by
        shard implicitly by the engine's per-shard lock.
        """
        self.counters["bulk"] += 1
        # parse-time validation of every action line BEFORE any item
        # executes: a rejected request must not be partially applied
        # (BulkRequestParser rejects during parsing)
        ln = 0
        for j, line in enumerate(operations):
            if j != ln:
                continue
            if not isinstance(line, dict) or len(line) != 1:
                # the reference names the parser state it hit
                if isinstance(line, dict) and len(line) > 1:
                    expected, found = "END_OBJECT", "FIELD_NAME"
                elif isinstance(line, dict):
                    expected, found = "FIELD_NAME", "END_OBJECT"
                else:
                    expected, found = "START_OBJECT", "VALUE_STRING"
                raise IllegalArgumentError(
                    f"Malformed action/metadata line [{j + 1}], expected "
                    f"{expected} but found [{found}]")
            ((act, m),) = line.items()
            if act not in ("index", "create", "update", "delete") \
                    or not isinstance(m, dict):
                raise IllegalArgumentError(
                    f"Malformed action/metadata line [{j + 1}], found "
                    f"[{act}]")
            for dep in ("_version", "_routing", "_parent", "fields",
                        "_version_type", "_retry_on_conflict"):
                if dep in m:
                    raise IllegalArgumentError(
                        f"Action/metadata line [{j + 1}] contains an "
                        f"unknown parameter [{dep}]")
            ln += 1 if act == "delete" else 2
        items = []
        errors = False
        touched = set()
        i = 0
        while i < len(operations):
            action_line = operations[i]
            i += 1
            ((action, meta),) = action_line.items()
            index = meta.get("_index", default_index)
            doc_id = meta.get("_id")
            if doc_id is not None:
                doc_id = str(doc_id)  # numeric ids arrive as JSON numbers
            routing = meta.get("routing")
            if_seq_no = meta.get("if_seq_no")
            if_primary_term = meta.get("if_primary_term")
            try:
                if action in ("index", "create"):
                    source = operations[i]
                    i += 1
                    if doc_id == "":
                        raise IllegalArgumentError(
                            "if _id is specified it must not be empty")
                    op_type = "create" if action == "create" \
                        else meta.get("op_type", "index")
                    resp = self.index_doc(
                        index, doc_id, source, op_type=op_type,
                        routing=routing, if_seq_no=if_seq_no,
                        if_primary_term=if_primary_term,
                        version=meta.get("version"),
                        version_type=meta.get("version_type", "internal"))
                    status = 201 if resp["result"] == "created" else 200
                    # `index` + op_type create reports under `create`
                    # (BulkItemResponse opType rendering)
                    action = "create" if op_type == "create" else action
                elif action == "update":
                    body = operations[i]
                    i += 1
                    if doc_id == "":
                        raise IllegalArgumentError(
                            "if _id is specified it must not be empty")
                    src_spec = (body.pop("_source", None)
                                if isinstance(body, dict) else None)
                    if src_spec is None:
                        src_spec = meta.get("_source", source_filter)
                    resp = self.update_doc(index, doc_id, body,
                                           routing=routing,
                                           if_seq_no=if_seq_no,
                                           if_primary_term=if_primary_term,
                                           source_filter=src_spec)
                    status = 200
                elif action == "delete":
                    resp = self.delete_doc(
                        index, doc_id, routing=routing,
                        if_seq_no=if_seq_no,
                        if_primary_term=if_primary_term,
                        version=meta.get("version"),
                        version_type=meta.get("version_type", "internal"))
                    status = 200
                else:
                    raise IllegalArgumentError(
                        f"Malformed action/metadata line, found [{action}]")
                touched.add(resp["_index"])
                items.append({action: {**resp, "status": status}})
            except SearchEngineError as e:
                errors = True
                if action in ("index", "create", "update") and i <= len(operations):
                    pass
                items.append({action: {"_index": index, "_id": doc_id,
                                       "status": e.status, "error": e.to_dict()}})
        if refresh in ("true", "wait_for", True, ""):
            self._refresh_indices(touched)
        if refresh in ("true", "", True):
            for item in items:
                for inner in item.values():
                    if "error" not in inner:
                        inner["forced_refresh"] = True
        return {"took": 0, "errors": errors, "items": items}

    def _index_or_autocreate(self, index: str) -> IndexService:
        if not self.indices.exists(index):
            # auto-create applying matching templates (reference:
            # TransportBulkAction auto-create + MetaDataIndexTemplateService)
            resolved = self.templates.resolve(index)
            return self.indices.create_index(
                index, settings=resolved["settings"] or None,
                mappings=resolved["mappings"] if resolved["mappings"]["properties"] else None,
                aliases=resolved["aliases"] or None)
        return self.indices.get(index)

    def create_index_with_templates(self, name: str, settings=None,
                                    mappings=None, aliases=None) -> IndexService:
        """Explicit create: template values apply under the request's own."""
        resolved = self.templates.resolve(name)
        merged_settings = dict(resolved["settings"])
        if settings:
            merged_settings.update(settings)
        merged_mappings = {"properties": dict(resolved["mappings"]["properties"])}
        for k, v in ((mappings or {}).get("properties") or {}).items():
            merged_mappings["properties"][k] = v
        for meta_key in ("dynamic", "_source", "_meta", "_routing"):
            if mappings and meta_key in mappings:
                merged_mappings[meta_key] = mappings[meta_key]
        merged_aliases = dict(resolved["aliases"])
        merged_aliases.update(aliases or {})
        return self.indices.create_index(
            name, settings=merged_settings or None,
            mappings=merged_mappings if merged_mappings["properties"] or mappings else mappings,
            aliases=merged_aliases or None)

    def _expand_collapse_inner_hits(self, readers, body, collapse_spec,
                                    hits) -> None:
        from elasticsearch_tpu.index.mapping import AliasFieldMapper
        from elasticsearch_tpu.search.service import (
            execute_fetch_phase, execute_query_phase)

        inner = collapse_spec.get("inner_hits")
        specs = inner if isinstance(inner, list) else [inner]
        cfield = collapse_spec["field"]
        for hit in hits:
            vals = (hit.get("fields") or {}).get(cfield)
            gv = vals[0] if vals else None
            for spec in specs:
                name = spec.get("name", cfield)
                want = int(spec.get("size", 3))
                merged = []
                total = 0
                for svc, reader, store in readers:
                    read_field = cfield
                    raw_m = svc.mapper_service.get_raw(cfield) \
                        if hasattr(svc.mapper_service, "get_raw") \
                        else svc.mapper_service.get(cfield)
                    if isinstance(raw_m, AliasFieldMapper):
                        read_field = (raw_m.params or {}).get("path", cfield)
                    sub_body = {"query": {"bool": {
                        "must": [body["query"]] if body.get("query") else [],
                        "filter": [{"term": {read_field: gv}}]}},
                        "size": want}
                    for key in ("sort", "version", "seq_no_primary_term",
                                "docvalue_fields", "_source"):
                        if spec.get(key) is not None:
                            sub_body[key] = spec[key]
                    sub_result = execute_query_phase(
                        reader, svc.mapper_service, sub_body,
                        vector_store=store, index_name=svc.name)
                    total += sub_result.total_hits
                    sub_hits = execute_fetch_phase(
                        reader, svc.mapper_service, sub_body, sub_result,
                        index_name=svc.name,
                        index_settings=svc.settings.as_flat_dict())
                    merged.extend(sub_hits)
                if spec.get("sort") is None:
                    merged.sort(key=lambda h: -(h.get("_score") or 0.0))
                else:
                    merged.sort(key=lambda h: tuple(h.get("sort") or []))
                hit.setdefault("inner_hits", {})[name] = {"hits": {
                    "total": {"value": total, "relation": "eq"},
                    "max_score": (merged[0].get("_score")
                                  if merged else None),
                    "hits": merged[:want]}}

    def _search_rrf(self, index_expr: Optional[str], body: dict,
                    rrf: dict, ignore_throttled: bool) -> dict:
        """Reciprocal-rank fusion at the coordinator (BASELINE config 3:
        hybrid BM25 + kNN; the reference's designated fusion point is the
        rescore boundary — RRF composes the ranked lists instead:
        score(d) = Σ_lists 1 / (rank_constant + rank_list(d))).

        Sub-searches come from `sub_searches: [{query}, ...]` or, in the
        common hybrid shape, the top-level `query` plus `knn` clauses.
        """
        rank_constant = int(rrf.get("rank_constant", 60))
        window = int(rrf.get("rank_window_size", rrf.get("window_size", 100)))
        size = int(body.get("size", 10))
        frm = int(body.get("from", 0) or 0)
        body = self._rewrite_terms_lookup(body)

        sub_queries: List[dict] = []
        if body.get("sub_searches"):
            sub_queries = [s.get("query", {"match_all": {}})
                           for s in body["sub_searches"]]
        else:
            if body.get("query") is not None:
                sub_queries.append(body["query"])
            if body.get("knn") is not None:
                knn = body["knn"]
                # a knn LIST is one ranked list per clause (matching the
                # fused plan's leg expansion — hybrid_plan._sub_queries_of)
                if isinstance(knn, list):
                    sub_queries.extend({"knn": spec} for spec in knn)
                else:
                    sub_queries.append({"knn": knn})
        if len(sub_queries) < 2:
            raise IllegalArgumentError(
                "[rrf] requires at least 2 ranked lists (sub_searches, or "
                "query + knn)")

        passthrough = {k: v for k, v in body.items()
                       if k in ("_source", "docvalue_fields", "highlight")}
        start = time.perf_counter()

        # Fast path (single index): run the sub-searches as QUERY PHASES
        # only, fuse ranks on row ids, and fetch just the final `size` docs
        # — the query-then-fetch shape (SearchPhaseController), vs. the
        # general path below that materializes `window` full hits per list.
        try:
            services = self.indices.resolve_open(index_expr) \
                if index_expr and ":" not in index_expr else []
        except SearchEngineError:
            services = []
        from elasticsearch_tpu.common.settings import setting_bool
        if len(services) == 1 \
                and not setting_bool(services[0].settings.get("index.frozen")) \
                and "highlight" not in body:  # highlighting needs the
            # per-sub-search query context — the general path keeps it
            from elasticsearch_tpu.search.service import (
                ShardSearchResult, execute_fetch_phase, execute_query_phase)

            svc = services[0]
            if not body.get("__rrf_two_phase__"):
                # fused hybrid plan: whole queries coalesce through the
                # bounded per-index batcher, legs score in one device
                # dispatch each, RRF fuses vectorized. The inline
                # two-phase path below stays as the parity oracle
                # (tests/test_hybrid_plan.py proves byte-identical
                # results) and the escape hatch.
                resp = self._hybrid_executor(svc).submit(body)
                # the hybrid device path must feed the same telemetry
                # surfaces as the host query path: e2e latency histogram
                # + per-index slow log with phase breakdown and trace.
                # The executor ships the breakdown on a private key so
                # UNPROFILED breaches carry it too; pop it before the
                # response reaches the client.
                phases = resp.pop("_took_phases", None)
                took_s = time.perf_counter() - start
                _telemetrics.record("search.took", int(took_s * 1e9))
                _task = _teletrace.current_task()
                self.search_slow_log.maybe_log(
                    svc.settings, svc.name, took_s,
                    source={"rank": {"rrf": rrf}},
                    opaque_id=getattr(_task, "opaque_id", None),
                    trace=_teletrace.current_trace(),
                    phases=phases)
                return resp
            reader = svc.combined_reader()
            store = _MultiShardVectorStore(svc)
            breaker_bytes = reader.num_docs * 16
            self.breakers.add_estimate("request", breaker_bytes, "<rrf>")
            try:
                fused_rows: Dict[int, float] = {}
                for q in sub_queries:
                    result = execute_query_phase(
                        reader, svc.mapper_service,
                        {"query": q, "size": window},
                        vector_store=store, query_cache=self.caches.query,
                        index_settings=svc.settings.as_flat_dict(),
                        max_buckets=self._max_buckets(),
                        allow_expensive=self._allow_expensive(),
                        index_name=svc.name)
                    for rank_pos, row in enumerate(result.rows):
                        row = int(row)
                        fused_rows[row] = fused_rows.get(row, 0.0) + 1.0 / (
                            rank_constant + rank_pos + 1)
                ordered = sorted(fused_rows.items(),
                                 key=lambda kv: (-kv[1], kv[0]))
                top = ordered[frm:frm + size]
                final = ShardSearchResult(
                    0, np.asarray([r for r, _ in top], dtype=np.int64),
                    np.asarray([s for _, s in top], dtype=np.float32),
                    None, len(fused_rows), "eq", None,
                    top[0][1] if top else None)
                hits = execute_fetch_phase(reader, svc.mapper_service,
                                           {**passthrough, "size": size},
                                           final, index_name=svc.name)
            finally:
                self.breakers.release("request", breaker_bytes)
            for h, (_, score) in zip(hits, top):
                h["_score"] = score
            return {"took": int((time.perf_counter() - start) * 1000),
                    "timed_out": False,
                    "hits": {"total": {"value": len(fused_rows),
                                       "relation": "eq"},
                             "max_score": hits[0]["_score"] if hits else None,
                             "hits": hits}}

        fused: Dict[tuple, float] = {}
        hit_by_key: Dict[tuple, dict] = {}
        for q in sub_queries:
            sub_body = {"query": q, "size": window, **passthrough}
            resp = self.search(index_expr, sub_body,
                               ignore_throttled=ignore_throttled)
            for rank_pos, hit in enumerate(resp["hits"]["hits"]):
                key = (hit["_index"], hit["_id"])
                fused[key] = fused.get(key, 0.0) + 1.0 / (
                    rank_constant + rank_pos + 1)
                hit_by_key.setdefault(key, hit)
        ordered = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))
        hits = []
        for key, score in ordered[frm:frm + size]:
            hit = dict(hit_by_key[key])
            hit["_score"] = score
            hit.pop("sort", None)
            hits.append(hit)
        return {"took": int((time.perf_counter() - start) * 1000),
                "timed_out": False,
                "hits": {"total": {"value": len(fused), "relation": "eq"},
                         "max_score": hits[0]["_score"] if hits else None,
                         "hits": hits}}

    def _evict_stale_hybrid(self) -> None:
        """Drop executors whose IndexService is no longer live (index
        deleted or recreated): they pin the closed service's engines and
        the lexical store's tile/device arrays, and their counters must
        not keep flowing into _nodes/stats. Swept from every hybrid
        entry point because deletion has several paths (REST, cascades,
        ILM) and none of them knows about this cache."""
        for name, ex in list(self._hybrid.items()):
            if self.indices.indices.get(name) is not ex.svc:
                del self._hybrid[name]

    def _evict_stale_aggs(self) -> None:
        """Same sweep for device-agg engines: a deleted/recreated index's
        engine pins its columnar store and pollutes _nodes/stats."""
        for name, (svc, _eng) in list(self._aggs.items()):
            if self.indices.indices.get(name) is not svc:
                del self._aggs[name]

    def _agg_engine(self, svc):
        """Per-index device aggregation engine (search/agg_plan.py),
        created lazily like the hybrid executor; None when device aggs
        are disabled (`search.aggs.device_enabled: false`). A refresh
        listener resyncs warm columns in the background so a dashboard's
        first post-refresh query doesn't pay the column rebuild inline —
        the agg-store analog of `vectors/store.sync` at refresh."""
        from elasticsearch_tpu.common.settings import setting_bool
        enabled = self.settings.get("search.aggs.device_enabled")
        if enabled is not None and not setting_bool(enabled):
            return None
        with self._aggs_lock:
            self._evict_stale_aggs()
            cached = self._aggs.get(svc.name)
            if cached is not None and cached[0] is svc:
                return cached[1]
            from elasticsearch_tpu.search.agg_plan import AggEngine
            router = self.settings.get("search.aggs.cost_router")
            engine = AggEngine(svc.mapper_service,
                               warmup=self._dispatch_warmup,
                               cost_router=(self._agg_cost_router()
                                            if router is None
                                            or setting_bool(router)
                                            else False))

            def _resync(_reader, svc=svc, engine=engine):
                def run():
                    try:
                        reader = svc.combined_reader()
                        for field in engine.store.fields():
                            col = engine.store.column(reader, field)
                            engine.store.schedule_warmup(col)
                    except Exception:  # pragma: no cover - background
                        pass
                if engine.store.fields():
                    _threading.Thread(target=run, daemon=True,
                                      name="agg-column-resync").start()

            for shard in svc.shards:
                shard.engine.add_refresh_listener(_resync)
            self._aggs[svc.name] = (svc, engine)
            return engine

    def _agg_cost_router(self):
        """The node's ONE shared cost router, disk-backed at
        `<data>/_state/agg_router.json`: every index's agg engine trains
        the same per-node EWMA tables, each observation persists them,
        and a restart seeds them back instead of re-probing cold (the
        PR 19 leftover — `router_restores` counts the seeded families)."""
        router = getattr(self, "_agg_router", None)
        if router is None:
            import os as _os

            from elasticsearch_tpu.search.agg_plan import CostRouter
            state_dir = _os.path.join(self.indices.data_path, "_state")
            _os.makedirs(state_dir, exist_ok=True)
            router = CostRouter(
                persist_path=_os.path.join(state_dir, "agg_router.json"))
            self._agg_router = router
        return router

    def _aggs_stats_section(self) -> dict:
        """Device-aggregation counters summed over local indices
        (`_nodes/stats indices.aggs`): per-node device vs host-fallback
        routing (with reasons), agg-plan cache hit rate, cumulative
        device/assembly time, mesh dispatches, and columnar-store
        footprint."""
        out = {"searches": 0, "device_nodes": 0, "host_nodes": 0,
               "plan_cache_hits": 0, "plan_cache_misses": 0,
               "device_nanos": 0, "assemble_nanos": 0, "host_nanos": 0,
               "mesh_dispatches": 0, "router_host_routed": 0,
               "router_probes": 0, "router_restores": 0,
               "fallback_reasons": {},
               "columns": 0, "column_bytes": 0, "column_rebuilds": 0}
        router = getattr(self, "_agg_router", None)
        if router is not None:
            out["router_restores"] = router.restores
        with self._aggs_lock:
            self._evict_stale_aggs()
            engines = [eng for _svc, eng in self._aggs.values()]
        for eng in engines:
            for key in ("searches", "device_nodes", "host_nodes",
                        "plan_cache_hits", "plan_cache_misses",
                        "device_nanos", "assemble_nanos", "host_nanos",
                        "mesh_dispatches", "router_host_routed",
                        "router_probes"):
                out[key] += eng.stats.get(key, 0)
            # per-reason entries are {count, docs[, observed_max]}: doc
            # totals rank reasons by routed WORK, observed_max sizes
            # ladder growth (e.g. the ordinal count that busted the grid)
            for reason, ent in eng.stats.get("fallback_reasons",
                                             {}).items():
                agg = out["fallback_reasons"].setdefault(
                    reason, {"count": 0, "docs": 0})
                agg["count"] += ent["count"]
                agg["docs"] += ent["docs"]
                if "observed_max" in ent:
                    agg["observed_max"] = max(ent["observed_max"],
                                              agg.get("observed_max", 0))
            out["columns"] += eng.store.stats.get("columns", 0)
            out["column_bytes"] += eng.store.stats.get("bytes", 0)
            out["column_rebuilds"] += eng.store.stats.get("rebuilds", 0)
        return out

    def _hybrid_executor(self, svc):
        """Per-index fused hybrid serving path (plan cache + bounded
        combining queue), created lazily; replaced when the index is
        recreated under the same name."""
        from elasticsearch_tpu.common.settings import setting_bool
        from elasticsearch_tpu.ops import dispatch as _dispatch
        from elasticsearch_tpu.search.hybrid_plan import HybridExecutor
        self._evict_stale_hybrid()
        ex = self._hybrid.get(svc.name)
        if ex is None or ex.svc is not svc:
            s = self.settings
            # dispatch/finalize overlap only pays where device compute
            # runs on separate silicon: depth 2 on accelerator backends,
            # 1 on CPU floors (measured: a second in-flight dispatch on
            # the CPU backend contends with batch N's finalize for the
            # same cores and only adds tail — hybrid closed-loop p99/p50
            # 3.28 at depth 2 vs 2.76 at depth 1, same throughput)
            depth_default = 2 if _dispatch.is_accelerator_backend() else 1
            ex = HybridExecutor(
                self, svc,
                max_batch=int(s.get("search.hybrid.max_batch", 64)),
                max_queue_depth=int(
                    s.get("search.hybrid.max_queue_depth", 256)),
                deadline_ms=float(
                    s.get("search.hybrid.queue_deadline_ms", 10_000)),
                topup=setting_bool(s.get("search.hybrid.topup", True)),
                target_batch_latency_ms=float(
                    s.get("search.hybrid.target_batch_latency_ms", 2.0)),
                async_depth=int(s.get("search.hybrid.async_depth",
                                      depth_default)))
            self._hybrid[svc.name] = ex
        return ex

    def _hybrid_stats_section(self) -> dict:
        """Fused-hybrid serving counters summed over local indices:
        searches/batches through the plan executor, plan-cache hit rate,
        admission-control shedding, the closed-loop tail attribution
        (queue-wait vs device dispatch+sync vs hydrate), and the
        continuous batcher's scheduler counters (topups,
        deadline_sheds, overlap_hits)."""
        out = {"searches": 0, "batches": 0, "plan_cache_hits": 0,
               "plan_cache_misses": 0, "plan_nanos": 0, "score_nanos": 0,
               "fuse_nanos": 0, "hydrate_nanos": 0, "queue_wait_nanos": 0,
               "dispatch_nanos": 0, "sync_nanos": 0, "rejected_depth": 0,
               "shed_deadline": 0, "max_queue_depth_seen": 0,
               "request_cache_hits": 0, "request_cache_misses": 0,
               "request_cache_stores": 0,
               "scheduler": {"topups": 0, "deadline_sheds": 0,
                             "overlap_hits": 0, "pipelined_batches": 0},
               "sparse": {"searches": 0, "queries": 0, "rebuilds": 0,
                          "score_nanos": 0, "grid_fallbacks": 0},
               "late_interaction": {"searches": 0, "queries": 0,
                                    "rebuilds": 0, "score_nanos": 0,
                                    "grid_fallbacks": 0, "fields": {}}}
        self._evict_stale_hybrid()
        for ex in self._hybrid.values():
            for key in ("searches", "batches", "plan_cache_hits",
                        "plan_cache_misses", "plan_nanos", "score_nanos",
                        "fuse_nanos", "hydrate_nanos", "queue_wait_nanos",
                        "dispatch_nanos", "sync_nanos",
                        "request_cache_hits", "request_cache_misses",
                        "request_cache_stores"):
                out[key] += ex.stats.get(key, 0)
            for key in ("searches", "queries", "rebuilds", "score_nanos"):
                out["sparse"][key] += ex.sparse.stats.get(key, 0)
                out["late_interaction"][key] += ex.late.stats.get(key, 0)
            out["sparse"]["grid_fallbacks"] += ex.stats.get(
                "sparse_grid_fallbacks", 0)
            out["late_interaction"]["grid_fallbacks"] += ex.stats.get(
                "maxsim_grid_fallbacks", 0)
            out["late_interaction"]["fields"].update(ex.late.field_stats())
            bs = ex.batcher.stats
            out["rejected_depth"] += bs.get("rejected_depth", 0)
            out["shed_deadline"] += bs.get("shed_deadline", 0)
            out["max_queue_depth_seen"] = max(
                out["max_queue_depth_seen"], bs.get("max_depth_seen", 0))
            for key, val in ex.scheduler_snapshot().items():
                out["scheduler"][key] += val
        return out

    def _run_query_phase(self, svc, reader, store, body, use_partial_aggs,
                         frozen):
        """One index's query phase. Frozen indices run on the
        single-threaded search_throttled pool (queue 100): cold data may
        be searched, never at the expense of hot traffic (x-pack
        frozen-indices + ThreadPool.java:129)."""
        kwargs = dict(vector_store=store, partial_aggs=use_partial_aggs,
                      query_cache=self.caches.query,
                      index_settings=svc.settings.as_flat_dict(),
                      max_buckets=self._max_buckets(),
                      allow_expensive=self._allow_expensive(),
                      index_name=svc.name,
                      agg_engine=self._agg_engine(svc))
        from elasticsearch_tpu.search.service import execute_query_phase
        if frozen:
            return self.thread_pool.submit(
                "search_throttled", execute_query_phase,
                reader, svc.mapper_service, body, **kwargs).result()
        return execute_query_phase(reader, svc.mapper_service, body, **kwargs)

    @staticmethod
    def _maybe_refresh(svc: IndexService, refresh, shard=None) -> None:
        # a doc-level ?refresh=true refreshes only the TARGET shard
        # (TransportShardBulkAction) — other shards' unrefreshed
        # tombstones/docs must stay invisible
        if refresh in ("true", "wait_for", True, ""):
            if shard is not None:
                shard.engine.refresh()
            else:
                svc.refresh()

    def _refresh_indices(self, names) -> None:
        """Refresh hook for bulk epilogues — overridden by the clustered
        deployment to broadcast instead of touching local services."""
        for name in names:
            self.indices.get(name).refresh()

    # ---------------------------------------------------------------- search
    def search(self, index_expr: Optional[str], body: Optional[dict],
               ignore_throttled: bool = True,
               ignore_unavailable: bool = False,
               allow_no_indices: bool = True,
               expand_wildcards: Optional[str] = None) -> dict:
        body = body or {}
        rank = body.get("rank")
        if isinstance(rank, dict) and "rrf" in rank:
            return self._search_rrf(index_expr, body, rank["rrf"] or {},
                                    ignore_throttled)
        # cross-cluster search: split `alias:index` parts, fan out, merge
        # (reference: TransportSearchAction + SearchResponseMerger)
        if index_expr and ":" in index_expr:
            from elasticsearch_tpu.xpack.ccr import merge_ccs_responses
            local_expr, remote_exprs = self.remotes.split_indices(index_expr)
            remote_resps, clusters = self.remotes.search_remotes(
                remote_exprs, body)
            local_resp = self.search(local_expr, body) if local_expr else None
            return merge_ccs_responses(local_resp, remote_resps, body,
                                       clusters)
        start = time.perf_counter()
        body = self._rewrite_terms_lookup(body)
        if ignore_unavailable and index_expr:
            # IndicesOptions.lenientExpandOpen: missing/closed concrete
            # names silently drop from the target set
            kept = []
            for part in index_expr.split(","):
                part = part.strip()
                try:
                    for svc in self.indices.resolve(part):
                        if not svc.closed:
                            kept.append(svc.name)
                except SearchEngineError:
                    continue
            services = self.indices.resolve_open(",".join(kept)) \
                if kept else []
        else:
            ew = {t.strip() for t in str(expand_wildcards or "open").split(",")
                  if t.strip()}
            if ew & {"closed", "all"}:
                # expand_wildcards=closed surfaces closed matches, and a
                # closed index in the target set is an error
                # (IndicesOptions.forbidClosedIndices for search)
                services = self.indices.resolve(index_expr,
                                                expand_closed=True)
                for svc in services:
                    self.indices.check_open(svc)
            else:
                services = self.indices.resolve_open(index_expr)
        if not allow_no_indices and not services and index_expr \
                and "*" in index_expr:
            raise IndexNotFoundError(index_expr)
        if ignore_throttled:
            # frozen indices sit out of normal searches unless the caller
            # passes ignore_throttled=false (reference:
            # x-pack/plugin/frozen-indices + search_throttled pool)
            from elasticsearch_tpu.common.settings import setting_bool
            services = [s for s in services
                        if not setting_bool(s.settings.get("index.frozen"))]
        readers = []
        for svc in services:
            reader = svc.combined_reader()
            store = _MultiShardVectorStore(svc)
            readers.append((svc, reader, store))

        # request breaker accounts the candidate working set (reference:
        # QueryPhase checks the request breaker while collecting)
        breaker_bytes = sum(r.num_docs for _, r, _ in readers) * 16
        self.breakers.add_estimate("request", breaker_bytes, "<search>")

        profile_enabled = bool(body.get("profile"))
        profile_shards = []
        # execute per index, merge across indices by score/sort; with >1
        # index the aggs travel as mergeable partial states and are
        # finalized once after the reduce (agg_partials, the
        # InternalAggregation.reduce analog)
        # indices_boost: per-index score multipliers, resolved up front so
        # unknown names fail the request (SearchRequest#indicesBoost)
        boosts: Dict[str, float] = {}
        ib = body.get("indices_boost")
        if ib:
            entries = ib.items() if isinstance(ib, dict) else \
                [e for d in ib for e in d.items()]
            for expr, boost in entries:
                matched = self.indices.resolve(expr, expand_hidden=True) \
                    if ("*" in expr or self.indices.exists(expr)) else []
                if not matched:
                    if ignore_unavailable:
                        continue
                    raise IndexNotFoundError(expr)
                for svc in matched:
                    boosts.setdefault(svc.name, float(boost))

        aggs_spec = body.get("aggs") or body.get("aggregations")
        if aggs_spec:
            # builder-time validation (the reference rejects bad agg params
            # at request parse, even when zero shards participate)
            from elasticsearch_tpu.search.aggregations import validate_aggs

            def _field_type(f):
                for svc in services:
                    m = svc.mapper_service.get(f)
                    if m is not None:
                        return m.type_name
                return None
            validate_aggs(aggs_spec, _field_type)
        use_partial_aggs = bool(aggs_spec) and len(readers) > 1
        all_hits = []
        total = 0
        relation = "eq"
        max_score = None
        merged_aggs = None
        phase_nanos = {"query_nanos": 0, "fetch_nanos": 0, "merge_nanos": 0}
        shard_failures: List[dict] = []
        pre_filter = body.pop("__pre_filter_shard_size__", None)
        skipped_shards = 0
        try:
            for svc, reader, store in readers:
                if pre_filter is not None and body.get("query") is not None \
                        and not _has_global_agg(body.get("aggs")
                                                or body.get("aggregations")):
                    from elasticsearch_tpu.search.caches import can_match
                    if not can_match(reader, svc.mapper_service, body):
                        # can_match pre-filter: provably-empty shards are
                        # SKIPPED, not executed (CanMatchPreFilterSearchPhase)
                        skipped_shards += svc.num_shards
                        continue
                q_start = time.perf_counter_ns()
                if profile_enabled:
                    # per-shard dispatch trace: which shape bucket every
                    # device kernel hit and what compiling cost (empty in
                    # steady state; `profile.dispatch` renders it)
                    from elasticsearch_tpu.ops import dispatch as _dispatch
                    _dispatch.DISPATCH.record_events(True)
                # shard request cache: query-phase results keyed on the
                # reader CONTENT fingerprint (search/caches.reader_
                # fingerprint) — a refresh that changed nothing keeps
                # its hits, any ingest/delete/merge invalidates. Two
                # rungs share the policy: the legacy host rung (size=0
                # aggs/counts, the device-agg engine's dashboard shape)
                # and the device rung (kNN-bearing bodies, size > 0 —
                # the query phase IS the device dispatch there).
                from elasticsearch_tpu.search.caches import (
                    reader_fingerprint)
                cache_key = None
                cache_used = None
                cache_hit = False
                result = None
                # device rung first: it claims every knn-bearing body
                # (flag-opted-in ones included), so the host rung keeps
                # its original host-side population (size=0 aggs/counts)
                if self._device_request_cache_enabled() \
                        and self.caches.device_request.device_cacheable(
                            body):
                    cache_used = self.caches.device_request
                elif self.caches.request.cacheable_tracked(body):
                    cache_used = self.caches.request
                if cache_used is not None:
                    # partial vs finalized agg trees differ per request shape
                    # (multi-index searches ship partials); max_buckets is
                    # dynamic, so a changed limit must miss the cache, and a
                    # mesh-policy reconfigure must miss rather than serve a
                    # result (and its routing diagnostics) computed under
                    # the old serving config
                    from elasticsearch_tpu.parallel import policy as _policy
                    cache_key = cache_used.key(
                        (svc.name, svc.uuid, use_partial_aggs,
                         self._max_buckets(), self._allow_expensive(),
                         _policy.config_epoch()),
                        reader_fingerprint(reader), body)
                    result = cache_used.get(cache_key)
                    cache_hit = result is not None
                if result is None:
                    from elasticsearch_tpu.common.settings import setting_bool
                    frozen = setting_bool(svc.settings.get("index.frozen"))
                    try:
                        result = self._run_query_phase(
                            svc, reader, store, body, use_partial_aggs,
                            frozen)
                    except ArrayIndexOutOfBoundsError as e:
                        # execution-class failure inside an aggregator
                        # (HDR percentiles fed a negative). The fused
                        # single-node pass spans every internal shard, but
                        # the reference fails at SHARD granularity: probe
                        # each shard alone — only shards whose MATCHED
                        # docs trip the aggregator fail — then retry the
                        # fused pass without them (partial response).
                        all_ids = frozenset(
                            s.shard_id for s in svc.shards)
                        failed = set()
                        for s in svc.shards:
                            probe_reader = svc.combined_reader(
                                exclude_shards=all_ids - {s.shard_id})
                            probe_store = _ShardScopedStore(
                                store, frozenset({s.shard_id}))
                            try:
                                self._run_query_phase(
                                    svc, probe_reader, probe_store, body,
                                    use_partial_aggs, frozen)
                            except ArrayIndexOutOfBoundsError:
                                failed.add(s.shard_id)
                        if not failed:
                            # combined raised but no single shard does —
                            # cannot attribute; fail them all
                            failed = set(all_ids)
                        for sid in sorted(failed):
                            shard_failures.append({
                                "shard": sid, "index": svc.name,
                                "node": self.node_id,
                                "reason": e.to_dict()})
                        if len(failed) >= svc.num_shards:
                            continue
                        reader = svc.combined_reader(
                            exclude_shards=frozenset(failed))
                        result = self._run_query_phase(
                            svc, reader,
                            _ShardScopedStore(store, all_ids - failed),
                            body, use_partial_aggs, frozen)
                        cache_key = None  # partial result: never cache
                    if cache_key is not None:
                        cache_used.put(cache_key, result)
                q_nanos = time.perf_counter_ns() - q_start
                phase_nanos["query_nanos"] += q_nanos
                _teletrace.record_span(f"query[{svc.name}]", q_nanos,
                                       index=svc.name)
                for f in getattr(result, "failures", None) or []:
                    f = dict(f)
                    f["index"] = svc.name
                    f["node"] = self.node_id
                    shard_failures.append(f)
                total += result.total_hits
                if result.total_relation == "gte":
                    relation = "gte"
                factor = boosts.get(svc.name, 1.0)
                if result.max_score is not None:
                    max_score = max(max_score or -1e30,
                                    result.max_score * factor)
                f_start = time.perf_counter_ns()
                hits = execute_fetch_phase(
                    reader, svc.mapper_service, body, result,
                    index_name=svc.name,
                    index_settings=svc.settings.as_flat_dict())
                f_nanos = time.perf_counter_ns() - f_start
                phase_nanos["fetch_nanos"] += f_nanos
                _teletrace.record_span(f"fetch[{svc.name}]", f_nanos,
                                       index=svc.name)
                for h, score, sv in zip(hits, result.scores,
                                        result.sort_values or [None] * len(hits)):
                    if factor != 1.0 and h.get("_score") is not None:
                        h["_score"] = float(h["_score"]) * factor
                    all_hits.append((h, float(score) * factor, sv))
                if result.aggregations is not None:
                    if merged_aggs is None:
                        merged_aggs = result.aggregations
                    else:
                        from elasticsearch_tpu.search.agg_partials import (
                            merge_partial_aggs,
                        )
                        merged_aggs = merge_partial_aggs(
                            merged_aggs, result.aggregations, aggs_spec)
                if profile_enabled:
                    from elasticsearch_tpu.ops import dispatch as _dispatch
                    from elasticsearch_tpu.search.profile import shard_profile
                    events = _dispatch.DISPATCH.drain_events()
                    _dispatch.DISPATCH.record_events(False)
                    cache_note = None
                    if cache_used is not None:
                        cache_note = {
                            "rung": ("shard_request"
                                     if cache_used is self.caches.request
                                     else "device_request"),
                            "hit": cache_hit}
                    profile_shards.append(shard_profile(
                        svc.name, body, q_nanos, f_nanos,
                        result.total_hits,
                        knn_phases=result.knn_phases,
                        dispatch_events=events,
                        aggs_profile=result.aggs_profile,
                        cache=cache_note))
        finally:
            self.breakers.release("request", breaker_bytes)
            if profile_enabled:
                # a query-phase error must not leave the thread-local
                # dispatch trace recording into later requests
                from elasticsearch_tpu.ops import dispatch as _dispatch
                _dispatch.DISPATCH.record_events(False)
        n_shards_total = sum(s.num_shards for s, _, _ in readers)
        if shard_failures and n_shards_total \
                and len(shard_failures) >= n_shards_total - skipped_shards:
            # every executed shard failed: the whole phase fails
            # (SearchPhaseExecutionException "all shards failed")
            from elasticsearch_tpu.common.errors import (
                SearchPhaseExecutionError,
            )
            raise SearchPhaseExecutionError("query", "all shards failed",
                                            shard_failures)
        self.counters["search"] += 1
        for g in body.get("stats") or []:
            self._search_groups[str(g)] = \
                self._search_groups.get(str(g), 0) + 1

        m_start = time.perf_counter_ns()
        sort_spec = body.get("sort")
        if sort_spec:
            all_hits.sort(key=lambda t: _sort_key_tuple(t[2], body))
        else:
            all_hits.sort(key=lambda t: -t[1])
        phase_nanos["merge_nanos"] = time.perf_counter_ns() - m_start
        _teletrace.record_span("merge", phase_nanos["merge_nanos"],
                               hits=len(all_hits))
        collapse_spec = body.get("collapse")
        if collapse_spec and len(readers) > 1:
            # cross-index collapse: per-index phases deduped their own
            # groups; the merged ranking dedupes across indices by the
            # group value each hit carries in `fields`
            seen_groups = set()
            deduped = []
            for t in all_hits:
                vals = (t[0].get("fields") or {}).get(collapse_spec["field"])
                key = vals[0] if vals else None
                if key in seen_groups:
                    continue
                seen_groups.add(key)
                deduped.append(t)
            all_hits = deduped
        frm = int(body.get("from", 0) or 0)
        size = int(body.get("size", 10) if body.get("size") is not None else 10)
        window = all_hits[frm:frm + size]
        if collapse_spec and collapse_spec.get("inner_hits") \
                and len(readers) > 1:
            # inner_hits expand across EVERY index (ExpandSearchPhase runs
            # one multi-index sub-search per collapsed hit); the per-index
            # fetch saw only its own shard
            self._expand_collapse_inner_hits(readers, body, collapse_spec,
                                             [t[0] for t in window])

        resp = {
            "took": int((time.perf_counter() - start) * 1000),
            "timed_out": False,
            "_shards": {"total": sum(s.num_shards for s, _, _ in readers),
                        "successful": sum(s.num_shards for s, _, _ in readers)
                        - len(shard_failures),
                        "skipped": skipped_shards,
                        "failed": len(shard_failures),
                        **({"failures": shard_failures}
                           if shard_failures else {})},
            "hits": {
                "total": {"value": total, "relation": relation},
                "max_score": max_score,
                "hits": [h for h, _, _ in window],
            },
        }
        brs = body.get("batched_reduce_size")
        n_sh = resp["_shards"]["total"]
        if brs and int(brs) < n_sh:
            # phases: one partial reduce per filled buffer + the final
            # reduce (QueryPhaseResultConsumer counting)
            resp["num_reduce_phases"] = -(-n_sh // int(brs)) + 1
        if body.get("track_total_hits") is False \
                or body.get("track_total_hits") == -1:
            # hit counting disabled (false or the -1 sentinel): no total
            # in the response (RestSearchAction)
            del resp["hits"]["total"]
        else:
            track = body.get("track_total_hits")
            if isinstance(track, int) and not isinstance(track, bool) \
                    and total > track:
                # coordinator-level cap: per-index phases may each be under
                # the limit while the summed total crosses it
                resp["hits"]["total"] = {"value": track, "relation": "gte"}
        if merged_aggs is not None:
            if use_partial_aggs:
                from elasticsearch_tpu.search.agg_partials import finalize_aggs
                merged_aggs = finalize_aggs(merged_aggs, aggs_spec)
            resp["aggregations"] = merged_aggs
        if profile_enabled:
            resp["profile"] = {"shards": profile_shards}
        # slow log (reference: SearchSlowLog thresholds per index) —
        # breaches carry the phase breakdown, the caller's X-Opaque-ID,
        # and this request's trace (id + top spans) when sampled
        took_s = time.perf_counter() - start
        _telemetrics.record("search.took", int(took_s * 1e9))
        _task = _teletrace.current_task()
        for svc, _, _ in readers:
            self.search_slow_log.maybe_log(
                svc.settings, svc.name, took_s, source=body.get("query"),
                opaque_id=getattr(_task, "opaque_id", None),
                trace=_teletrace.current_trace(),
                phases=dict(phase_nanos))

        suggest_spec = body.get("suggest")
        if suggest_spec:
            from elasticsearch_tpu.search.extras import execute_suggest
            from elasticsearch_tpu.search.queries import SearchContext
            merged_suggest: Dict[str, list] = {}
            for svc, reader, _ in readers:
                ctx = SearchContext(reader, svc.mapper_service)
                for name, entries in execute_suggest(
                        ctx, suggest_spec, index_name=svc.name).items():
                    if name not in merged_suggest:
                        merged_suggest[name] = entries
                    else:
                        for a, b in zip(merged_suggest[name], entries):
                            a["options"] = sorted(
                                a["options"] + b["options"],
                                key=lambda o: -o.get("score", o.get("_score", 0.0)))
            resp["suggest"] = merged_suggest
        return resp

    # ----------------------------------------------------------------- scroll
    def search_scroll_start(self, index_expr: Optional[str], body: Optional[dict],
                            keep_alive: str = "1m",
                            ignore_throttled: bool = True) -> dict:
        """Initial search with ?scroll=: snapshot all matching docs in order,
        return the first page + a scroll id."""
        body = self._rewrite_terms_lookup(dict(body or {}))
        if body.get("collapse") is not None:
            raise IllegalArgumentError(
                "cannot use `collapse` in a scroll context")
        size = int(body.get("size", 10) if body.get("size") is not None else 10)
        entries = []  # (svc, reader, row, score, sort_values)
        total = 0
        from elasticsearch_tpu.common.settings import setting_bool
        services = self.indices.resolve_open(index_expr)
        for svc in services:
            mrw = int(svc.settings.get("index.max_result_window", 10_000))
            if size > mrw:
                raise IllegalArgumentError(
                    f"Batch size is too large, size must be less than or "
                    f"equal to: [{mrw}] but was [{size}]. Scroll batch "
                    f"sizes cost as much memory as result windows so they "
                    f"are controlled by the [index.max_result_window] index "
                    f"level setting.")
        if ignore_throttled:
            services = [s for s in services
                        if not setting_bool(s.settings.get("index.frozen"))]
        # scroll slicing (search/slice/SliceBuilder) is applied inside
        # execute_query_phase: shard-level when max <= shards, hashed
        # _id terms otherwise
        for svc in services:
            reader = svc.combined_reader()
            store = _MultiShardVectorStore(svc)
            # scroll snapshots EVERY matching doc — deep pagination past the
            # 10k window is the point of scrolling
            big = dict(body)
            big["size"] = max(reader.num_docs, 1)
            big["__unbounded_window__"] = True
            big["track_total_hits"] = True
            big.pop("from", None)
            result = execute_query_phase(
                reader, svc.mapper_service, big, vector_store=store,
                index_settings=svc.settings.as_flat_dict(),
                index_name=svc.name)
            kept_rows = list(range(len(result.rows)))
            total += result.total_hits
            for i in kept_rows:
                row = result.rows[i]
                sv = result.sort_values[i] if result.sort_values is not None else None
                entries.append((svc, reader, int(row), float(result.scores[i]), sv))
        if body.get("sort"):
            entries.sort(key=lambda t: _sort_key_tuple(t[4], body))
        else:
            entries.sort(key=lambda t: -t[3])
        keep_s = parse_time_value(keep_alive, "scroll")
        scroll_id = self.scrolls.create(entries, body, keep_s)
        sc = self.scrolls.get(scroll_id)
        sc.total = total
        resp = self._scroll_page(sc, size)
        resp["_scroll_id"] = scroll_id
        return resp

    def search_scroll_next(self, scroll_id: str,
                           keep_alive: Optional[str] = None) -> dict:
        sc = self.scrolls.get(scroll_id)
        if keep_alive:
            sc.keep_alive = parse_time_value(keep_alive, "scroll")
        size = int(sc.body.get("size", 10) if sc.body.get("size") is not None else 10)
        resp = self._scroll_page(sc, size)
        resp["_scroll_id"] = scroll_id
        return resp

    def _scroll_page(self, sc, size: int) -> dict:
        page = sc.slices[sc.cursor: sc.cursor + size]
        sc.cursor += len(page)
        hits = []
        for svc, reader, row, score, sv in page:
            hit = {"_index": svc.name, "_id": reader.get_id(row),
                   "_score": score if not sc.body.get("sort") else None,
                   "_source": reader.get_source(row)}
            if sv is not None:
                hit["sort"] = list(sv)
            hits.append(hit)
        total = getattr(sc, "total", len(sc.slices))
        return {"took": 0, "timed_out": False,
                "_shards": {"total": 1, "successful": 1, "skipped": 0, "failed": 0},
                "hits": {"total": {"value": total, "relation": "eq"},
                         "max_score": None, "hits": hits}}

    def pending_cluster_tasks(self) -> list:
        return []

    def clear_scroll(self, scroll_id: str) -> dict:
        freed = 1 if self.scrolls.delete(scroll_id) else 0
        return {"succeeded": True, "num_freed": freed}

    def clear_all_scrolls(self) -> dict:
        return {"succeeded": True, "num_freed": self.scrolls.delete_all()}

    def count(self, index_expr: Optional[str], body: Optional[dict]) -> dict:
        body = self._rewrite_terms_lookup(dict(body or {}))
        body["size"] = 0
        body.pop("sort", None)
        total = 0
        for svc in self.indices.resolve_open(index_expr):
            reader = svc.combined_reader()
            result = execute_query_phase(
                reader, svc.mapper_service,
                {**body, "track_total_hits": True},
                vector_store=_MultiShardVectorStore(svc),
                index_name=svc.name)
            total += result.total_hits
        return {"count": total, "_shards": {"total": 1, "successful": 1,
                                            "skipped": 0, "failed": 0}}

    def msearch(self, lines: List[dict]) -> dict:
        responses = []
        i = 0
        while i < len(lines):
            header = lines[i]
            i += 1
            body = lines[i] if i < len(lines) else {}
            i += 1
            try:
                resp = self.search(header.get("index"), body)
                resp["status"] = 200
                responses.append(resp)
            except SearchEngineError as e:
                responses.append({"error": e.to_wrapped_dict(),
                                  "status": e.status})
        return {"took": 0, "responses": responses}

    def analyze(self, body: dict, index: Optional[str] = None) -> dict:
        from elasticsearch_tpu.index.analysis import (
            Analyzer, _as_list, _builtin_filter, _builtin_tokenizer,
            _build_filter, _build_tokenizer,
        )
        text = body.get("text", "")
        texts = text if isinstance(text, list) else [text]
        registry = DEFAULT_REGISTRY
        max_tokens = 10_000
        if index and self.indices.exists(index):
            # index-scoped: custom analyzers from index.analysis.* settings
            svc = self.indices.get(index)
            registry = svc.analysis_registry
            max_tokens = int(svc.settings.get(
                "index.analyze.max_token_count", 10_000))

        custom = "tokenizer" in body or "filter" in body \
            or "char_filter" in body
        filters = []
        filter_names = []
        if custom:
            tok_spec = body.get("tokenizer", "keyword")
            if isinstance(tok_spec, dict):
                tokenizer = _build_tokenizer(tok_spec)
                tok_name = tok_spec.get("type", "custom")
            else:
                tokenizer = _builtin_tokenizer(str(tok_spec))
                tok_name = str(tok_spec)
            for f in _as_list(body.get("filter", [])) \
                    if not isinstance(body.get("filter"), dict) \
                    else [body["filter"]]:
                if isinstance(f, dict):
                    filters.append(_build_filter(f))
                    filter_names.append(f.get("type", "custom"))
                else:
                    filters.append(_builtin_filter(str(f)))
                    filter_names.append(str(f))
            analyzer = Analyzer("__custom__", tokenizer, filters)
            analyzer_name = None
        else:
            analyzer_name = body.get("analyzer", "standard")
            analyzer = registry.get(analyzer_name)

        def _render(toks, pos_base=0):
            return [{"token": t.term, "start_offset": t.start_offset,
                     "end_offset": t.end_offset, "type": "<ALPHANUM>",
                     "position": pos_base + t.position} for t in toks]

        tokens = []
        tokenizer_tokens = []
        pos = 0
        for t in texts:
            text_tokens = analyzer.analyze(str(t))
            if len(tokens) + len(text_tokens) > max_tokens:
                raise IllegalArgumentError(
                    f"The number of tokens produced by calling _analyze "
                    f"has exceeded the allowed maximum of [{max_tokens}]. "
                    f"This limit can be set by changing the "
                    f"[index.analyze.max_token_count] index level setting.")
            tokens.extend(_render(text_tokens, pos))
            if custom:
                tokenizer_tokens.extend(_render(analyzer.tokenizer(str(t)),
                                                pos))
            # position gap of 1 between texts, like multi-valued fields
            pos += len(text_tokens) + 1
        if body.get("explain"):
            if custom:
                detail = {"custom_analyzer": True,
                          "tokenizer": {"name": tok_name,
                                        "tokens": tokenizer_tokens}}
                if filter_names:
                    detail["tokenfilters"] = [
                        {"name": n, "tokens": tokens}
                        for n in filter_names]
                return {"detail": detail}
            return {"detail": {"custom_analyzer": False,
                               "analyzer": {"name": analyzer_name,
                                            "tokens": tokens}}}
        return {"tokens": tokens}

    # ----------------------------------------------------------------- stats
    def _rewrite_terms_lookup(self, body: dict) -> dict:
        """Coordinator rewrite of terms-lookup clauses: fetch the source
        doc ONCE and inline its values (reference:
        TermsQueryBuilder.doRewrite + GetRequest on the coordinator)."""
        def has_terms(node):
            # cheap key scan — str()/dumps of a body holding a dense query
            # vector costs more than the whole rewrite
            if isinstance(node, dict):
                if "terms" in node:
                    return True
                return any(has_terms(v) for v in node.values())
            if isinstance(node, list) and node \
                    and isinstance(node[0], (dict, list)):
                return any(has_terms(i) for i in node)
            return False

        scope = {k: (body or {}).get(k)
                 for k in ("query", "aggs", "aggregations")
                 if (body or {}).get(k) is not None}
        if not scope or not has_terms(scope):
            return body
        import copy as _copy
        from elasticsearch_tpu.search.service import _get_path
        body = dict(body)
        for k in scope:
            body[k] = _copy.deepcopy(body[k])

        def walk(node):
            if isinstance(node, dict):
                t = node.get("terms")
                if isinstance(t, dict):
                    for f, v in list(t.items()):
                        if f in ("boost", "_name") or not isinstance(v, dict):
                            continue
                        if "index" not in v:
                            continue
                        doc = self.get_doc(v["index"], str(v.get("id")),
                                           routing=v.get("routing"))
                        vals = _get_path(doc.get("_source") or {},
                                         str(v.get("path", "")))
                        t[f] = (vals if isinstance(vals, list)
                                else [vals] if vals is not None else [])
                for val in node.values():
                    walk(val)
            elif isinstance(node, list):
                for item in node:
                    walk(item)
        for k in scope:
            walk(body[k])
        return body

    def _cluster_setting(self, key: str):
        """Dynamic cluster setting lookup, transient before persistent
        (ClusterSettings precedence); accepts flat or nested storage."""
        for scope in ("transient", "persistent"):
            s = self.cluster_settings.get(scope, {})
            v = s.get(key)
            if v is None:
                node = s
                for part in key.split("."):
                    node = node.get(part) if isinstance(node, dict) else None
                v = node
            if v is not None:
                return v
        return None

    def _allow_expensive(self) -> bool:
        v = self._cluster_setting("search.allow_expensive_queries")
        return v is None or str(v).lower() != "false"

    def _device_request_cache_enabled(self) -> bool:
        """`search.request_cache.device_paths` (default on): the shard
        request cache rung on the fused device paths — hybrid executor
        responses and kNN/device-agg query-phase results. Dynamic
        cluster setting wins over the node setting, so a live cluster
        can turn the rung off without restart."""
        v = self._cluster_setting("search.request_cache.device_paths")
        if v is None:
            v = self.settings.get("search.request_cache.device_paths")
        return v is None or str(v).lower() != "false"

    def _max_buckets(self) -> Optional[int]:
        v = self._cluster_setting("search.max_buckets")
        return int(v) if v is not None else None

    def cluster_health(self, index: Optional[str] = None,
                       level: str = "cluster",
                       expand_wildcards: str = "all") -> dict:
        """Single-node health: replicas can never assign, so a replicated
        index makes the cluster yellow (ClusterStateHealth semantics).
        Closed indices count too (replicated in 8.0); health defaults to
        expanding BOTH open and closed wildcards."""
        tokens = {t for t in str(expand_wildcards).split(",") if t}
        want_open = bool(tokens & {"open", "all"})
        want_closed = bool(tokens & {"closed", "all"})
        missing_concrete = False
        if index:
            import fnmatch as _fn
            services = []
            for part in index.split(","):
                part = part.strip()
                matched = False
                for name, svc in self.indices.indices.items():
                    if not (_fn.fnmatch(name, part) if "*" in part
                            else name == part):
                        continue
                    if svc.closed and not want_closed and "*" in part:
                        continue
                    if not svc.closed and not want_open and "*" in part:
                        continue
                    services.append(svc)
                    matched = True
                # a concrete index that doesn't exist makes health RED and
                # the request time out (ClusterStateHealth: nonexistent
                # index -> red, TransportClusterHealthAction waits -> 408)
                if not matched and "*" not in part:
                    missing_concrete = True
        else:
            services = [s for s in self.indices.indices.values()
                        if (s.closed and want_closed)
                        or (not s.closed and want_open)]
        seen = set()
        services = [s for s in services
                    if s.name not in seen and not seen.add(s.name)]
        shards = sum(s.num_shards for s in services)
        unassigned = sum(s.num_shards * s.num_replicas for s in services)
        total = shards + unassigned
        out = {
            "cluster_name": self.cluster_name,
            "status": "yellow" if unassigned else "green",
            "timed_out": False, "number_of_nodes": 1,
            "number_of_data_nodes": 1, "active_primary_shards": shards,
            "active_shards": shards, "relocating_shards": 0,
            "initializing_shards": 0, "unassigned_shards": unassigned,
            "delayed_unassigned_shards": 0, "number_of_pending_tasks": 0,
            "number_of_in_flight_fetch": 0, "task_max_waiting_in_queue_millis": 0,
            "active_shards_percent_as_number":
                (shards / total * 100.0) if total else 100.0,
        }
        if missing_concrete:
            out["status"] = "red"
            out["timed_out"] = True
        if level in ("indices", "shards"):
            indices_out = {}
            for svc in services:
                un = svc.num_shards * svc.num_replicas
                entry = {
                    "status": "yellow" if un else "green",
                    "number_of_shards": svc.num_shards,
                    "number_of_replicas": svc.num_replicas,
                    "active_primary_shards": svc.num_shards,
                    "active_shards": svc.num_shards,
                    "relocating_shards": 0, "initializing_shards": 0,
                    "unassigned_shards": un,
                }
                if level == "shards":
                    entry["shards"] = {
                        str(s.shard_id): {
                            "status": "yellow" if svc.num_replicas
                            else "green",
                            "primary_active": True,
                            "active_shards": 1,
                            "relocating_shards": 0,
                            "initializing_shards": 0,
                            "unassigned_shards": svc.num_replicas,
                        } for s in svc.shards}
                indices_out[svc.name] = entry
            out["indices"] = indices_out
        return out

    # metric flag -> response section key (RestIndicesStatsAction METRICS;
    # the `merge` flag renders as `merges`)
    _STATS_METRIC_TO_SECTION = {
        "docs": "docs", "store": "store", "indexing": "indexing",
        "get": "get", "search": "search", "merge": "merges",
        "refresh": "refresh", "flush": "flush", "warmer": "warmer",
        "query_cache": "query_cache", "fielddata": "fielddata",
        "completion": "completion", "segments": "segments",
        "translog": "translog", "request_cache": "request_cache",
        "recovery": "recovery", "bulk": "bulk",
    }

    @staticmethod
    def _fielddata_bytes(shard_list, field: str) -> int:
        """On-demand fielddata size estimate: the inverted doc-values the
        reference builds lazily for text fielddata (terms + entries)."""
        total = 0
        for shard in shard_list:
            reader = shard.engine.acquire_searcher()
            for view in reader.views:
                postings = view.segment.postings.get(field) or {}
                for term, p in postings.items():
                    total += len(str(term)) * 2 + 8 * p.doc_freq
        return total

    def index_stats(self, name: Optional[str] = None,
                    metrics: Optional[List[str]] = None,
                    level: str = "indices",
                    fields: Optional[str] = None,
                    fielddata_fields: Optional[str] = None,
                    completion_fields: Optional[str] = None,
                    groups: Optional[str] = None,
                    include_segment_file_sizes: bool = False,
                    include_unloaded_segments: bool = False,
                    forbid_closed_indices: bool = True,
                    expand_hidden: bool = False) -> dict:
        """`GET [/{index}]/_stats[/{metric}]` (IndicesStatsAction):
        per-index stat sections with metric filtering, level=cluster/
        indices/shards, fields/groups breakdowns; `_shards.total` counts
        primaries + configured replicas."""
        import difflib as _difflib
        import fnmatch as _fn
        if metrics and not any(m in ("_all", "*") for m in metrics):
            keep = set()
            for m in metrics:
                section = self._STATS_METRIC_TO_SECTION.get(m)
                if section is None:
                    close = _difflib.get_close_matches(
                        m, self._STATS_METRIC_TO_SECTION, n=1)
                    hint = f" -> did you mean [{close[0]}]?" if close else ""
                    raise IllegalArgumentError(
                        f"request [/_stats/{m}] contains unrecognized "
                        f"metric: [{m}]{hint}")
                keep.add(section)
        else:
            keep = set(self._STATS_METRIC_TO_SECTION.values())

        services = list(self.indices.resolve(name,
                                             expand_hidden=expand_hidden))
        if not forbid_closed_indices:
            have = {s.name for s in services}
            services += [s for s in self.indices.indices.values()
                         if s.closed and s.name not in have]
        else:
            services = [s for s in services if not s.closed]

        def _match_any(field, patterns):
            return any(_fn.fnmatchcase(field, p.strip())
                       for p in str(patterns).split(","))

        import os as _os

        def shard_sections(svc, shard_list) -> dict:
            closed = svc.closed
            docs = sum(s.engine.doc_count() for s in shard_list)
            segs = 0 if closed and not include_unloaded_segments else \
                sum(len(s.engine.segments) for s in shard_list)
            # size counts the operation files only: the checkpoint file's
            # length varies with digit counts and would break the
            # size-returns-to-creation invariant the reference suite pins
            tlog_bytes = 0
            for s in shard_list:
                tdir = _os.path.join(s.engine.path, "translog")
                if _os.path.isdir(tdir):
                    tlog_bytes += sum(
                        _os.path.getsize(_os.path.join(tdir, f))
                        for f in _os.listdir(tdir) if f.endswith(".tlog"))
            tlog_ops = sum(len(s.engine.translog.read_ops())
                           for s in shard_list) \
                if "translog" in keep else 0
            uncommitted = sum(
                max(s.engine.local_checkpoint
                    - (s.engine.last_commit_checkpoint
                       if s.engine.last_commit_checkpoint is not None
                       else -1), 0)
                for s in shard_list)
            ops_total = sum(s.engine.local_checkpoint + 1
                            for s in shard_list)
            # fielddata / completion on-demand sizes with per-field
            # breakdowns controlled by the fields params — only computed
            # when the section is requested (full postings walk)
            fd_fields: Dict[str, int] = {}
            comp_fields: Dict[str, int] = {}
            if keep & {"fielddata", "completion"}:
                loaded = getattr(svc.mapper_service,
                                 "loaded_fielddata", set())
                for path, mapper in svc.mapper_service.all_mappers():
                    t = getattr(mapper, "type_name", None)
                    fd_capable = (t == "keyword"
                                  or (t == "text"
                                      and mapper.params.get("fielddata")))
                    if fd_capable and "fielddata" in keep:
                        # fielddata/global-ordinals are built LAZILY: bytes
                        # appear only once an aggregation actually loaded
                        # the field (map execution hint never does)
                        fd_fields[path] = self._fielddata_bytes(
                            shard_list, path) if path in loaded else 0
                    elif t == "completion" and "completion" in keep:
                        comp_fields[path] = max(
                            self._fielddata_bytes(shard_list, path),
                            64 * docs)
            fielddata = {"memory_size_in_bytes": sum(fd_fields.values()),
                         "evictions": 0}
            fd_pat = fielddata_fields if fielddata_fields is not None \
                else fields
            if fd_pat is not None:
                fielddata["fields"] = {
                    f: {"memory_size_in_bytes": b}
                    for f, b in fd_fields.items() if _match_any(f, fd_pat)}
            completion = {"size_in_bytes": sum(comp_fields.values())}
            comp_pat = completion_fields if completion_fields is not None \
                else fields
            if comp_pat is not None:
                completion["fields"] = {
                    f: {"size_in_bytes": b}
                    for f, b in comp_fields.items()
                    if _match_any(f, comp_pat)}
            search_sec = {"query_total": 0, "query_time_in_millis": 0,
                          "fetch_total": 0, "open_contexts": 0}
            segments_sec = {"count": segs, "memory_in_bytes": 0,
                            "index_writer_memory_in_bytes": 0,
                            "version_map_memory_in_bytes": 0,
                            "fixed_bit_set_memory_in_bytes": 0}
            if include_segment_file_sizes:
                segments_sec["file_sizes"] = {
                    "seg": {"size_in_bytes": max(
                        sum(_dir_size(s.engine.path) for s in shard_list)
                        - tlog_bytes, 1),
                        "description": "segment data"}}
            newest = max((_os.path.getmtime(_os.path.join(
                s.engine.path, "translog"))
                for s in shard_list
                if _os.path.isdir(_os.path.join(s.engine.path, "translog"))),
                default=time.time())
            full = {
                "docs": {"count": docs, "deleted": 0},
                "store": {"size_in_bytes": max(
                    sum(_dir_size(s.engine.path) for s in shard_list)
                    - tlog_bytes, 0),
                    "reserved_in_bytes": 0},
                "indexing": {"index_total": ops_total, "index_failed": 0,
                             "delete_total": 0, "index_time_in_millis": 0},
                "get": {"total": self._index_get_counts.get(svc.name, 0),
                        "missing_total": 0, "time_in_millis": 0},
                "search": search_sec,
                "merges": {"total": 0, "total_docs": 0,
                           "total_size_in_bytes": 0,
                           "total_time_in_millis": 0},
                "refresh": {"total": 0, "external_total": 0,
                            "total_time_in_millis": 0},
                "flush": {"total": getattr(svc, "flush_count", 0),
                          "periodic": 0,
                          "total_time_in_millis": 0},
                "warmer": {"current": 0, "total": 0,
                           "total_time_in_millis": 0},
                "segments": segments_sec,
                "translog": {"operations": tlog_ops if not closed else 0,
                             "size_in_bytes": tlog_bytes,
                             "uncommitted_operations":
                                 uncommitted if not closed else 0,
                             "uncommitted_size_in_bytes": tlog_bytes,
                             "earliest_last_modified_age":
                                 max(int((time.time() - newest) * 1000), 0)},
                "query_cache": {"memory_size_in_bytes": 0, "hit_count": 0,
                                "miss_count": 0, "evictions": 0},
                "request_cache": {"memory_size_in_bytes": 0, "hit_count": 0,
                                  "miss_count": 0, "evictions": 0},
                "fielddata": fielddata,
                "completion": completion,
                "recovery": {"current_as_source": 0,
                             "current_as_target": 0},
                "bulk": {"total_operations": 0,
                         "total_time_in_millis": 0},
            }
            return {k: v for k, v in full.items() if k in keep}

        indices_out = {}
        total_shards = 0
        successful = 0
        agg: dict = {}
        for svc in services:
            total_shards += svc.num_shards * (1 + svc.num_replicas)
            successful += svc.num_shards
            sections = shard_sections(svc, svc.shards)
            entry = {"uuid": svc.uuid,
                     "primaries": sections,
                     "total": sections}
            if level == "shards":
                entry["shards"] = {
                    str(s.shard_id): [{
                        **shard_sections(svc, [s]),
                        "routing": {"state": "STARTED", "primary": True,
                                    "node": self.node_id},
                        "commit": {"id": f"{svc.uuid}-{s.shard_id}",
                                   "generation": 1, "num_docs":
                                       s.engine.doc_count(),
                                   "user_data": {}},
                        "seq_no": {"max_seq_no": s.engine.local_checkpoint,
                                   "local_checkpoint":
                                       s.engine.local_checkpoint,
                                   "global_checkpoint":
                                       s.engine.local_checkpoint},
                    }] for s in svc.shards}
            indices_out[svc.name] = entry
            _deep_merge_add(agg, sections)
        # node-global counters attributed once at the _all level
        if "search" in keep and "search" in agg:
            agg["search"]["query_total"] = max(
                self.counters.get("search", 0),
                agg["search"].get("query_total", 0))
            if groups is not None:
                agg["search"]["groups"] = {
                    g: {"query_total": n, "query_time_in_millis": 0,
                        "fetch_total": n}
                    for g, n in self._search_groups.items()
                    if _match_any(g, groups) and n > 0}
        if "query_cache" in keep and "query_cache" in agg:
            agg["query_cache"].update(
                memory_size_in_bytes=self.caches.query.bytes,
                hit_count=self.caches.query.hits,
                miss_count=self.caches.query.misses,
                evictions=self.caches.query.evictions)
        if "request_cache" in keep and "request_cache" in agg:
            # both rungs of the shard request cache: the legacy host
            # path and the device-path cache (hybrid/kNN/device-agg);
            # bytes are the LruCache's tracked approximation, not 0
            host, dev = self.caches.request, self.caches.device_request
            agg["request_cache"].update(
                memory_size_in_bytes=host.bytes + dev.bytes,
                hit_count=host.hits + dev.hits,
                miss_count=host.misses + dev.misses,
                evictions=host.evictions + dev.evictions,
                skipped_uncacheable=(host.skipped_uncacheable
                                     + dev.skipped_uncacheable))
        if "bulk" in keep and "bulk" in agg:
            # node-global counter: once at _all, not summed per index
            agg["bulk"]["total_operations"] = self.counters.get("bulk", 0)
        out = {"_shards": {"total": total_shards, "successful": successful,
                           "failed": 0},
               "_all": {"primaries": agg, "total": agg}}
        if level != "cluster":
            out["indices"] = indices_out
        return out

    # -------------------------------------------------- node-level admin APIs
    # The per-node sections below are the "nodeOperation" halves of the
    # reference's TransportNodesAction pattern: REST handlers call the
    # *_api envelope methods, which the clustered deployment overrides with
    # a transport fan-out + merge (cluster/rest_node.py) while these local
    # collectors run unchanged on every node.

    def local_node_info(self) -> dict:
        natives = getattr(self, "natives", None)
        nested_settings: dict = {"client": {"type": "node"},
                                 "node": {"name": self.node_name},
                                 "cluster": {"name": self.cluster_name}}
        for key, value in (self.settings or {}).items():
            node_ = nested_settings
            parts = str(key).split(".")
            for part in parts[:-1]:
                nxt = node_.setdefault(part, {})
                if not isinstance(nxt, dict):
                    break
                node_ = nxt
            else:
                node_[parts[-1]] = value
        return {"name": self.node_name, "version": __version__,
                "roles": ["master", "data", "ingest"],
                "settings": nested_settings,
                "process": {
                    "mlockall": bool(natives and natives.memory_locked),
                    "seccomp": bool(natives and natives.seccomp_installed)},
                "plugins": self.plugins.info()}

    def local_node_stats(self, level: str = None,
                         include_segment_file_sizes: bool = False) -> dict:
        from elasticsearch_tpu.monitor.probes import (
            fs_probe, os_probe, process_probe, runtime_probe,
        )
        def _index_section(svc):
            segs = sum(len(sh.engine.acquire_searcher().views)
                       for sh in svc.shards)
            return {
                "docs": {"count": svc.doc_count(), "deleted": 0},
                "store": {"size_in_bytes": svc.store_size_bytes()
                          if hasattr(svc, "store_size_bytes") else 0},
                "segments": {"count": segs},
            }

        indices_section = {
            "docs": {"count": sum(
                s.doc_count()
                for s in self.indices.indices.values())},
            "store": {"size_in_bytes": sum(
                getattr(s, "store_size_bytes", lambda: 0)()
                for s in self.indices.indices.values())},
            "segments": {"count": sum(
                len(sh.engine.acquire_searcher().views)
                for s in self.indices.indices.values()
                for sh in s.shards),
                "device": self._device_segments_section(),
                **({"file_sizes": {"columns": {"size_in_bytes": 0}}}
                   if include_segment_file_sizes else {})},
            "get": {"total": self.counters.get("get", 0)},
            "merges": {"total": self.counters.get("merge", 0)},
            "recovery": self._recovery_section(),
            "translog": {"operations": 0},
            "fielddata": {"memory_size_in_bytes": 0, "evictions": 0},
            "completion": {"size_in_bytes": 0},
            "refresh": {"total": self.counters.get("refresh", 0)},
            "flush": {"total": self.counters.get("flush", 0)},
            "warmer": {"total": 0},
            "search": {"query_total": self.counters.get("search", 0)},
            "indexing": {"index_total":
                         self.counters.get("index", 0)},
            "request_cache": {
                "memory_size_in_bytes": (self.caches.request.bytes
                                         + self.caches.device_request.bytes),
                "hit_count": (self.caches.request.hits
                              + self.caches.device_request.hits),
                "miss_count": (self.caches.request.misses
                               + self.caches.device_request.misses),
                "evictions": (self.caches.request.evictions
                              + self.caches.device_request.evictions),
                "skipped_uncacheable": (
                    self.caches.request.skipped_uncacheable
                    + self.caches.device_request.skipped_uncacheable),
                # per-rung breakdown: `device` is the fused hybrid /
                # kNN / device-agg request cache (fingerprint-keyed),
                # the top-level counters remain the combined view
                "host": self.caches.request.stats(),
                "device": self.caches.device_request.stats()},
            "query_cache": {
                "memory_size_in_bytes": self.caches.query.bytes,
                "hit_count": self.caches.query.hits,
                "miss_count": self.caches.query.misses,
                "evictions": self.caches.query.evictions},
            "knn": self._knn_stats_section(),
            "hybrid": self._hybrid_stats_section(),
            "aggs": self._aggs_stats_section(),
            "dispatch": self._dispatch_stats_section(),
            "mesh": self._mesh_stats_section(),
            "columnar": self._columnar_stats_section(),
            "slowlog": {"search": self.search_slow_log.stats(),
                        "indexing": self.indexing_slow_log.stats()}}
        discovery_section = {
            "cluster_state_queue": {"total": 0, "pending": 0,
                                    "committed": 0},
            "published_cluster_states": {"full_states": 0,
                                         "incompatible_diffs": 0,
                                         "compatible_diffs": 0}}
        if level in ("indices", "shards"):
            # per-index breakdown (`?level=indices` —
            # NodeIndicesStats.toXContent level handling)
            indices_section["indices"] = {
                name: _index_section(svc)
                for name, svc in self.indices.indices.items()}
        return {"name": self.node_name,
                "roles": ["data", "ingest", "master"],
                "jvm": runtime_probe(),
                "os": os_probe(),
                "fs": fs_probe(self.indices.data_path),
                "process": process_probe(),
                "indices": indices_section,
                "discovery": discovery_section,
                "breakers": self.breakers.stats(),
                "thread_pool": self.thread_pool.stats(),
                "telemetry": self._telemetry_stats_section()}

    def _recovery_section(self) -> dict:
        """`indices.recovery` for a single node: block-level restore
        accounting folded over every index restored from a repository
        (recovery/progress.py shape; cluster nodes report live peer
        recoveries through the same keys via `recovery_summary`)."""
        done = reused = shipped = bytes_shipped = 0
        for svc in self.indices.indices.values():
            for st in (getattr(svc, "recovery_block_stats", None)
                       or {}).values():
                done += 1
                reused += int(st.get("blocks_reused", 0))
                shipped += int(st.get("blocks_shipped", 0))
                bytes_shipped += int(st.get("bytes_shipped", 0))
        from elasticsearch_tpu.recovery.snapshot import NODE_STREAM_LIMITER
        streams = dict(NODE_STREAM_LIMITER.stats)
        streams["max_streams"] = NODE_STREAM_LIMITER.max_streams
        streams["max_bytes_per_sec"] = NODE_STREAM_LIMITER.max_bytes_per_sec
        return {"current_as_source": 0, "current_as_target": 0,
                "completed": done, "blocks_reused": reused,
                "blocks_shipped": shipped, "bytes_shipped": bytes_shipped,
                "throttle_time_in_millis":
                    int(streams["throttle_time_in_millis"]),
                # bounded-concurrency snapshot block upload + per-node
                # byte-rate throttle (recovery/snapshot.py limiter)
                "snapshot_streams": streams,
                "attempts": 0, "retries": 0, "giveups": 0}

    def _device_segments_section(self) -> dict:
        """Generational device-corpus counters summed over local shards
        (`elasticsearch_tpu/segments/`): generation counts/bytes per
        tier, seals, merges run + merge nanos, tombstoned rows, and the
        full-rebuild accounting (rebuilds by reason vs rebuilds the
        incremental path avoided) — the before/after ledger of the
        write-while-search stall."""
        out: dict = {"full_rebuilds": 0, "rebuilds_avoided": 0,
                     "rebuild_reasons": {}, "tiers": {}}
        for svc in self.indices.indices.values():
            for shard in svc.shards:
                stats_fn = getattr(shard.vector_store, "segment_stats",
                                   None)
                if stats_fn is None:
                    continue
                for key, val in stats_fn().items():
                    if key in ("rebuild_reasons", "tiers"):
                        slot = out[key]
                        for k2, v2 in val.items():
                            if isinstance(v2, dict):
                                tier = slot.setdefault(
                                    k2, {k3: 0 for k3 in v2})
                                for k3, v3 in v2.items():
                                    tier[k3] += v3
                            else:
                                slot[k2] = slot.get(k2, 0) + v2
                    elif isinstance(val, bool):
                        out[key] = out.get(key, False) or val
                    elif isinstance(val, (int, float)):
                        out[key] = out.get(key, 0) + val
        return out

    @staticmethod
    def _columnar_stats_section() -> dict:
        """Segment block store counters (`elasticsearch_tpu/columnar/`):
        live per-field block counts/bytes, cache hits vs extractions
        (+ extract nanos), evictions, and the delta-vs-full composition
        ledger — the counter form of the O(delta) refresh claim.
        Process-wide like the dispatch section: one block per (segment,
        field, kind) serves every consumer on this node."""
        from elasticsearch_tpu import columnar
        return columnar.STORE.stats()

    @staticmethod
    def _dispatch_stats_section() -> dict:
        """Shape-bucketed kernel dispatch counters (`ops/dispatch.py`):
        executable-cache hits/misses, compiles and cumulative compile
        time, warmup/out-of-grid compiles, plus the per-bucket breakdown.
        The process-wide dispatcher serves every index on this node, so
        this section is node-level by construction (like the query
        cache)."""
        from elasticsearch_tpu.ops import dispatch
        return dispatch.stats(per_bucket=True)

    @staticmethod
    def _mesh_stats_section() -> dict:
        """Mesh-sharded serving counters (`parallel/policy.py`): shard
        count, the host router's mesh-vs-single-device decisions (with
        reasons), and per-leg SPMD timings + analytic all-gather bytes.
        Process-wide like the dispatch section — one physical mesh serves
        every index on this node."""
        from elasticsearch_tpu.parallel import policy
        return policy.stats()

    def _knn_stats_section(self) -> dict:
        """Vector-search engine counters summed over local shards: total
        searches, how many took the pruned tpu_ivf path vs fell back to
        exhaustive (or rode the SPMD mesh), fused-probe dispatches and
        two-phase rescore window stats (the quant subsystem's serving
        counters), cumulative per-phase device time, the per-field
        encoding/bytes-per-doc ladder breakdown, and the per-(field, k)
        continuous-batching scheduler counters (queue wait / topups /
        overlap — the 1cl/4cl closed-loop tail attribution)."""
        out = {"searches": 0, "ivf_searches": 0, "fallback_searches": 0,
               "mesh_searches": 0, "fused_probe_searches": 0,
               "rescore_searches": 0, "rescore_window_rows": 0,
               "rescore_promoted": 0, "rescore_nanos": 0,
               "route_nanos": 0, "score_nanos": 0, "merge_nanos": 0,
               "semantic_probes": 0, "semantic_hits": 0,
               "semantic_rejects": 0, "semantic_inserts": 0,
               "semantic_invalidations": 0, "semantic_probe_nanos": 0}
        sched: dict = {}
        fields: dict = {}
        for svc in self.indices.indices.values():
            for shard in svc.shards:
                stats = getattr(shard.vector_store, "knn_stats", None)
                if stats:
                    for key in out:
                        out[key] += stats.get(key, 0)
                sched_fn = getattr(shard.vector_store, "scheduler_stats",
                                   None)
                if sched_fn is not None:
                    for key, val in sched_fn().items():
                        sched[key] = sched.get(key, 0) + val
                fields_fn = getattr(shard.vector_store, "field_stats",
                                    None)
                if fields_fn is not None:
                    for field, fs in fields_fn().items():
                        slot = fields.get(field)
                        if slot is None:
                            fields[field] = dict(fs)
                        else:
                            # shards of one field share the encoding
                            # plan; the size halves sum
                            for key in ("rows", "device_bytes"):
                                slot[key] = (slot.get(key, 0)
                                             + fs.get(key, 0))
        out["scheduler"] = sched
        out["fields"] = fields
        return out

    @staticmethod
    def _telemetry_stats_section() -> dict:
        """Live percentile surfaces (`_nodes/stats telemetry`): the
        process-wide metrics registry's histograms (end-to-end search
        latency, queue wait, device dispatch/sync, fan-out leg latency —
        p50/p90/p99/p999 each, no bench harness required) plus the
        tracer's sampling/ring counters. Process-wide like the dispatch
        section."""
        from elasticsearch_tpu.telemetry import REGISTRY, TRACER
        return {**REGISTRY.snapshot(), "tracing": TRACER.snapshot()}

    def local_traces_section(self, limit: int = 50) -> dict:
        """This node's completed-trace ring (`GET _nodes/traces`): most
        recent first, filtered to traces/segments that completed on THIS
        node (the tracer is process-wide; a simulated multi-node process
        shares one ring with per-node attribution)."""
        from elasticsearch_tpu.telemetry import TRACER
        return {"name": self.node_name,
                "traces": TRACER.traces(node_id=self.node_id,
                                        limit=limit)}

    def local_hot_threads(self, interval_s: float = 0.05,
                          top_n: int = 3) -> str:
        from elasticsearch_tpu.monitor import hot_threads_report
        return hot_threads_report(interval_s=min(interval_s, 0.5),
                                  top_n=top_n,
                                  node_name=self.node_name)

    def local_tasks_section(self, actions: Optional[str] = None) -> dict:
        return {"name": self.node_name,
                "roles": ["data", "ingest", "master"],
                "tasks": {t.task_id: t.to_dict(self.node_id)
                          for t in self.tasks.list_tasks(actions)}}

    @staticmethod
    def _matches_csv_patterns(name: str, patterns_csv) -> bool:
        from elasticsearch_tpu.common.patterns import matches_csv_patterns
        return matches_csv_patterns(name, patterns_csv)

    def local_cat_threadpool_rows(self, pool_filter=None) -> list:
        import os as _os
        info = self.thread_pool.info()
        rows = []
        for name, s in sorted(self.thread_pool.stats().items()):
            if not self._matches_csv_patterns(name, pool_filter):
                continue
            meta = info.get(name, {})
            ptype = meta.get("type", "fixed")
            threads = meta.get("size", 0)
            scaling = ptype == "scaling"
            rows.append([self.node_name, self.node_id, self.node_id,
                         _os.getpid(), "127.0.0.1", "127.0.0.1",
                         9300, name, ptype, s["active"],
                         s.get("threads", 0), s["queue"],
                         meta.get("queue_size", -1),
                         s["rejected"], s.get("largest", 0),
                         s.get("completed", 0),
                         1 if scaling else "", threads if scaling else "",
                         "" if scaling else threads,
                         "5m" if scaling else ""])
        return rows

    def cat_threadpool_rows_api(self, pool_filter=None) -> list:
        return self.local_cat_threadpool_rows(pool_filter)

    def local_cat_nodeattrs_rows(self) -> list:
        import os as _os
        attrs = dict(getattr(self, "node_attrs", {}) or {})
        return [[self.node_name, self.node_id, _os.getpid(),
                 "127.0.0.1", "127.0.0.1", 9300, k, v]
                for k, v in sorted(attrs.items())]

    def cat_nodeattrs_rows_api(self) -> list:
        return self.local_cat_nodeattrs_rows()

    def local_cat_fielddata_rows(self, field_filter=None) -> list:
        """Plain-value rows (size as int — the REST handler applies the cat
        Bytes wrapper; wrappers don't survive the transport)."""
        rows = []
        seen = set()
        for svc in self.indices.indices.values():
            for path, mapper in svc.mapper_service.all_mappers():
                if mapper.type_name != "text" \
                        or not mapper.params.get("fielddata"):
                    continue
                if not self._matches_csv_patterns(path, field_filter):
                    continue
                if path in seen:
                    continue
                seen.add(path)
                size = max(svc.doc_count() * 32, 1)
                rows.append([self.node_id, "127.0.0.1", "127.0.0.1",
                             self.node_name, path, size])
        return rows

    def cat_fielddata_rows_api(self, field_filter=None) -> list:
        return self.local_cat_fielddata_rows(field_filter)

    def local_cat_tasks_rows(self) -> list:
        """Plain-value rows (running time in ns — handler applies Millis)."""
        me = self.tasks.register("cluster:monitor/tasks/lists", "cat tasks")
        try:
            rows = []
            for t in self.tasks.list_tasks():
                d = t.to_dict(self.node_id)
                rows.append([d["action"], t.task_id, "-", d["type"],
                             d["start_time_in_millis"],
                             d["running_time_in_nanos"],
                             "127.0.0.1", self.node_name,
                             d["description"] or "-"])
        finally:
            self.tasks.unregister(me)
        return rows

    def cat_tasks_rows_api(self) -> list:
        return self.local_cat_tasks_rows()

    def termvectors_api(self, index: str, doc_id, spec: dict) -> dict:
        """TermVectorsService analog: per-field term/position/offset stats.

        Field statistics come from the READER (sum_doc_freq = Σ doc_freq of
        the field's distinct indexed terms), not from the one document.
        realtime=false reads only refreshed segments (found: false for docs
        sitting in the unrefreshed buffer)."""
        spec = spec or {}
        svc = self.indices.get(index)
        reader = svc.combined_reader()
        realtime = spec.get("realtime", True)
        if isinstance(realtime, str):
            realtime = realtime not in ("false", "0")
        source = None
        if doc_id is not None:
            if not realtime:
                visible = any(reader.get_id(int(r)) == str(doc_id)
                              for r in reader.live_global_rows())
                if not visible:
                    return {"_index": index, "_id": doc_id, "_version": 1,
                            "found": False, "took": 0}
            got = self.get_doc(index, str(doc_id))
            if not got.get("found"):
                return {"_index": index, "_id": doc_id, "found": False,
                        "took": 0}
            source = got["_source"]
        else:
            source = spec.get("doc") or {}
        fields = spec.get("fields")
        want_stats = spec.get("term_statistics") in (True, "true", "")
        out_fields = {}
        for fname, value in (source or {}).items():
            if fields and fname not in fields:
                continue
            mapper = svc.mapper_service.get(fname)
            if mapper is None or not hasattr(mapper, "analyze") \
                    or getattr(mapper, "type_name", "") not in ("text",):
                continue
            tokens = mapper.analyze(str(value))
            text_lower = str(value).lower()
            terms: Dict[str, dict] = {}
            cursor = 0
            for pos, t in enumerate(tokens):
                start = text_lower.find(str(t).lower(), cursor)
                end = start + len(str(t)) if start >= 0 else -1
                if start >= 0:
                    cursor = end
                entry = terms.setdefault(t, {"term_freq": 0, "tokens": []})
                entry["term_freq"] += 1
                tok = {"position": pos}
                if start >= 0:
                    tok["start_offset"] = start
                    tok["end_offset"] = end
                entry["tokens"].append(tok)
            if want_stats:
                for t, entry in terms.items():
                    entry["doc_freq"] = reader.doc_freq(fname, t)
                    ttf = 0
                    for view in reader.views:
                        p = view.segment.postings.get(fname, {}).get(t)
                        if p is not None:
                            ttf += int(p.freqs.sum())
                    entry["ttf"] = ttf
            # field statistics describe the INDEX, not this document
            distinct = set()
            for view in reader.views:
                distinct.update(view.segment.postings.get(fname, {}).keys())
            sum_doc_freq = sum(reader.doc_freq(fname, t) for t in distinct)
            out_fields[fname] = {
                "field_statistics": {
                    "sum_doc_freq": sum_doc_freq,
                    "doc_count": reader.docs_with_field_count(fname),
                    "sum_ttf": reader.total_term_count(fname)},
                "terms": terms}
        return {"_index": index, "_id": doc_id, "_version": 1, "found": True,
                "took": 0, "term_vectors": out_fields}

    def _nodes_envelope(self, nodes: dict, failed: int = 0) -> dict:
        return {"_nodes": {"total": len(nodes) + failed,
                           "successful": len(nodes), "failed": failed},
                "cluster_name": self.cluster_name, "nodes": nodes}

    def nodes_info_api(self) -> dict:
        return self._nodes_envelope({self.node_id: self.local_node_info()})

    def nodes_stats_api(self, level: str = None,
                        include_segment_file_sizes: bool = False) -> dict:
        return self._nodes_envelope(
            {self.node_id: self.local_node_stats(
                level, include_segment_file_sizes)})

    def hot_threads_api(self, interval_s: float = 0.05,
                        top_n: int = 3) -> str:
        return self.local_hot_threads(interval_s, top_n=top_n)

    def traces_api(self, limit: int = 50) -> dict:
        return self._nodes_envelope(
            {self.node_id: self.local_traces_section(limit)})

    def tasks_list_api(self, actions: Optional[str] = None) -> dict:
        return {"nodes": {self.node_id: self.local_tasks_section(actions)}}

    def task_get_api(self, task_id: str) -> dict:
        t = self.tasks.get(task_id)
        return {"completed": False, "task": t.to_dict(self.node_id)}

    def task_cancel_api(self, task_id: str) -> dict:
        t = self.tasks.cancel(task_id)
        return {"nodes": {self.node_id: {
            "tasks": {t.task_id: t.to_dict(self.node_id)}}}}

    def close(self):
        self.ml.close_all()
        self.plugins.remove_extensions()
        for alias in list(self.remotes.remotes):
            self.remotes.unregister(alias)
        self.indices.close()
        self.thread_pool.shutdown()


# ---------------------------------------------------------------------------

def _has_global_agg(aggs) -> bool:
    """Aggregations that need EVERY shard disable can_match skipping:
    `global` aggs and min_doc_count:0 bucket aggs (the reference's
    SearchSourceBuilder#aggregations rewrite check)."""
    for spec in (aggs or {}).values():
        if not isinstance(spec, dict):
            continue
        if "global" in spec:
            return True
        for kind, body in spec.items():
            if kind in ("aggs", "aggregations", "meta"):
                continue
            if isinstance(body, dict) \
                    and str(body.get("min_doc_count")) == "0":
                return True
        if _has_global_agg(spec.get("aggs") or spec.get("aggregations")):
            return True
    return False


def _dir_size(path: str) -> int:
    import os as _os
    total = 0
    for root, _dirs, files in _os.walk(path):
        for f in files:
            try:
                total += _os.path.getsize(_os.path.join(root, f))
            except OSError:
                pass
    return total


def _deep_merge_add(dst: dict, src: dict) -> None:
    """Numeric stat sections sum; nested dicts merge recursively."""
    for k, v in src.items():
        if isinstance(v, dict):
            _deep_merge_add(dst.setdefault(k, {}), v)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            dst[k] = dst.get(k, 0) + v
        else:
            dst.setdefault(k, v)


def _deep_merge(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v


def _apply_update_script(source: dict, script_spec, ctx_extra=None) -> dict:
    """Update scripts run through the sandboxed Painless interpreter
    (script/painless.py): `ctx._source.*` mutation, loops, conditionals,
    list/map methods, user functions. Returns the mutated source; the
    script's operation verdict lands in ctx['op'] (UpdateHelper honors
    'none'/'delete'). Raises on compile/sandbox violations."""
    from elasticsearch_tpu.script.painless import (
        FrozenParams, compile_painless, execute,
    )

    if isinstance(script_spec, str):
        script_spec = {"source": script_spec}
    if isinstance(script_spec, dict) and "id" in script_spec and "source" not in script_spec:
        from elasticsearch_tpu.script.service import GLOBAL_SCRIPTS
        resolved = GLOBAL_SCRIPTS.resolve(script_spec)
        if resolved["lang"] == "mustache":
            raise IllegalArgumentError(
                f"stored script [{script_spec['id']}] is a [mustache] template, "
                "not usable as an update script")
        script_spec = {"source": resolved["source"],
                       "params": script_spec.get("params", {})}
    src = script_spec.get("source", "")
    params = script_spec.get("params", {})
    ctx_obj = {"_source": source, "op": "index"}
    if ctx_extra:
        ctx_obj.update(ctx_extra)
    try:
        program = compile_painless(src)
    except Exception as e:
        raise IllegalArgumentError(f"compile error in update script: {e}")
    execute(program, {"ctx": ctx_obj, "params": FrozenParams(params)})
    if ctx_extra is not None:
        ctx_extra["op"] = ctx_obj.get("op", "index")
    return source


def _sort_key_tuple(sort_values, body):
    sort = body.get("sort")
    if isinstance(sort, (str, dict)):
        sort = [sort]
    keys = []
    for spec, v in zip(sort or [], sort_values or []):
        direction = "asc"
        if isinstance(spec, dict):
            ((_, o),) = spec.items()
            direction = o if isinstance(o, str) else o.get("order", "asc")
        if isinstance(v, str):
            keys.append(v if direction == "asc" else _InvStr(v))
        elif v is None:
            # missing sorts last regardless of direction; _MissingLast
            # compares greater than both floats and strings so mixed-type
            # columns (string field absent on some docs) don't TypeError
            keys.append(_MISSING_SENTINEL)
        else:
            keys.append(float(v) if direction == "asc" else -float(v))
    return tuple(keys)


class _InvStr:
    """Inverted string ordering for desc sorts in tuple keys."""

    __slots__ = ("s",)

    def __init__(self, s):
        self.s = s

    def __lt__(self, other):
        if isinstance(other, _MissingLast):
            return True
        return self.s > other.s

    def __eq__(self, other):
        return isinstance(other, _InvStr) and self.s == other.s


class _MissingLast:
    """Compares greater than every other sort key (missing sorts last)."""

    __slots__ = ()

    def __lt__(self, other):
        return False

    def __gt__(self, other):
        return not isinstance(other, _MissingLast)

    def __eq__(self, other):
        return isinstance(other, _MissingLast)


_MISSING_SENTINEL = _MissingLast()


# cross-index / cross-shard agg merging lives in search/agg_partials.py:
# shards emit mergeable partial states, the coordinator reduces + finalizes
# (InternalAggregation.reduce analog)
