"""Node-local content-addressed block cache for peer recovery.

One directory per node (`<data_path>/_blocks/`), one file per block
named by its sha256 digest. The recovery target diffs the source's
manifest against this cache: blocks it already holds (from an earlier
attempt that died mid-way, from a previous life of the same shard, or
from a snapshot restore) are REUSED, not re-shipped — a retry resumes
from the last acked block for free, because acked blocks live here.

Both directions verify the digest: `put` refuses bytes that do not hash
to their claimed address, `get` re-hashes what it reads back (a torn
write or bit rot surfaces as a miss, never as a corrupt shard).
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional, Set


def safe_digest(digest: str) -> str:
    """Validate a wire digest before it becomes a path component —
    digests are hex, but never trust a remote value as a filename."""
    safe = "".join(c for c in digest if c in "0123456789abcdef")
    if safe != digest or not safe:
        raise ValueError(f"invalid block digest [{digest}]")
    return safe


class BlockCache:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, digest: str) -> str:
        return os.path.join(self.directory, safe_digest(digest))

    def has(self, digest: str) -> bool:
        try:
            return os.path.exists(self._path(digest))
        except ValueError:
            return False

    def held(self) -> Set[str]:
        try:
            return set(os.listdir(self.directory))
        except OSError:
            return set()

    def put(self, digest: str, data: bytes) -> None:
        if hashlib.sha256(data).hexdigest() != digest:
            raise ValueError(
                f"block digest mismatch on write: expected [{digest}]")
        path = self._path(digest)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, digest: str) -> Optional[bytes]:
        """The block's bytes, digest-verified on read-back; None when
        missing OR corrupt (a corrupt cached block is dropped so the
        next attempt re-fetches it)."""
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return None
        if hashlib.sha256(data).hexdigest() != digest:
            try:
                os.unlink(path)
            except OSError:
                pass
            return None
        return data

    def evict(self, digest: str) -> None:
        try:
            os.unlink(self._path(digest))
        except (OSError, ValueError):
            pass
