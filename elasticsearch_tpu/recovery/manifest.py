"""The per-shard block manifest and its digest-diff.

A manifest is a JSON-safe list of entries, one per block:

  {"kind": "segment",   "seg_id": 3, "digest": "...", "size": 1234}
  {"kind": "cache",     "seg_id": 3, "key": ["vector_enc", "emb",
                                             "int4", "cosine"],
                        "digest": "...", "size": 99}
  {"kind": "ledger",    "digest": "...", "size": 321}
  {"kind": "ivf",       "field": "emb", "digest": "...", "size": 42}

Segment entries appear in reader order — assembly rebuilds the commit's
segment list from that order. `diff_entries` is the whole incremental
story: everything (snapshot dedup, peer-recovery resume, relocation)
reduces to "which digests is the holder missing".
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple


def entry_key(entry: dict) -> tuple:
    """Stable identity of one manifest entry (for tests/debugging)."""
    return (entry["kind"], entry.get("seg_id"),
            tuple(entry.get("key") or ()), entry.get("field"),
            entry["digest"])


def diff_entries(entries: Iterable[dict],
                 held: Set[str]) -> Tuple[List[dict], List[dict]]:
    """Split manifest entries into (missing, present) against a set of
    digests the target already holds — locally cached blocks never
    re-ship, which is both snapshot incrementality and the
    resume-from-last-acked-block retry contract."""
    missing, present = [], []
    for entry in entries:
        (present if entry["digest"] in held else missing).append(entry)
    return missing, present


def manifest_totals(entries: Iterable[dict]) -> Dict[str, int]:
    entries = list(entries)
    return {
        "blocks_total": len(entries),
        "bytes_total": sum(int(e.get("size", 0)) for e in entries),
    }
