"""Block <-> bytes: the serialization layer of durable elasticity.

Every durable unit is one immutable "block" with a sha256 content digest
as its address:

- a sealed engine `Segment` (the corpus data itself);
- a cached columnar block (`EncodedVectorBlock` / `ValuesBlock` /
  `PostingsBlock`) — derived state that is expensive to recompute
  (codec re-encode) and fingerprinted against its segment;
- the tombstone/merge ledger (`deleted_rows` + `version_map`) — the
  only mutable shard state, small and rewritten whole;
- a trained IVF layout (centroids + shape) — corpus-independent and
  tiny, so restore re-places rows instead of re-training k-means.

Digests are computed over the serialized bytes, so a reader verifies a
block by re-hashing what it received — transport and blob-store
corruption both surface as a digest mismatch, never as a half-applied
shard (the TPU014 durability contract).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Dict, List

# a stable protocol: protocol 4 is available everywhere this runs and
# keeps digests comparable across minor Python versions in one fleet
_PICKLE_PROTOCOL = 4

SIDECAR_FILE = "_restore_seed.bin"


def block_digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def dumps_block(obj) -> bytes:
    return pickle.dumps(obj, protocol=_PICKLE_PROTOCOL)


def loads_block(data: bytes):
    return pickle.loads(data)


def serialize_segment(segment) -> bytes:
    """One sealed segment as bytes. Segments are immutable and already
    pickle-clean (the engine commit pickles them); serializing each one
    separately is what makes the second snapshot O(delta): unchanged
    segments re-hash to the same digest and ship nothing."""
    return dumps_block(segment)


def serialize_ledger(deleted_rows: Dict[int, set],
                     version_map: Dict[str, object]) -> bytes:
    """The tombstone/merge ledger: deleted locals per segment + the live
    version map. Small (id-sized, not corpus-sized) and rewritten whole
    on every snapshot — the one block expected to churn."""
    return dumps_block({
        "deleted_rows": {int(k): sorted(v)
                         for k, v in deleted_rows.items()},
        "version_map": dict(version_map),
    })


def ledger_state(data: bytes) -> tuple:
    """(deleted_rows, version_map) reconstructed from a ledger block."""
    obj = loads_block(data)
    deleted = {int(k): set(v) for k, v in obj["deleted_rows"].items()}
    return deleted, dict(obj["version_map"])


def write_commit_files(path: str, segments: List[object],
                       deleted_rows: Dict[int, set],
                       version_map: Dict[str, object],
                       meta: dict) -> None:
    """Reconstruct the exact commit files `Engine.flush` writes —
    commit.bin / commit.json — plus an HONEST translog checkpoint: the
    restored translog is empty, so `min_retained_seq_no` must say
    history below the checkpoint is gone (otherwise a restored primary
    would claim it can ops-replay a replica from seq_no 0 and silently
    hand it nothing)."""
    os.makedirs(path, exist_ok=True)
    tmp = os.path.join(path, "commit.tmp")
    with open(tmp, "wb") as f:
        pickle.dump({
            "segments": list(segments),
            "deleted_rows": deleted_rows,
            "version_map": version_map,
            "meta": dict(meta),
        }, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, "commit.bin"))
    with open(os.path.join(path, "commit.json"), "w") as f:
        json.dump(dict(meta), f)
    tl_dir = os.path.join(path, "translog")
    os.makedirs(tl_dir, exist_ok=True)
    ckp = {
        "generation": 1,
        "min_translog_generation": 1,
        "global_checkpoint": int(meta["local_checkpoint"]),
        "max_seq_no": int(meta["max_seq_no"]),
        "min_retained_seq_no": int(meta["local_checkpoint"]) + 1,
    }
    ckp_tmp = os.path.join(tl_dir, "translog.ckp.tmp")
    with open(ckp_tmp, "w") as f:
        json.dump(ckp, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(ckp_tmp, os.path.join(tl_dir, "translog.ckp"))


def commit_meta(engine) -> dict:
    """The commit metadata dict for an engine's CURRENT durable state
    (callers flush first — this mirrors what flush just wrote)."""
    return {
        "local_checkpoint": engine.tracker.checkpoint,
        "max_seq_no": engine.tracker.max_seq_no,
        "primary_term": engine.primary_term,
        "next_row": engine._next_row,
        "next_seg_id": engine._next_seg_id,
    }
