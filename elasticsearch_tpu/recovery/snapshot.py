"""Collect a shard into blocks / assemble a shard from blocks.

The SAME collect/assemble pair serves every durability flow:

- repository snapshot:  collect -> put missing blobs (content-addressed
  dedup makes the second snapshot O(new blocks)) -> manifest entry;
- repository restore:   fetch blobs (digest-verified) -> assemble;
- peer recovery:        source collects into a staging dir, target
  diffs + fetches missing blocks over chunked transport -> assemble;
- relocation:           identical to peer recovery; the warm handoff
  happens after assembly.

Assembly writes the exact commit files `Engine.flush` would have
written plus the seed sidecar (`recovery/seed.py`), so the reopened
engine is byte-identical and its derived caches never recompute.
"""

from __future__ import annotations

import zlib
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.recovery.blocks import (
    block_digest, commit_meta, dumps_block, ledger_state, loads_block,
    serialize_ledger, serialize_segment, write_commit_files,
)
from elasticsearch_tpu.recovery.manifest import manifest_totals
from elasticsearch_tpu.recovery.seed import write_sidecar


def collect_shard_blocks(engine, vector_store=None
                         ) -> Tuple[List[dict], Dict[str, bytes], dict]:
    """Serialize one shard's durable state into (manifest entries,
    {digest: bytes}, commit meta). Callers flush first — this reads the
    committed segment set. Derived blocks are taken from whatever the
    columnar store has ALREADY cached (snapshotting must not trigger
    extractions of its own); the IVF layout comes from the vector
    store's live routers."""
    from elasticsearch_tpu import columnar

    entries: List[dict] = []
    payloads: Dict[str, bytes] = {}

    def add(entry: dict, data: bytes) -> None:
        digest = block_digest(data)
        entry["digest"] = digest
        entry["size"] = len(data)
        entry["crc32"] = zlib.crc32(data) & 0xFFFFFFFF
        entries.append(entry)
        payloads.setdefault(digest, data)

    reader = engine.acquire_searcher()
    for view in reader.views:
        seg = view.segment
        add({"kind": "segment", "seg_id": int(seg.seg_id)},
            serialize_segment(seg))
        for key, blk in columnar.STORE.cached_blocks(seg).items():
            if key[0] == "vector":
                # f32 vector blocks are zero-copy views of segment
                # arrays the segment blob above already carries
                continue
            add({"kind": "cache", "seg_id": int(seg.seg_id),
                 "key": list(key)},
                dumps_block(blk))
    add({"kind": "ledger"},
        serialize_ledger(engine.deleted_rows, engine.version_map))
    if vector_store is not None:
        for field, layout in vector_store.export_ivf_layout().items():
            add({"kind": "ivf", "field": field}, dumps_block(layout))
    return entries, payloads, commit_meta(engine)


def assemble_shard(path: str, entries: List[dict], meta: dict,
                   fetch: Callable[[str], bytes]) -> dict:
    """Materialize a shard directory from manifest entries: rebuild the
    commit files + translog checkpoint and stage the derived blocks in
    the seed sidecar. Every fetched block is digest-verified HERE as
    well — `fetch` implementations verify too, but assembly is the last
    line before bytes become an engine."""
    segments = []
    seg_entries = sorted(
        (e for e in entries if e["kind"] == "segment"),
        key=lambda e: int(e["seg_id"]))
    ledger_entry = next(e for e in entries if e["kind"] == "ledger")
    cache_entries = []
    ivf_layouts = {}

    def verified(entry: dict) -> bytes:
        data = fetch(entry["digest"])
        if data is None or block_digest(data) != entry["digest"]:
            raise ValueError(
                f"block [{entry['digest']}] failed digest verification")
        return data

    for entry in seg_entries:
        segments.append(loads_block(verified(entry)))
    deleted_rows, version_map = ledger_state(verified(ledger_entry))
    for entry in entries:
        if entry["kind"] == "cache":
            cache_entries.append({
                "seg_id": int(entry["seg_id"]),
                "key": tuple(entry["key"]),
                "block": loads_block(verified(entry))})
        elif entry["kind"] == "ivf":
            ivf_layouts[entry["field"]] = loads_block(verified(entry))
    write_commit_files(path, segments, deleted_rows, version_map, meta)
    write_sidecar(path, cache_entries, ivf_layouts)
    return {**manifest_totals(entries),
            "segments": len(segments),
            "cache_blocks": len(cache_entries),
            "ivf_fields": sorted(ivf_layouts)}


# ------------------------------------------------------------ repository

def snapshot_shard(repo, engine, vector_store=None) -> dict:
    """Upload one shard's blocks to a content-addressed repository;
    returns the shard's manifest entry. Blocks whose digest the repo
    already holds are REUSED (counted, not re-uploaded) — that is the
    incremental-snapshot contract the acceptance gate measures."""
    entries, payloads, meta = collect_shard_blocks(engine, vector_store)
    reused = shipped = bytes_shipped = 0
    for digest, data in payloads.items():
        if repo.has_blob(digest):
            reused += 1
        else:
            repo.put_bytes(data)
            shipped += 1
            bytes_shipped += len(data)
    return {"blocks": entries, "meta": meta,
            "stats": {**manifest_totals(entries),
                      "blocks_reused": reused,
                      "blocks_shipped": shipped,
                      "bytes_shipped": bytes_shipped}}


def restore_shard(repo, shard_entry: dict, path: str,
                  cache=None) -> Optional[dict]:
    """Materialize one shard from its snapshot manifest entry. With a
    node block cache, fetched blobs also land there so a later peer
    recovery of the same data re-ships nothing."""
    entries = shard_entry.get("blocks")
    if entries is None:
        return None
    stats = {"blocks_reused": 0, "blocks_shipped": 0, "bytes_shipped": 0}

    def fetch(digest: str) -> bytes:
        if cache is not None:
            held = cache.get(digest)
            if held is not None:
                stats["blocks_reused"] += 1
                return held
        data = repo.get_bytes(digest)
        stats["blocks_shipped"] += 1
        stats["bytes_shipped"] += len(data)
        if cache is not None:
            cache.put(digest, data)
        return data

    return {**assemble_shard(path, entries, shard_entry["meta"], fetch),
            **stats}
