"""Collect a shard into blocks / assemble a shard from blocks.

The SAME collect/assemble pair serves every durability flow:

- repository snapshot:  collect -> put missing blobs (content-addressed
  dedup makes the second snapshot O(new blocks)) -> manifest entry;
- repository restore:   fetch blobs (digest-verified) -> assemble;
- peer recovery:        source collects into a staging dir, target
  diffs + fetches missing blocks over chunked transport -> assemble;
- relocation:           identical to peer recovery; the warm handoff
  happens after assembly.

Assembly writes the exact commit files `Engine.flush` would have
written plus the seed sidecar (`recovery/seed.py`), so the reopened
engine is byte-identical and its derived caches never recompute.
"""

from __future__ import annotations

import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from elasticsearch_tpu.recovery.blocks import (
    block_digest, commit_meta, dumps_block, ledger_state, loads_block,
    serialize_ledger, serialize_segment, write_commit_files,
)
from elasticsearch_tpu.recovery.manifest import manifest_totals
from elasticsearch_tpu.recovery.seed import write_sidecar


def collect_shard_blocks(engine, vector_store=None
                         ) -> Tuple[List[dict], Dict[str, bytes], dict]:
    """Serialize one shard's durable state into (manifest entries,
    {digest: bytes}, commit meta). Callers flush first — this reads the
    committed segment set. Derived blocks are taken from whatever the
    columnar store has ALREADY cached (snapshotting must not trigger
    extractions of its own); the IVF layout comes from the vector
    store's live routers."""
    from elasticsearch_tpu import columnar

    entries: List[dict] = []
    payloads: Dict[str, bytes] = {}

    def add(entry: dict, data: bytes) -> None:
        digest = block_digest(data)
        entry["digest"] = digest
        entry["size"] = len(data)
        entry["crc32"] = zlib.crc32(data) & 0xFFFFFFFF
        entries.append(entry)
        payloads.setdefault(digest, data)

    reader = engine.acquire_searcher()
    for view in reader.views:
        seg = view.segment
        add({"kind": "segment", "seg_id": int(seg.seg_id)},
            serialize_segment(seg))
        for key, blk in columnar.STORE.cached_blocks(seg).items():
            if key[0] == "vector":
                # f32 vector blocks are zero-copy views of segment
                # arrays the segment blob above already carries
                continue
            add({"kind": "cache", "seg_id": int(seg.seg_id),
                 "key": list(key)},
                dumps_block(blk))
    add({"kind": "ledger"},
        serialize_ledger(engine.deleted_rows, engine.version_map))
    if vector_store is not None:
        for field, layout in vector_store.export_ivf_layout().items():
            add({"kind": "ivf", "field": field}, dumps_block(layout))
    return entries, payloads, commit_meta(engine)


def assemble_shard(path: str, entries: List[dict], meta: dict,
                   fetch: Callable[[str], bytes]) -> dict:
    """Materialize a shard directory from manifest entries: rebuild the
    commit files + translog checkpoint and stage the derived blocks in
    the seed sidecar. Every fetched block is digest-verified HERE as
    well — `fetch` implementations verify too, but assembly is the last
    line before bytes become an engine."""
    segments = []
    seg_entries = sorted(
        (e for e in entries if e["kind"] == "segment"),
        key=lambda e: int(e["seg_id"]))
    ledger_entry = next(e for e in entries if e["kind"] == "ledger")
    cache_entries = []
    ivf_layouts = {}

    def verified(entry: dict) -> bytes:
        data = fetch(entry["digest"])
        if data is None or block_digest(data) != entry["digest"]:
            raise ValueError(
                f"block [{entry['digest']}] failed digest verification")
        return data

    for entry in seg_entries:
        segments.append(loads_block(verified(entry)))
    deleted_rows, version_map = ledger_state(verified(ledger_entry))
    for entry in entries:
        if entry["kind"] == "cache":
            cache_entries.append({
                "seg_id": int(entry["seg_id"]),
                "key": tuple(entry["key"]),
                "block": loads_block(verified(entry))})
        elif entry["kind"] == "ivf":
            ivf_layouts[entry["field"]] = loads_block(verified(entry))
    write_commit_files(path, segments, deleted_rows, version_map, meta)
    write_sidecar(path, cache_entries, ivf_layouts)
    return {**manifest_totals(entries),
            "segments": len(segments),
            "cache_blocks": len(cache_entries),
            "ivf_fields": sorted(ivf_layouts)}


# ------------------------------------------------------------ repository

class SnapshotStreamLimiter:
    """Per-node upload governor for snapshot block streams: bounded
    concurrency (`snapshot.max_concurrent_streams`) plus a byte-rate
    token bucket (`snapshot.max_bytes_per_sec`, 0 = unthrottled). The
    reference throttles snapshots the same way (`indices.recovery.
    max_bytes_per_sec` / SnapshotShardsService); the accumulated wait is
    surfaced in `_nodes/stats indices.recovery.snapshot_streams` so an
    operator can see when the throttle — not the repository — is the
    snapshot's critical path."""

    def __init__(self, max_streams: int = 4, max_bytes_per_sec: int = 0):
        self._lock = threading.Lock()
        self._allowance = 0.0
        self._last_refill = time.monotonic()
        self._in_flight = 0
        self.stats = {"throttle_time_in_millis": 0,
                      "blocks_throttled": 0,
                      "blocks_uploaded": 0,
                      "bytes_uploaded": 0,
                      "max_concurrent_streams": 0}
        self.configure(max_streams, max_bytes_per_sec)

    def configure(self, max_streams=None, max_bytes_per_sec=None) -> None:
        with self._lock:
            if max_streams is not None:
                self.max_streams = max(1, int(max_streams))
            if max_bytes_per_sec is not None:
                rate = max(0, int(max_bytes_per_sec))
                if rate != getattr(self, "max_bytes_per_sec", None):
                    # a CHANGED rate restarts the bucket full; re-applying
                    # the same setting (every shard upload re-reads the
                    # cluster settings) must not refund spent allowance
                    self.max_bytes_per_sec = rate
                    self._allowance = float(rate)
                    self._last_refill = time.monotonic()

    def configure_from_settings(self, settings) -> None:
        from elasticsearch_tpu.common.settings import parse_byte_size
        raw_rate = settings.get("snapshot.max_bytes_per_sec")
        try:
            rate = parse_byte_size(raw_rate) if raw_rate else None
        except Exception:
            rate = None
        try:
            raw_streams = settings.get("snapshot.max_concurrent_streams")
            streams = int(raw_streams) if raw_streams else None
        except Exception:
            streams = None
        self.configure(max_streams=streams, max_bytes_per_sec=rate)

    def throttle(self, nbytes: int) -> None:
        """Debit `nbytes` from the token bucket, sleeping out any
        deficit. Runs on upload-stream worker threads — never on a node's
        event loop."""
        if self.max_bytes_per_sec <= 0:
            return
        with self._lock:
            now = time.monotonic()
            self._allowance = min(
                float(self.max_bytes_per_sec),
                self._allowance
                + (now - self._last_refill) * self.max_bytes_per_sec)
            self._last_refill = now
            deficit = nbytes - self._allowance
            self._allowance -= nbytes
            if deficit <= 0:
                return
            wait_s = deficit / self.max_bytes_per_sec
            self.stats["blocks_throttled"] += 1
            self.stats["throttle_time_in_millis"] += int(wait_s * 1000)
        time.sleep(wait_s)

    def _enter(self) -> None:
        with self._lock:
            self._in_flight += 1
            self.stats["max_concurrent_streams"] = max(
                self.stats["max_concurrent_streams"], self._in_flight)

    def _exit(self, nbytes: int) -> None:
        with self._lock:
            self._in_flight -= 1
            self.stats["blocks_uploaded"] += 1
            self.stats["bytes_uploaded"] += int(nbytes)


# node-wide default: every snapshot upload in the process shares one
# throttle budget, which is the per-node semantic the setting names
NODE_STREAM_LIMITER = SnapshotStreamLimiter()


def snapshot_shard(repo, engine, vector_store=None, limiter=None,
                   settings=None) -> dict:
    """Upload one shard's blocks to a content-addressed repository;
    returns the shard's manifest entry. Blocks whose digest the repo
    already holds are REUSED (counted, not re-uploaded) — that is the
    incremental-snapshot contract the acceptance gate measures. Missing
    blocks upload CONCURRENTLY (bounded by the stream limiter) under the
    per-node byte-rate throttle."""
    entries, payloads, meta = collect_shard_blocks(engine, vector_store)
    limiter = limiter or NODE_STREAM_LIMITER
    if settings:
        limiter.configure_from_settings(settings)
    reused = 0
    to_ship: List[bytes] = []
    for digest, data in payloads.items():
        if repo.has_blob(digest):
            reused += 1
        else:
            to_ship.append(data)

    def upload(data: bytes) -> int:
        limiter._enter()
        try:
            limiter.throttle(len(data))
            repo.put_bytes(data)
            return len(data)
        finally:
            limiter._exit(len(data))

    if len(to_ship) > 1 and limiter.max_streams > 1:
        with ThreadPoolExecutor(
                max_workers=min(limiter.max_streams, len(to_ship)),
                thread_name_prefix="snapshot-stream") as pool:
            sizes = list(pool.map(upload, to_ship))
    else:
        sizes = [upload(data) for data in to_ship]
    return {"blocks": entries, "meta": meta,
            "stats": {**manifest_totals(entries),
                      "blocks_reused": reused,
                      "blocks_shipped": len(to_ship),
                      "bytes_shipped": sum(sizes)}}


def restore_shard(repo, shard_entry: dict, path: str,
                  cache=None) -> Optional[dict]:
    """Materialize one shard from its snapshot manifest entry. With a
    node block cache, fetched blobs also land there so a later peer
    recovery of the same data re-ships nothing."""
    entries = shard_entry.get("blocks")
    if entries is None:
        return None
    stats = {"blocks_reused": 0, "blocks_shipped": 0, "bytes_shipped": 0}

    def fetch(digest: str) -> bytes:
        if cache is not None:
            held = cache.get(digest)
            if held is not None:
                stats["blocks_reused"] += 1
                return held
        data = repo.get_bytes(digest)
        stats["blocks_shipped"] += 1
        stats["bytes_shipped"] += len(data)
        if cache is not None:
            cache.put(digest, data)
        return data

    return {**assemble_shard(path, entries, shard_entry["meta"], fetch),
            **stats}
