"""Block-level recovery progress records.

One dict per recovery (target side owns it; sources count themselves in
the node summary), mutated in place as stages advance:

  INIT -> BLOCKS (manifest diff + block transfer)
       -> TRANSLOG (ops tail replay past the block checkpoint)
       -> FINALIZE (refresh + warm handoff)
       -> DONE

`summarize` folds a node's live + finished recoveries and its retry
counters into the `_nodes/stats indices.recovery` section.
"""

from __future__ import annotations

from typing import Dict, Iterable

STAGE_INIT = "INIT"
STAGE_BLOCKS = "BLOCKS"
STAGE_TRANSLOG = "TRANSLOG"
STAGE_FINALIZE = "FINALIZE"
STAGE_DONE = "DONE"


def new_progress(index: str, shard_id: int, allocation_id: str,
                 rtype: str, source_node: str = "",
                 target_node: str = "", now_ms: int = 0) -> dict:
    """rtype: "PEER" | "RELOCATION" | "SNAPSHOT" | "EMPTY_STORE"."""
    return {
        "index": index, "shard": shard_id,
        "allocation_id": allocation_id,
        "type": rtype, "stage": STAGE_INIT,
        "source_node": source_node, "target_node": target_node,
        "blocks_total": 0, "blocks_reused": 0, "blocks_shipped": 0,
        "bytes_total": 0, "bytes_shipped": 0,
        "ops_replayed": 0,
        # time spent waiting in backoff between attempts — the recovery
        # analog of the reference's throttle_time
        "throttle_ms": 0,
        "attempts": 0,
        "start_ms": now_ms, "stop_ms": None,
    }


def summarize(recoveries: Iterable[dict], stats: Dict[str, int],
              current_as_source: int = 0) -> dict:
    """`_nodes/stats indices.recovery`: live counts + lifetime block and
    retry counters for one node."""
    live = done = 0
    blocks_reused = blocks_shipped = bytes_shipped = throttle = 0
    for rec in recoveries:
        if rec["stage"] == STAGE_DONE:
            done += 1
        else:
            live += 1
        blocks_reused += rec["blocks_reused"]
        blocks_shipped += rec["blocks_shipped"]
        bytes_shipped += rec["bytes_shipped"]
        throttle += rec["throttle_ms"]
    return {
        "current_as_source": int(current_as_source),
        "current_as_target": live,
        "completed": done,
        "blocks_reused": blocks_reused,
        "blocks_shipped": blocks_shipped,
        "bytes_shipped": bytes_shipped,
        "throttle_time_in_millis": throttle,
        "attempts": int(stats.get("attempts", 0)),
        "retries": int(stats.get("retries", 0)),
        "giveups": int(stats.get("giveups", 0)),
    }
