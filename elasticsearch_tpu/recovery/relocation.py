"""Warm-HBM handoff for live shard relocation.

A relocation target that reports started the moment its blocks land
would flip routing onto cold state: device arrays not yet laid out on
the target's mesh, dispatch grid not compiled — the first real queries
eat the upload + XLA compile stall the source had already paid. The
handoff runs BEFORE the target sends MASTER_SHARD_STARTED (the source
keeps serving until the routing flip, so this latency is invisible):

1. refresh: the vector sync lays the corpus out on the target's
   devices through `parallel/layout.py`'s rule table (mesh shard_put /
   extend_or_build inside the store), seeded by the shipped columnar
   blocks + IVF layout so nothing re-encodes or re-trains;
2. probe: one tiny kNN per vector field through the REAL serving entry
   (`VectorStoreShard.search`) compiles and caches the dispatch grid
   programs the first user query would otherwise compile.
"""

from __future__ import annotations

import time

import numpy as np


def warm_handoff(local_shard) -> dict:
    """Warm one relocated/recovered shard; returns a summary for the
    recovery progress record. Never raises — a warmup failure costs the
    first query a compile, not the relocation."""
    t0 = time.perf_counter_ns()
    warmed = []
    try:
        local_shard.engine.refresh()
    except Exception:
        return {"warmed_fields": [], "warm_nanos": 0}
    store = getattr(local_shard, "vector_store", None)
    mapper = getattr(local_shard, "mapper_service", None)
    if store is not None and mapper is not None:
        for field, fm in (mapper.vector_fields() or {}).items():
            try:
                probe = np.ones(int(fm.dims), dtype=np.float32)
                store.search(field, probe, k=1)
                warmed.append(field)
            except Exception:
                continue
    return {"warmed_fields": warmed,
            "warm_nanos": time.perf_counter_ns() - t0}
