"""Seed restored derived state into the live caches.

Restoring a shard's raw segments is only half of "byte-identical with
zero re-encoding": the codec-encoded columnar blocks and the trained
IVF layout must come back too, or the first sync after restore would
re-encode every row and re-train k-means. Assembly writes a SIDECAR
file next to the commit; `maybe_apply` runs after the engine opens and
BEFORE the first vector sync:

- cached columnar blocks re-install into `columnar.STORE` against the
  freshly-loaded Segment objects, fingerprint-verified (a block whose
  fingerprint does not match the live segment view is dropped, not
  installed — stale derived state must lose to the source of truth);
- IVF layouts hand to the vector store, whose next sync re-places rows
  into the restored centroids instead of calling `build_ivf_index`.

The sidecar is consumed (deleted) on apply, so a later reopen of the
same path syncs normally.
"""

from __future__ import annotations

import os
from typing import Optional

from elasticsearch_tpu.recovery.blocks import (
    SIDECAR_FILE, dumps_block, loads_block,
)


def write_sidecar(path: str, cache_entries, ivf_layouts) -> None:
    """cache_entries: [{"seg_id", "key", "block"}]; ivf_layouts:
    {field: layout}. Written atomically next to commit.bin."""
    os.makedirs(path, exist_ok=True)
    data = dumps_block({"cache": list(cache_entries),
                        "ivf": dict(ivf_layouts or {})})
    tmp = os.path.join(path, SIDECAR_FILE + ".tmp")
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, SIDECAR_FILE))


def has_sidecar(path: str) -> bool:
    """Cheap existence probe: lets a lazily-materialized shard decide
    whether a recovery seed is waiting without building a device store."""
    return os.path.exists(os.path.join(path, SIDECAR_FILE))


def load_sidecar(path: str, consume: bool = True) -> Optional[dict]:
    sidecar = os.path.join(path, SIDECAR_FILE)
    try:
        with open(sidecar, "rb") as f:
            payload = loads_block(f.read())
    except OSError:
        return None
    except Exception:
        # a torn/corrupt sidecar only costs a re-encode; drop it
        payload = None
    if consume:
        try:
            os.unlink(sidecar)
        except OSError:
            pass
    return payload


def maybe_apply(engine, vector_store) -> Optional[dict]:
    """Load + apply the sidecar for `engine.path` if one exists.
    Returns a summary dict ({"seeded", "skipped", "ivf_fields"}) or
    None when there was nothing to seed."""
    payload = load_sidecar(engine.path)
    if payload is None:
        return None
    from elasticsearch_tpu import columnar

    reader = engine.acquire_searcher()
    views = {view.segment.seg_id: view for view in reader.views}
    seeded = skipped = 0
    for entry in payload.get("cache", ()):
        view = views.get(entry.get("seg_id"))
        blk = entry.get("block")
        key = tuple(entry.get("key") or ())
        if view is None or blk is None or len(key) < 2:
            skipped += 1
            continue
        if columnar.STORE.install(view, key, blk):
            seeded += 1
        else:
            skipped += 1
    ivf = payload.get("ivf") or {}
    if ivf and vector_store is not None:
        vector_store.restore_ivf_layout(ivf)
    return {"seeded": seeded, "skipped": skipped,
            "ivf_fields": sorted(ivf)}
