"""Durable elasticity: the block-level lifecycle of the device corpus.

Re-design of the reference's `indices/recovery/` + `snapshots/` layer
(PAPER.md §1, §3.5) on top of the segment subsystem this repo already
has: sealed engine segments and per-(segment, field) columnar blocks are
immutable and fingerprinted, so THEY are the unit of durability —

- `blocks`    : deterministic block <-> bytes serialization + digests,
                and reconstruction of the exact engine commit files;
- `manifest`  : the per-shard block manifest (digest-addressed entries)
                and the digest-diff that makes everything incremental;
- `snapshot`  : collect/assemble a shard as blocks; repository snapshot
                and restore built on the content-addressed blob store;
- `seed`      : re-install restored columnar blocks + the trained IVF
                layout into the live caches so a restored shard serves
                byte-identically with ZERO re-encoding / IVF retraining;
- `peer`      : the node-local content-addressed block cache peer
                recovery diffs against (retry resumes from the last
                acked block);
- `progress`  : block-level recovery progress records + node summary
                (`_nodes/stats indices.recovery`, `_cat/recovery`);
- `relocation`: warm-HBM handoff — device arrays laid out and the
                dispatch grid warmed on the target BEFORE routing flips.
"""

from elasticsearch_tpu.recovery.blocks import (  # noqa: F401
    block_digest, dumps_block, loads_block, serialize_ledger,
    serialize_segment, write_commit_files,
)
from elasticsearch_tpu.recovery.manifest import (  # noqa: F401
    diff_entries, entry_key,
)
from elasticsearch_tpu.recovery.peer import BlockCache  # noqa: F401
from elasticsearch_tpu.recovery.progress import (  # noqa: F401
    new_progress, summarize,
)
from elasticsearch_tpu.recovery.seed import (  # noqa: F401
    load_sidecar, maybe_apply, write_sidecar,
)
from elasticsearch_tpu.recovery.snapshot import (  # noqa: F401
    assemble_shard, collect_shard_blocks, restore_shard, snapshot_shard,
)
