"""ctypes bindings for the C++ hot-loop kernels in native/es_native.cc.

The TPU owns vector scoring (ops/, parallel/); these cover the host-side
scalar loops the reference delegates to Lucene's Java hot loops
(SURVEY.md §2.9): sorted-postings intersection, union-with-score-sum,
fused BM25, and top-k selection.

The library is compiled on first use with `make` (g++ is in the image;
pybind11 is not, hence the plain C ABI + ctypes). Every binding has a
numpy fallback, so the package works — just slower — without a compiler.
Callers use the module-level functions and never need to know which
implementation ran; `AVAILABLE` reports it for stats/tests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libes_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
AVAILABLE = False


def _try_build() -> bool:
    src = os.path.join(_NATIVE_DIR, "es_native.cc")
    if not os.path.exists(src):
        return False
    if (os.path.exists(_SO_PATH)
            and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src)):
        return True
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR, "libes_native.so"], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, AVAILABLE, _load_attempted
    if _lib is not None:
        return _lib
    if _load_attempted:
        return None  # build/load failed once; don't retry per call
    _load_attempted = True
    if not _try_build():
        return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.es_bm25_score.argtypes = [i32p, f32p, ctypes.c_int64,
                                  ctypes.c_float, ctypes.c_float,
                                  ctypes.c_float, ctypes.c_float,
                                  ctypes.c_float, f32p]
    lib.es_bm25_score.restype = None
    lib.es_intersect_i64.argtypes = [i64p, ctypes.c_int64, i64p,
                                     ctypes.c_int64, i64p, i64p]
    lib.es_intersect_i64.restype = ctypes.c_int64
    lib.es_union_sum_i64.argtypes = [i64p, f32p, ctypes.c_int64,
                                     i64p, f32p, ctypes.c_int64, i64p, f32p]
    lib.es_union_sum_i64.restype = ctypes.c_int64
    lib.es_topk_f32.argtypes = [f32p, ctypes.c_int64, ctypes.c_int64, i32p]
    lib.es_topk_f32.restype = ctypes.c_int64
    _lib = lib
    AVAILABLE = True
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def bm25_score(freqs: np.ndarray, lengths: np.ndarray, idf: float,
               avg_len: float, k1: float, b: float,
               boost: float) -> np.ndarray:
    """Fused BM25 term scores for one posting list."""
    freqs = np.ascontiguousarray(freqs, dtype=np.int32)
    lengths = np.ascontiguousarray(lengths, dtype=np.float32)
    lib = _load()
    if lib is None:
        f = freqs.astype(np.float32)
        tf = f / (f + k1 * (1.0 - b + (b / avg_len if avg_len else 0.0) * lengths))
        return (boost * idf * (k1 + 1.0) * tf).astype(np.float32)
    out = np.empty(len(freqs), dtype=np.float32)
    lib.es_bm25_score(_ptr(freqs, ctypes.c_int32),
                      _ptr(lengths, ctypes.c_float), len(freqs),
                      idf, avg_len, k1, b, boost,
                      _ptr(out, ctypes.c_float))
    return out


def intersect_sorted(a: np.ndarray, b: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Positions (ia, ib) where two sorted unique int64 arrays meet —
    the np.intersect1d(..., return_indices=True) contract."""
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    lib = _load()
    if lib is None:
        _, ia, ib = np.intersect1d(a, b, assume_unique=True,
                                   return_indices=True)
        return ia, ib
    cap = min(len(a), len(b))
    ia = np.empty(cap, dtype=np.int64)
    ib = np.empty(cap, dtype=np.int64)
    n = lib.es_intersect_i64(_ptr(a, ctypes.c_int64), len(a),
                             _ptr(b, ctypes.c_int64), len(b),
                             _ptr(ia, ctypes.c_int64),
                             _ptr(ib, ctypes.c_int64))
    return ia[:n], ib[:n]


def union_sum(a: np.ndarray, sa: Optional[np.ndarray],
              b: np.ndarray, sb: Optional[np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray]:
    """Union of sorted unique int64 row arrays, summing aligned scores on
    rows present in both (bool-SHOULD accumulation)."""
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    if sa is not None:
        sa = np.ascontiguousarray(sa, dtype=np.float32)
    if sb is not None:
        sb = np.ascontiguousarray(sb, dtype=np.float32)
    lib = _load()
    if lib is None:
        rows = np.union1d(a, b)
        scores = np.zeros(len(rows), dtype=np.float32)
        if sa is not None and len(a):
            scores[np.searchsorted(rows, a)] += sa
        if sb is not None and len(b):
            scores[np.searchsorted(rows, b)] += sb
        return rows, scores
    cap = len(a) + len(b)
    rows = np.empty(cap, dtype=np.int64)
    scores = np.empty(cap, dtype=np.float32)
    null_f32 = ctypes.POINTER(ctypes.c_float)()
    n = lib.es_union_sum_i64(
        _ptr(a, ctypes.c_int64),
        _ptr(sa, ctypes.c_float) if sa is not None else null_f32, len(a),
        _ptr(b, ctypes.c_int64),
        _ptr(sb, ctypes.c_float) if sb is not None else null_f32, len(b),
        _ptr(rows, ctypes.c_int64), _ptr(scores, ctypes.c_float))
    return rows[:n], scores[:n]


def topk(scores: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k best scores ordered by (score desc, index asc) —
    the tie-break `SearchPhaseController.mergeTopDocs` uses."""
    scores = np.ascontiguousarray(scores, dtype=np.float32)
    lib = _load()
    if lib is None:
        # full (score desc, index asc) sort: argpartition would leave the
        # boundary cut nondeterministic on ties, diverging from the native
        # heap's ordering — a no-compiler host pays O(n log n) instead
        order = np.lexsort((np.arange(len(scores)), -scores))
        return order[:k].astype(np.int32)
    k = min(k, len(scores))
    out = np.empty(max(k, 0), dtype=np.int32)
    n = lib.es_topk_f32(_ptr(scores, ctypes.c_float), len(scores), k,
                        _ptr(out, ctypes.c_int32))
    return out[:n]


def knn_i8p_topk(queries: np.ndarray, packed: np.ndarray, n: int, d4: int,
                 row_scales: np.ndarray, row_bias: Optional[np.ndarray],
                 dot_mul: float, mask: Optional[np.ndarray], k: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Batched int8 kNN over a 16-row-interleaved packed corpus (the
    `es_knn_i8p_topk` kernel; see vectors/host_corpus.py for the layout
    builder). queries [B, D] f32 metric-prepped; mask None, [ng*16] shared
    or [B, ng*16] per-query u8. Returns (scores [B, k], rows [B, k]) with
    -inf/-1 padding. Requires the native library (no numpy fallback — the
    caller routes to the device path when unavailable)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native kernels unavailable")
    queries = np.ascontiguousarray(queries, dtype=np.float32)
    b, d = queries.shape
    out_s = np.empty((b, k), dtype=np.float32)
    out_r = np.empty((b, k), dtype=np.int32)
    mask_ptr, mask_stride = None, 0
    if mask is not None:
        mask = np.ascontiguousarray(mask, dtype=np.uint8)
        if mask.ndim == 2:
            mask_stride = mask.shape[1]
        mask_ptr = _ptr(mask, ctypes.c_uint8)
    lib.es_knn_i8p_topk(
        _ptr(queries, ctypes.c_float), b, d,
        _ptr(packed, ctypes.c_uint8), n, d4,
        _ptr(row_scales, ctypes.c_float),
        _ptr(row_bias, ctypes.c_float) if row_bias is not None else None,
        dot_mul, mask_ptr, mask_stride, k,
        _ptr(out_s, ctypes.c_float), _ptr(out_r, ctypes.c_int32))
    return out_s, out_r


def knn_has_vnni() -> bool:
    """True when the native int8 kNN kernel runs its AVX512-VNNI path on
    this host (the scalar fallback is ~100x slower; the serving cost model
    prices the scan accordingly)."""
    lib = _load()
    return bool(lib is not None and lib.es_knn_i8p_has_vnni())


def _bind_knn(lib: ctypes.CDLL) -> None:
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.es_knn_i8p_topk.argtypes = [
        f32p, ctypes.c_int64, ctypes.c_int64,
        u8p, ctypes.c_int64, ctypes.c_int64,
        f32p, f32p, ctypes.c_float,
        u8p, ctypes.c_int64, ctypes.c_int64, f32p, i32p]
    lib.es_knn_i8p_topk.restype = None
    lib.es_knn_i8p_has_vnni.argtypes = []
    lib.es_knn_i8p_has_vnni.restype = ctypes.c_int32


# Build/load at import so the first search request never pays the compile
# (a stat-only no-op once libes_native.so is newer than the source).
_load()
if _lib is not None:
    _bind_knn(_lib)
