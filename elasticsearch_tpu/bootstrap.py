"""Bootstrap: process hardening + startup checks.

Re-design of the reference's bootstrap layer (SURVEY.md §2.1):
- `Bootstrap.initializeNatives` (`Bootstrap.java:104`) / `JNANatives` /
  `JNACLibrary` — mlockall, rlimit probes — here via ctypes on libc
  (the "thin C++/ctypes shim" SURVEY.md §2.9 prescribes).
- `SystemCallFilter.java` — a seccomp-BPF program built in userspace and
  installed with prctl; here the same construction in Python: BPF
  bytecode blocking process-spawning syscalls, installed via
  PR_SET_NO_NEW_PRIVS + PR_SET_SECCOMP. Off by default in this build
  because the ML sidecar spawns per-job processes lazily (the reference
  spawns its native controller *before* installing the filter, then the
  controller does all spawning — see Spawner.java); enable with
  `bootstrap.system_call_filter: true` on nodes without ML jobs.
- `BootstrapChecks.java` — fail-fast startup checks (file descriptors,
  memory lock sanity) that harden production nodes.
- `modules/systemd` — sd_notify readiness over the NOTIFY_SOCKET
  datagram socket.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import resource
import socket
import struct
import sys
from typing import List, Optional

from elasticsearch_tpu.common.settings import setting_bool

# ---------------------------------------------------------------------------
# libc natives (reference: JNACLibrary / JNANatives)
# ---------------------------------------------------------------------------

_MCL_CURRENT = 1
_MCL_FUTURE = 2

_PR_SET_NO_NEW_PRIVS = 38
_PR_SET_SECCOMP = 22
_SECCOMP_MODE_FILTER = 2
_NR_SECCOMP = 317  # x86_64 seccomp(2); filter is arch-gated to x86_64 anyway
_SECCOMP_SET_MODE_FILTER = 1
_SECCOMP_FILTER_FLAG_TSYNC = 1


def _libc() -> Optional[ctypes.CDLL]:
    try:
        return ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                           use_errno=True)
    except OSError:
        return None


class Natives:
    """Results of native hardening attempts (queryable via _nodes info,
    like the reference's JNANatives.LOCAL_MLOCKALL flag)."""

    def __init__(self):
        self.memory_locked = False
        self.seccomp_installed = False
        self.errors: List[str] = []

    def try_mlockall(self) -> None:
        libc = _libc()
        if libc is None:
            self.errors.append("libc unavailable; cannot mlockall")
            return
        if libc.mlockall(_MCL_CURRENT | _MCL_FUTURE) == 0:
            self.memory_locked = True
        else:
            err = ctypes.get_errno()
            self.errors.append(
                f"mlockall failed (errno {err}): memory is not locked; "
                f"raise RLIMIT_MEMLOCK (ulimit -l) to enable")

    def try_seccomp_filter(self) -> None:
        """Install a BPF filter denying process-spawning syscalls
        (reference: SystemCallFilter.java builds the same program)."""
        libc = _libc()
        if libc is None:
            self.errors.append("libc unavailable; cannot install seccomp")
            return
        if libc.prctl(_PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0:
            self.errors.append("prctl(PR_SET_NO_NEW_PRIVS) failed")
            return
        prog = _build_bpf_program()
        filt = ctypes.create_string_buffer(prog)
        # struct sock_fprog { unsigned short len; struct sock_filter *filter; }
        class SockFprog(ctypes.Structure):
            _fields_ = [("len", ctypes.c_ushort),
                        ("filter", ctypes.c_void_p)]

        fprog = SockFprog(len(prog) // 8, ctypes.cast(filt, ctypes.c_void_p))
        # prefer seccomp(2) with TSYNC so the filter applies to EVERY
        # thread, not just the caller — prctl(PR_SET_SECCOMP) is
        # per-thread and leaves already-running threads unfiltered
        # (reference: SystemCallFilter uses SECCOMP_FILTER_FLAG_TSYNC)
        if libc.syscall(_NR_SECCOMP, _SECCOMP_SET_MODE_FILTER,
                        _SECCOMP_FILTER_FLAG_TSYNC, ctypes.byref(fprog)) == 0:
            self.seccomp_installed = True
            return
        # fallback for kernels without seccomp(2): per-thread prctl —
        # only safe because bootstrap runs before worker threads spawn
        if libc.prctl(_PR_SET_SECCOMP, _SECCOMP_MODE_FILTER,
                      ctypes.byref(fprog), 0, 0) == 0:
            self.seccomp_installed = True
            self.errors.append(
                "seccomp installed via prctl (no TSYNC): filter is "
                "per-thread; install happened before thread spawn")
        else:
            err = ctypes.get_errno()
            self.errors.append(f"seccomp install failed (errno {err})")


def _bpf_stmt(code: int, k: int) -> bytes:
    return struct.pack("<HBBI", code, 0, 0, k)


def _bpf_jump(code: int, k: int, jt: int, jf: int) -> bytes:
    return struct.pack("<HBBI", code, jt, jf, k)


# BPF opcodes
_BPF_LD_W_ABS = 0x20
_BPF_JMP_JEQ_K = 0x15
_BPF_RET_K = 0x06
_SECCOMP_RET_ALLOW = 0x7FFF0000
_SECCOMP_RET_ERRNO = 0x00050000  # | errno
_EACCES = 13

# syscall numbers (x86_64) the reference's filter denies: spawning
_X86_64_BLOCKED = {
    "fork": 57, "vfork": 58, "execve": 59, "execveat": 322,
}
_AUDIT_ARCH_X86_64 = 0xC000003E


def _build_bpf_program() -> bytes:
    """Allow-all except blocked syscalls → EACCES (matching the reference's
    'deny process execution' policy, SystemCallFilter.java)."""
    blocked = sorted(_X86_64_BLOCKED.values())
    prog = bytearray()
    # load arch; bail out (allow) on non-x86_64 so we never misinterpret
    # syscall numbers of another ABI
    prog += _bpf_stmt(_BPF_LD_W_ABS, 4)  # seccomp_data.arch
    # jf skips LD nr + every blocked-JEQ, landing exactly on RET ALLOW
    prog += _bpf_jump(_BPF_JMP_JEQ_K, _AUDIT_ARCH_X86_64, 0,
                      len(blocked) + 1)
    prog += _bpf_stmt(_BPF_LD_W_ABS, 0)  # seccomp_data.nr
    for i, nr in enumerate(blocked):
        remaining = len(blocked) - 1 - i
        prog += _bpf_jump(_BPF_JMP_JEQ_K, nr, remaining + 1, 0)
    prog += _bpf_stmt(_BPF_RET_K, _SECCOMP_RET_ALLOW)
    prog += _bpf_stmt(_BPF_RET_K, _SECCOMP_RET_ERRNO | _EACCES)
    return bytes(prog)


# ---------------------------------------------------------------------------
# bootstrap checks (reference: BootstrapChecks.java)
# ---------------------------------------------------------------------------

class BootstrapCheckFailure(Exception):
    pass


def run_bootstrap_checks(settings: dict, enforce: bool = False) -> List[str]:
    """Run startup checks; in enforce mode (production: a non-loopback
    publish address, reference BootstrapChecks.enforceLimits) failures
    abort startup, otherwise they are warnings."""
    failures: List[str] = []

    # file descriptor check (reference: FileDescriptorCheck, 65535 floor)
    try:
        soft, _hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft != resource.RLIM_INFINITY and soft < 4096:
            failures.append(
                f"max file descriptors [{soft}] is too low, increase to at "
                f"least [4096] (ulimit -n)")
    except (OSError, ValueError):
        pass

    # memory lock requested but not grantable (reference: MlockallCheck)
    if setting_bool(settings.get("bootstrap.memory_lock")):
        try:
            soft, _ = resource.getrlimit(resource.RLIMIT_MEMLOCK)
            if soft != resource.RLIM_INFINITY and soft < (1 << 24):
                failures.append(
                    "bootstrap.memory_lock is set but RLIMIT_MEMLOCK is "
                    "too low; memory locking will fail (ulimit -l)")
        except (OSError, ValueError):
            pass

    # data path must be writable (reference: NodeEnvironment startup) —
    # check the directory itself when it exists; only when it must be
    # created does the parent's writability matter
    data_path = settings.get("path.data")
    if data_path:
        if os.path.isdir(data_path):
            writable = os.access(data_path, os.W_OK)
        else:
            parent = os.path.dirname(os.path.abspath(data_path)) or "."
            writable = os.path.isdir(parent) and os.access(parent, os.W_OK)
        if not writable:
            failures.append(f"data path [{data_path}] is not writable")

    if enforce and failures:
        raise BootstrapCheckFailure("; ".join(failures))
    return failures


def initialize_natives(settings: dict) -> Natives:
    """reference: Bootstrap.initializeNatives (Bootstrap.java:104)."""
    natives = Natives()
    if setting_bool(settings.get("bootstrap.memory_lock")):
        natives.try_mlockall()
    if setting_bool(settings.get("bootstrap.system_call_filter")):
        natives.try_seccomp_filter()
    return natives


# ---------------------------------------------------------------------------
# systemd notify (reference: modules/systemd — sd_notify)
# ---------------------------------------------------------------------------

def sd_notify(state: str = "READY=1") -> bool:
    """Send a readiness datagram to the NOTIFY_SOCKET if systemd set one."""
    addr = os.environ.get("NOTIFY_SOCKET")
    if not addr:
        return False
    if addr.startswith("@"):  # abstract namespace
        addr = "\0" + addr[1:]
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        try:
            sock.sendto(state.encode("utf-8"), addr)
        finally:
            sock.close()
        return True
    except OSError:
        return False
