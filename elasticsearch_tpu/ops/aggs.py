"""Device-resident aggregations: columnar field store + segment-reduce kernels.

The analytics half of `_search` (`search/aggregations` is one of the
reference's largest subsystems) served entirely host-side until this
module: `search/aggregations.py` reduced in numpy after a per-doc Python
`get_doc_value` loop, so a terms agg over 100k matching rows cost 100k
interpreter round-trips while the TPU idled. Terms/histogram/range/stats
aggs are segment-reduce shapes — scatter-add over bucket ids — the exact
kernel family `ops/bm25.py` already proves out for impact scoring, so this
module gives doc-value fields the treatment `vectors/store.py` gives
`dense_vector` and `ops/bm25.py` gives text:

* build (at refresh, lazily on first agg use like `LexicalShard`): each
  aggregated field becomes an `AggColumn` — an f64 value column + presence
  mask over the reader's live rows (padded to a pow-2 row bucket so the
  compiled shapes survive refreshes), plus, for terms aggs, a global
  ordinal column (int32 ord per row over the sorted-unique value set).
  Per-segment extractions cache by segment fingerprint, so append-only
  refreshes re-extract only delta segments (copy-on-write rebuild — an
  in-flight search keeps the previous column's arrays).

* search: ONE dispatch per (bucket-source, metric) pair computes the fused
  filter→aggregate: the query's matched rows arrive as a boolean mask over
  the row bucket, bucket ids derive in-kernel from the resident key column
  (ordinals for terms, affine floor for histogram/date_histogram, bound
  comparisons for range), and a scatter-add reduces counts / sums / mins /
  maxs per bucket into a board of `n_buckets + 1` lanes (the trash lane
  collects pad rows and, for terms, the `missing` bucket).

* exactness: every kernel traces and executes under the dispatcher's
  scoped x64 flag — counts accumulate in int64 (order-free, exact), sums
  in f64. Host parity for sums is guaranteed only for *integral* columns
  (every value integer-valued, sum of |values| < 2^53 — dates, longs,
  counts), where any accumulation order reproduces numpy's pairwise sum
  bit-for-bit; `search/agg_plan.py` routes sum-bearing aggs on other
  columns to the host path. min/max/counts are order-insensitive and run
  on device for any numeric column.

* mesh: columns past the `parallel/policy.py` row floor keep a row-sharded
  device copy; the `aggs.mesh_*` twins reduce each shard's row range
  locally inside one shard_map program and merge boards with
  psum/pmin/pmax — exact for the integral-sum contract above, so the
  per-shard device partials merge like every other mesh kernel.

Kernel keys (`ops/dispatch.py`, strict closed grid): rows pad to the
pow-2 row bucket fixed at column build; `n_buckets` rounds up
AGG_B_LADDER; warmup pre-compiles the interactive rungs at column build.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.ops import dispatch

logger = logging.getLogger("elasticsearch_tpu.aggs")

# bucket-count ladder: terms cardinality / histogram span rounds UP so one
# compiled program serves a band of bucket counts; beyond the last rung the
# plan falls back to the host path (search.max_buckets territory anyway)
AGG_B_LADDER = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                16384, 32768, 65536)

# sums of integer-valued f64 are exact (== numpy's pairwise sum in any
# accumulation order) while |sum| stays under 2^53
_EXACT_INT = float(1 << 53)

# warmup rungs: small terms/histogram dashboards; the persistent cache and
# steady traffic fill the tail
WARMUP_AGG_BUCKETS = (8, 64)

# ladder-top warmup clamp: a single pathological high-cardinality field
# must not AOT-compile the giant rungs at column build — those compile on
# first use (and persist) instead of burning warmup time for every column
WARMUP_MAX_ORD_B = 4096

# HLL register geometry — MUST mirror search/agg_partials.py (_HLL_P /
# _HLL_M) so device register boards pack into host-identical `$p` states
HLL_P = 12
HLL_M = 1 << HLL_P

# composite sub-agg trees: per-level bucket counts ride the same ladder;
# the flat board is the PRODUCT of the levels, so trees cap on total
# lanes (HLL boards are HLL_M registers per lane and cap much lower)
TREE_MAX_DEPTH = 3
TREE_MAX_LANES = 65536
HLL_MAX_LANES = 256

# per-level kernel-arg arity for the composite tree kernels: level args
# flatten in level order, each level contributing (row-shaped..., then
# replicated params...) — see _split_level_args
_LEVEL_ROW = {"ord": 1, "hist": 2, "cal": 2}
_LEVEL_REPL = {"ord": 1, "hist": 1, "cal": 2}


def bucket_count(n: int) -> Optional[int]:
    """Round a bucket count up the AGG_B_LADDER; None = off the grid
    (the caller must fall back to the host path)."""
    n = max(int(n), 1)
    for b in AGG_B_LADDER:
        if b >= n:
            return b
    return None


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def in_b_grid(b: int) -> bool:
    return b in AGG_B_LADDER


# ---------------------------------------------------------------------------
# kernels (traced under scoped x64 — see ops/dispatch.py _Kernel.x64)
# ---------------------------------------------------------------------------


def _ord_targets(ords, n_buckets: int):
    import jax.numpy as jnp
    in_range = (ords >= 0) & (ords < n_buckets)
    return jnp.where(in_range, ords, n_buckets)


def _agg_ord_counts(ords, mask, n_buckets: int):
    """Doc counts per ordinal: [B+1] int64; lane B collects matched rows
    whose key is missing (the terms `missing` bucket) — pad rows have
    mask False and never land anywhere."""
    import jax.numpy as jnp
    tgt = _ord_targets(ords, n_buckets)
    return jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(mask, jnp.int64(1), jnp.int64(0)))


def _metric_boards(tgt, ok, v_eff, n_buckets: int):
    import jax.numpy as jnp
    one = jnp.where(ok, jnp.int64(1), jnp.int64(0))
    cnt = jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(one)
    s = jnp.zeros(n_buckets + 1, dtype=jnp.float64).at[tgt].add(
        jnp.where(ok, v_eff, 0.0))
    mn = jnp.full(n_buckets + 1, jnp.inf, dtype=jnp.float64).at[tgt].min(
        jnp.where(ok, v_eff, jnp.inf))
    mx = jnp.full(n_buckets + 1, -jnp.inf, dtype=jnp.float64).at[tgt].max(
        jnp.where(ok, v_eff, -jnp.inf))
    return cnt, s, mn, mx


def _metric_eff(vals, present, mparams):
    """Apply the metric field's `missing` substitute: mparams f64[2] =
    (flag, value)."""
    import jax.numpy as jnp
    use_missing = mparams[0] > 0.0
    p_eff = present | use_missing
    v_eff = jnp.where(present, vals, mparams[1])
    return v_eff, p_eff


def _agg_ord_metric(ords, mask, mparams, vals, present, n_buckets: int):
    """Per-ordinal numeric metric boards (count/sum/min/max); lane B is
    the missing-key bucket's metrics."""
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    tgt = _ord_targets(ords, n_buckets)
    return _metric_boards(tgt, mask & p_eff, v_eff, n_buckets)


def _hist_ids(keys, kpresent, hparams, n_buckets: int):
    """Bucket ids from the resident key column: hparams f64[6] =
    (interval, offset, base, div, kflag, kmissing). `div` pre-divides
    (date_nanos → millis); `base` rebases floor((v-off)/interval) so ids
    land in [0, B). All f64 — bitwise-identical to the host's numpy key
    math."""
    import jax.numpy as jnp
    interval, offset, base, div = (hparams[0], hparams[1], hparams[2],
                                   hparams[3])
    p_eff = kpresent | (hparams[4] > 0.0)
    v = jnp.where(kpresent, keys / div, hparams[5])
    m = jnp.floor((v - offset) / interval)
    ids = (m - base).astype(jnp.int32)
    ok = p_eff & (ids >= 0) & (ids < n_buckets)
    return jnp.where(ok, ids, n_buckets), ok


def _agg_hist_counts(keys, kpresent, mask, hparams, n_buckets: int):
    import jax.numpy as jnp
    tgt, ok = _hist_ids(keys, kpresent, hparams, n_buckets)
    return jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(mask & ok, jnp.int64(1), jnp.int64(0)))


def _agg_hist_metric(keys, kpresent, mask, hparams, mparams, vals, present,
                     n_buckets: int):
    tgt, ok = _hist_ids(keys, kpresent, hparams, n_buckets)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    return _metric_boards(tgt, mask & ok & p_eff, v_eff, n_buckets)


def _range_members(keys, kpresent, mask, bounds, rparams):
    """[B, R] membership: bounds f64[B, 2] (lo, hi) with -inf/+inf for
    open ends and (+inf, +inf) pad rows; rparams f64[2] applies the key
    field's `missing` substitute. A row may belong to several overlapping
    ranges — exactly the host semantics."""
    import jax.numpy as jnp
    p_eff = kpresent | (rparams[0] > 0.0)
    v = jnp.where(kpresent, keys, rparams[1])
    ok = mask & p_eff
    return ((v[None, :] >= bounds[:, 0:1]) & (v[None, :] < bounds[:, 1:2])
            & ok[None, :])


def _agg_range_counts(keys, kpresent, mask, bounds, rparams):
    import jax.numpy as jnp
    m = _range_members(keys, kpresent, mask, bounds, rparams)
    return m.astype(jnp.int64).sum(axis=1)


def _agg_range_metric(keys, kpresent, mask, bounds, rparams, mparams, vals,
                      present):
    import jax.numpy as jnp
    m = _range_members(keys, kpresent, mask, bounds, rparams)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    mm = m & p_eff[None, :]
    cnt = mm.astype(jnp.int64).sum(axis=1)
    s = jnp.where(mm, v_eff[None, :], 0.0).sum(axis=1)
    mn = jnp.where(mm, v_eff[None, :], jnp.inf).min(axis=1)
    mx = jnp.where(mm, v_eff[None, :], -jnp.inf).max(axis=1)
    return cnt, s, mn, mx


# ------------------------------------------------- calendar / tree / HLL ---

def _cal_ids(keys, kpresent, cbounds, cparams, n_buckets: int):
    """Bucket ids for calendar-interval date_histograms from a
    precomputed sorted boundary table: cbounds f64[B] holds the
    `_calendar_floor` outputs over the offset-shifted millis domain
    (+inf pads past the real span), cparams f64[2] = (div, offset).
    One searchsorted pass — no wall-clock arithmetic in traced code.
    Rows first truncate exactly like the host's `int(v - offset)`
    (toward zero, not floor)."""
    import jax.numpy as jnp
    shifted = jnp.trunc(keys / cparams[0] - cparams[1])
    idx = jnp.searchsorted(cbounds, shifted, side="right") - 1
    ids = idx.astype(jnp.int32)
    ok = kpresent & (ids >= 0) & (ids < n_buckets)
    return jnp.where(ok, ids, 0), ok


def _agg_cal_counts(keys, kpresent, mask, cbounds, cparams, n_buckets: int):
    import jax.numpy as jnp
    ids, ok = _cal_ids(keys, kpresent, cbounds, cparams, n_buckets)
    tgt = jnp.where(ok, ids, n_buckets)
    return jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(mask & ok, jnp.int64(1), jnp.int64(0)))


def _agg_cal_metric(keys, kpresent, mask, cbounds, cparams, mparams, vals,
                    present, n_buckets: int):
    import jax.numpy as jnp
    ids, ok = _cal_ids(keys, kpresent, cbounds, cparams, n_buckets)
    tgt = jnp.where(ok, ids, n_buckets)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    return _metric_boards(tgt, mask & ok & p_eff, v_eff, n_buckets)


def _tree_targets(mask, levels, n_buckets, flat_args):
    """Composite bucket ids over a chain of bucket levels: per level the
    id derives like the single-level kernels, the composite folds as
    `cid = cid * k_level + id`. A row is ok only if EVERY level resolves
    (the global trash lane catches the rest). Level arg layout:
    ord → (ords, oparams f64[1]: missing-lane flag), hist → (keys,
    kpresent, hparams), cal → (keys, kpresent, cbounds, cparams).
    Returns (tgt, ok, total) with tgt == total for not-ok rows."""
    import jax.numpy as jnp
    cid = jnp.zeros(mask.shape, dtype=jnp.int32)
    ok = mask
    total = 1
    i = 0
    for kind, k in zip(levels, n_buckets):
        if kind == "ord":
            ords, op = flat_args[i], flat_args[i + 1]
            i += 2
            absent = ords < 0
            # with a `missing` param the level's last lane IS the missing
            # bucket (k was sized for it); otherwise absent rows drop out
            ids = jnp.where(absent, jnp.int32(k - 1), ords)
            lok = (~absent) | (op[0] > 0.0)
        elif kind == "hist":
            keys, kp, hp = flat_args[i], flat_args[i + 1], flat_args[i + 2]
            i += 3
            tgt_l, lok = _hist_ids(keys, kp, hp, k)
            ids = jnp.where(lok, tgt_l, 0).astype(jnp.int32)
        else:  # "cal"
            keys, kp, cb, cp = (flat_args[i], flat_args[i + 1],
                                flat_args[i + 2], flat_args[i + 3])
            i += 4
            ids, lok = _cal_ids(keys, kp, cb, cp, k)
        cid = cid * k + jnp.where(lok, ids, 0)
        ok = ok & lok
        total *= k
    return jnp.where(ok, cid, total), ok, total


def _agg_tree_counts(mask, *level_args, levels, n_buckets):
    """Composite doc counts: int64[prod(n_buckets) + 1]; the last lane is
    the global trash (pad rows + rows failing any level)."""
    import jax.numpy as jnp
    tgt, ok, total = _tree_targets(mask, levels, n_buckets, level_args)
    return jnp.zeros(total + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(ok, jnp.int64(1), jnp.int64(0)))


def _agg_tree_metric(mask, mparams, vals, present, *level_args, levels,
                     n_buckets):
    """Per-composite-bucket metric boards (count/sum/min/max)."""
    tgt, ok, total = _tree_targets(mask, levels, n_buckets, level_args)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    return _metric_boards(tgt, ok & p_eff, v_eff, total)


def _agg_hll_board(mask, hidx, hrho, *level_args, levels, n_buckets):
    """Per-composite-bucket HLL register board: int32[total+1, HLL_M],
    max-merged per (bucket, register). hidx/hrho are the precomputed
    per-row register index and rank (rho == 0 marks an absent value, so
    absent rows never raise a register). levels may be empty: the
    top-level cardinality board with every matched row in lane 0."""
    import jax.numpy as jnp
    tgt, ok, total = _tree_targets(mask, levels, n_buckets, level_args)
    rho = jnp.where(ok, hrho, 0)
    board = jnp.zeros((total + 1, HLL_M), dtype=jnp.int32)
    return board.at[tgt, hidx].max(rho)


# ----------------------------------------------------------------- mesh ----

def _mesh_reduce(local_fn, mesh, row_args, repl_args, n_boards,
                 merges=None):
    """Run a board-producing local reduce per shard over row-sharded
    columns and merge boards with psum/pmin/pmax (exact under the
    integral-sum contract). Boards are (cnt int64[, sum f64, min f64,
    max f64]): index 0 and 1 merge by sum, 2 by min, 3 by max — unless
    `merges` names a per-board rule ('sum' | 'min' | 'max') explicitly
    (the HLL register board merges by max)."""
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.parallel.sharded_knn import shard_map

    axis = mesh_lib.SHARD_AXIS
    row_spec = jax.sharding.PartitionSpec(axis)
    repl = jax.sharding.PartitionSpec()

    def body(*args):
        boards = local_fn(*args)
        if not isinstance(boards, tuple):
            boards = (boards,)
        merged = []
        for i, b in enumerate(boards):
            rule = merges[i] if merges is not None else (
                "min" if i == 2 else "max" if i == 3 else "sum")
            if rule == "min":
                merged.append(jax.lax.pmin(b, axis))
            elif rule == "max":
                merged.append(jax.lax.pmax(b, axis))
            else:
                merged.append(jax.lax.psum(b, axis))
        return merged[0] if n_boards == 1 else tuple(merged)

    in_specs = tuple([row_spec] * len(row_args) + [repl] * len(repl_args))
    out_specs = repl if n_boards == 1 else tuple([repl] * n_boards)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return fn(*row_args, *repl_args)


# Every row-shaped array (key column, presence, mask, metric columns)
# shards over the row axis; small per-query params/bounds replicate. Each
# shard reduces its own row range into a full [B+1] board, then the boards
# merge in-program (psum for counts/sums, pmin/pmax for extrema).

def _agg_mesh_ord_counts(ords, mask, n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda o, m: _agg_ord_counts(o, m, n_buckets), mesh,
        (ords, mask), (), 1)


def _agg_mesh_ord_metric(ords, mask, vals, present, mparams,
                         n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda o, m, v, p, mp: _agg_ord_metric(o, m, mp, v, p, n_buckets),
        mesh, (ords, mask, vals, present), (mparams,), 4)


def _agg_mesh_hist_counts(keys, kpresent, mask, hparams, n_buckets: int,
                          mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, hp: _agg_hist_counts(k, kp, m, hp, n_buckets),
        mesh, (keys, kpresent, mask), (hparams,), 1)


def _agg_mesh_hist_metric(keys, kpresent, mask, vals, present, hparams,
                          mparams, n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, v, p, hp, mp: _agg_hist_metric(
            k, kp, m, hp, mp, v, p, n_buckets),
        mesh, (keys, kpresent, mask, vals, present), (hparams, mparams), 4)


def _agg_mesh_range_counts(keys, kpresent, mask, bounds, rparams, mesh=None):
    return _mesh_reduce(
        _agg_range_counts, mesh, (keys, kpresent, mask), (bounds, rparams),
        1)


def _agg_mesh_range_metric(keys, kpresent, mask, vals, present, bounds,
                           rparams, mparams, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, v, p, b, rp, mp: _agg_range_metric(
            k, kp, m, b, rp, mp, v, p),
        mesh, (keys, kpresent, mask, vals, present),
        (bounds, rparams, mparams), 4)


def _agg_mesh_cal_counts(keys, kpresent, mask, cbounds, cparams,
                         n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, cb, cp: _agg_cal_counts(k, kp, m, cb, cp,
                                                 n_buckets),
        mesh, (keys, kpresent, mask), (cbounds, cparams), 1)


def _agg_mesh_cal_metric(keys, kpresent, mask, vals, present, cbounds,
                         cparams, mparams, n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, v, p, cb, cp, mp: _agg_cal_metric(
            k, kp, m, cb, cp, mp, v, p, n_buckets),
        mesh, (keys, kpresent, mask, vals, present),
        (cbounds, cparams, mparams), 4)


def _split_level_args(levels, level_args):
    """Split the flat per-level args into (row-shaped, replicated) tuples
    for shard_map in_specs, plus a rebuild() that restores the interleaved
    layout `_tree_targets` expects inside the mesh body."""
    rows: list = []
    repls: list = []
    i = 0
    for kind in levels:
        nr, np_ = _LEVEL_ROW[kind], _LEVEL_REPL[kind]
        rows.extend(level_args[i:i + nr])
        repls.extend(level_args[i + nr:i + nr + np_])
        i += nr + np_

    def rebuild(row_args, repl_args):
        out: list = []
        ri = pi = 0
        for kind in levels:
            nr, np_ = _LEVEL_ROW[kind], _LEVEL_REPL[kind]
            out.extend(row_args[ri:ri + nr])
            ri += nr
            out.extend(repl_args[pi:pi + np_])
            pi += np_
        return tuple(out)

    return tuple(rows), tuple(repls), rebuild


def _agg_mesh_tree_counts(mask, *level_args, levels, n_buckets, mesh=None):
    rows, repls, rebuild = _split_level_args(levels, level_args)
    nr = len(rows)

    def local(m, *args):
        la = rebuild(args[:nr], args[nr:])
        return _agg_tree_counts(m, *la, levels=levels, n_buckets=n_buckets)

    return _mesh_reduce(local, mesh, (mask,) + rows, repls, 1)


def _agg_mesh_tree_metric(mask, mparams, vals, present, *level_args,
                          levels, n_buckets, mesh=None):
    rows, repls, rebuild = _split_level_args(levels, level_args)
    nr = len(rows)

    def local(m, v, p, *args):
        la = rebuild(args[:nr], args[nr:-1])
        return _agg_tree_metric(m, args[-1], v, p, *la, levels=levels,
                                n_buckets=n_buckets)

    return _mesh_reduce(local, mesh, (mask, vals, present) + rows,
                        repls + (mparams,), 4)


def _agg_mesh_hll_board(mask, hidx, hrho, *level_args, levels, n_buckets,
                        mesh=None):
    """HLL register boards merge per (bucket, register) by MAX across the
    shard axis — the only board family whose cross-shard merge is not the
    positional default."""
    rows, repls, rebuild = _split_level_args(levels, level_args)
    nr = len(rows)

    def local(m, hi, hr, *args):
        la = rebuild(args[:nr], args[nr:])
        return _agg_hll_board(m, hi, hr, *la, levels=levels,
                              n_buckets=n_buckets)

    return _mesh_reduce(local, mesh, (mask, hidx, hrho) + rows, repls, 1,
                        merges=("max",))


# ------------------------------------------------------------ grid checks --

def _row_bucket_ok(r: int) -> bool:
    return r >= 1 and (r & (r - 1)) == 0


def _grid_ord(statics, sigs) -> bool:
    r = sigs[0][0][0]
    return _row_bucket_ok(int(r)) and in_b_grid(int(statics["n_buckets"]))


def _grid_hist(statics, sigs) -> bool:
    r = sigs[0][0][0]
    return _row_bucket_ok(int(r)) and in_b_grid(int(statics["n_buckets"]))


def _grid_range(statics, sigs) -> bool:
    r = sigs[0][0][0]
    # bounds [B, 2] rides the 4th positional array arg
    b = None
    for s in sigs:
        if s and s[0] != "py" and len(s[0]) == 2 and s[0][1] == 2:
            b = s[0][0]
            break
    return _row_bucket_ok(int(r)) and (b is None or in_b_grid(int(b)))


def _grid_cal(statics, sigs) -> bool:
    r = sigs[0][0][0]
    return _row_bucket_ok(int(r)) and in_b_grid(int(statics["n_buckets"]))


def _tree_lanes(statics):
    """(ladder_ok, total lanes) for a tuple-valued n_buckets static."""
    total = 1
    for k in statics["n_buckets"]:
        if not in_b_grid(int(k)):
            return False, 0
        total *= int(k)
    return True, total


def _grid_tree(statics, sigs) -> bool:
    r = sigs[0][0][0]
    nb = tuple(statics["n_buckets"])
    ok, total = _tree_lanes(statics)
    return (_row_bucket_ok(int(r)) and ok
            and 1 <= len(nb) <= TREE_MAX_DEPTH + 1
            and total <= TREE_MAX_LANES)


def _grid_hll(statics, sigs) -> bool:
    r = sigs[0][0][0]
    nb = tuple(statics["n_buckets"])
    ok, total = _tree_lanes(statics)
    return (_row_bucket_ok(int(r)) and ok and len(nb) <= TREE_MAX_DEPTH
            and total <= HLL_MAX_LANES)


def _register():
    reg = dispatch.DISPATCH.register
    reg("aggs.ord_counts", _agg_ord_counts,
        static_argnames=("n_buckets",), grid_check=_grid_ord, x64=True)
    reg("aggs.ord_metric", _agg_ord_metric,
        static_argnames=("n_buckets",), grid_check=_grid_ord, x64=True)
    reg("aggs.hist_counts", _agg_hist_counts,
        static_argnames=("n_buckets",), grid_check=_grid_hist, x64=True)
    reg("aggs.hist_metric", _agg_hist_metric,
        static_argnames=("n_buckets",), grid_check=_grid_hist, x64=True)
    reg("aggs.range_counts", _agg_range_counts,
        grid_check=_grid_range, x64=True)
    reg("aggs.range_metric", _agg_range_metric,
        grid_check=_grid_range, x64=True)
    reg("aggs.mesh_ord_counts", _agg_mesh_ord_counts,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_ord,
        x64=True)
    reg("aggs.mesh_ord_metric", _agg_mesh_ord_metric,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_ord,
        x64=True)
    reg("aggs.mesh_hist_counts", _agg_mesh_hist_counts,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_hist,
        x64=True)
    reg("aggs.mesh_hist_metric", _agg_mesh_hist_metric,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_hist,
        x64=True)
    reg("aggs.mesh_range_counts", _agg_mesh_range_counts,
        static_argnames=("mesh",), grid_check=_grid_range, x64=True)
    reg("aggs.mesh_range_metric", _agg_mesh_range_metric,
        static_argnames=("mesh",), grid_check=_grid_range, x64=True)
    reg("aggs.cal_counts", _agg_cal_counts,
        static_argnames=("n_buckets",), grid_check=_grid_cal, x64=True)
    reg("aggs.cal_metric", _agg_cal_metric,
        static_argnames=("n_buckets",), grid_check=_grid_cal, x64=True)
    reg("aggs.tree_counts", _agg_tree_counts,
        static_argnames=("levels", "n_buckets"), grid_check=_grid_tree,
        x64=True)
    reg("aggs.tree_metric", _agg_tree_metric,
        static_argnames=("levels", "n_buckets"), grid_check=_grid_tree,
        x64=True)
    reg("aggs.hll_board", _agg_hll_board,
        static_argnames=("levels", "n_buckets"), grid_check=_grid_hll,
        x64=True)
    reg("aggs.mesh_cal_counts", _agg_mesh_cal_counts,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_cal,
        x64=True)
    reg("aggs.mesh_cal_metric", _agg_mesh_cal_metric,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_cal,
        x64=True)
    reg("aggs.mesh_tree_counts", _agg_mesh_tree_counts,
        static_argnames=("levels", "n_buckets", "mesh"),
        grid_check=_grid_tree, x64=True)
    reg("aggs.mesh_tree_metric", _agg_mesh_tree_metric,
        static_argnames=("levels", "n_buckets", "mesh"),
        grid_check=_grid_tree, x64=True)
    reg("aggs.mesh_hll_board", _agg_mesh_hll_board,
        static_argnames=("levels", "n_buckets", "mesh"),
        grid_check=_grid_hll, x64=True)


_register()


# ---------------------------------------------------------------------------
# columnar field store
# ---------------------------------------------------------------------------


# per-segment doc-values extraction lives in the shared segment block
# store (`elasticsearch_tpu/columnar/`): `ValuesBlock` is the exact
# shape the retired `_SegmentColumn` held, extracted once per (segment,
# field, live-set) and shared with every other consumer — this module's
# private `_seg_cache` is gone (tpulint TPU011 keeps it from growing
# back)


class AggColumn:
    """One field's columnar agg data over a reader snapshot, padded to the
    store's pow-2 row bucket. Device mirrors upload lazily (under the
    scoped x64 flag so f64 survives) and a mesh-sharded copy is kept when
    the serving policy would route this corpus to the mesh."""

    __slots__ = ("field", "version", "n_rows", "r_pad", "vals", "present",
                 "numeric", "integral_exact", "multi_valued", "ords_built",
                 "ords", "ord_keys", "vmin", "vmax",
                 "hll_built", "hll_idx", "hll_rho",
                 "_device", "_device_mesh", "_device_mesh_key",
                 "_device_hll", "_device_hll_mesh", "_device_hll_mesh_key")

    def __init__(self, field: str):
        self.field = field
        self.version: tuple = None
        self.n_rows = 0
        self.r_pad = 1
        self.vals = np.full(1, np.nan, dtype=np.float64)
        self.present = np.zeros(1, dtype=bool)
        self.numeric = False
        self.integral_exact = False
        self.multi_valued = False
        self.ords_built = False
        self.ords: Optional[np.ndarray] = None    # int32[r_pad], -1 absent
        self.ord_keys: List[Any] = []             # ord -> raw key value
        self.vmin = None
        self.vmax = None
        self.hll_built = False
        self.hll_idx: Optional[np.ndarray] = None  # int32[r_pad] register
        self.hll_rho: Optional[np.ndarray] = None  # int32[r_pad], 0 absent
        self._device = None
        self._device_mesh = None
        self._device_mesh_key = None
        self._device_hll = None
        self._device_hll_mesh = None
        self._device_hll_mesh_key = None

    # ------------------------------------------------------------- device
    def device_arrays(self):
        """(vals f64, present, ords int32|None) resident jax arrays."""
        if self._device is not None:
            return self._device
        import jax.numpy as jnp
        from elasticsearch_tpu.ops.dispatch import _x64_scope
        with _x64_scope(True):
            vals = jnp.asarray(self.vals)
            present = jnp.asarray(self.present)
            ords = None if self.ords is None else jnp.asarray(self.ords)
        self._device = (vals, present, ords)
        return self._device

    def device_arrays_mesh(self, mesh):
        """Row-sharded device copies for the mesh kernels (r_pad must
        divide by the shard count; the caller checks)."""
        if (self._device_mesh is not None
                and self._device_mesh_key is mesh):
            return self._device_mesh
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elasticsearch_tpu.ops.dispatch import _x64_scope
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        row = NamedSharding(mesh, P(mesh_lib.SHARD_AXIS))
        with _x64_scope(True):
            vals = jax.device_put(jnp.asarray(self.vals), row)
            present = jax.device_put(jnp.asarray(self.present), row)
            ords = None if self.ords is None else \
                jax.device_put(jnp.asarray(self.ords), row)
        self._device_mesh = (vals, present, ords)
        self._device_mesh_key = mesh
        return self._device_mesh

    def hll_device_arrays(self):
        """(hidx int32, hrho int32) resident jax arrays — the per-row HLL
        register index and rank columns."""
        if self._device_hll is not None:
            return self._device_hll
        import jax.numpy as jnp
        self._device_hll = (jnp.asarray(self.hll_idx),
                            jnp.asarray(self.hll_rho))
        return self._device_hll

    def hll_device_arrays_mesh(self, mesh):
        if (self._device_hll_mesh is not None
                and self._device_hll_mesh_key is mesh):
            return self._device_hll_mesh
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elasticsearch_tpu.parallel import mesh as mesh_lib
        row = NamedSharding(mesh, P(mesh_lib.SHARD_AXIS))
        self._device_hll_mesh = (
            jax.device_put(jnp.asarray(self.hll_idx), row),
            jax.device_put(jnp.asarray(self.hll_rho), row))
        self._device_hll_mesh_key = mesh
        return self._device_hll_mesh


class StoreSnapshot:
    """Immutable per-reader row-space description: built once per segment
    composition and handed to the whole compute pass, so a concurrent
    refresh-resync (which advances the store to a NEWER reader) can never
    swap the row map out from under an in-flight search's mask."""

    __slots__ = ("version", "row_map", "n_rows", "r_pad")

    def __init__(self, version, row_map):
        self.version = version
        self.row_map = row_map
        self.n_rows = len(row_map)
        self.r_pad = _pow2(max(self.n_rows, 1))

    def filter_mask(self, rows: np.ndarray) -> np.ndarray:
        """Matched-row mask over the padded row bucket — the `filter` half
        of the fused plan (vectorized; rows are engine global rows)."""
        mask = np.zeros(self.r_pad, dtype=bool)
        if len(rows):
            mask[: self.n_rows] = np.isin(self.row_map, rows)
        return mask


class AggFieldStore:
    """Per-index columnar agg store over the combined reader: one
    AggColumn per touched field, rebuilt copy-on-write when the segment
    composition changes. Mirrors `ops/bm25.LexicalShard`'s lazy-sync
    contract — most refreshes never serve an agg, so columns build on
    first agg use and re-extract only delta segments after that."""

    def __init__(self, warmup: Optional[bool] = None):
        self._columns: Dict[str, AggColumn] = {}
        self._lock = threading.Lock()
        self._snap: Optional[StoreSnapshot] = None
        self.warmup = warmup
        self.stats = {"rebuilds": 0, "columns": 0, "bytes": 0}
        # per-field columnar composition summary of the LAST column
        # (re)build — the `columnar` annotation `profile.aggs` carries
        self.columnar_refresh: Dict[str, dict] = {}
        self._zero_ords: Dict[Any, Any] = {}

    @staticmethod
    def _fingerprint(reader) -> tuple:
        return tuple((v.segment.seg_id, v.segment.num_docs,
                      int(v.live.sum())) for v in reader.views)

    def snapshot(self, reader) -> StoreSnapshot:
        """The (cached) immutable row-space snapshot for this reader."""
        version = self._fingerprint(reader)
        with self._lock:
            if self._snap is not None and self._snap.version == version:
                return self._snap
        snap = StoreSnapshot(version, reader.live_global_rows())
        with self._lock:
            cur = self._snap
            if cur is not None and cur.version == version:
                return cur  # raced with an identical build: share it
            self._snap = snap
        return snap

    def fields(self) -> List[str]:
        with self._lock:
            return sorted(self._columns)

    def column(self, reader, field: str, want_ords: bool = False,
               snap: Optional[StoreSnapshot] = None,
               want_hll: bool = False) -> AggColumn:
        """The field's column for this reader snapshot, building or
        delta-rebuilding as needed. The returned column is consistent
        with `snap` (same version/row bucket) by construction."""
        if snap is None:
            snap = self.snapshot(reader)
        with self._lock:
            col = self._columns.get(field)
            if col is not None and col.version == snap.version \
                    and (not want_ords or col.ords_built) \
                    and (not want_hll or col.hll_built):
                return col
            col = self._build(reader, snap, field,
                              want_ords
                              or (col is not None and col.ords_built),
                              want_hll
                              or (col is not None and col.hll_built))
            self._columns[field] = col
            self.stats["rebuilds"] += 1
            self.stats["columns"] = len(self._columns)
            self.stats["bytes"] = sum(
                c.vals.nbytes + c.present.nbytes
                + (c.ords.nbytes if c.ords is not None else 0)
                + (c.hll_idx.nbytes + c.hll_rho.nbytes
                   if c.hll_idx is not None else 0)
                for c in self._columns.values())
            return col

    def _build(self, reader, snap: StoreSnapshot, field: str,
               want_ords: bool, want_hll: bool = False) -> AggColumn:
        from elasticsearch_tpu import columnar
        col = AggColumn(field)
        col.version = snap.version
        col.n_rows = snap.n_rows
        col.r_pad = snap.r_pad
        vals = np.full(snap.r_pad, np.nan, dtype=np.float64)
        present = np.zeros(snap.r_pad, dtype=bool)
        obj_parts: List[np.ndarray] = []
        off = 0
        multi = False
        n_cached = n_extracted = 0
        want_objs = want_ords or want_hll
        for view in reader.views:
            n_live = int(view.live.sum())
            # shared block-store read: append-only refreshes find every
            # pre-existing segment's block cached and extract only the
            # delta segments (one block per (segment, field, live-set),
            # shared with every consumer)
            sc, was_cached = columnar.STORE.values_block(
                view, field, want_objs)
            if was_cached:
                n_cached += 1
            else:
                n_extracted += 1
            vals[off:off + n_live] = sc.vals
            present[off:off + n_live] = sc.present
            if sc.objs is not None:
                obj_parts.append(sc.objs)
            elif want_objs:
                obj_parts.append(np.empty(n_live, dtype=object))
            multi = multi or sc.multi_valued
            off += n_live
        mode = columnar.STORE.note_composition(
            field, "values", n_cached, n_extracted)
        self.columnar_refresh[field] = {
            "blocks": n_cached + n_extracted, "cached": n_cached,
            "extracted": n_extracted, "mode": mode}
        col.vals = vals
        col.present = present
        col.multi_valued = multi
        col.ords_built = bool(want_ords)
        # the f64 column IS the numeric_values view: string/geo values are
        # simply absent from it, which matches the host loop's skip
        col.numeric = True
        pv = vals[present]
        if len(pv):
            col.vmin = float(pv.min())
            col.vmax = float(pv.max())
            finite = np.isfinite(pv)
            col.integral_exact = bool(
                finite.all() and np.all(pv == np.floor(pv))
                and float(np.abs(pv).sum()) < _EXACT_INT)
        else:
            col.integral_exact = True  # empty sums are trivially exact
        if want_ords and not multi:
            # global ordinals over the raw doc values (raw objects, not the
            # f64 view — terms keys keep int/str/bool identity)
            ords = np.full(snap.r_pad, -1, dtype=np.int32)
            keys: List[Any] = []
            index: Dict[Any, int] = {}
            if obj_parts:
                objs = np.concatenate(obj_parts)
                for i in range(off):
                    v = objs[i]
                    if v is None:
                        continue
                    k = tuple(v) if isinstance(v, (list, tuple)) else v
                    o = index.get(k)
                    if o is None:
                        o = index[k] = len(keys)
                        keys.append(v)
                    ords[i] = o
            col.ords = ords
            col.ord_keys = keys
        # like ords_built, hll_built marks the REQUEST satisfied even for
        # multi-valued columns (arrays stay None; the plan falls back on
        # multi_valued before touching them) so the cache check holds
        col.hll_built = bool(want_hll)
        if want_hll and not multi:
            # per-row HLL register columns over the same hash the host's
            # partial walker uses — so device register boards pack into
            # `$p` states any shard's host partial merges with exactly
            from elasticsearch_tpu.search.agg_partials import _hll_hash
            from elasticsearch_tpu.search.aggregations import _hashable
            hidx = np.zeros(snap.r_pad, dtype=np.int32)
            hrho = np.zeros(snap.r_pad, dtype=np.int32)
            if obj_parts:
                objs = np.concatenate(obj_parts)
                for i in range(off):
                    v = objs[i]
                    if v is None:
                        continue
                    h = _hll_hash(_hashable(v))
                    hidx[i] = h & (HLL_M - 1)
                    hrho[i] = (64 - HLL_P) - (h >> HLL_P).bit_length() + 1
            col.hll_idx = hidx
            col.hll_rho = hrho
        return col

    # ------------------------------------------------------------- warmup
    def warmup_entries(self, col: AggColumn, mesh=None) -> list:
        """Dispatch warmup grid for one freshly-built column (shape-only
        specs — no data materialized)."""
        import jax
        import jax.numpy as jnp
        r = col.r_pad
        f64 = jax.ShapeDtypeStruct((r,), np.dtype(np.float64))
        b1 = jax.ShapeDtypeStruct((r,), np.dtype(bool))
        i32 = jax.ShapeDtypeStruct((r,), np.dtype(np.int32))
        hp = jax.ShapeDtypeStruct((6,), np.dtype(np.float64))
        mp = jax.ShapeDtypeStruct((2,), np.dtype(np.float64))
        op = jax.ShapeDtypeStruct((1,), np.dtype(np.float64))
        entries = []
        rungs = set(WARMUP_AGG_BUCKETS)
        if col.ords is not None and col.ord_keys:
            b_ord = bucket_count(len(col.ord_keys))
            if b_ord is not None:
                # clamp: one pathological high-cardinality field must not
                # AOT-compile the giant rungs for every column build
                rungs.add(min(b_ord, WARMUP_MAX_ORD_B))
        for b in sorted(rungs):
            if col.ords is not None:
                entries.append(("aggs.ord_counts", (i32, b1),
                                {"n_buckets": b}))
                entries.append(("aggs.ord_metric", (i32, b1, mp, f64, b1),
                                {"n_buckets": b}))
                entries.append(("aggs.tree_counts", (b1, i32, op),
                                {"levels": ("ord",), "n_buckets": (b,)}))
                entries.append(("aggs.tree_metric",
                                (b1, mp, f64, b1, i32, op),
                                {"levels": ("ord",), "n_buckets": (b,)}))
            if col.numeric:
                entries.append(("aggs.hist_counts", (f64, b1, b1, hp),
                                {"n_buckets": b}))
                entries.append(("aggs.hist_metric",
                                (f64, b1, b1, hp, mp, f64, b1),
                                {"n_buckets": b}))
                cb = jax.ShapeDtypeStruct((b,), np.dtype(np.float64))
                entries.append(("aggs.cal_counts", (f64, b1, b1, cb, mp),
                                {"n_buckets": b}))
                entries.append(("aggs.cal_metric",
                                (f64, b1, b1, cb, mp, mp, f64, b1),
                                {"n_buckets": b}))
        if col.numeric:
            bounds = jax.ShapeDtypeStruct((AGG_B_LADDER[0], 2),
                                          np.dtype(np.float64))
            entries.append(("aggs.range_counts", (f64, b1, b1, bounds, mp),
                            {}))
            entries.append(("aggs.range_metric",
                            (f64, b1, b1, bounds, mp, mp, f64, b1), {}))
        if col.hll_built and col.hll_idx is not None:
            entries.append(("aggs.hll_board", (b1, i32, i32),
                            {"levels": (), "n_buckets": ()}))
        return entries

    def schedule_warmup(self, col: AggColumn) -> None:
        if not dispatch.warmup_enabled(self.warmup):
            return
        entries = self.warmup_entries(col)
        if entries:
            dispatch.DISPATCH.warmup(entries, background=True)

    def zero_ords(self, r_pad: int, mesh=None):
        """Cached all-zero int32 ordinal column over the row bucket — the
        bucket-id source for whole-match metric reduces (every row lands
        in lane 0)."""
        key = (r_pad, mesh)
        with self._lock:
            z = self._zero_ords.get(key)
            if z is not None:
                return z
        import jax
        import jax.numpy as jnp
        zeros = jnp.zeros(r_pad, dtype=jnp.int32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from elasticsearch_tpu.parallel import mesh as mesh_lib
            zeros = jax.device_put(
                zeros, NamedSharding(mesh, P(mesh_lib.SHARD_AXIS)))
        with self._lock:
            if len(self._zero_ords) > 8:
                self._zero_ords.clear()
            self._zero_ords[key] = zeros
        return zeros

    @staticmethod
    def mesh_ready(snap: StoreSnapshot, mesh) -> bool:
        """The aggs mesh kernels shard the row bucket evenly; a row bucket
        smaller than the shard axis can't."""
        if mesh is None:
            return False
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        s = int(mesh.shape[mesh_lib.SHARD_AXIS])
        return snap.r_pad % s == 0 and snap.r_pad >= s
