"""Device-resident aggregations: columnar field store + segment-reduce kernels.

The analytics half of `_search` (`search/aggregations` is one of the
reference's largest subsystems) served entirely host-side until this
module: `search/aggregations.py` reduced in numpy after a per-doc Python
`get_doc_value` loop, so a terms agg over 100k matching rows cost 100k
interpreter round-trips while the TPU idled. Terms/histogram/range/stats
aggs are segment-reduce shapes — scatter-add over bucket ids — the exact
kernel family `ops/bm25.py` already proves out for impact scoring, so this
module gives doc-value fields the treatment `vectors/store.py` gives
`dense_vector` and `ops/bm25.py` gives text:

* build (at refresh, lazily on first agg use like `LexicalShard`): each
  aggregated field becomes an `AggColumn` — an f64 value column + presence
  mask over the reader's live rows (padded to a pow-2 row bucket so the
  compiled shapes survive refreshes), plus, for terms aggs, a global
  ordinal column (int32 ord per row over the sorted-unique value set).
  Per-segment extractions cache by segment fingerprint, so append-only
  refreshes re-extract only delta segments (copy-on-write rebuild — an
  in-flight search keeps the previous column's arrays).

* search: ONE dispatch per (bucket-source, metric) pair computes the fused
  filter→aggregate: the query's matched rows arrive as a boolean mask over
  the row bucket, bucket ids derive in-kernel from the resident key column
  (ordinals for terms, affine floor for histogram/date_histogram, bound
  comparisons for range), and a scatter-add reduces counts / sums / mins /
  maxs per bucket into a board of `n_buckets + 1` lanes (the trash lane
  collects pad rows and, for terms, the `missing` bucket).

* exactness: every kernel traces and executes under the dispatcher's
  scoped x64 flag — counts accumulate in int64 (order-free, exact), sums
  in f64. Host parity for sums is guaranteed only for *integral* columns
  (every value integer-valued, sum of |values| < 2^53 — dates, longs,
  counts), where any accumulation order reproduces numpy's pairwise sum
  bit-for-bit; `search/agg_plan.py` routes sum-bearing aggs on other
  columns to the host path. min/max/counts are order-insensitive and run
  on device for any numeric column.

* mesh: columns past the `parallel/policy.py` row floor keep a row-sharded
  device copy; the `aggs.mesh_*` twins reduce each shard's row range
  locally inside one shard_map program and merge boards with
  psum/pmin/pmax — exact for the integral-sum contract above, so the
  per-shard device partials merge like every other mesh kernel.

Kernel keys (`ops/dispatch.py`, strict closed grid): rows pad to the
pow-2 row bucket fixed at column build; `n_buckets` rounds up
AGG_B_LADDER; warmup pre-compiles the interactive rungs at column build.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

import numpy as np

from elasticsearch_tpu.ops import dispatch

logger = logging.getLogger("elasticsearch_tpu.aggs")

# bucket-count ladder: terms cardinality / histogram span rounds UP so one
# compiled program serves a band of bucket counts; beyond the last rung the
# plan falls back to the host path (search.max_buckets territory anyway)
AGG_B_LADDER = (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192,
                16384, 32768, 65536)

# sums of integer-valued f64 are exact (== numpy's pairwise sum in any
# accumulation order) while |sum| stays under 2^53
_EXACT_INT = float(1 << 53)

# warmup rungs: small terms/histogram dashboards; the persistent cache and
# steady traffic fill the tail
WARMUP_AGG_BUCKETS = (8, 64)


def bucket_count(n: int) -> Optional[int]:
    """Round a bucket count up the AGG_B_LADDER; None = off the grid
    (the caller must fall back to the host path)."""
    n = max(int(n), 1)
    for b in AGG_B_LADDER:
        if b >= n:
            return b
    return None


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def in_b_grid(b: int) -> bool:
    return b in AGG_B_LADDER


# ---------------------------------------------------------------------------
# kernels (traced under scoped x64 — see ops/dispatch.py _Kernel.x64)
# ---------------------------------------------------------------------------


def _ord_targets(ords, n_buckets: int):
    import jax.numpy as jnp
    in_range = (ords >= 0) & (ords < n_buckets)
    return jnp.where(in_range, ords, n_buckets)


def _agg_ord_counts(ords, mask, n_buckets: int):
    """Doc counts per ordinal: [B+1] int64; lane B collects matched rows
    whose key is missing (the terms `missing` bucket) — pad rows have
    mask False and never land anywhere."""
    import jax.numpy as jnp
    tgt = _ord_targets(ords, n_buckets)
    return jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(mask, jnp.int64(1), jnp.int64(0)))


def _metric_boards(tgt, ok, v_eff, n_buckets: int):
    import jax.numpy as jnp
    one = jnp.where(ok, jnp.int64(1), jnp.int64(0))
    cnt = jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(one)
    s = jnp.zeros(n_buckets + 1, dtype=jnp.float64).at[tgt].add(
        jnp.where(ok, v_eff, 0.0))
    mn = jnp.full(n_buckets + 1, jnp.inf, dtype=jnp.float64).at[tgt].min(
        jnp.where(ok, v_eff, jnp.inf))
    mx = jnp.full(n_buckets + 1, -jnp.inf, dtype=jnp.float64).at[tgt].max(
        jnp.where(ok, v_eff, -jnp.inf))
    return cnt, s, mn, mx


def _metric_eff(vals, present, mparams):
    """Apply the metric field's `missing` substitute: mparams f64[2] =
    (flag, value)."""
    import jax.numpy as jnp
    use_missing = mparams[0] > 0.0
    p_eff = present | use_missing
    v_eff = jnp.where(present, vals, mparams[1])
    return v_eff, p_eff


def _agg_ord_metric(ords, mask, mparams, vals, present, n_buckets: int):
    """Per-ordinal numeric metric boards (count/sum/min/max); lane B is
    the missing-key bucket's metrics."""
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    tgt = _ord_targets(ords, n_buckets)
    return _metric_boards(tgt, mask & p_eff, v_eff, n_buckets)


def _hist_ids(keys, kpresent, hparams, n_buckets: int):
    """Bucket ids from the resident key column: hparams f64[6] =
    (interval, offset, base, div, kflag, kmissing). `div` pre-divides
    (date_nanos → millis); `base` rebases floor((v-off)/interval) so ids
    land in [0, B). All f64 — bitwise-identical to the host's numpy key
    math."""
    import jax.numpy as jnp
    interval, offset, base, div = (hparams[0], hparams[1], hparams[2],
                                   hparams[3])
    p_eff = kpresent | (hparams[4] > 0.0)
    v = jnp.where(kpresent, keys / div, hparams[5])
    m = jnp.floor((v - offset) / interval)
    ids = (m - base).astype(jnp.int32)
    ok = p_eff & (ids >= 0) & (ids < n_buckets)
    return jnp.where(ok, ids, n_buckets), ok


def _agg_hist_counts(keys, kpresent, mask, hparams, n_buckets: int):
    import jax.numpy as jnp
    tgt, ok = _hist_ids(keys, kpresent, hparams, n_buckets)
    return jnp.zeros(n_buckets + 1, dtype=jnp.int64).at[tgt].add(
        jnp.where(mask & ok, jnp.int64(1), jnp.int64(0)))


def _agg_hist_metric(keys, kpresent, mask, hparams, mparams, vals, present,
                     n_buckets: int):
    tgt, ok = _hist_ids(keys, kpresent, hparams, n_buckets)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    return _metric_boards(tgt, mask & ok & p_eff, v_eff, n_buckets)


def _range_members(keys, kpresent, mask, bounds, rparams):
    """[B, R] membership: bounds f64[B, 2] (lo, hi) with -inf/+inf for
    open ends and (+inf, +inf) pad rows; rparams f64[2] applies the key
    field's `missing` substitute. A row may belong to several overlapping
    ranges — exactly the host semantics."""
    import jax.numpy as jnp
    p_eff = kpresent | (rparams[0] > 0.0)
    v = jnp.where(kpresent, keys, rparams[1])
    ok = mask & p_eff
    return ((v[None, :] >= bounds[:, 0:1]) & (v[None, :] < bounds[:, 1:2])
            & ok[None, :])


def _agg_range_counts(keys, kpresent, mask, bounds, rparams):
    import jax.numpy as jnp
    m = _range_members(keys, kpresent, mask, bounds, rparams)
    return m.astype(jnp.int64).sum(axis=1)


def _agg_range_metric(keys, kpresent, mask, bounds, rparams, mparams, vals,
                      present):
    import jax.numpy as jnp
    m = _range_members(keys, kpresent, mask, bounds, rparams)
    v_eff, p_eff = _metric_eff(vals, present, mparams)
    mm = m & p_eff[None, :]
    cnt = mm.astype(jnp.int64).sum(axis=1)
    s = jnp.where(mm, v_eff[None, :], 0.0).sum(axis=1)
    mn = jnp.where(mm, v_eff[None, :], jnp.inf).min(axis=1)
    mx = jnp.where(mm, v_eff[None, :], -jnp.inf).max(axis=1)
    return cnt, s, mn, mx


# ----------------------------------------------------------------- mesh ----

def _mesh_reduce(local_fn, mesh, row_args, repl_args, n_boards):
    """Run a board-producing local reduce per shard over row-sharded
    columns and merge boards with psum/pmin/pmax (exact under the
    integral-sum contract). Boards are (cnt int64[, sum f64, min f64,
    max f64]): index 0 and 1 merge by sum, 2 by min, 3 by max."""
    import jax
    import jax.numpy as jnp

    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.parallel.sharded_knn import shard_map

    axis = mesh_lib.SHARD_AXIS
    row_spec = jax.sharding.PartitionSpec(axis)
    repl = jax.sharding.PartitionSpec()

    def body(*args):
        boards = local_fn(*args)
        if not isinstance(boards, tuple):
            boards = (boards,)
        merged = []
        for i, b in enumerate(boards):
            if i == 2:
                merged.append(jax.lax.pmin(b, axis))
            elif i == 3:
                merged.append(jax.lax.pmax(b, axis))
            else:
                merged.append(jax.lax.psum(b, axis))
        return merged[0] if n_boards == 1 else tuple(merged)

    in_specs = tuple([row_spec] * len(row_args) + [repl] * len(repl_args))
    out_specs = repl if n_boards == 1 else tuple([repl] * n_boards)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    return fn(*row_args, *repl_args)


# Every row-shaped array (key column, presence, mask, metric columns)
# shards over the row axis; small per-query params/bounds replicate. Each
# shard reduces its own row range into a full [B+1] board, then the boards
# merge in-program (psum for counts/sums, pmin/pmax for extrema).

def _agg_mesh_ord_counts(ords, mask, n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda o, m: _agg_ord_counts(o, m, n_buckets), mesh,
        (ords, mask), (), 1)


def _agg_mesh_ord_metric(ords, mask, vals, present, mparams,
                         n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda o, m, v, p, mp: _agg_ord_metric(o, m, mp, v, p, n_buckets),
        mesh, (ords, mask, vals, present), (mparams,), 4)


def _agg_mesh_hist_counts(keys, kpresent, mask, hparams, n_buckets: int,
                          mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, hp: _agg_hist_counts(k, kp, m, hp, n_buckets),
        mesh, (keys, kpresent, mask), (hparams,), 1)


def _agg_mesh_hist_metric(keys, kpresent, mask, vals, present, hparams,
                          mparams, n_buckets: int, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, v, p, hp, mp: _agg_hist_metric(
            k, kp, m, hp, mp, v, p, n_buckets),
        mesh, (keys, kpresent, mask, vals, present), (hparams, mparams), 4)


def _agg_mesh_range_counts(keys, kpresent, mask, bounds, rparams, mesh=None):
    return _mesh_reduce(
        _agg_range_counts, mesh, (keys, kpresent, mask), (bounds, rparams),
        1)


def _agg_mesh_range_metric(keys, kpresent, mask, vals, present, bounds,
                           rparams, mparams, mesh=None):
    return _mesh_reduce(
        lambda k, kp, m, v, p, b, rp, mp: _agg_range_metric(
            k, kp, m, b, rp, mp, v, p),
        mesh, (keys, kpresent, mask, vals, present),
        (bounds, rparams, mparams), 4)


# ------------------------------------------------------------ grid checks --

def _row_bucket_ok(r: int) -> bool:
    return r >= 1 and (r & (r - 1)) == 0


def _grid_ord(statics, sigs) -> bool:
    r = sigs[0][0][0]
    return _row_bucket_ok(int(r)) and in_b_grid(int(statics["n_buckets"]))


def _grid_hist(statics, sigs) -> bool:
    r = sigs[0][0][0]
    return _row_bucket_ok(int(r)) and in_b_grid(int(statics["n_buckets"]))


def _grid_range(statics, sigs) -> bool:
    r = sigs[0][0][0]
    # bounds [B, 2] rides the 4th positional array arg
    b = None
    for s in sigs:
        if s and s[0] != "py" and len(s[0]) == 2 and s[0][1] == 2:
            b = s[0][0]
            break
    return _row_bucket_ok(int(r)) and (b is None or in_b_grid(int(b)))


def _register():
    reg = dispatch.DISPATCH.register
    reg("aggs.ord_counts", _agg_ord_counts,
        static_argnames=("n_buckets",), grid_check=_grid_ord, x64=True)
    reg("aggs.ord_metric", _agg_ord_metric,
        static_argnames=("n_buckets",), grid_check=_grid_ord, x64=True)
    reg("aggs.hist_counts", _agg_hist_counts,
        static_argnames=("n_buckets",), grid_check=_grid_hist, x64=True)
    reg("aggs.hist_metric", _agg_hist_metric,
        static_argnames=("n_buckets",), grid_check=_grid_hist, x64=True)
    reg("aggs.range_counts", _agg_range_counts,
        grid_check=_grid_range, x64=True)
    reg("aggs.range_metric", _agg_range_metric,
        grid_check=_grid_range, x64=True)
    reg("aggs.mesh_ord_counts", _agg_mesh_ord_counts,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_ord,
        x64=True)
    reg("aggs.mesh_ord_metric", _agg_mesh_ord_metric,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_ord,
        x64=True)
    reg("aggs.mesh_hist_counts", _agg_mesh_hist_counts,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_hist,
        x64=True)
    reg("aggs.mesh_hist_metric", _agg_mesh_hist_metric,
        static_argnames=("n_buckets", "mesh"), grid_check=_grid_hist,
        x64=True)
    reg("aggs.mesh_range_counts", _agg_mesh_range_counts,
        static_argnames=("mesh",), grid_check=_grid_range, x64=True)
    reg("aggs.mesh_range_metric", _agg_mesh_range_metric,
        static_argnames=("mesh",), grid_check=_grid_range, x64=True)


_register()


# ---------------------------------------------------------------------------
# columnar field store
# ---------------------------------------------------------------------------


# per-segment doc-values extraction lives in the shared segment block
# store (`elasticsearch_tpu/columnar/`): `ValuesBlock` is the exact
# shape the retired `_SegmentColumn` held, extracted once per (segment,
# field, live-set) and shared with every other consumer — this module's
# private `_seg_cache` is gone (tpulint TPU011 keeps it from growing
# back)


class AggColumn:
    """One field's columnar agg data over a reader snapshot, padded to the
    store's pow-2 row bucket. Device mirrors upload lazily (under the
    scoped x64 flag so f64 survives) and a mesh-sharded copy is kept when
    the serving policy would route this corpus to the mesh."""

    __slots__ = ("field", "version", "n_rows", "r_pad", "vals", "present",
                 "numeric", "integral_exact", "multi_valued", "ords_built",
                 "ords", "ord_keys", "vmin", "vmax",
                 "_device", "_device_mesh", "_device_mesh_key")

    def __init__(self, field: str):
        self.field = field
        self.version: tuple = None
        self.n_rows = 0
        self.r_pad = 1
        self.vals = np.full(1, np.nan, dtype=np.float64)
        self.present = np.zeros(1, dtype=bool)
        self.numeric = False
        self.integral_exact = False
        self.multi_valued = False
        self.ords_built = False
        self.ords: Optional[np.ndarray] = None    # int32[r_pad], -1 absent
        self.ord_keys: List[Any] = []             # ord -> raw key value
        self.vmin = None
        self.vmax = None
        self._device = None
        self._device_mesh = None
        self._device_mesh_key = None

    # ------------------------------------------------------------- device
    def device_arrays(self):
        """(vals f64, present, ords int32|None) resident jax arrays."""
        if self._device is not None:
            return self._device
        import jax.numpy as jnp
        from elasticsearch_tpu.ops.dispatch import _x64_scope
        with _x64_scope(True):
            vals = jnp.asarray(self.vals)
            present = jnp.asarray(self.present)
            ords = None if self.ords is None else jnp.asarray(self.ords)
        self._device = (vals, present, ords)
        return self._device

    def device_arrays_mesh(self, mesh):
        """Row-sharded device copies for the mesh kernels (r_pad must
        divide by the shard count; the caller checks)."""
        if (self._device_mesh is not None
                and self._device_mesh_key is mesh):
            return self._device_mesh
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elasticsearch_tpu.ops.dispatch import _x64_scope
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        row = NamedSharding(mesh, P(mesh_lib.SHARD_AXIS))
        with _x64_scope(True):
            vals = jax.device_put(jnp.asarray(self.vals), row)
            present = jax.device_put(jnp.asarray(self.present), row)
            ords = None if self.ords is None else \
                jax.device_put(jnp.asarray(self.ords), row)
        self._device_mesh = (vals, present, ords)
        self._device_mesh_key = mesh
        return self._device_mesh


class StoreSnapshot:
    """Immutable per-reader row-space description: built once per segment
    composition and handed to the whole compute pass, so a concurrent
    refresh-resync (which advances the store to a NEWER reader) can never
    swap the row map out from under an in-flight search's mask."""

    __slots__ = ("version", "row_map", "n_rows", "r_pad")

    def __init__(self, version, row_map):
        self.version = version
        self.row_map = row_map
        self.n_rows = len(row_map)
        self.r_pad = _pow2(max(self.n_rows, 1))

    def filter_mask(self, rows: np.ndarray) -> np.ndarray:
        """Matched-row mask over the padded row bucket — the `filter` half
        of the fused plan (vectorized; rows are engine global rows)."""
        mask = np.zeros(self.r_pad, dtype=bool)
        if len(rows):
            mask[: self.n_rows] = np.isin(self.row_map, rows)
        return mask


class AggFieldStore:
    """Per-index columnar agg store over the combined reader: one
    AggColumn per touched field, rebuilt copy-on-write when the segment
    composition changes. Mirrors `ops/bm25.LexicalShard`'s lazy-sync
    contract — most refreshes never serve an agg, so columns build on
    first agg use and re-extract only delta segments after that."""

    def __init__(self, warmup: Optional[bool] = None):
        self._columns: Dict[str, AggColumn] = {}
        self._lock = threading.Lock()
        self._snap: Optional[StoreSnapshot] = None
        self.warmup = warmup
        self.stats = {"rebuilds": 0, "columns": 0, "bytes": 0}
        # per-field columnar composition summary of the LAST column
        # (re)build — the `columnar` annotation `profile.aggs` carries
        self.columnar_refresh: Dict[str, dict] = {}
        self._zero_ords: Dict[Any, Any] = {}

    @staticmethod
    def _fingerprint(reader) -> tuple:
        return tuple((v.segment.seg_id, v.segment.num_docs,
                      int(v.live.sum())) for v in reader.views)

    def snapshot(self, reader) -> StoreSnapshot:
        """The (cached) immutable row-space snapshot for this reader."""
        version = self._fingerprint(reader)
        with self._lock:
            if self._snap is not None and self._snap.version == version:
                return self._snap
        snap = StoreSnapshot(version, reader.live_global_rows())
        with self._lock:
            cur = self._snap
            if cur is not None and cur.version == version:
                return cur  # raced with an identical build: share it
            self._snap = snap
        return snap

    def fields(self) -> List[str]:
        with self._lock:
            return sorted(self._columns)

    def column(self, reader, field: str, want_ords: bool = False,
               snap: Optional[StoreSnapshot] = None) -> AggColumn:
        """The field's column for this reader snapshot, building or
        delta-rebuilding as needed. The returned column is consistent
        with `snap` (same version/row bucket) by construction."""
        if snap is None:
            snap = self.snapshot(reader)
        with self._lock:
            col = self._columns.get(field)
            if col is not None and col.version == snap.version \
                    and (not want_ords or col.ords_built):
                return col
            col = self._build(reader, snap, field, want_ords
                              or (col is not None and col.ords_built))
            self._columns[field] = col
            self.stats["rebuilds"] += 1
            self.stats["columns"] = len(self._columns)
            self.stats["bytes"] = sum(
                c.vals.nbytes + c.present.nbytes
                + (c.ords.nbytes if c.ords is not None else 0)
                for c in self._columns.values())
            return col

    def _build(self, reader, snap: StoreSnapshot, field: str,
               want_ords: bool) -> AggColumn:
        from elasticsearch_tpu import columnar
        col = AggColumn(field)
        col.version = snap.version
        col.n_rows = snap.n_rows
        col.r_pad = snap.r_pad
        vals = np.full(snap.r_pad, np.nan, dtype=np.float64)
        present = np.zeros(snap.r_pad, dtype=bool)
        obj_parts: List[np.ndarray] = []
        off = 0
        multi = False
        n_cached = n_extracted = 0
        for view in reader.views:
            n_live = int(view.live.sum())
            # shared block-store read: append-only refreshes find every
            # pre-existing segment's block cached and extract only the
            # delta segments (one block per (segment, field, live-set),
            # shared with every consumer)
            sc, was_cached = columnar.STORE.values_block(
                view, field, want_ords)
            if was_cached:
                n_cached += 1
            else:
                n_extracted += 1
            vals[off:off + n_live] = sc.vals
            present[off:off + n_live] = sc.present
            if sc.objs is not None:
                obj_parts.append(sc.objs)
            elif want_ords:
                obj_parts.append(np.empty(n_live, dtype=object))
            multi = multi or sc.multi_valued
            off += n_live
        mode = columnar.STORE.note_composition(
            field, "values", n_cached, n_extracted)
        self.columnar_refresh[field] = {
            "blocks": n_cached + n_extracted, "cached": n_cached,
            "extracted": n_extracted, "mode": mode}
        col.vals = vals
        col.present = present
        col.multi_valued = multi
        col.ords_built = bool(want_ords)
        # the f64 column IS the numeric_values view: string/geo values are
        # simply absent from it, which matches the host loop's skip
        col.numeric = True
        pv = vals[present]
        if len(pv):
            col.vmin = float(pv.min())
            col.vmax = float(pv.max())
            finite = np.isfinite(pv)
            col.integral_exact = bool(
                finite.all() and np.all(pv == np.floor(pv))
                and float(np.abs(pv).sum()) < _EXACT_INT)
        else:
            col.integral_exact = True  # empty sums are trivially exact
        if want_ords and not multi:
            # global ordinals over the raw doc values (raw objects, not the
            # f64 view — terms keys keep int/str/bool identity)
            ords = np.full(snap.r_pad, -1, dtype=np.int32)
            keys: List[Any] = []
            index: Dict[Any, int] = {}
            if obj_parts:
                objs = np.concatenate(obj_parts)
                for i in range(off):
                    v = objs[i]
                    if v is None:
                        continue
                    k = tuple(v) if isinstance(v, (list, tuple)) else v
                    o = index.get(k)
                    if o is None:
                        o = index[k] = len(keys)
                        keys.append(v)
                    ords[i] = o
            col.ords = ords
            col.ord_keys = keys
        return col

    # ------------------------------------------------------------- warmup
    def warmup_entries(self, col: AggColumn, mesh=None) -> list:
        """Dispatch warmup grid for one freshly-built column (shape-only
        specs — no data materialized)."""
        import jax
        import jax.numpy as jnp
        r = col.r_pad
        f64 = jax.ShapeDtypeStruct((r,), np.dtype(np.float64))
        b1 = jax.ShapeDtypeStruct((r,), np.dtype(bool))
        i32 = jax.ShapeDtypeStruct((r,), np.dtype(np.int32))
        hp = jax.ShapeDtypeStruct((6,), np.dtype(np.float64))
        mp = jax.ShapeDtypeStruct((2,), np.dtype(np.float64))
        entries = []
        rungs = set(WARMUP_AGG_BUCKETS)
        if col.ords is not None and col.ord_keys:
            b_ord = bucket_count(len(col.ord_keys))
            if b_ord is not None:
                rungs.add(b_ord)
        for b in sorted(rungs):
            if col.ords is not None:
                entries.append(("aggs.ord_counts", (i32, b1),
                                {"n_buckets": b}))
                entries.append(("aggs.ord_metric", (i32, b1, mp, f64, b1),
                                {"n_buckets": b}))
            if col.numeric:
                entries.append(("aggs.hist_counts", (f64, b1, b1, hp),
                                {"n_buckets": b}))
                entries.append(("aggs.hist_metric",
                                (f64, b1, b1, hp, mp, f64, b1),
                                {"n_buckets": b}))
        if col.numeric:
            bounds = jax.ShapeDtypeStruct((AGG_B_LADDER[0], 2),
                                          np.dtype(np.float64))
            entries.append(("aggs.range_counts", (f64, b1, b1, bounds, mp),
                            {}))
            entries.append(("aggs.range_metric",
                            (f64, b1, b1, bounds, mp, mp, f64, b1), {}))
        return entries

    def schedule_warmup(self, col: AggColumn) -> None:
        if not dispatch.warmup_enabled(self.warmup):
            return
        entries = self.warmup_entries(col)
        if entries:
            dispatch.DISPATCH.warmup(entries, background=True)

    def zero_ords(self, r_pad: int, mesh=None):
        """Cached all-zero int32 ordinal column over the row bucket — the
        bucket-id source for whole-match metric reduces (every row lands
        in lane 0)."""
        key = (r_pad, mesh)
        with self._lock:
            z = self._zero_ords.get(key)
            if z is not None:
                return z
        import jax
        import jax.numpy as jnp
        zeros = jnp.zeros(r_pad, dtype=jnp.int32)
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from elasticsearch_tpu.parallel import mesh as mesh_lib
            zeros = jax.device_put(
                zeros, NamedSharding(mesh, P(mesh_lib.SHARD_AXIS)))
        with self._lock:
            if len(self._zero_ords) > 8:
                self._zero_ords.clear()
            self._zero_ords[key] = zeros
        return zeros

    @staticmethod
    def mesh_ready(snap: StoreSnapshot, mesh) -> bool:
        """The aggs mesh kernels shard the row bucket evenly; a row bucket
        smaller than the shard axis can't."""
        if mesh is None:
            return False
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        s = int(mesh.shape[mesh_lib.SHARD_AXIS])
        return snap.r_pad % s == 0 and snap.r_pad >= s
