"""Pallas fused gather+MaxSim rescore for late-interaction retrieval.

A late-interaction (ColBERT-style) query holds Tq token vectors and
scores a doc as ``sum_q max_t dot(q_token, doc_token)`` (MaxSim). The
serving shape is two-phase (`vectors/late_interaction.py`): a coarse
single-vector retrieval over pooled doc centroids picks a
top-(k·oversample) candidate window, then THIS kernel rescores the
window against the full token blocks. A scan-based rescore would
`jnp.take` a [Q, W, cap, D] token-tile gather out to HBM before the
matmul reads it back — the exact staging cost `pallas_ivf_fused.py`
killed for IVF probes, reproduced here for candidate docs: the
candidate ids ride in as a scalar-prefetch operand
(`pltpu.PrefetchScalarGridSpec`), the BlockSpec index_map selects each
(query, candidate) step's token tile straight out of the resident
[N_pad, cap, D] block, and the tile flows through VMEM into the MXU
dot. The [Q, W] MaxSim board is the only new array.

Variants follow the storage ladder (`quant/codec.py` via
`quant/tokens.py`): f32/bf16/int8 token tiles matmul directly (int8
upcasts in-register and de-scales per TOKEN row); int4 packed-nibble
tiles unpack into (even, odd) level planes against matching query
planes. Per-token scales are 0 on padding slots (both intra-doc cap
padding and whole padding docs), which pins those lanes to NEG_INF
before the max — and zero-padded QUERY tokens contribute exactly 0.0
to the sum (all their dots are 0, and the max over a doc's valid
tokens of 0 is 0).

Registered as `maxsim.rescore` under its own closed grid (bucketed
query count, candidate window on the k ladder or a LANE multiple,
pow-2 query-token and doc-token caps) with warmup entries; kept honest
on CPU by interpret mode and the jnp reference twin below
(byte-tested in tests/test_late_interaction.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops.similarity import NEG_INF

# python-float sentinel for in-kernel use (a jnp constant would be a
# captured array, which pallas_call rejects)
_NEG = float(NEG_INF)

LANE = 128


def default_interpret() -> bool:
    """Mosaic compiles only on TPU-class backends (same probe as the
    fused IVF kernel)."""
    return not dispatch.is_accelerator_backend()


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return default_interpret() if interpret is None else bool(interpret)


# ---------------------------------------------------------------------------
# kernel bodies — one (query, candidate doc) token tile per grid step
# ---------------------------------------------------------------------------

def _dense_kernel(ids_ref, q_ref, toks_ref, scales_ref, out_ref):
    """f32/bf16/int8 token tiles: [Tq, D] x [cap, D]^T with f32
    accumulation (int8 upcasts in-register to bf16, exact for
    [-127, 127]), per-token de-scale, NEG_INF mask on zero-scale
    padding slots, then the MaxSim reduce: max over doc tokens, sum
    over query tokens."""
    dots = jax.lax.dot_general(
        q_ref[0].astype(jnp.bfloat16), toks_ref[0].astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [Tq, cap]
    s = scales_ref[:]                                   # [1, cap]
    masked = jnp.where(s > 0, dots * s, _NEG)
    out_ref[...] = jnp.sum(jnp.max(masked, axis=1)).reshape(1, 1)


def _int4_kernel(ids_ref, qe_ref, qo_ref, toks_ref, scales_ref, out_ref):
    """int4 packed-nibble token tiles: unpack the (even, odd) level
    planes in-register and run two half-width passes against the
    matching query planes (the codec's one bit layout), then the same
    masked MaxSim reduce."""
    tile = toks_ref[0]
    lo = ((tile & jnp.uint8(0x0F)).astype(jnp.int32) - 8).astype(jnp.bfloat16)
    hi = ((tile >> 4).astype(jnp.int32) - 8).astype(jnp.bfloat16)
    dn = (((1,), (1,)), ((), ()))
    dots = (jax.lax.dot_general(qe_ref[0].astype(jnp.bfloat16), lo, dn,
                                preferred_element_type=jnp.float32)
            + jax.lax.dot_general(qo_ref[0].astype(jnp.bfloat16), hi, dn,
                                  preferred_element_type=jnp.float32))
    s = scales_ref[:]
    masked = jnp.where(s > 0, dots * s, _NEG)
    out_ref[...] = jnp.sum(jnp.max(masked, axis=1)).reshape(1, 1)


def _maxsim_impl(ids, q, qe, qo, toks, scales, interpret: bool):
    """[Q, W] MaxSim board: token tiles gathered via the scalar-
    prefetched candidate ids (one (query, candidate) tile per grid
    step). Dense path passes `q` [Q, Tq, D] with qe/qo None; the int4
    path passes the (even, odd) query planes [Q, Tq, W] with q None."""
    nq, wc = ids.shape
    _n_pad, cap, wd = toks.shape
    out_shape = jax.ShapeDtypeStruct((nq, wc), jnp.float32)
    out_spec = pl.BlockSpec((1, 1), lambda qi, j, ids_: (qi, j))
    tok_spec = pl.BlockSpec((1, cap, wd),
                            lambda qi, j, ids_: (ids_[qi, j], 0, 0))
    scale_spec = pl.BlockSpec((1, cap), lambda qi, j, ids_: (ids_[qi, j], 0))
    if toks.dtype == jnp.uint8:
        tq = qe.shape[1]
        qspec = pl.BlockSpec((1, tq, wd), lambda qi, j, ids_: (qi, 0, 0))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(nq, wc),
            in_specs=[qspec, qspec, tok_spec, scale_spec],
            out_specs=out_spec)
        return pl.pallas_call(
            _int4_kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=interpret,
        )(ids, qe.astype(jnp.float32), qo.astype(jnp.float32), toks, scales)
    tq = q.shape[1]
    qspec = pl.BlockSpec((1, tq, wd), lambda qi, j, ids_: (qi, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(nq, wc),
        in_specs=[qspec, tok_spec, scale_spec],
        out_specs=out_spec)
    return pl.pallas_call(
        _dense_kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=interpret,
    )(ids, q.astype(jnp.float32), toks, scales)


def _grid_maxsim(statics, sigs) -> bool:
    """Bucketed query count; candidate window on the k ladder or a
    LANE multiple (the coarse phase's bucket_k clamp lands on LANE-
    padded corpus rows); pow-2 query-token pad; pow-2 doc-token cap
    and block count; lane-multiple packed width."""
    nq, wc = sigs[0][0]                     # ids [Q, W]
    tq = sigs[1][0][1]                      # q or qe [Q, Tq, *]
    n_pad, cap, _wd = sigs[-2][0]           # toks [N_pad, cap, W]
    return (dispatch.is_query_bucket(nq)
            and wc >= 1 and (dispatch.in_k_grid(wc) or wc % LANE == 0)
            and tq >= 1 and (tq & (tq - 1)) == 0
            and cap >= 1 and (cap & (cap - 1)) == 0
            and n_pad >= 1 and (n_pad & (n_pad - 1)) == 0)


dispatch.DISPATCH.register(
    "maxsim.rescore", _maxsim_impl,
    static_argnames=("interpret",),
    grid_check=_grid_maxsim)


def _split_token_planes(q):
    """(even, odd) dim planes of a [Q, Tq, D] token batch — the 3-D
    twin of `quant_codec.split_query_planes_jnp` (same bit layout)."""
    return q[:, :, 0::2], q[:, :, 1::2]


def maxsim_rescore(ids, q_tokens, toks, scales,
                   interpret: Optional[bool] = None):
    """Rescore candidate docs `ids` [Q, W] against the resident token
    blocks with the fused gather+MaxSim kernel.

    q_tokens [Q, Tq, D] f32 must be metric-prepped and zero-padded to
    the tile's lane width and a pow-2 Tq; toks/scales are the field's
    [N_pad, cap, W] device tile + [N_pad, cap] per-token scales.
    Invalid candidate slots must point at an all-padding doc row (the
    field layout reserves one), which scores NEG_INF. Returns the
    [Q, W] f32 board."""
    if toks.dtype == jnp.uint8:
        qe, qo = _split_token_planes(q_tokens)
        return dispatch.call("maxsim.rescore", ids, None, qe, qo, toks,
                             scales, interpret=_resolve_interpret(interpret))
    return dispatch.call("maxsim.rescore", ids, q_tokens, None, None, toks,
                         scales, interpret=_resolve_interpret(interpret))


def maxsim_reference(ids, q_tokens, toks, scales):
    """Reference twin of the fused kernel — IDENTICAL math on
    IDENTICAL shapes: one [Tq, D] x [cap, D] dot per (query, candidate)
    pair, bf16 operands, f32 accumulation, per-token de-scale, NEG_INF
    padding mask, max-then-sum. The per-pair python loop is deliberate:
    a vmapped batch dot lowers to a different XLA contraction tiling
    with much larger drift, while per-pair dots replay the primitive the
    kernel body executes shape-for-shape. Residual few-ULP differences
    remain possible even so (the interpret-mode grid loop can steer XLA
    CPU to a different accumulation order for the same dot), so the
    parity tests pin ordering exactly and scores to tight tolerances —
    the convention test_pallas_parity.py established for the IVF twin."""
    import numpy as np

    ids = np.asarray(ids)
    q_tokens = jnp.asarray(q_tokens, dtype=jnp.float32)
    nq, wc = ids.shape
    int4 = toks.dtype == jnp.uint8
    dn = (((1,), (1,)), ((), ()))
    rows = []
    for qi in range(nq):
        row = []
        qtok = q_tokens[qi]
        if int4:
            qe = qtok[:, 0::2].astype(jnp.bfloat16)
            qo = qtok[:, 1::2].astype(jnp.bfloat16)
        else:
            qb = qtok.astype(jnp.bfloat16)
        for j in range(wc):
            tile = toks[ids[qi, j]]
            s = scales[ids[qi, j]][None, :]
            if int4:
                lo = ((tile & jnp.uint8(0x0F)).astype(jnp.int32)
                      - 8).astype(jnp.bfloat16)
                hi = ((tile >> 4).astype(jnp.int32) - 8).astype(jnp.bfloat16)
                dots = (jax.lax.dot_general(
                            qe, lo, dn, preferred_element_type=jnp.float32)
                        + jax.lax.dot_general(
                            qo, hi, dn, preferred_element_type=jnp.float32))
            else:
                dots = jax.lax.dot_general(
                    qb, tile.astype(jnp.bfloat16), dn,
                    preferred_element_type=jnp.float32)
            masked = jnp.where(s > 0, dots * s, _NEG)
            row.append(jnp.sum(jnp.max(masked, axis=1)))
        rows.append(jnp.stack(row))
    return jnp.stack(rows).astype(jnp.float32)


def warmup_entries(n_pad: int, cap: int, packed_w: int, tok_dtype,
                   tq_rungs, w_buckets, query_buckets,
                   interpret: Optional[bool] = None):
    """(kernel, specs, statics) entries pre-compiling the fused MaxSim
    grid over the interactive buckets. `interpret` defaults through the
    same resolution serving uses, so the warmed programs ARE the ones
    `maxsim_rescore` dispatches."""
    entries = []
    interp = _resolve_interpret(interpret)
    toks_spec = jax.ShapeDtypeStruct((n_pad, cap, packed_w), tok_dtype)
    scales_spec = jax.ShapeDtypeStruct((n_pad, cap), jnp.float32)
    int4 = tok_dtype == jnp.uint8
    for q in query_buckets:
        for tq in tq_rungs:
            qspec = jax.ShapeDtypeStruct(
                (q, tq, packed_w if int4 else packed_w), jnp.float32)
            for w in w_buckets:
                ids_spec = jax.ShapeDtypeStruct((q, w), jnp.int32)
                if int4:
                    args = (ids_spec, None, qspec, qspec, toks_spec,
                            scales_spec)
                else:
                    args = (ids_spec, qspec, None, None, toks_spec,
                            scales_spec)
                entries.append(("maxsim.rescore", args,
                                {"interpret": interp}))
    return entries
