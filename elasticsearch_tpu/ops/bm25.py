"""Device-resident BM25 lexical scoring: tile-padded impacts + batched top-k.

The lexical half of the fused hybrid plan (`search/hybrid_plan.py`). The
round-3 record's one losing row (config 3 hybrid, 7.2 QPS) lost because
BM25 ran per-query in host Python while only the kNN leg rode the device.
Block-max / impact-ordered top-k literature (Ding & Suel, BMW 2011) frames
lexical scoring as bounded linear algebra over quantized impacts — exactly
the shape the MXU already serves for vectors — so this module gives text
fields the same treatment `vectors/store.py` gives `dense_vector`:

* build (at refresh): every posting's full BM25 impact
  ``idf(term) * (k1+1) * tf(freq, len)`` is precomputed ONCE and laid out
  as a tile-padded CSR — postings concatenated term-major, each term's run
  padded to TILE-lane boundaries, so the score stage moves whole
  lane-aligned tiles through HBM with zero per-row gathers (the same
  layout discipline as `ops/knn_ivf.py` partitions). Impacts quantize to
  bf16/int8 for HBM thrift; the default f32 keeps scores bit-identical to
  the host `search/queries.py` BM25 path (`native.bm25_score` computes the
  impacts here too, so even the C++-vs-numpy rounding choice matches).

* search: ONE device dispatch scores a whole batch of queries — a scan
  over each query's term tiles scatter-adds impacts into a [Q, n_slots]
  score board, a parallel match-count board enforces operator/
  minimum_should_match, and `lax.top_k` cuts the ranked window. Ties
  break by ascending row (slots are laid out in ascending global-row
  order), matching `native.topk`'s shard-level convention exactly.

* refresh deltas: per-segment CSR extractions are cached by segment id —
  an append-only refresh (new sealed segments, no new tombstones) only
  tokenizes/extracts the delta segments; impacts are recomputed from the
  cached extractions because idf/avg_len are corpus-global (a cheap
  vectorized pass, grouped by document frequency so `native.bm25_score`
  is called once per distinct df, not once per term).

A numpy host twin (`_score_host`) runs the identical math for corpora
below the device-dispatch break-even (the `serving/batcher.py` CostModel
call), so routing is invisible to callers — the same contract the vector
store's host VNNI mirror keeps.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu import native

TILE = 128

BM25_K1 = 1.2
BM25_B = 0.75


def _pad_query_bucket(tile_ids, boosts, required):
    """Pad a planned query batch up to the dispatch bucket (the jit
    specializes on Q, and a compile per distinct batch size would stall
    serving — same motive as vectors/store._pad_batch). Pad queries
    reference no tiles and require 1 match, so the required-mask keeps
    their whole board at -inf. Shared by the single-board and sharded
    scoring paths so their padding semantics can never diverge.
    Returns (tile_ids, boosts, required, n_pad)."""
    from elasticsearch_tpu.ops import dispatch
    n_real = tile_ids.shape[0]
    n_pad = dispatch.bucket_queries(n_real)
    if n_pad == n_real:
        return tile_ids, boosts, required, n_pad
    pad = n_pad - n_real
    tile_ids = np.concatenate(
        [tile_ids, np.full((pad, tile_ids.shape[1]), -1, dtype=np.int32)])
    boosts = np.concatenate(
        [boosts, np.zeros((pad, boosts.shape[1]), dtype=np.float32)])
    required = np.concatenate([required, np.ones(pad, dtype=np.int32)])
    return tile_ids, boosts, required, n_pad


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# per-segment postings extraction lives in the shared segment block
# store (`elasticsearch_tpu/columnar/` — `PostingsBlock`): one
# extraction per (segment, field, live-set), shared across fields'
# consumers and evicted with the segment; the private per-instance
# `_seg_cache` dict is gone (tpulint TPU011 keeps it from growing back)


class LexicalField:
    """One text field's tile-padded impact layout over a reader snapshot.

    Host arrays are the source of truth (and the host scoring twin);
    device mirrors upload lazily on the first device-routed dispatch.

    Subclasses retarget the SAME scoring program at other posting
    sources by overriding the kernel/family names plus `sync` and
    `plan_queries` (`ops/sparse.py` does this for `rank_features`
    learned-sparse fields: stored weights become the impacts, query
    token weights fold into the boosts, everything below — boards,
    buckets, mesh twin, tie-breaks — is shared verbatim).
    """

    KERNEL = "bm25.topk"
    MESH_KERNEL = "bm25.mesh_topk"
    FAMILY = "bm25"

    def __init__(self, field: str, dtype: str = "f32"):
        self.field = field
        self.dtype = dtype              # f32 (exact) | bf16 | int8
        self.version: tuple = ()
        self.n_slots = 0
        self.row_map = np.zeros(0, dtype=np.int64)  # slot -> engine global row
        # tile-padded CSR (term-major): [n_tiles, TILE]
        self.tile_slots = np.full((0, TILE), -1, dtype=np.int32)
        self.tile_impacts = np.zeros((0, TILE), dtype=np.float32)
        self.term_tiles: Dict[str, Tuple[int, int]] = {}  # term -> (first, n)
        self.nnz = 0
        # columnar composition summary of the LAST rebuild (profile /
        # stats annotation — the delta-vs-full extraction ledger)
        self.columnar_refresh: dict = {}
        self._device = None             # (slots, impacts[, scales]) jnp arrays
        self._device_version: tuple = ()
        # mesh-replicated tile mirrors, one entry per mesh the router
        # dispatches on (full serving mesh + dp-group submeshes when
        # dp > 1); dropped whole on any corpus version change
        self._device_mesh: dict = {}
        self._device_mesh_version: tuple = ()

    # ------------------------------------------------------------- build
    def sync(self, reader) -> bool:
        """(Re)build from a reader snapshot; returns True if rebuilt.
        Per-segment extractions come from the shared segment block store
        (`columnar.STORE.postings_block`, cached by fingerprint), so
        append-only refreshes pay tokenized extraction only for the
        delta segments."""
        from elasticsearch_tpu import columnar
        version = tuple((v.segment.seg_id, v.segment.num_docs,
                         int(v.live.sum())) for v in reader.views)
        if version == self.version:
            return False
        segs: List = []
        n_cached = n_extracted = 0
        for view in reader.views:
            blk, was_cached = columnar.STORE.postings_block(
                view, self.field)
            if was_cached:
                n_cached += 1
            else:
                n_extracted += 1
            segs.append(blk)
        mode = columnar.STORE.note_composition(
            self.field, "postings", n_cached, n_extracted)
        self.columnar_refresh = {
            "blocks": n_cached + n_extracted, "cached": n_cached,
            "extracted": n_extracted, "mode": mode}

        # dense slot space: segment-major, ascending local order — the
        # row map is therefore ascending iff reader views are base-ordered
        # (they are), which is what makes slot-index tie-breaks equal
        # row tie-breaks
        bases = []
        total = 0
        row_parts = []
        for view, sp in zip(reader.views, segs):
            bases.append(total)
            live_locals = np.nonzero(view.live)[0]
            row_parts.append(live_locals.astype(np.int64)
                            + view.segment.base)
            total += sp.n_live
        self.n_slots = total
        self.row_map = (np.concatenate(row_parts) if row_parts
                        else np.zeros(0, dtype=np.int64))
        lengths = (np.concatenate([sp.lengths for sp in segs])
                   if segs else np.zeros(0, dtype=np.float32))

        # merge terms across segments (slots already ascending per segment
        # and bases ascend, so concatenation keeps ascending order)
        merged: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        for base, sp in zip(bases, segs):
            for term, (slots, freqs) in sp.terms.items():
                merged.setdefault(term, []).append((slots + base, freqs))

        # global stats — the SAME quantities bm25_scores() reads live
        n = max(reader.docs_with_field_count(self.field), 1)
        avg_len = reader.avg_field_length(self.field) or 1.0

        terms = sorted(merged)
        ptr = [0]
        slot_parts, freq_parts, dfs = [], [], []
        for t in terms:
            chunks = merged[t]
            s = (np.concatenate([c[0] for c in chunks])
                 if len(chunks) > 1 else chunks[0][0])
            f = (np.concatenate([c[1] for c in chunks])
                 if len(chunks) > 1 else chunks[0][1])
            slot_parts.append(s)
            freq_parts.append(f)
            dfs.append(len(s))
            ptr.append(ptr[-1] + len(s))
        slot_flat = (np.concatenate(slot_parts) if slot_parts
                     else np.zeros(0, dtype=np.int32))
        freq_flat = (np.concatenate(freq_parts) if freq_parts
                     else np.zeros(0, dtype=np.int32))
        self.nnz = len(slot_flat)
        len_flat = lengths[slot_flat] if self.nnz else \
            np.zeros(0, dtype=np.float32)

        # impacts, grouped by distinct df so native.bm25_score (the exact
        # engine the host query path uses) runs once per df value
        impact_flat = np.zeros(self.nnz, dtype=np.float32)
        dfs_arr = np.asarray(dfs, dtype=np.int64)
        import math
        for df in np.unique(dfs_arr):
            idf = math.log(1.0 + (n - int(df) + 0.5) / (int(df) + 0.5))
            t_idx = np.nonzero(dfs_arr == df)[0]
            pieces = [np.arange(ptr[i], ptr[i + 1]) for i in t_idx]
            gather = np.concatenate(pieces)
            impact_flat[gather] = native.bm25_score(
                freq_flat[gather], len_flat[gather], idf, avg_len,
                BM25_K1, BM25_B, 1.0)

        self._install_tiles(terms, dfs, ptr, slot_flat, impact_flat)
        self.version = version
        return True

    def _install_tiles(self, terms, dfs, ptr, slot_flat, impact_flat):
        """Tile-pad term-major flat (slot, impact) runs: each term's run
        rounds up to whole TILE-lane tiles. Shared verbatim with the
        learned-sparse subclass — the layout below the impact math is
        identical by construction."""
        n_tiles_per = [max(1, -(-df // TILE)) if df else 0 for df in dfs]
        total_tiles = sum(n_tiles_per)
        tile_slots = np.full((max(total_tiles, 1), TILE), -1, dtype=np.int32)
        tile_impacts = np.zeros((max(total_tiles, 1), TILE), dtype=np.float32)
        self.term_tiles = {}
        tile = 0
        for i, t in enumerate(terms):
            df = dfs[i]
            if not df:
                continue
            nt = n_tiles_per[i]
            flat_s = tile_slots[tile:tile + nt].reshape(-1)
            flat_i = tile_impacts[tile:tile + nt].reshape(-1)
            flat_s[:df] = slot_flat[ptr[i]:ptr[i + 1]]
            flat_i[:df] = impact_flat[ptr[i]:ptr[i + 1]]
            self.term_tiles[t] = (tile, nt)
            tile += nt
        self.tile_slots = tile_slots[:max(tile, 1)]
        self.tile_impacts = tile_impacts[:max(tile, 1)]

    # ------------------------------------------------------------ search
    def nbytes(self) -> int:
        per = {"f32": 4, "bf16": 2, "int8": 1}[self.dtype]
        return self.tile_slots.size * 4 + self.tile_impacts.size * per

    def _device_arrays(self):
        if self._device is not None and self._device_version == self.version:
            return self._device
        slots = jnp.asarray(self.tile_slots)
        if self.dtype == "bf16":
            impacts = jnp.asarray(self.tile_impacts, dtype=jnp.bfloat16)
            scales = None
        elif self.dtype == "int8":
            # per-tile symmetric scale (the quant codec's int8 recipe at
            # tile granularity: impacts within a tile share one term's
            # idf, so the dynamic range per tile is narrow)
            from elasticsearch_tpu.quant import codec as quant_codec
            enc = quant_codec.get("int8").encode_np(self.tile_impacts)
            impacts = jnp.asarray(enc.data)
            scales = jnp.asarray(enc.scales)
        else:
            impacts = jnp.asarray(self.tile_impacts)
            scales = None
        self._device = (slots, impacts, scales)
        self._device_version = self.version
        return self._device

    def _device_arrays_mesh(self, mesh):
        """Tile mirrors replicated across `mesh` (the sharded kernel
        reads every tile but scatter-adds only its own doc range, so the
        CSR replicates while the score board shards). Cached per mesh —
        the dp-vs-shard router alternates between the full mesh and its
        dp groups, and each must keep its mirror resident. The dict
        holds mesh OBJECTS as keys (not id(mesh)): a GC'd mesh's address
        can be reused by a differently-shaped one."""
        if self._device_mesh_version != self.version:
            self._device_mesh = {}
            self._device_mesh_version = self.version
        cached = self._device_mesh.get(mesh)
        if cached is not None:
            return cached
        import jax
        from jax.sharding import NamedSharding

        from elasticsearch_tpu.parallel import layout
        repl = NamedSharding(mesh, layout.replicated_spec())
        slots, impacts, scales = self._device_arrays()
        arrays = (
            jax.device_put(slots, repl), jax.device_put(impacts, repl),
            None if scales is None else jax.device_put(scales, repl))
        return self._device_mesh.setdefault(mesh, arrays)

    def plan_queries(self, queries: Sequence[Tuple[Sequence[str], float]]
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Resolve (terms, boost) per query to padded tile id / boost
        matrices; per-query required-match counts are the caller's
        business (operator semantics live in the plan layer).

        Every tile of every resolved term is scanned — NO truncation: the
        scan work is O(touched postings), the same bound the host query
        path pays, so dropping tiles would silently change scores without
        saving the corpus-bound part of the cost."""
        per_q: List[List[Tuple[int, float]]] = []
        for terms, boost in queries:
            tiles: List[Tuple[int, float]] = []
            for t in terms:
                span = self.term_tiles.get(t)
                if span is None:
                    continue
                first, nt = span
                tiles.extend((first + j, boost) for j in range(nt))
            per_q.append(tiles)
        m = _pow2(max(max((len(t) for t in per_q), default=1), 1))
        tile_ids = np.full((len(per_q), m), -1, dtype=np.int32)
        boosts = np.zeros((len(per_q), m), dtype=np.float32)
        for qi, tiles in enumerate(per_q):
            for j, (tid, b) in enumerate(tiles):
                tile_ids[qi, j] = tid
                boosts[qi, j] = b
        return tile_ids, boosts, m

    def _score_host(self, tile_ids, boosts, required, k):
        """Numpy twin of the device kernel: identical accumulation order
        (term-major, f32), identical tie-breaks."""
        nq = tile_ids.shape[0]
        out = []
        for qi in range(nq):
            scores = np.zeros(self.n_slots, dtype=np.float32)
            counts = np.zeros(self.n_slots, dtype=np.int32)
            for tid, b in zip(tile_ids[qi], boosts[qi]):
                if tid < 0:
                    continue
                s = self.tile_slots[tid]
                valid = s >= 0
                sv = s[valid]
                scores[sv] += self.tile_impacts[tid][valid] * np.float32(b)
                counts[sv] += 1
            req = int(required[qi])
            elig = np.nonzero(counts >= max(req, 1))[0]
            kk = min(k, len(elig))
            top = native.topk(scores[elig], kk)
            sel = elig[top]
            out.append((self.row_map[sel],
                        scores[sel].astype(np.float32)))
        return out

    def _score_device_mesh(self, tile_ids, boosts, required, k, mesh):
        """Doc-range-sharded SPMD scoring: every shard scatter-adds the
        replicated impact CSR into ITS slot range's board, local top-k,
        all-gather merge (`bm25.mesh_topk`). Bit-identical sums to the
        single-board kernel (same term-major add order per slot), ties
        preserved (merge concatenates ascending shard = ascending slot
        ranges). Returns None when the sharded program can't hold the
        contract (ranked window deeper than a shard's slot range) — the
        caller then runs the single-device board."""
        import time as _time

        from elasticsearch_tpu.ops import dispatch
        from elasticsearch_tpu.parallel import mesh as mesh_lib
        from elasticsearch_tpu.parallel import policy

        n_shards = int(mesh.shape[mesh_lib.SHARD_AXIS])
        width = _pow2(max(-(-self.n_slots // n_shards), 1))
        k_req = min(k, max(self.n_slots, 1))
        k_b = dispatch.bucket_k(k_req, limit=width)
        if k_req > width:
            return None
        n_real = tile_ids.shape[0]
        tile_ids, boosts, required, n_pad = _pad_query_bucket(
            tile_ids, boosts, required)
        slots_d, impacts_d, scales_d = self._device_arrays_mesh(mesh)
        t0 = _time.perf_counter_ns()
        # launch-guarded enqueue: collective programs sharing devices
        # must enqueue in one order (parallel/mesh.launch_guard)
        with mesh_lib.launch_guard(mesh):
            vals, gslots = dispatch.call(
                self.MESH_KERNEL, jnp.asarray(tile_ids),
                jnp.asarray(boosts),
                jnp.asarray(required.astype(np.int32)), slots_d,
                impacts_d, scales_d, k=k_b, width=width, mesh=mesh)
        vals = np.asarray(vals)[:, :k_req]
        gslots = np.asarray(gslots)[:, :k_req]
        t1 = _time.perf_counter_ns()
        out = []
        for qi in range(n_real):
            v, si = vals[qi], gslots[qi]
            keep = (v > -np.inf) & (si >= 0) & (si < self.n_slots)
            v, si = v[keep], si[keep]
            out.append((self.row_map[si], v.astype(np.float32)))
        t2 = _time.perf_counter_ns()
        policy.record_leg(self.FAMILY, t1 - t0, t2 - t1,
                          policy.gather_bytes(n_shards, n_pad, k_b))
        return out

    def _score_device(self, tile_ids, boosts, required, k):
        from elasticsearch_tpu.ops import dispatch
        from elasticsearch_tpu.parallel import policy

        mesh = policy.decide(
            self.FAMILY, self.n_slots,
            batch=dispatch.bucket_queries(tile_ids.shape[0]))
        if mesh is not None:
            out = self._score_device_mesh(tile_ids, boosts, required, k,
                                          mesh)
            if out is not None:
                return out
            # ranked window deeper than one shard's slot range: the
            # sharded merge would be lossy, so this dispatch ran
            # single-device after all — keep the router stats honest
            policy.reclassify_single(
                self.FAMILY + "_window_deeper_than_shard")

        n_real = tile_ids.shape[0]
        tile_ids, boosts, required, n_pad = _pad_query_bucket(
            tile_ids, boosts, required)
        slots_d, impacts_d, scales_d = self._device_arrays()
        # score-board width pads to a pow2 bucket: n_slots changes on
        # every refresh, and a jit re-specialization per refresh would
        # stall the first post-refresh batch for seconds — pad slots
        # score 0 with match-count 0, so the required-mask turns them to
        # -inf and they can never surface
        n_slots_pad = _pow2(max(self.n_slots, 1))
        # window k rounds up the dispatch bucket ladder (one compile per
        # rung, results sliced back down — lax.top_k prefixes are exact)
        k_req = min(k, max(self.n_slots, 1))
        k_b = dispatch.bucket_k(k_req, limit=n_slots_pad)
        # score/count boards are allocated here and DONATED: XLA reuses
        # their HBM for the scan carry instead of holding board + carry
        # live at once — the largest transient of the lexical path
        scores0 = jnp.zeros((n_pad, n_slots_pad + 1), dtype=jnp.float32)
        counts0 = jnp.zeros((n_pad, n_slots_pad + 1), dtype=jnp.int32)
        vals, slot_idx = dispatch.call(
            self.KERNEL, scores0, counts0, jnp.asarray(tile_ids),
            jnp.asarray(boosts), jnp.asarray(required.astype(np.int32)),
            slots_d, impacts_d, scales_d, k=k_b)
        vals = np.asarray(vals)[:, :k_req]
        slot_idx = np.asarray(slot_idx)[:, :k_req]
        out = []
        for qi in range(n_real):
            v, si = vals[qi], slot_idx[qi]
            keep = v > -np.inf
            v, si = v[keep], si[keep]
            out.append((self.row_map[si], v.astype(np.float32)))
        return out

    def search_batch(self, queries, window: int, required=None,
                     route: str = "auto"):
        """Score a batch of (terms, boost) queries; returns per query
        (global rows ranked by (-score, row), f32 scores), len <= window.

        required: per-query minimum matched clauses (operator=and /
        minimum_should_match), default 1.
        """
        if self.n_slots == 0 or not self.term_tiles:
            return [(np.zeros(0, dtype=np.int64),
                     np.zeros(0, dtype=np.float32)) for _ in queries]
        tile_ids, boosts, _m = self.plan_queries(queries)
        if required is None:
            required = np.ones(len(queries), dtype=np.int32)
        else:
            required = np.asarray(required, dtype=np.int32)
        if route == "host" or (route == "auto"
                               and not self._prefer_device(len(queries))):
            res = self._score_host(tile_ids, boosts, required, window)
        else:
            res = self._score_device(tile_ids, boosts, required, window)
        return res[:len(queries)]

    def _prefer_device(self, batch: int) -> bool:
        """Device dispatch pays the fixed round-trip; the host twin pays a
        scan over ~nnz + n_slots per query. Same break-even logic as the
        vector CostModel, priced for the scatter-bound lexical shape."""
        from elasticsearch_tpu.serving.batcher import device_overhead_ms
        host_ms = batch * (self.nnz + self.n_slots) / 2.0e8 * 1000.0
        return host_ms > device_overhead_ms()


def _bm25_topk(scores0, counts0, tile_ids, boosts, required, tile_slots,
               tile_impacts, tile_scales, k: int):
    """One-dispatch batched BM25 window: scan each query's term tiles,
    scatter-add impacts into a [Q, n_slots_pad(+1)] score board (slot
    n_slots_pad is the padding trash lane), mask by match count,
    lax.top_k.

    scores0/counts0 are caller-allocated zero boards, DONATED through the
    dispatch layer (`ops/dispatch.py` registers this kernel with
    donate_argnums=(0, 1)): the caller must treat them as consumed. Their
    width is the caller's pow2 bucket over the live-doc count, so
    refreshes don't re-specialize the program; pad slots keep count 0 and
    mask to -inf. Accumulation is term-major in query order — each
    (term, doc) posting lands in exactly one tile, so per-doc adds happen
    in query-term order and the f32 sums are bit-identical to the host
    union-sum fold.
    """
    nq = tile_ids.shape[0]
    n_slots_pad = scores0.shape[1] - 1
    qi = jnp.arange(nq)

    def body(carry, inp):
        scores, counts = carry
        tid, b = inp                                   # [Q], [Q]
        safe = jnp.maximum(tid, 0)
        slots = tile_slots[safe]                       # [Q, TILE]
        imp = tile_impacts[safe].astype(jnp.float32)
        if tile_scales is not None:
            imp = imp * tile_scales[safe][:, None]
        imp = imp * b[:, None]
        valid = (tid >= 0)[:, None] & (slots >= 0)
        tgt = jnp.where(valid, slots, n_slots_pad)
        scores = scores.at[qi[:, None], tgt].add(
            jnp.where(valid, imp, 0.0))
        counts = counts.at[qi[:, None], tgt].add(
            jnp.where(valid, 1, 0))
        return (scores, counts), None

    (scores, counts), _ = jax.lax.scan(
        body, (scores0, counts0), (tile_ids.T, boosts.T))
    sc = scores[:, :n_slots_pad]
    ct = counts[:, :n_slots_pad]
    masked = jnp.where(ct >= jnp.maximum(required, 1)[:, None],
                       sc, -jnp.inf)
    return jax.lax.top_k(masked, k)


def _grid_bm25(statics, sigs) -> bool:
    """Bucketed query count, pow-2 board width (the _pow2(n_slots) pad —
    NOT the query-bucket ladder: tiny corpora legitimately produce 2/4
    wide boards), k on the ladder (or clamped to the board)."""
    from elasticsearch_tpu.ops import dispatch
    nq, width = sigs[0][0]           # scores0 [Q, n_slots_pad + 1]
    w = width - 1
    return (dispatch.is_query_bucket(nq)
            and w >= 1 and (w & (w - 1)) == 0
            and dispatch.in_k_grid(int(statics["k"]), limit=w))


def _bm25_topk_sharded(tile_ids, boosts, required, tile_slots,
                       tile_impacts, tile_scales, k: int, width: int,
                       mesh):
    """Doc-range-sharded BM25 window: shard s owns global slots
    [s*width, (s+1)*width); each shard scans the SAME replicated tiles
    but scatter-adds only its own range into a local [Q, width+1] board
    (allocated in-program — no donated transient), masks by match count,
    takes a local top-k, and the [S, Q, k] candidates merge over ICI.

    Per-slot accumulation order is the single-board kernel's (term-major
    in query order), so scores are bit-identical; the merge concatenates
    shards in ascending slot-range order, so score ties still resolve to
    the ascending global slot — `native.topk`'s convention.

    Cost shape: the tile SCAN is replicated on every shard (only the
    score board and its top-k shard), so this wins on board-bound
    workloads (large n_slots) and is roughly flat on scatter-bound ones;
    partitioning the tiles themselves by doc range is the follow-up that
    would shard the scan too."""
    from elasticsearch_tpu.ops.topk import merge_top_k
    from elasticsearch_tpu.parallel import mesh as mesh_lib
    from elasticsearch_tpu.parallel.sharded_knn import shard_map

    def body_shard(tids, bsts, req, t_slots, t_impacts, t_scales):
        nq = tids.shape[0]
        shard_id = jax.lax.axis_index(mesh_lib.SHARD_AXIS)
        lo = shard_id * width
        qi = jnp.arange(nq)
        scores0 = jnp.zeros((nq, width + 1), dtype=jnp.float32)
        counts0 = jnp.zeros((nq, width + 1), dtype=jnp.int32)

        def step(carry, inp):
            scores, counts = carry
            tid, b = inp
            safe = jnp.maximum(tid, 0)
            slots = t_slots[safe]                      # [Q, TILE] global
            imp = t_impacts[safe].astype(jnp.float32)
            if t_scales is not None:
                imp = imp * t_scales[safe][:, None]
            imp = imp * b[:, None]
            local = slots - lo
            valid = ((tid >= 0)[:, None] & (slots >= 0)
                     & (local >= 0) & (local < width))
            tgt = jnp.where(valid, local, width)
            scores = scores.at[qi[:, None], tgt].add(
                jnp.where(valid, imp, 0.0))
            counts = counts.at[qi[:, None], tgt].add(
                jnp.where(valid, 1, 0))
            return (scores, counts), None

        (scores, counts), _ = jax.lax.scan(
            step, (scores0, counts0), (tids.T, bsts.T))
        sc = scores[:, :width]
        ct = counts[:, :width]
        masked = jnp.where(ct >= jnp.maximum(req, 1)[:, None],
                           sc, -jnp.inf)
        vals, idx = jax.lax.top_k(masked, k)
        gslots = jnp.where(vals > -jnp.inf, idx + lo, -1)
        all_v = jax.lax.all_gather(vals, mesh_lib.SHARD_AXIS)
        all_s = jax.lax.all_gather(gslots, mesh_lib.SHARD_AXIS)
        return merge_top_k(all_v, all_s, k)

    from elasticsearch_tpu.parallel import layout

    # rule-driven specs (parallel/layout.py): query-side inputs split
    # over dp (each dp row scores its batch slice against the full
    # replicated CSR), tiles replicate — the dp axis applies here with
    # no hand-widened specs
    q2, q1 = layout.query_spec(2), layout.query_spec(1)
    repl = layout.replicated_spec()
    in_specs = (q2, q2, q1, repl, repl)
    if tile_scales is None:
        def run(tids, bsts, req, t_slots, t_impacts):
            return body_shard(tids, bsts, req, t_slots, t_impacts, None)
        fn = shard_map(run, mesh=mesh, in_specs=in_specs,
                       out_specs=(q2, q2))
        return fn(tile_ids, boosts, required, tile_slots, tile_impacts)
    # tile_scales is rank-1 [T]: a rank-2 spec would be rejected by
    # shard_map's rank check
    fn = shard_map(body_shard, mesh=mesh,
                   in_specs=in_specs + (repl,), out_specs=(q2, q2))
    return fn(tile_ids, boosts, required, tile_slots, tile_impacts,
              tile_scales)


def _grid_bm25_mesh(statics, sigs) -> bool:
    """Bucketed query count, pow-2 per-shard board width, k on the
    ladder (or clamped to the shard width)."""
    from elasticsearch_tpu.ops import dispatch
    nq = sigs[0][0][0]                # tile_ids [Q, M]
    w = int(statics["width"])
    return (dispatch.is_query_bucket(nq)
            and w >= 1 and (w & (w - 1)) == 0
            and dispatch.in_k_grid(int(statics["k"]), limit=w))


def _register_bm25():
    from elasticsearch_tpu.ops import dispatch
    dispatch.DISPATCH.register("bm25.topk", _bm25_topk,
                               static_argnames=("k",),
                               donate_argnums=(0, 1),
                               grid_check=_grid_bm25)
    dispatch.DISPATCH.register("bm25.mesh_topk", _bm25_topk_sharded,
                               static_argnames=("k", "width", "mesh"),
                               grid_check=_grid_bm25_mesh)


_register_bm25()


class LexicalShard:
    """Per-reader lexical store: one LexicalField per text field, synced
    lazily on first hybrid use (unlike the vector store's eager refresh
    listener — most refreshes never serve a hybrid query, and the build
    is a full tokenized-postings pass)."""

    FIELD_CLS: type = None  # set below (LexicalField) — subclasses override

    def __init__(self, dtype: str = "f32"):
        self.dtype = dtype
        self._fields: Dict[str, LexicalField] = {}
        self._lock = threading.Lock()
        self.stats = {"searches": 0, "queries": 0, "rebuilds": 0,
                      "score_nanos": 0}

    def field(self, reader, name: str) -> LexicalField:
        with self._lock:
            lf = self._fields.get(name)
            if lf is None:
                lf = self.FIELD_CLS(name, dtype=self.dtype)
                self._fields[name] = lf
            if lf.sync(reader):
                self.stats["rebuilds"] += 1
            return lf

    def search_batch(self, reader, field: str, queries, window: int,
                     required=None, route: str = "auto"):
        import time
        lf = self.field(reader, field)
        t0 = time.perf_counter_ns()
        out = lf.search_batch(queries, window, required=required,
                              route=route)
        self.stats["searches"] += 1
        self.stats["queries"] += len(queries)
        self.stats["score_nanos"] += time.perf_counter_ns() - t0
        return out


LexicalShard.FIELD_CLS = LexicalField
