"""Scalar quantization entries for the HBM-resident vector matrix.

Compatibility façade: the arithmetic moved into the vector codec
subsystem (`elasticsearch_tpu/quant/codec.py`), the ONE owner of every
encoding recipe on the ladder (f32 / bf16 / int8 / int4 / binary) —
tpulint TPU013 enforces that hand-rolled quantize/dequantize arithmetic
lives nowhere else. These names stay because every storage path (flat
corpus, IVF partitions, sharded mesh layout) historically imported the
int8 recipe from here; they now delegate to the registry so a policy
change in the codec lands everywhere at once.

On TPU the motivation is HBM: Cohere-Wiki-10M x 768 f32 is ~30.7 GB,
over a single v5e core's 16 GB; int8 per-row symmetric quantization cuts
storage 4x (int4 8x, binary 32x — see the codec ladder). The matmul
itself runs in bfloat16 (int8 rows are upcast on the fly — the kernel is
HBM-bandwidth bound, so shrinking the bytes read dominates; the upcast
fuses into the matmul read).
"""

from __future__ import annotations

import jax

from elasticsearch_tpu.quant import codec as _codec


def quantize_int8(matrix: jax.Array):
    """Per-row symmetric int8 quantization (device twin).

    Returns (q [N, D] int8, scales [N] f32) with row_i ≈ q_i * scales_i.
    """
    return _codec.get("int8").encode_jnp(matrix)


def dequantize_int8(q: jax.Array, scales: jax.Array, dtype=None) -> jax.Array:
    import jax.numpy as jnp
    dtype = jnp.bfloat16 if dtype is None else dtype
    return q.astype(dtype) * scales[:, None].astype(dtype)


def quantize_int8_np(matrix):
    """Host-side per-row symmetric int8 quantization (same policy as
    `quantize_int8`: max-abs/127 scale with a 1e-30 floor), chunked so a
    10M x 768 corpus never materializes a second full-size f32 temp.

    Returns (q8 [N, D] int8, scales [N] f32).
    """
    enc = _codec.get("int8").encode_np(matrix)
    return enc.data, enc.scales
