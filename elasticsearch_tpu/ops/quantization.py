"""Scalar quantization for the HBM-resident vector matrix.

Plays the role of the reference's (absent) int8_hnsw scalar quantization
(BASELINE config 4 — the reference stores only f32 BinaryDocValues,
`DenseVectorFieldMapper.java:184-226`). On TPU the motivation is HBM:
Cohere-Wiki-10M x 768 f32 is ~30.7 GB, over a single v5e core's 16 GB; int8
per-row symmetric quantization cuts storage 4x. The matmul itself runs in
bfloat16 (int8 rows are upcast on the fly — the kernel is HBM-bandwidth
bound, so shrinking the bytes read dominates; the upcast fuses into the
matmul read).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_int8(matrix: jax.Array):
    """Per-row symmetric int8 quantization.

    Returns (q [N, D] int8, scales [N] f32) with row_i ≈ q_i * scales_i.
    """
    matrix = matrix.astype(jnp.float32)
    max_abs = jnp.max(jnp.abs(matrix), axis=-1)
    scales = jnp.maximum(max_abs, 1e-30) / 127.0
    q = jnp.clip(jnp.round(matrix / scales[:, None]), -127, 127).astype(jnp.int8)
    return q, scales


def dequantize_int8(q: jax.Array, scales: jax.Array, dtype=jnp.bfloat16) -> jax.Array:
    return q.astype(dtype) * scales[:, None].astype(dtype)


def quantize_int8_np(matrix):
    """Host-side per-row symmetric int8 quantization (same policy as
    `quantize_int8`: max-abs/127 scale with a 1e-30 floor).

    The ONE owner of the quantization recipe for host build paths — both
    levels of `knn.build_corpus` and `parallel.sharded_knn` route through
    here so a policy change lands everywhere at once. Works in row chunks
    so a 10M x 768 corpus never materializes a second full-size f32 temp.

    Returns (q8 [N, D] int8, scales [N] f32).
    """
    import numpy as np

    matrix = np.asarray(matrix, dtype=np.float32)
    n = matrix.shape[0]
    q8 = np.empty(matrix.shape, dtype=np.int8)
    scales = np.empty((n,), dtype=np.float32)
    chunk = max(1, (64 << 20) // max(matrix.shape[1] * 4, 1))
    for lo in range(0, n, chunk):
        hi = lo + chunk
        block = matrix[lo:hi]
        s = np.maximum(np.abs(block).max(axis=-1), 1e-30) / 127.0
        scales[lo:hi] = s
        q8[lo:hi] = np.clip(np.round(block / s[:, None]),
                            -127, 127).astype(np.int8)
    return q8, scales
