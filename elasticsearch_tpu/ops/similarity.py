"""Batched vector-similarity kernels.

TPU-native replacement for the reference's per-document scripted scoring loop
(`x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:86-171`: L1Norm, L2Norm,
DotProduct, CosineSimilarity invoked per doc from Painless). Here a whole
query batch is scored against a whole corpus block with one MXU matmul:

    scores[Q, N] = queries[Q, D] @ corpus[N, D]^T

All metrics are expressed as "bigger is better" raw similarities so top-k is
uniform; `to_es_score` converts to the `_score` conventions of the `_search`
knn API ((1+cos)/2 for cosine, 1/(1+d2) for l2_norm, (1+dot)/2 for
dot_product).

Matmuls run in bfloat16 with float32 accumulation by default — the MXU's
native mode — with an f32 path for exactness testing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

DOT_PRODUCT = "dot_product"
COSINE = "cosine"
L2_NORM = "l2_norm"
MAX_INNER_PRODUCT = "max_inner_product"

METRICS = (DOT_PRODUCT, COSINE, L2_NORM, MAX_INNER_PRODUCT)

NEG_INF = jnp.float32(-3.0e38)


def _matmul(q: jax.Array, c: jax.Array, precision: str) -> jax.Array:
    """q[Q,D] @ c[N,D]^T with f32 accumulation.

    precision: "bf16" casts operands to bfloat16 (MXU native, ~2x flops),
    "f32" keeps float32 operands (still f32 accumulation).
    """
    if precision == "bf16":
        q = q.astype(jnp.bfloat16)
        c = c.astype(jnp.bfloat16)
        xla_prec = None
    else:
        # DEFAULT lets backends (incl. XLA:CPU) drop to bf16-passes; the f32
        # path must force full-precision accumulation explicitly.
        xla_prec = jax.lax.Precision.HIGHEST
    return jax.lax.dot_general(
        q, c,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=xla_prec,
    )


def l2_raw_from_dots(dots: jax.Array, queries: jax.Array, corpus_sq_norms: jax.Array) -> jax.Array:
    """-||q - c||^2 = 2 q·c - ||q||^2 - ||c||^2 (negated distance, bigger=better).

    Expanding via the dot matrix keeps the MXU in play instead of an O(N·D)
    subtract-square reduction over HBM. Single authoritative implementation —
    used by both the f32/bf16 and int8 scoring paths.
    """
    q_sq = jnp.sum(queries * queries, axis=-1, keepdims=True).astype(jnp.float32)
    return 2.0 * dots - q_sq - corpus_sq_norms[None, :]


# NOT jitted here: in the serving path this only ever runs inside the
# trace of a dispatcher-registered kernel (`knn.exact` and friends call
# it via _block_scores), where a nested jit would just inline. A raw
# decorator-level jax.jit was a second, unbucketed compile path the
# strict closed-grid gate couldn't see (tpulint TPU001); eager execution
# remains for direct/test callers.
def similarity_scores(
    queries: jax.Array,
    corpus: jax.Array,
    corpus_sq_norms: jax.Array,
    metric: str = COSINE,
    precision: str = "bf16",
    normalize_queries: bool = True,
) -> jax.Array:
    """Raw similarity matrix [Q, N], bigger = better.

    corpus_sq_norms: precomputed ||c||^2 per row (used by l2; ignored
    otherwise) — the analog of the magnitude the reference appends to each
    stored vector (`DenseVectorFieldMapper.java:184-226` stores f32be values +
    trailing 4-byte L2 magnitude).

    For COSINE the corpus is expected pre-normalized (done once at index/merge
    time by the vector store); queries are normalized here.
    """
    queries = queries.astype(jnp.float32)
    if metric == COSINE:
        if normalize_queries:
            qn = jnp.linalg.norm(queries, axis=-1, keepdims=True)
            queries = queries / jnp.maximum(qn, 1e-30)
        return _matmul(queries, corpus, precision)
    if metric in (DOT_PRODUCT, MAX_INNER_PRODUCT):
        return _matmul(queries, corpus, precision)
    if metric == L2_NORM:
        dots = _matmul(queries, corpus, precision)
        return l2_raw_from_dots(dots, queries, corpus_sq_norms)
    raise ValueError(f"unknown similarity metric [{metric}]")


def _np_for(x):
    """jnp for device arrays, numpy for host arrays — score conversions on
    host results must NOT ship the array through a device round-trip (the
    serving path's host results stay host-side end to end)."""
    import numpy as _np
    return jnp if isinstance(x, jax.Array) else _np


def to_es_score(raw, metric: str):
    """Convert raw similarity to the `_search` knn `_score` convention."""
    xp = _np_for(raw)
    if metric == COSINE:
        return (1.0 + raw) / 2.0
    if metric == DOT_PRODUCT:
        return (1.0 + raw) / 2.0
    if metric == MAX_INNER_PRODUCT:
        return xp.where(raw < 0, 1.0 / (1.0 - raw), raw + 1.0)
    if metric == L2_NORM:
        # raw = -d^2  →  score = 1 / (1 + d^2)
        return 1.0 / (1.0 - raw)
    raise ValueError(f"unknown similarity metric [{metric}]")


def from_es_score(score, metric: str):
    """Inverse of to_es_score (used when merging with externally-scored hits)."""
    xp = _np_for(score)
    if metric in (COSINE, DOT_PRODUCT):
        return 2.0 * score - 1.0
    if metric == L2_NORM:
        return 1.0 - 1.0 / score
    if metric == MAX_INNER_PRODUCT:
        return xp.where(score < 1.0, 1.0 - 1.0 / score, score - 1.0)
    raise ValueError(f"unknown similarity metric [{metric}]")
