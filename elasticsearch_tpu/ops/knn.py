"""Exact kNN as batched matmul + top-k: the north-star device program.

Replaces the reference's O(N·D) per-document scripted loop inside the Lucene
collector (`ScoreScriptUtils.java:151-171` called per doc from
`search/query/QueryPhase.java:171`'s BulkScorer) with one MXU-shaped program:

    scores = queries @ corpus^T          (bf16 MXU, f32 accumulate)
    top-k  = lax.top_k(scores + masks)

Two execution shapes:
  * single-shot for corpora whose [Q, N] score matrix fits comfortably;
  * blocked `lax.scan` over corpus tiles with a running top-k merge, for
    corpora where materializing [Q, N] would blow HBM — the structural
    analog of ring attention's KV rotation, but over corpus blocks
    (SURVEY.md §5.7).

The corpus lives in a `Corpus` pytree built once at index/refresh time
(normalization, squared norms, optional int8 quantization), matching the
reference's encode-at-parse-time design (`DenseVectorFieldMapper.parse`).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.ops.quantization import quantize_int8_np
from elasticsearch_tpu.ops.similarity import NEG_INF
from elasticsearch_tpu.quant import codec as quant_codec

LANE = 128  # TPU lane width; corpus rows are padded to a multiple of this.


class Corpus(NamedTuple):
    """Device-resident searchable vector block (a pytree).

    matrix:    [N_pad, D] f32 / bf16 / int8 storage
    sq_norms:  [N_pad] f32 — ||row||^2 (post-normalization for cosine)
    scales:    [N_pad] f32 — int8 per-row scales (all-ones when unquantized)
    num_valid: int32 scalar — rows beyond this are padding and never match
    residual / residual_scales: optional second int8 quantization level
      (row ≈ matrix*scales + residual*residual_scales, error ~1/127² of
      max|row|). The main scan never reads it; rescore variants gather it
      to reconstruct near-exact rows (the ScaNN scan-int8/rescore-float
      recipe, re-shaped so total storage equals bf16 while the scan still
      moves only int8 bytes through HBM).
    """

    matrix: jax.Array
    sq_norms: jax.Array
    scales: jax.Array
    num_valid: jax.Array
    residual: Optional[jax.Array] = None
    residual_scales: Optional[jax.Array] = None


def pad_rows(n: int, multiple: int = LANE) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def preferred_pad_multiple(n: int, metric: str = sim.COSINE) -> int:
    """Pad large dot-metric corpora to the binned kernel's tile size on TPU
    backends so the fast path stays eligible; everywhere the fast path can't
    trigger (CPU, l2), keep minimal lane padding — no wasted HBM/FLOPs."""
    if n < 8192 or metric == sim.L2_NORM:
        return LANE
    try:
        if jax.devices()[0].platform not in ("tpu", "axon"):
            return LANE
    except Exception:
        return LANE
    return 8192


def build_corpus(
    vectors: np.ndarray,
    metric: str = sim.COSINE,
    dtype: str = "bf16",
    pad_to: Optional[int] = None,
    residual: bool = True,
) -> Corpus:
    """Build the device corpus from raw host vectors.

    dtype: "f32" | "bf16" | "int8" storage for the matrix.
    For cosine, rows are L2-normalized here, once — so query-time work is a
    pure dot product (the reference instead stores the magnitude beside each
    vector and divides per doc per query, `ScoreScriptUtils.java:161`).

    residual: for int8 storage, also keep the second-level int8 residual
    used by the rescore variants (doubles storage to bf16-parity; pass
    False when HBM capacity matters more than rescore headroom).
    int8 quantization happens host-side in numpy — for a 10M x 768 corpus
    the f32 intermediate is ~30 GB and must never be materialized on device.
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, d = vectors.shape
    # packed encodings never ride the binned Pallas path, so they keep
    # minimal lane padding instead of its 8192-row tiles
    pad_mult = (LANE if dtype in quant_codec.PACKED_ENCODINGS
                else preferred_pad_multiple(n, metric))
    n_pad = pad_to if pad_to is not None else pad_rows(max(n, 1), pad_mult)
    if n_pad < n:
        raise ValueError(f"pad_to {n_pad} < corpus size {n}")

    if metric == sim.COSINE:
        norms = np.linalg.norm(vectors, axis=-1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-30)

    padded = np.zeros((n_pad, d), dtype=np.float32)
    padded[:n] = vectors
    # einsum keeps sq_norms temp-free (padded*padded would materialize a
    # second full-size f32 array — ~30 GB at the 10M x 768 scale)
    sq_norms = jnp.asarray(np.einsum("nd,nd->n", padded, padded),
                           dtype=jnp.float32)

    res = res_scales = None
    if dtype in quant_codec.PACKED_ENCODINGS:
        # packed ladder rungs (int4 nibbles / binary sign bits): encode
        # through the codec registry — the one owner of the bit layout
        # (the device kernels unpack with the matching codec helpers)
        if dtype == "binary" and metric in (sim.L2_NORM,
                                            sim.MAX_INNER_PRODUCT):
            raise ValueError(
                "binary encoding scores sign-bit Hamming — incompatible "
                f"with magnitude-dependent {metric} similarity")
        enc = quant_codec.get(dtype).encode_np(padded)
        matrix = jnp.asarray(enc.data)
        scales = jnp.asarray(enc.scales)
    elif dtype == "int8":
        q8, scales_np = quantize_int8_np(padded)
        matrix = jnp.asarray(q8)
        scales = jnp.asarray(scales_np)
        if residual:
            # second level, chunked so the f32 residual temp stays bounded
            r8 = np.empty_like(q8)
            rscales_np = np.empty((n_pad,), dtype=np.float32)
            chunk = max(1, (64 << 20) // max(d * 4, 1))
            for lo in range(0, n_pad, chunk):
                hi = lo + chunk
                res_f = (padded[lo:hi]
                         - q8[lo:hi].astype(np.float32)
                         * scales_np[lo:hi, None])
                r8[lo:hi], rscales_np[lo:hi] = quantize_int8_np(res_f)
            res = jnp.asarray(r8)
            res_scales = jnp.asarray(rscales_np)
    else:
        matrix = jnp.asarray(padded, dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32)
        scales = jnp.ones((n_pad,), dtype=jnp.float32)

    return Corpus(matrix=matrix, sq_norms=sq_norms, scales=scales,
                  num_valid=jnp.int32(n), residual=res,
                  residual_scales=res_scales)


def corpus_from_encoded(
    data: np.ndarray,
    scales: np.ndarray,
    vectors: np.ndarray,
    metric: str = sim.COSINE,
    dtype: str = "int4",
    pad_to: Optional[int] = None,
) -> Corpus:
    """Build a packed-encoding corpus from ALREADY-ENCODED rows (the
    columnar store's per-segment encoded blocks, `columnar.encoded_rows`)
    — refresh re-encodes only delta segments instead of the whole
    matrix. `vectors` is the raw f32 matrix (for sq-norms); padding rows
    take the codec's encode-of-zeros so the result is byte-identical to
    `build_corpus(vectors, dtype=dtype)`.
    """
    codec = quant_codec.get(dtype)
    vectors = np.asarray(vectors, dtype=np.float32)
    n, d = vectors.shape
    n_pad = pad_to if pad_to is not None else pad_rows(max(n, 1), LANE)
    if n_pad < n:
        raise ValueError(f"pad_to {n_pad} < corpus size {n}")
    # sq-norms in row chunks: the rows themselves are ALREADY encoded,
    # so this must not re-materialize a corpus-sized f32 temp (the whole
    # point of the per-segment encoded blocks); cosine rows are
    # normalized before encoding, so their post-normalization sq-norm is
    # exactly 1 for any non-zero row
    sq_np = np.zeros((n_pad,), dtype=np.float32)
    chunk = max(1, (64 << 20) // max(d * 4, 1))
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        block_sq = np.einsum("nd,nd->n", vectors[lo:hi], vectors[lo:hi])
        if metric == sim.COSINE:
            sq_np[lo:hi] = (block_sq > 0).astype(np.float32)
        else:
            sq_np[lo:hi] = block_sq
    sq_norms = jnp.asarray(sq_np)
    w = codec.packed_width(d)
    pad_enc = codec.encode_np(np.zeros((1, d), dtype=np.float32))
    full_data = np.empty((n_pad, w), dtype=codec.packed_np_dtype)
    full_scales = np.empty((n_pad,), dtype=np.float32)
    full_data[:n] = data.reshape(n, w)
    full_scales[:n] = scales
    full_data[n:] = pad_enc.data[0]
    full_scales[n:] = pad_enc.scales[0]
    return Corpus(matrix=jnp.asarray(full_data),
                  sq_norms=sq_norms,
                  scales=jnp.asarray(full_scales),
                  num_valid=jnp.int32(n))


def _block_scores(queries, matrix, sq_norms, scales, metric: str, precision: str):
    """Raw similarity for one corpus block, handling int8 dequant-after-matmul.

    Queries arrive already metric-prepped (see _prep_queries) — in particular
    cosine queries are unit vectors, so no renormalization happens per block.
    """
    if matrix.dtype == jnp.int8:
        # upcast the int8 rows, delegate to the one authoritative matmul
        # (precision policy lives in sim._matmul), de-scale after
        mat = matrix.astype(jnp.float32 if precision == "f32" else jnp.bfloat16)
        dots = sim._matmul(queries, mat, precision) * scales[None, :]
        if metric == sim.L2_NORM:
            return sim.l2_raw_from_dots(dots, queries, sq_norms)
        return dots
    if matrix.dtype == jnp.uint8:
        # int4 packed nibbles: two half-width matmuls on the (even, odd)
        # level planes — no interleave materializes, the planes unpack
        # in-register ahead of the MXU read
        mm = jnp.float32 if precision == "f32" else jnp.bfloat16
        lo, hi = quant_codec.int4_planes_jnp(matrix, mm)
        q_even, q_odd = quant_codec.split_query_planes_jnp(queries)
        dots = (sim._matmul(q_even, lo, precision)
                + sim._matmul(q_odd, hi, precision)) * scales[None, :]
        if metric == sim.L2_NORM:
            return sim.l2_raw_from_dots(dots, queries, sq_norms)
        return dots
    if matrix.dtype == jnp.uint32:
        # binary sign bits: XOR + popcount pseudo-dots ((D - 2·ham)/D —
        # the 1-bit cosine estimate; two-phase rescore restores exact
        # ordering). l2 is rejected at encode time.
        qbits = quant_codec.pack_sign_bits_jnp(queries)
        return quant_codec.hamming_pseudo_dots_jnp(qbits, matrix)
    return sim.similarity_scores(queries, matrix, sq_norms, metric=metric,
                                 precision=precision, normalize_queries=False)


def _prep_queries(queries, metric: str):
    queries = queries.astype(jnp.float32)
    if metric == sim.COSINE:
        qn = jnp.linalg.norm(queries, axis=-1, keepdims=True)
        queries = queries / jnp.maximum(qn, 1e-30)
    return queries


def knn_search_auto(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    filter_mask: Optional[jax.Array] = None,
    precision: str = "bf16",
    rescore_candidates: int = 128,
):
    """Route to the fastest eligible kernel.

    Preference order:
      1. binned Pallas kernel (TPU, dot-like metric, no filter, tiled
         corpus, k within candidate budget) — ~7x the exact path at
         recall ≈ 1.0 for 1M-doc corpora (pallas_knn_binned.py). A corpus
         carrying the residual rescore level (index_options.rescore)
         additionally re-ranks the kernel's own top candidates at
         near-exact precision — a few % QPS for the recall headroom;
      2. exact XLA matmul + lax.top_k (all metrics, filters, any backend).
    """
    from elasticsearch_tpu.ops import pallas_knn_binned as binned

    n_pad = corpus.matrix.shape[0]
    if (filter_mask is None
            and metric in (sim.COSINE, sim.DOT_PRODUCT, sim.MAX_INNER_PRODUCT)
            and corpus.matrix.dtype not in (jnp.uint8, jnp.uint32)
            and n_pad % binned.BLOCK_N == 0
            and k <= 64
            and precision == "bf16"):
        try:
            if dispatch.is_accelerator_backend():
                if corpus.residual is not None:
                    # `index_options.rescore_oversample` sizes this
                    # window (store-threaded); the old fixed 128 is the
                    # default-oversample value
                    return binned.binned_knn_search_rescored_packed(
                        queries, corpus, k, metric=metric,
                        rescore_candidates=rescore_candidates)
                return binned.binned_knn_search(queries, corpus, k, metric=metric)
        except Exception:
            pass
    return knn_search(queries, corpus, k, metric=metric, filter_mask=filter_mask,
                      precision=precision)


def _knn_search_impl(
    queries: jax.Array,
    corpus: Corpus,
    filter_mask: Optional[jax.Array],
    k: int,
    metric: str = sim.COSINE,
    precision: str = "bf16",
    block_size: Optional[int] = None,
):
    n_pad = corpus.matrix.shape[0]
    q = _prep_queries(queries, metric)
    # cosine corpus rows are already normalized; its sq_norms are 1 for valid
    # rows, 0 for padding — handled by the validity mask below either way.
    valid = jnp.arange(n_pad, dtype=jnp.int32) < corpus.num_valid
    if filter_mask is not None:
        valid = valid & filter_mask  # broadcasts [N] or [Q, N]

    if block_size is None or block_size >= n_pad:
        scores = _block_scores(q, corpus.matrix, corpus.sq_norms, corpus.scales, metric, precision)
        return topk_ops.masked_top_k(scores, valid, k)

    # Blocked path: scan corpus tiles with a running top-k. Keeps peak HBM at
    # [Q, block_size] scores instead of [Q, N].
    if n_pad % block_size != 0:
        raise ValueError(f"n_pad {n_pad} not divisible by block_size {block_size}")
    nblocks = n_pad // block_size
    mat = corpus.matrix.reshape(nblocks, block_size, -1)
    sqn = corpus.sq_norms.reshape(nblocks, block_size)
    scl = corpus.scales.reshape(nblocks, block_size)
    if valid.ndim == 1:
        vmask = valid.reshape(nblocks, 1, block_size)
    else:
        vmask = valid.reshape(-1, nblocks, block_size).transpose(1, 0, 2)

    nq = q.shape[0]
    init = (jnp.full((nq, k), NEG_INF, dtype=jnp.float32),
            jnp.zeros((nq, k), dtype=jnp.int32))

    def body(carry, xs):
        best_s, best_i = carry
        block_mat, block_sqn, block_scl, block_valid, block_idx = xs
        s = _block_scores(q, block_mat, block_sqn, block_scl, metric, precision)
        s = jnp.where(block_valid, s, NEG_INF)
        ids = block_idx * block_size + jnp.arange(block_size, dtype=jnp.int32)[None, :]
        ids = jnp.broadcast_to(ids, s.shape)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate([best_i, ids], axis=1)
        vals, pos = jax.lax.top_k(cat_s, k)
        return (vals, jnp.take_along_axis(cat_i, pos, axis=1)), None

    xs = (mat, sqn, scl, vmask, jnp.arange(nblocks, dtype=jnp.int32))
    (best_s, best_i), _ = jax.lax.scan(body, init, xs)
    return best_s, best_i


def _grid_knn(statics, sigs) -> bool:
    """Closed grid: bucketed query count, k on the ladder (or clamped to
    the corpus), corpus rows lane-padded (they are, by build_corpus)."""
    q_shape = sigs[0][0]          # queries [Q, D]
    n_rows = sigs[1][0][0]        # corpus.matrix [N_pad, D]
    return (dispatch.is_query_bucket(q_shape[0])
            and dispatch.in_k_grid(int(statics["k"]), limit=n_rows)
            and n_rows % LANE == 0)


dispatch.DISPATCH.register(
    "knn.exact", _knn_search_impl,
    static_argnames=("k", "metric", "precision", "block_size"),
    grid_check=_grid_knn)


def knn_search(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    filter_mask: Optional[jax.Array] = None,
    precision: str = "bf16",
    block_size: Optional[int] = None,
):
    """Exact top-k search of `queries` [Q, D] against `corpus`.

    filter_mask: optional [N_pad] or [Q, N_pad] bool — True = searchable
    (filtered kNN; host-computed bitset from the boolean pre-filter).

    Returns (scores [Q, k] raw similarity, ids [Q, k] int32 row indices).
    Padded / filtered-out rows return score NEG_INF (callers treat those as
    "fewer than k hits").

    Executes through the shape-bucketed dispatch cache (`ops/dispatch.py`):
    serving callers pad queries to pow-2 buckets and round k up the bucket
    ladder, so steady-state traffic never compiles.
    """
    return dispatch.call("knn.exact", queries, corpus, filter_mask,
                         k=k, metric=metric, precision=precision,
                         block_size=block_size)
