"""Top-k selection and cross-block merge.

Replaces the reference's per-shard Lucene top-k heaps and the coordinator's
`SearchPhaseController.mergeTopDocs` (`action/search/SearchPhaseController.java:221-243`)
with `lax.top_k` plus a concat-and-reselect merge. `lax.top_k` is stable
(ties resolve to the lower index), so ordering the concatenation by shard
index reproduces the reference's tie-break-by-shard-index semantics.

Outermost calls route through `ops/dispatch.py`'s AOT executable cache
(shape-bucketed, counted); calls from inside an enclosing jit inline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops.similarity import NEG_INF


def _top_k_impl(scores: jax.Array, k: int):
    return jax.lax.top_k(scores, k)


def _masked_top_k_impl(scores: jax.Array, mask: jax.Array, k: int):
    masked = jnp.where(mask, scores, NEG_INF)
    return jax.lax.top_k(masked, k)


def _merge_top_k_impl(scores_blocks: jax.Array, index_blocks: jax.Array,
                      k: int):
    b, q, kb = scores_blocks.shape
    flat_scores = jnp.transpose(scores_blocks, (1, 0, 2)).reshape(q, b * kb)
    flat_ids = jnp.transpose(index_blocks, (1, 0, 2)).reshape(q, b * kb)
    vals, pos = jax.lax.top_k(flat_scores, k)
    return vals, jnp.take_along_axis(flat_ids, pos, axis=1)


def _grid_topk(statics, sigs) -> bool:
    """k on the ladder (or clamped to the scored width); 2-D score boards
    additionally require a bucketed query count."""
    shape = sigs[0][0]
    n = shape[-1]
    if not dispatch.in_k_grid(int(statics["k"]), limit=n):
        return False
    if len(shape) == 2:
        return dispatch.is_query_bucket(shape[0])
    return True


dispatch.DISPATCH.register("topk.top_k", _top_k_impl,
                           static_argnames=("k",), grid_check=_grid_topk)
dispatch.DISPATCH.register("topk.masked_top_k", _masked_top_k_impl,
                           static_argnames=("k",), grid_check=_grid_topk)
dispatch.DISPATCH.register("topk.merge_top_k", _merge_top_k_impl,
                           static_argnames=("k",))


def top_k(scores: jax.Array, k: int):
    """scores [..., N] → (values [..., k], indices [..., k]) descending."""
    return dispatch.call("topk.top_k", scores, k=k)


def masked_top_k(scores: jax.Array, mask: jax.Array, k: int):
    """Top-k over scores where mask==True; masked-out slots score -inf.

    This is the device half of filtered kNN (BASELINE config 5): the host
    computes the filter bitset from the boolean query, ships it as a packed
    bool array, and the device applies it as an additive mask — the
    reference's collector-level filter composition
    (`BoolQueryBuilder` + `script_score`) doesn't translate to XLA.
    """
    return dispatch.call("topk.masked_top_k", scores, mask, k=k)


def merge_top_k(scores_blocks: jax.Array, index_blocks: jax.Array, k: int):
    """Merge per-block top-k results into a global top-k.

    scores_blocks: [B, Q, k_b] per-block descending scores
    index_blocks:  [B, Q, k_b] matching global doc ids
    Returns (scores [Q, k], ids [Q, k]).

    Concatenation is ordered by block (shard) index, so lax.top_k's stability
    gives the reference's tie-break (`mergeTopDocs:221` breaks equal scores by
    shard index).
    """
    return dispatch.call("topk.merge_top_k", scores_blocks, index_blocks, k=k)
