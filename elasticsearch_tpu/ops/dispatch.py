"""Shape-bucketed kernel dispatch: every device program is pre-compiled.

BENCH_MATRIX_r06 showed the serving path dominated by XLA recompilation,
not arithmetic: batch=4 ran at 149 ms p50 while batch=16 ran at 31.6 ms,
and both closed-loop rows blew the p99 <= 3x p50 gate — every distinct
(batch, k, corpus) shape hit `jax.jit`'s tracing path in the serving hot
loop. LLM inference stacks solved this problem years ago (Orca's
iteration-level batching, vLLM's bucketed serving): the set of compiled
shapes must be SMALL and CLOSED, and steady-state traffic must only ever
execute programs compiled before it arrived. This module is that layer
for the search engine — every device kernel (`ops/knn.py`, `ops/knn_ivf
.py`, `ops/bm25.py`, `ops/topk.py`, `ops/pallas_knn_binned.py`) routes
through one dispatcher that owns:

* the global bucketing policy — pow-2 query-batch buckets, k rounded up
  to a fixed ladder, corpora already tile-padded at build time — so the
  shape universe per kernel is a grid, not a stream;
* a keyed executable cache over `jax.jit(...).lower(...).compile()` AOT
  artifacts, with `donate_argnums` on score-board/accumulator buffers
  (the caller allocates them fresh per call; XLA reuses their HBM for
  the outputs) and optional wiring to JAX's persistent compilation
  cache directory so node restarts don't re-pay compiles;
* warmup — `warmup()` pre-compiles a declared bucket grid on a
  background thread when an index opens / a batcher starts, so the
  first real query of any bucket finds its program ready;
* observability — global and per-bucket hit/miss/compile-time counters
  (`stats()`), surfaced in `_nodes/stats indices.dispatch` and, via the
  thread-local event trace, in `profile.dispatch`.

Composability rule: a dispatched kernel called with TRACERS (i.e. from
inside another jit/scan, as bench_matrix's `_scan_searcher` does) falls
through to the raw function and inlines into the enclosing trace — the
dispatcher only manages OUTERMOST calls on concrete arrays.

Closed-grid enforcement: each kernel registers a grid predicate over its
(static args, arg shapes). A cache miss whose key falls outside the grid
counts `out_of_grid_compiles` (and raises under strict mode — the tier-1
recompile-regression test in tests/test_dispatch.py runs strict), so a
future caller that forgets to pad to a bucket fails CI instead of
silently reintroducing shape churn.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("elasticsearch_tpu.dispatch")

# ---------------------------------------------------------------------------
# Bucketing policy
# ---------------------------------------------------------------------------

# k rounds UP this ladder (then clamps to the corpus/slot count): lax.top_k
# at a larger k returns a superset in identical order, so slicing the first
# k_req columns is byte-identical to running at k_req — one compile serves
# every k in the gap.
K_BUCKETS = (1, 4, 10, 16, 32, 64, 100, 128, 256, 512, 1024)

# query batches pad to pow-2 up to this; beyond it, to multiples of it
# (a 4096-query dispatch is a bulk job, not a serving shape)
MAX_QUERY_BUCKET = 2048


def bucket_queries(n: int) -> int:
    """Query-count bucket (the vectors/store + bm25 pad policy,
    centralized): 1, 8, 16, 32, ..., MAX, then multiples of MAX.

    2 and 4 are DEAD RUNGS on purpose — measured on the r06 CPU floor,
    XLA-CPU's dot_general hits a pathological small-M gemm path for
    M in {2..7} ([4, 131072] scores ran ~350 ms vs ~100 ms at M=8 and
    ~40 ms at M=1: the literal batch=4-slower-than-batch=16 anomaly,
    with zero recompiles). Padding 2..7 up to 8 rides the fast path
    everywhere; on TPU the MXU pads sublanes to 8 regardless, so the
    rung costs nothing there. Batch 1 keeps its own bucket — the
    single-query latency path beats the 8-bucket on every backend."""
    if n <= 1:
        return 1
    if n <= 8:
        return 8
    if n > MAX_QUERY_BUCKET:
        return -(-n // MAX_QUERY_BUCKET) * MAX_QUERY_BUCKET
    p = 8
    while p < n:
        p *= 2
    return p


def bucket_k(k: int, limit: Optional[int] = None) -> int:
    """Round k up the K_BUCKETS ladder, clamped to `limit` (corpus rows /
    live slots — lax.top_k requires k <= N). A clamped value is inside
    the grid by definition: it is a function of the corpus, not the
    request stream."""
    k = max(int(k), 1)
    kb = K_BUCKETS[-1]
    for b in K_BUCKETS:
        if b >= k:
            kb = b
            break
    else:
        # beyond the ladder: next multiple of the last rung
        kb = -(-k // K_BUCKETS[-1]) * K_BUCKETS[-1]
    if limit is not None:
        kb = min(kb, int(limit))
        kb = max(kb, min(k, int(limit)))
    return kb


def is_query_bucket(n: int) -> bool:
    return n >= 1 and n == bucket_queries(n)


# generational device segments (elasticsearch_tpu/segments/): sealed
# generations pad their row count to this pow-2 ladder so the per-
# generation search kernel (`segments.knn`) compiles over a closed,
# bounded shape universe — refresh deltas of any size reuse a handful
# of programs. The ladder tops out at MAX_GEN_ROW_BUCKET (merged base
# generations in the millions of rows would waste up to 2x HBM on pow-2
# padding); beyond it, multiples of the cap keep the universe closed.
GEN_ROW_BUCKET_MIN = 128          # one lane tile (ops/knn.LANE)
MAX_GEN_ROW_BUCKET = 1 << 20


def bucket_gen_rows(n: int) -> int:
    """Row bucket a device generation pads to: pow-2 from
    GEN_ROW_BUCKET_MIN up to MAX_GEN_ROW_BUCKET, then multiples of the
    cap."""
    n = max(int(n), 1)
    if n > MAX_GEN_ROW_BUCKET:
        return -(-n // MAX_GEN_ROW_BUCKET) * MAX_GEN_ROW_BUCKET
    b = GEN_ROW_BUCKET_MIN
    while b < n:
        b *= 2
    return b


def in_gen_row_grid(n: int) -> bool:
    """True when a generation row count sits on the sealed-generation
    ladder (the `segments.knn` grid predicate)."""
    return n >= GEN_ROW_BUCKET_MIN and n == bucket_gen_rows(n)


def bucket_headroom(n: int, max_batch: Optional[int] = None) -> int:
    """Free rows left in `n` requests' dispatch bucket — the continuous
    batcher's top-up budget. A batch of n dispatches padded to
    `bucket_queries(n)` rows either way, so admitting up to this many
    late arrivals into the forming batch costs ZERO recompiles (the
    compiled shape is the bucket) and zero extra padding work. `max_batch`
    additionally clamps to a caller's batch ceiling."""
    bucket = bucket_queries(n)
    if max_batch is not None:
        bucket = min(bucket, int(max_batch))
    return max(bucket - n, 0)


def is_accelerator_backend() -> bool:
    """True when the default jax backend is a real accelerator (TPU, or
    the axon plugin) — the ONE probe behind every TPU-class policy:
    whether compiles stall serving (warmup), whether Mosaic kernels
    compile natively (pallas interpret fallback), and whether a 10M-row
    bench row is a measurement or a skip."""
    try:
        import jax
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def in_k_grid(k: int, limit: Optional[int] = None) -> bool:
    """True when k sits on the ladder or equals the clamp limit."""
    return k in K_BUCKETS or (limit is not None and k == int(limit)) \
        or (k > K_BUCKETS[-1] and k % K_BUCKETS[-1] == 0)


# ---------------------------------------------------------------------------
# Persistent compilation cache
# ---------------------------------------------------------------------------

_persistent_cache_dir: Optional[str] = None


def configure_persistent_cache(cache_dir: Optional[str]) -> bool:
    """Point JAX's persistent compilation cache at `cache_dir` so node
    restarts re-load compiled executables from disk instead of re-paying
    XLA compiles (setting: `search.dispatch.persistent_cache_dir`).
    Returns True when the cache was wired."""
    global _persistent_cache_dir
    if not cache_dir:
        return False
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        # serving kernels are small; cache everything, not just slow builds
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # knob renamed across jax versions; best-effort
        _persistent_cache_dir = str(cache_dir)
        return True
    except Exception as exc:  # pragma: no cover - depends on jax build
        logger.warning("persistent compilation cache not wired: %s", exc)
        return False


def persistent_cache_dir() -> Optional[str]:
    return _persistent_cache_dir


# ---------------------------------------------------------------------------
# Dispatcher
# ---------------------------------------------------------------------------

class DispatchGridEscape(RuntimeError):
    """A kernel compiled for a shape outside its declared bucket grid."""


class _Kernel:
    __slots__ = ("name", "fn", "static_argnames", "donate_argnums",
                 "grid_check", "jitted", "x64")

    def __init__(self, name, fn, static_argnames, donate_argnums, grid_check,
                 x64=False):
        self.name = name
        self.fn = fn
        self.static_argnames = tuple(static_argnames)
        self.donate_argnums = tuple(donate_argnums)
        self.grid_check = grid_check
        self.jitted = None  # built lazily (jax import cost)
        # x64 kernels trace AND execute under jax.experimental.enable_x64:
        # the process default stays 32-bit (the serving kernels are f32 by
        # design), but 64-bit accumulator kernels (aggs.*: int64 counts,
        # f64 sums — date millis don't fit int32/f32) need the scoped flag
        # both at lower() time (canonicalization runs during tracing) and
        # at call time (the AOT executable's arg-aval check canonicalizes
        # host numpy inputs against the active config).
        self.x64 = bool(x64)


def _x64_scope(enabled: bool):
    if not enabled:
        import contextlib
        return contextlib.nullcontext()
    from jax.experimental import enable_x64
    return enable_x64()


class _Entry:
    __slots__ = ("compiled", "key_str", "hits", "compile_nanos")

    def __init__(self, compiled, key_str, compile_nanos):
        self.compiled = compiled
        self.key_str = key_str
        self.hits = 0
        self.compile_nanos = compile_nanos


class _PinnedLeaf:
    """Identity key for a non-primitive python leaf in a cache signature.

    Keying on bare `id(x)` is the PR 5 mesh-cache bug class (tpulint
    TPU003): addresses recycle after GC, so a dead object's cache entries
    alias a new object at the same address. The wrapper compares by
    identity but HOLDS the referent — while the cache entry lives, the
    address cannot be reused, so aliasing is impossible by construction.
    (Identity, not value, semantics on purpose: an executable compiled
    against one leaf object must not serve a merely-equal other.)
    """

    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        # id() is safe HERE precisely because self.obj is a strong
        # reference: the address is pinned for this wrapper's lifetime
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _PinnedLeaf) and self.obj is other.obj


def _leaf_sig(x) -> Any:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        # an AOT executable bakes its input shardings at lower() time, so
        # a mesh-sharded array and a single-device array of identical
        # shape must key to DIFFERENT executables. Only NamedShardings
        # (mesh layouts) join the key: host numpy, single-device arrays,
        # and sharding-less ShapeDtypeStructs all normalize to None so
        # warmup specs keep hitting the entries serving calls use.
        sharding = getattr(x, "sharding", None)
        try:
            from jax.sharding import NamedSharding
            if not isinstance(sharding, NamedSharding):
                sharding = None
        except Exception:
            sharding = None
        return (tuple(shape), str(dtype), sharding)
    return ("py", type(x).__name__, x if isinstance(
        x, (int, float, bool, str, bytes, type(None))) else _PinnedLeaf(x))


class Dispatcher:
    """Keyed AOT-executable cache + bucket grid + counters (one process-
    wide instance, `dispatch.DISPATCH`). Thread-safe; compiles serialize
    per key so concurrent first-callers of one bucket pay one compile."""

    def __init__(self, strict: Optional[bool] = None):
        self._kernels: Dict[str, _Kernel] = {}
        self._cache: Dict[Any, _Entry] = {}
        self._lock = threading.Lock()
        self._compile_locks: Dict[Any, threading.Lock] = {}
        self.strict = (os.environ.get("ES_TPU_DISPATCH_STRICT", "") == "1"
                       if strict is None else strict)
        self._counters = {"hits": 0, "misses": 0, "compiles": 0,
                          "compile_nanos": 0, "out_of_grid_compiles": 0,
                          "warmup_compiles": 0, "inline_calls": 0,
                          "async_calls": 0}
        self._bucket: Dict[str, Dict[str, int]] = {}
        self._trace = threading.local()

    # ------------------------------------------------------------ registry
    def register(self, name: str, fn: Callable, *,
                 static_argnames: Sequence[str] = (),
                 donate_argnums: Sequence[int] = (),
                 grid_check: Optional[Callable[..., bool]] = None,
                 x64: bool = False) -> None:
        """Register a raw (un-jitted) kernel. `grid_check(statics, sigs)`
        receives the static kwargs dict and the flat arg signature list
        [(shape, dtype) | py-leaf ...]; return False to flag the compile
        as outside the declared grid. `x64` kernels trace and execute
        under the scoped jax enable_x64 flag (64-bit accumulators)."""
        with self._lock:
            self._kernels[name] = _Kernel(name, fn, static_argnames,
                                          donate_argnums, grid_check,
                                          x64=x64)

    def kernels(self) -> List[str]:
        return sorted(self._kernels)

    # ------------------------------------------------------------- tracing
    def record_events(self, on: bool) -> None:
        """Enable/disable the thread-local per-call event trace (the
        profile.dispatch feed). Events: {kernel, bucket, hit, compile_ms}."""
        self._trace.events = [] if on else None

    def drain_events(self) -> List[dict]:
        events = getattr(self._trace, "events", None)
        if events is None:
            return []
        self._trace.events = []
        return events

    def events_enabled(self) -> bool:
        """Is THIS thread currently recording a dispatch trace?"""
        return getattr(self._trace, "events", None) is not None

    def event_count(self) -> int:
        events = getattr(self._trace, "events", None)
        return 0 if events is None else len(events)

    def annotate_events(self, since: int, **fields) -> None:
        """Tag events appended after index `since` on THIS thread's
        trace. The combining batcher uses this to label a coalesced
        batch's dispatches (`coalesced_batch: N`): the runner thread
        executes device work on behalf of N requests, and without the
        tag a profiled leader's trace silently claims the followers'
        dispatches as its own."""
        events = getattr(self._trace, "events", None)
        if events is None:
            return
        for e in events[since:]:
            e.update(fields)

    def _event(self, kernel: str, key_str: str, hit: bool,
               compile_nanos: int) -> None:
        events = getattr(self._trace, "events", None)
        if events is not None:
            events.append({"kernel": kernel, "bucket": key_str,
                           "cache": "hit" if hit else "miss",
                           "compile_ms": round(compile_nanos / 1e6, 3)})

    # ---------------------------------------------------------------- call
    def call(self, name: str, *args, **static_kwargs):
        """Execute `name` on concrete arrays through the AOT cache.

        Inside an enclosing trace (any arg is a jax Tracer) the raw
        function inlines instead — the dispatcher manages only outermost
        dispatches."""
        import jax

        kernel = self._kernels[name]
        # one flatten serves both the tracer check and the cache key —
        # this runs on every steady-state dispatch
        leaves, treedef = jax.tree_util.tree_flatten(args)
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            with self._lock:
                self._counters["inline_calls"] += 1
            return kernel.fn(*args, **static_kwargs)
        sig = (treedef, tuple(_leaf_sig(x) for x in leaves))
        entry, key_str, compiled_now, compile_nanos = self._get_entry(
            kernel, args, static_kwargs, warmup=False, sig=sig)
        self._event(name, key_str, not compiled_now, compile_nanos)
        with _x64_scope(kernel.x64):
            return entry.compiled(*args)

    def note_async(self, n: int = 1) -> None:
        """Count `n` dispatches whose device sync was deferred to
        response-assembly time (the pipelined serving path). The handle
        PRODUCER calls this when it hands back un-synced arrays —
        `vectors/store._dispatch_many` for the exhaustive kNN path — so
        `_nodes/stats indices.dispatch` `async_calls` honestly reports
        how much of the serving load actually pipelines, including
        dispatches that go through higher-level wrappers rather than
        `call_async` itself."""
        with self._lock:
            self._counters["async_calls"] += n

    def call_async(self, name: str, *args, **static_kwargs):
        """`call`, with the no-sync contract made explicit (and counted).

        JAX dispatch is asynchronous on every backend: the returned
        arrays are futures whose values materialize when the host first
        reads them (`np.asarray` / `block_until_ready`). `call` already
        returns them un-synced — this entry exists for callers built
        around that fact (the continuous batcher's pipelined dispatch
        stage): it promises the caller launches work and DEFERS the sync
        to response-assembly time, letting batch N's host hydrate overlap
        batch N+1's device dispatch. Feeds the `async_calls` counter
        (as does `note_async` for wrapped dispatches)."""
        self.note_async()
        return self.call(name, *args, **static_kwargs)

    def _signature(self, args) -> Tuple[Any, Tuple]:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        return treedef, tuple(_leaf_sig(x) for x in leaves)

    @staticmethod
    def _key_str(name: str, static_kwargs: dict, sigs: Tuple) -> str:
        statics = ",".join(f"{k}={v}" for k, v in sorted(static_kwargs.items()))
        shapes = ",".join("x".join(map(str, s[0])) + f":{s[1]}"
                          for s in sigs if not (s and s[0] == "py"))
        return f"{name}[{statics}|{shapes}]"

    def _get_entry(self, kernel: _Kernel, args, static_kwargs: dict,
                   warmup: bool, sig: Optional[Tuple[Any, Tuple]] = None):
        treedef, sigs = self._signature(args) if sig is None else sig
        key = (kernel.name, tuple(sorted(static_kwargs.items())),
               treedef, sigs)
        with self._lock:
            entry = self._cache.get(key)
            if entry is not None:
                entry.hits += 1
                self._counters["hits"] += 1
                b = self._bucket.setdefault(
                    entry.key_str, {"hits": 0, "misses": 0,
                                    "compile_nanos": 0})
                b["hits"] += 1
                return entry, entry.key_str, False, 0
            clock = self._compile_locks.setdefault(key, threading.Lock())
        with clock:
            with self._lock:
                entry = self._cache.get(key)
                if entry is not None:  # raced: another thread compiled it
                    entry.hits += 1
                    self._counters["hits"] += 1
                    self._bucket[entry.key_str]["hits"] += 1
                    return entry, entry.key_str, False, 0
            key_str = self._key_str(kernel.name, static_kwargs, sigs)
            in_grid = True
            if kernel.grid_check is not None:
                try:
                    in_grid = bool(kernel.grid_check(static_kwargs, sigs))
                except Exception:
                    in_grid = False
            if not in_grid:
                with self._lock:
                    self._counters["out_of_grid_compiles"] += 1
                if self.strict:
                    raise DispatchGridEscape(
                        f"dispatch grid escape: {key_str} is outside "
                        f"[{kernel.name}]'s declared bucket grid")
                logger.warning("dispatch grid escape (compiling anyway): %s",
                               key_str)
            entry = self._compile(kernel, args, static_kwargs, key, key_str,
                                  warmup)
            return entry, key_str, True, entry.compile_nanos

    def _compile(self, kernel: _Kernel, args, static_kwargs: dict, key,
                 key_str: str, warmup: bool) -> _Entry:
        import jax

        if kernel.jitted is None:
            kernel.jitted = jax.jit(
                kernel.fn, static_argnames=kernel.static_argnames,
                donate_argnums=kernel.donate_argnums)
        # CPU backends can't honor donation; the fallback is silent
        # copy-free-anyway execution, not an error worth a log line. The
        # filter re-installs per compile (misses are rare; filterwarnings
        # dedups an already-present filter) rather than once behind a
        # latch — an enclosing catch_warnings() (pytest wraps every test
        # in one) would pop a latched install for good — and rather than
        # catch_warnings() here, which mutates GLOBAL warning state and
        # is unsafe across concurrent compiles (warmup thread + serving
        # thread compiling different buckets).
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")
        t0 = time.perf_counter_ns()
        with _x64_scope(kernel.x64):
            compiled = kernel.jitted.lower(*args, **static_kwargs).compile()
        nanos = time.perf_counter_ns() - t0
        # telemetry-registry mirror of the compile counters: a live
        # p99 over compile cost (and a compile-rate counter) sits next
        # to the serving latency histograms in `_nodes/stats telemetry`
        # — a nonzero steady-state rate there is the recompile-
        # regression signal without waiting for the strict-mode gate
        from elasticsearch_tpu.telemetry import metrics as _metrics
        _metrics.counter("dispatch.compiles").inc()
        _metrics.record("dispatch.compile", nanos)
        entry = _Entry(compiled, key_str, nanos)
        with self._lock:
            self._cache[key] = entry
            self._counters["misses"] += 1
            self._counters["compiles"] += 1
            self._counters["compile_nanos"] += nanos
            if warmup:
                self._counters["warmup_compiles"] += 1
            b = self._bucket.setdefault(
                key_str, {"hits": 0, "misses": 0, "compile_nanos": 0})
            b["misses"] += 1
            b["compile_nanos"] += nanos
        return entry

    # -------------------------------------------------------------- warmup
    def warmup(self, entries: Sequence[Tuple[str, tuple, dict]],
               background: bool = True) -> Optional[threading.Thread]:
        """AOT-compile a bucket grid off the critical path.

        entries: (kernel name, arg specs, static kwargs) — arg specs may
        be `jax.ShapeDtypeStruct` pytrees (no data materialized). Already-
        cached buckets are skipped for free. Returns the warmup thread
        (joinable, for deterministic tests) when `background`."""
        def run():
            for name, args, statics in entries:
                kernel = self._kernels.get(name)
                if kernel is None:
                    continue
                try:
                    self._get_entry(kernel, args, statics, warmup=True)
                except Exception as exc:
                    logger.debug("warmup compile failed for %s: %s",
                                 name, exc)
        if not background:
            run()
            return None
        t = threading.Thread(target=run, daemon=True,
                             name="dispatch-warmup")
        t.start()
        return t

    # --------------------------------------------------------------- stats
    def stats(self, per_bucket: bool = True) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["cached_executables"] = len(self._cache)
            out["persistent_cache_dir"] = _persistent_cache_dir
            if per_bucket:
                out["buckets"] = {k: dict(v)
                                  for k, v in sorted(self._bucket.items())}
            return out

    def compile_count(self) -> int:
        with self._lock:
            return self._counters["compiles"]

    def reset_stats(self) -> None:
        """Zero the counters (tests); compiled executables stay cached."""
        with self._lock:
            for k in self._counters:
                self._counters[k] = 0
            self._bucket.clear()

    def clear(self) -> None:
        """Drop every cached executable AND counters (tests only)."""
        with self._lock:
            self._cache.clear()
            self._compile_locks.clear()
            for k in self._counters:
                self._counters[k] = 0
            self._bucket.clear()


DISPATCH = Dispatcher()


def call(name: str, *args, **static_kwargs):
    return DISPATCH.call(name, *args, **static_kwargs)


def call_async(name: str, *args, **static_kwargs):
    return DISPATCH.call_async(name, *args, **static_kwargs)


def stats(per_bucket: bool = True) -> dict:
    return DISPATCH.stats(per_bucket=per_bucket)


# ---------------------------------------------------------------------------
# Spec helpers (warmup grids)
# ---------------------------------------------------------------------------

def specs_like(tree):
    """Map a pytree of concrete arrays to `jax.ShapeDtypeStruct`s (warmup
    without materializing data)."""
    import jax

    def spec(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x
    return jax.tree_util.tree_map(spec, tree)


def query_spec(n_queries: int, dims: int):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct((n_queries, dims), jnp.float32)


# default warmup ladders: the interactive serving shapes. Kept small on
# purpose — warmup is a floor, not the whole grid; the persistent cache
# catches the tail across restarts.
WARMUP_QUERY_BUCKETS = (1, 8, 16, 64)
WARMUP_K_BUCKETS = (10, 100)


_default_warmup: Optional[bool] = None


def set_default_warmup(value: Optional[bool]) -> None:
    """Node-level warmup override (`search.dispatch.warmup` setting);
    None restores the env/platform auto policy."""
    global _default_warmup
    _default_warmup = value


def warmup_enabled(override: Optional[bool] = None) -> bool:
    """Shared warmup policy: explicit override > node setting >
    ES_TPU_DISPATCH_WARMUP env > platform auto (warm only where compiles
    actually stall serving — real accelerator backends; CPU test runs
    skip the background threads)."""
    if override is not None:
        return override
    if _default_warmup is not None:
        return _default_warmup
    env = os.environ.get("ES_TPU_DISPATCH_WARMUP")
    if env is not None:
        return env != "0"
    return is_accelerator_backend()
