"""Pallas fused gather+score for IVF probes.

The scan-based probe scorer (`ops/knn_ivf.score_probes`) pays posting-
list materialization: every probe step `jnp.take`s a [Q, cap, D]
partition-tile gather out to HBM before the einsum reads it back — at
nprobe=32, batch=256 that is gigabytes of staged tiles per dispatch.
This kernel fuses the gather INTO the score: the probe ids ride in as a
scalar-prefetch operand (`pltpu.PrefetchScalarGridSpec`), the BlockSpec
index_map selects each (query, probe) step's partition tile directly
out of the resident `parts` array, and the tile is read once, through
VMEM, straight into the MXU matmul — no staged copy exists at any
point. The [Q, nprobe, cap] score board is the only new array.

Variants follow the storage ladder (`quant/codec.py`): f32/bf16 tiles
matmul directly; int8 tiles upcast in-register and de-scale per row;
int4 packed-nibble tiles unpack into (even, odd) level planes against
the matching query planes. Binary stays on the scan path (sign-bit
probes are bandwidth-trivial already). l2 routing stays on the scan
path too — the fused kernel serves the dot-like metrics.

Registered as `ivf.fused_probe` under the same closed-grid predicate as
the scan kernels (bucketed query count, pow-2 nprobe), and kept honest
on CPU by interpret mode (`tests/test_pallas_parity.py` pins program
structure, byte parity vs the scan scorer, validity masking, and the
strict zero-recompile gate).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.knn_ivf import IVFPartitions, _grid_ivf
from elasticsearch_tpu.ops.similarity import NEG_INF
from elasticsearch_tpu.quant import codec as quant_codec

# python-float sentinel for in-kernel use (a jnp constant would be a
# captured array, which pallas_call rejects)
_NEG = float(NEG_INF)


def default_interpret() -> bool:
    """Mosaic compiles only on TPU-class backends (same probe as the
    binned kNN kernel)."""
    return not dispatch.is_accelerator_backend()


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return default_interpret() if interpret is None else bool(interpret)


def fused_eligible(parts_dtype, metric: str, precision: str = "bf16") -> bool:
    """Can the fused kernel serve this layout? (dtype on the fused
    ladder, dot-like metric, bf16 serving precision). Callers separately
    decide WHETHER to prefer it (accelerator backend, or the
    ES_TPU_IVF_FUSED=1 interpret-mode override for tests/bench)."""
    return (str(parts_dtype) in ("float32", "bfloat16", "int8", "uint8")
            and metric != sim.L2_NORM
            and precision != "f32")


def fused_preferred() -> bool:
    """Route probes through the fused kernel? On by default on real
    accelerator backends (where the staged-gather HBM traffic is the
    cost); ES_TPU_IVF_FUSED=1 forces it in interpret mode, =0 forces it
    off."""
    env = os.environ.get("ES_TPU_IVF_FUSED")
    if env is not None:
        return env != "0"
    return dispatch.is_accelerator_backend()


# ---------------------------------------------------------------------------
# kernel bodies — one (query, probe) tile per grid step
# ---------------------------------------------------------------------------

def _dense_kernel(ids_ref, q_ref, parts_ref, scales_ref, out_ref):
    """f32/bf16/int8 tiles: [1, D] x [cap, D]^T with f32 accumulation
    (int8 tiles upcast in-register to bf16, exact for [-127, 127]).
    `scales_ref` is the per-row dequant scale for int8 and the validity
    row (1/0) otherwise — zero on padding either way, so the same mask
    pins padding slots to NEG_INF before the board leaves the kernel."""
    dots = jax.lax.dot_general(
        q_ref[:].astype(jnp.bfloat16), parts_ref[0].astype(jnp.bfloat16),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    s = dots * scales_ref[:]
    out_ref[0] = jnp.where(scales_ref[:] > 0, s, _NEG)


def _int4_kernel(ids_ref, qe_ref, qo_ref, parts_ref, scales_ref, out_ref):
    """int4 packed-nibble tiles: unpack the (even, odd) level planes
    in-register and run two half-width passes against the matching
    query planes (the codec's one bit layout)."""
    tile = parts_ref[0]
    lo = ((tile & jnp.uint8(0x0F)).astype(jnp.int32) - 8).astype(jnp.bfloat16)
    hi = ((tile >> 4).astype(jnp.int32) - 8).astype(jnp.bfloat16)
    dn = (((1,), (1,)), ((), ()))
    dots = (jax.lax.dot_general(qe_ref[:].astype(jnp.bfloat16), lo, dn,
                                preferred_element_type=jnp.float32)
            + jax.lax.dot_general(qo_ref[:].astype(jnp.bfloat16), hi, dn,
                                  preferred_element_type=jnp.float32))
    s = dots * scales_ref[:]
    out_ref[0] = jnp.where(scales_ref[:] > 0, s, _NEG)


def _fused_probe_board(queries, ivf: IVFPartitions, probe_ids,
                       interpret: bool):
    """[Q, nprobe, cap] masked score board, tiles gathered via the
    scalar-prefetched probe ids (one partition tile per grid step)."""
    nq = queries.shape[0]
    nprobe = probe_ids.shape[1]
    nlist, cap, w = ivf.parts.shape
    out_shape = jax.ShapeDtypeStruct((nq, nprobe, cap), jnp.float32)
    out_spec = pl.BlockSpec((1, 1, cap), lambda q, j, ids: (q, j, 0))
    part_spec = pl.BlockSpec((1, cap, w), lambda q, j, ids: (ids[q, j], 0, 0))
    scale_spec = pl.BlockSpec((1, cap), lambda q, j, ids: (ids[q, j], 0))
    if ivf.parts.dtype == jnp.uint8:
        qe, qo = quant_codec.split_query_planes_jnp(
            queries.astype(jnp.float32))
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(nq, nprobe),
            in_specs=[
                pl.BlockSpec((1, w), lambda q, j, ids: (q, 0)),
                pl.BlockSpec((1, w), lambda q, j, ids: (q, 0)),
                part_spec, scale_spec,
            ],
            out_specs=out_spec)
        return pl.pallas_call(
            _int4_kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=interpret,
        )(probe_ids, qe, qo, ivf.parts, ivf.part_scales)
    d = w
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(nq, nprobe),
        in_specs=[
            pl.BlockSpec((1, d), lambda q, j, ids: (q, 0)),
            part_spec, scale_spec,
        ],
        out_specs=out_spec)
    return pl.pallas_call(
        _dense_kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=interpret,
    )(probe_ids, queries.astype(jnp.float32), ivf.parts, ivf.part_scales)


def _fused_probe_impl(queries, ivf: IVFPartitions, probe_ids, k: int,
                      metric: str = sim.COSINE, interpret: bool = False):
    """Fused board + global top-k merge. The row-id join stays a cheap
    int32 take ([Q, nprobe, cap] ids — ~D× smaller than the vector
    tiles the scan path staged)."""
    board = _fused_probe_board(queries, ivf, probe_ids, interpret)
    nq = board.shape[0]
    rows = jnp.take(ivf.part_rows, probe_ids, axis=0)   # [Q, nprobe, cap]
    flat_s = board.reshape(nq, -1)
    flat_r = rows.reshape(nq, -1)
    flat_s = jnp.where(flat_r >= 0, flat_s, NEG_INF)
    vals, pos = jax.lax.top_k(flat_s, k)
    return vals, jnp.take_along_axis(flat_r, pos, axis=1)


dispatch.DISPATCH.register(
    "ivf.fused_probe", _fused_probe_impl,
    static_argnames=("k", "metric", "interpret"),
    grid_check=_grid_ivf)


def fused_probe_scores(queries, ivf: IVFPartitions, probe_ids, k: int,
                       metric: str = sim.COSINE,
                       interpret: Optional[bool] = None):
    """Score probed partitions with the fused gather+score kernel.

    queries must be metric-prepped (like `knn_ivf.score_probes`);
    probe_ids [Q, nprobe] int32 from `knn_ivf.route`. Returns
    (scores [Q, k], rows [Q, k]) — the `score_probes` contract exactly
    (NEG_INF / -1 padding), pinned by the interpret-mode parity tests.
    """
    return dispatch.call("ivf.fused_probe", queries, ivf, probe_ids,
                         k=k, metric=metric,
                         interpret=_resolve_interpret(interpret))


def warmup_entries(ivf: IVFPartitions, nprobe: int, dims: int, k_buckets,
                   query_buckets, metric: str = sim.COSINE,
                   interpret: Optional[bool] = None):
    """(kernel, specs, statics) entries pre-compiling the fused probe
    grid over the interactive buckets (the store's router warmup).
    `interpret` defaults through the same resolution serving uses, so
    the warmed programs ARE the ones `fused_probe_scores` dispatches
    (an ES_TPU_IVF_FUSED=1 interpret-mode run warms interpret=True)."""
    parts_spec = dispatch.specs_like(ivf)
    entries = []
    cap = ivf.parts.shape[1]
    interp = _resolve_interpret(interpret)
    for q in query_buckets:
        qspec = dispatch.query_spec(q, dims)
        pspec = jax.ShapeDtypeStruct((q, nprobe), jnp.int32)
        for k in k_buckets:
            k_b = dispatch.bucket_k(min(k, nprobe * cap),
                                    limit=nprobe * cap)
            entries.append((
                "ivf.fused_probe", (qspec, parts_spec, pspec),
                {"k": k_b, "metric": metric, "interpret": interp}))
    return entries


def warmup_entries_for_index(index, nprobe: int, k_buckets, query_buckets,
                             metric: str = sim.COSINE):
    """SHAPE-ONLY warmup entries derived from an `ann/ivf_index.IVFIndex`
    HOST layout — never touches `device_partitions()`, so scheduling
    warmup on the refresh thread cannot pay (or re-pay, since
    `IVFIndex.add` invalidates the cached upload) the partition-layout
    transfer (the same contract as `sharded_ivf.warmup_entries`)."""
    nlist, cap, dims = index.part_vecs.shape
    part_dtype = {"int8": jnp.int8, "bf16": jnp.bfloat16,
                  "int4": jnp.uint8, "binary": jnp.uint32}.get(
        index.dtype, jnp.float32)
    part_w = dims
    if index.dtype in quant_codec.PACKED_ENCODINGS:
        part_w = quant_codec.get(index.dtype).packed_width(dims)
    spec = IVFPartitions(
        centroids=jax.ShapeDtypeStruct((nlist, dims), jnp.float32),
        centroid_sq=jax.ShapeDtypeStruct((nlist,), jnp.float32),
        parts=jax.ShapeDtypeStruct((nlist, cap, part_w), part_dtype),
        part_scales=jax.ShapeDtypeStruct((nlist, cap), jnp.float32),
        part_sq=jax.ShapeDtypeStruct((nlist, cap), jnp.float32),
        part_rows=jax.ShapeDtypeStruct((nlist, cap), jnp.int32))
    return warmup_entries(spec, nprobe, dims, k_buckets, query_buckets,
                          metric=metric)
