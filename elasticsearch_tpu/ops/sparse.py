"""Learned-sparse retrieval (`rank_features`) on the BM25 kernel substrate.

SPLADE-family learned-sparse models emit per-doc (term, weight) maps and
score a query's token weights by a weighted dot product over the shared
vocabulary — structurally the SAME computation BM25's impact layout
already serves: a term-major scatter-add of per-posting values into a
score board, masked, top-k'd. This module is therefore a thin mapping,
not a new kernel: `SparseField` subclasses `ops/bm25.py`'s
`LexicalField` and overrides exactly the two ends the docstring there
promises —

* build: postings come from the stored `rank_features` doc values
  (`columnar.STORE.sparse_postings_block`, refresh-delta cached like the
  tokenized postings), and the stored WEIGHTS are installed directly as
  the impacts (no idf/tf math — the model already folded relevance into
  the weight). The tile-padded CSR below (`_install_tiles`), the dtype
  ladder (f32/bf16/int8 per-tile codec scales), the donated score
  boards, and the doc-range-sharded mesh twin are inherited verbatim.

* search: a query is a {token: weight} map; each token's weight (times
  the leg boost) becomes that token's per-tile boost, so the kernel's
  `impact * boost` multiply computes `doc_weight * query_weight` — the
  sparse dot product. `required=1` (any overlapping token matches; the
  weighted union IS the score, there is no operator=and analogue).

The scoring programs register under their own dispatch names
(`sparse.topk` / `sparse.mesh_topk`) pointing at the SAME compiled
callables as the bm25 grid — separate names keep per-kernel dispatch
stats, warmup ledgers, and strict-mode grids honest about which workload
is running, while XLA still shares the underlying executables per shape.

Queries wider than MAX_QUERY_TOKENS fall back to the host walker (the
plan layer counts the fallback reason): the tile-id matrix is [Q, m]
with m a pow2 over the widest query in the batch, so one pathological
1k-token query would re-specialize the program AND drag every other
query in the batch through its scan width.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from elasticsearch_tpu.ops.bm25 import (
    LexicalField,
    LexicalShard,
    _bm25_topk,
    _bm25_topk_sharded,
    _grid_bm25,
    _grid_bm25_mesh,
    _pow2,
)

# widest device-eligible query, in distinct tokens; SPLADE-style
# expansions run 20-120 tokens, so 256 covers real models while capping
# the scan width one outlier can impose on a shared batch
MAX_QUERY_TOKENS = 256


class SparseField(LexicalField):
    """One `rank_features` field's tile-padded weight layout.

    Same layout, boards, buckets, tie-breaks, host/device/mesh routing
    as the BM25 parent — only the posting source (stored weights) and
    the query planner (token weights as boosts) differ.
    """

    KERNEL = "sparse.topk"
    MESH_KERNEL = "sparse.mesh_topk"
    FAMILY = "sparse"

    # ------------------------------------------------------------- build
    def sync(self, reader) -> bool:
        """(Re)build from the stored (feature -> weight) doc values.
        Stored weights land as the impacts unchanged: corpus-global
        stats don't exist here, so unlike BM25 the cached per-segment
        extractions need NO recompute pass on refresh."""
        from elasticsearch_tpu import columnar
        version = tuple((v.segment.seg_id, v.segment.num_docs,
                         int(v.live.sum())) for v in reader.views)
        if version == self.version:
            return False
        segs: List = []
        n_cached = n_extracted = 0
        for view in reader.views:
            blk, was_cached = columnar.STORE.sparse_postings_block(
                view, self.field)
            if was_cached:
                n_cached += 1
            else:
                n_extracted += 1
            segs.append(blk)
        mode = columnar.STORE.note_composition(
            self.field, "sparse_postings", n_cached, n_extracted)
        self.columnar_refresh = {
            "blocks": n_cached + n_extracted, "cached": n_cached,
            "extracted": n_extracted, "mode": mode}

        # dense slot space over ALL live docs (docs without the field
        # simply appear in no feature's run) — identical to the lexical
        # slot space, so slot-index tie-breaks equal row tie-breaks
        bases = []
        total = 0
        row_parts = []
        for view, sp in zip(reader.views, segs):
            bases.append(total)
            live_locals = np.nonzero(view.live)[0]
            row_parts.append(live_locals.astype(np.int64)
                            + view.segment.base)
            total += sp.n_live
        self.n_slots = total
        self.row_map = (np.concatenate(row_parts) if row_parts
                        else np.zeros(0, dtype=np.int64))

        merged: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {}
        for base, sp in zip(bases, segs):
            for feat, (slots, weights) in sp.features.items():
                merged.setdefault(feat, []).append((slots + base, weights))

        terms = sorted(merged)
        ptr = [0]
        slot_parts, weight_parts, dfs = [], [], []
        for t in terms:
            chunks = merged[t]
            s = (np.concatenate([c[0] for c in chunks])
                 if len(chunks) > 1 else chunks[0][0])
            w = (np.concatenate([c[1] for c in chunks])
                 if len(chunks) > 1 else chunks[0][1])
            slot_parts.append(s)
            weight_parts.append(w)
            dfs.append(len(s))
            ptr.append(ptr[-1] + len(s))
        slot_flat = (np.concatenate(slot_parts) if slot_parts
                     else np.zeros(0, dtype=np.int32))
        impact_flat = (np.concatenate(weight_parts) if weight_parts
                       else np.zeros(0, dtype=np.float32))
        self.nnz = len(slot_flat)

        self._install_tiles(terms, dfs, ptr, slot_flat, impact_flat)
        self.version = version
        return True

    # ------------------------------------------------------------ search
    def plan_queries(self, queries: Sequence[Tuple[Dict[str, float], float]]
                     ) -> Tuple[np.ndarray, np.ndarray, int]:
        """Resolve ({token: weight}, boost) per query: every tile of a
        matched token carries boost = f32(weight * leg_boost), so the
        kernel's impact*boost multiply IS the sparse dot product.
        Token order is the query dict's iteration order — the host
        oracle (`search/queries_ext.py`) folds its f32 sums in the same
        order, which is what makes host/device scores byte-identical."""
        per_q: List[List[Tuple[int, float]]] = []
        for tokens, boost in queries:
            tiles: List[Tuple[int, float]] = []
            for t, w in tokens.items():
                span = self.term_tiles.get(str(t))
                if span is None:
                    continue
                b = np.float32(np.float32(w) * np.float32(boost))
                first, nt = span
                tiles.extend((first + j, b) for j in range(nt))
            per_q.append(tiles)
        m = _pow2(max(max((len(t) for t in per_q), default=1), 1))
        tile_ids = np.full((len(per_q), m), -1, dtype=np.int32)
        boosts = np.zeros((len(per_q), m), dtype=np.float32)
        for qi, tiles in enumerate(per_q):
            for j, (tid, b) in enumerate(tiles):
                tile_ids[qi, j] = tid
                boosts[qi, j] = b
        return tile_ids, boosts, m


class SparseShard(LexicalShard):
    """Per-reader learned-sparse store: one SparseField per
    `rank_features` field, lazily synced — the parent's locking, stats,
    and search_batch timing apply unchanged."""


SparseShard.FIELD_CLS = SparseField


def _register_sparse():
    """`sparse.*` dispatch names over the SAME scoring callables as the
    bm25 grid — per-name stats/warmup/strict-grids, shared executables."""
    from elasticsearch_tpu.ops import dispatch
    dispatch.DISPATCH.register("sparse.topk", _bm25_topk,
                               static_argnames=("k",),
                               donate_argnums=(0, 1),
                               grid_check=_grid_bm25)
    dispatch.DISPATCH.register("sparse.mesh_topk", _bm25_topk_sharded,
                               static_argnames=("k", "width", "mesh"),
                               grid_check=_grid_bm25_mesh)


_register_sparse()
