"""Centroid-pruned IVF search kernel: route → pruned matmul → top-k.

The device half of the `tpu_ivf` engine (`elasticsearch_tpu/ann/`). Where
`ops/knn.py` scores all N rows per query, this scores only the `nprobe`
partitions a tiny centroid matmul routes each query to:

    route:  c[Q, nlist] = q @ centroids^T          (~nlist·D FLOPs/query)
    probe:  top-nprobe partition ids per query
    score:  for each probe slot, a block `take` of [Q, cap, D] partition
            tiles + one batched matmul → [Q, cap] scores
    merge:  running top-k across probe slots (the knn.py blocked-scan
            merge, over probed partitions instead of corpus tiles)

The layout is gather-free at the row level: partitions live bucketed and
padded to one common capacity (`parts[nlist, cap, D]`, rows padded with
`row_ids == -1`), so the score stage moves whole lane-aligned tiles
through HBM — `jnp.take` of contiguous blocks, never per-row gathers.
Total bytes read per query ≈ nprobe·cap·D — the ~nprobe/nlist corpus
fraction that buys IVF its speedup.

int8 storage reuses the per-row symmetric scheme of `ops/quantization`:
rows upcast in-register during the matmul read, scores de-scaled after.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.similarity import NEG_INF
from elasticsearch_tpu.quant import codec as quant_codec


class IVFPartitions(NamedTuple):
    """Device-resident partitioned corpus (a pytree).

    centroids:    [nlist, D] f32 routing centroids (unit vectors when the
                  corpus is cosine-normalized)
    centroid_sq:  [nlist] f32 ||c||² (l2 routing)
    parts:        [nlist, cap, D] f32 / bf16 / int8 partition tiles
    part_scales:  [nlist, cap] f32 int8 per-row scales (ones otherwise)
    part_sq:      [nlist, cap] f32 ||row||² (l2 scoring)
    part_rows:    [nlist, cap] int32 device-corpus row ids; -1 = padding
    """

    centroids: jax.Array
    centroid_sq: jax.Array
    parts: jax.Array
    part_scales: jax.Array
    part_sq: jax.Array
    part_rows: jax.Array


def _prep_queries(queries: jax.Array, metric: str) -> jax.Array:
    queries = queries.astype(jnp.float32)
    if metric == sim.COSINE:
        qn = jnp.linalg.norm(queries, axis=-1, keepdims=True)
        queries = queries / jnp.maximum(qn, 1e-30)
    return queries


def _route_impl(queries: jax.Array, ivf: IVFPartitions, nprobe: int,
                metric: str = sim.COSINE):
    dots = jax.lax.dot_general(
        queries, ivf.centroids.astype(jnp.float32),
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
    if metric == sim.L2_NORM:
        scores = sim.l2_raw_from_dots(dots, queries, ivf.centroid_sq)
    else:
        scores = dots
    vals, ids = jax.lax.top_k(scores, nprobe)
    return ids.astype(jnp.int32), vals


def _grid_ivf(statics, sigs) -> bool:
    """Bucketed query count, and nprobe on the pow-2 ladder (or the full
    partition count): k is a corpus-tuned constant, but nprobe is widened
    per request by the `num_candidates` knob — the router snaps that
    widening to pow-2 rungs so the request stream can't churn the grid,
    and this predicate is what catches a caller that forgets to."""
    if not dispatch.is_query_bucket(sigs[0][0][0]):
        return False
    nlist = sigs[1][0][0]                   # centroids: [nlist, D]
    npro = statics.get("nprobe")
    if npro is None:                        # score_probes: [Q, nprobe] ids
        npro = sigs[-1][0][1]
    npro = int(npro)
    return npro == int(nlist) or (npro >= 1 and npro & (npro - 1) == 0)


dispatch.DISPATCH.register("ivf.route", _route_impl,
                           static_argnames=("nprobe", "metric"),
                           grid_check=_grid_ivf)


def route(queries: jax.Array, ivf: IVFPartitions, nprobe: int,
          metric: str = sim.COSINE):
    """Centroid routing: [Q, D] queries → ([Q, nprobe] partition ids,
    [Q, nprobe] centroid scores). Queries must be metric-prepped."""
    return dispatch.call("ivf.route", queries, ivf, nprobe=nprobe,
                         metric=metric)


def _score_probes_impl(queries: jax.Array, ivf: IVFPartitions,
                       probe_ids: jax.Array, k: int,
                       metric: str = sim.COSINE, precision: str = "bf16"):
    q = queries.astype(jnp.float32)
    nq = q.shape[0]
    mm_dtype = jnp.float32 if precision == "f32" else jnp.bfloat16
    init = (jnp.full((nq, k), NEG_INF, dtype=jnp.float32),
            jnp.full((nq, k), -1, dtype=jnp.int32))

    qbits = None
    if ivf.parts.dtype == jnp.uint32:
        qbits = quant_codec.pack_sign_bits_jnp(q)

    def body(carry, pid):
        best_s, best_i = carry
        # block take: whole [cap, D] tiles per query, no row gathers
        block = jnp.take(ivf.parts, pid, axis=0)        # [Q, cap, D]
        rows = jnp.take(ivf.part_rows, pid, axis=0)     # [Q, cap]
        if ivf.parts.dtype == jnp.uint8:
            # int4 packed nibbles: two half-width plane einsums, then
            # per-row de-scale (the codec's one bit layout)
            dots = quant_codec.int4_blocked_dots_jnp(q, block, mm_dtype)
            dots = dots * jnp.take(ivf.part_scales, pid, axis=0)
        elif ivf.parts.dtype == jnp.uint32:
            # binary sign bits: blocked XOR+popcount pseudo-dots
            dots = quant_codec.hamming_pseudo_dots_blocked_jnp(qbits, block)
        else:
            dots = jnp.einsum(
                "qd,qcd->qc", q.astype(mm_dtype), block.astype(mm_dtype),
                preferred_element_type=jnp.float32)
            if ivf.parts.dtype == jnp.int8:
                dots = dots * jnp.take(ivf.part_scales, pid, axis=0)
        if metric == sim.L2_NORM:
            part_sq = jnp.take(ivf.part_sq, pid, axis=0)
            q_sq = jnp.sum(q * q, axis=-1, keepdims=True)
            s = 2.0 * dots - q_sq - part_sq
        else:
            s = dots
        s = jnp.where(rows >= 0, s, NEG_INF)
        cat_s = jnp.concatenate([best_s, s], axis=1)
        cat_i = jnp.concatenate([best_i, rows], axis=1)
        vals, pos = jax.lax.top_k(cat_s, k)
        return (vals, jnp.take_along_axis(cat_i, pos, axis=1)), None

    (best_s, best_i), _ = jax.lax.scan(body, init, probe_ids.T)
    return best_s, best_i


dispatch.DISPATCH.register("ivf.score_probes", _score_probes_impl,
                           static_argnames=("k", "metric", "precision"),
                           grid_check=_grid_ivf)


def score_probes(queries: jax.Array, ivf: IVFPartitions,
                 probe_ids: jax.Array, k: int, metric: str = sim.COSINE,
                 precision: str = "bf16"):
    """Score the probed partitions and merge a global top-k.

    queries:   [Q, D] metric-prepped
    probe_ids: [Q, nprobe] int32 partition ids from `route`
    Returns (scores [Q, k] raw similarity, rows [Q, k] int32 device-corpus
    row ids). Empty slots come back as NEG_INF / row -1 — same contract as
    `ops/knn.knn_search` padding.
    """
    return dispatch.call("ivf.score_probes", queries, ivf, probe_ids,
                         k=k, metric=metric, precision=precision)


def ivf_search(queries: jax.Array, ivf: IVFPartitions, k: int,
               nprobe: int, metric: str = sim.COSINE,
               precision: str = "bf16"):
    """Fused route + score convenience entry (two device dispatches; the
    serving router calls the stages itself to time them separately)."""
    nprobe = min(nprobe, ivf.centroids.shape[0])
    q = _prep_queries(queries, metric)
    probe_ids, _ = route(q, ivf, nprobe, metric=metric)
    return score_probes(q, ivf, probe_ids, k, metric=metric,
                        precision=precision)
