"""Binned-reduction Pallas kNN: the peak-throughput path.

The TPU-KNN recipe (Chern et al., "TPU-KNN: K Nearest Neighbor Search at
Peak FLOP/s", 2022 — PAPERS.md pattern): instead of exact top-k inside the
scan, keep only the max of every BIN_SIZE-column bin — one packed VPU
reduction per tile, fully fused behind the MXU matmul in VMEM — then one
small `lax.top_k` over the [Q, n_bins] candidates. A bin can hold at most
one of the true top-k, so recall@k ≈ 1 - C(k,2)/n_bins (≈0.997 for k=10,
2048 bins over 1M docs); BASELINE's gate is recall@10 ≥ 0.95.

Score+index travel together through the reduction by packing the bin-local
chunk index into the low mantissa bits of the (positively-shifted) f32
score — max over the packed int32 is simultaneously argmax. The chunk-index
pattern (column j belongs to chunk j // 128) is a precomputed [1, BLOCK_N]
input OR-ed in with ONE full-array pass, leaving the 64-deep reduction a
pure `maximum` chain — measured ~2x the per-chunk mask-and-or formulation
on v5e (the reduction is the VPU-bound tail behind the MXU matmul).

int8 corpora run the matmul ON the int8 MXU path (dot_general s8xs8→s32,
~2x bf16 peak on v5e) with per-query and per-row dequant scales applied to
the [Q, BINS] score tile — the corpus is never upcast, so HBM traffic
halves vs bf16.

Grid: one step per corpus tile of BLOCK_N rows; each step writes its
(Q, BINS_PER_TILE) packed maxima to its own output column block, so there is
no cross-step carry at all.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from elasticsearch_tpu.ops import dispatch
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.knn import Corpus, _prep_queries

BLOCK_N = 8192
BIN_SIZE = 64
BINS_PER_TILE = BLOCK_N // BIN_SIZE   # 128 — one aligned lane tile
IDX_BITS = 6                          # log2(BIN_SIZE)
MASK = ~((1 << IDX_BITS) - 1)
# cosine scores live in [-1, 1]; dot products are clamped into this window
SHIFT = 4.0
CLAMP = 3.0


def default_interpret() -> bool:
    """Mosaic compiles only on TPU-class backends; everywhere else the
    kernel must run in interpret mode or `pallas_call` raises "Only
    interpret mode is supported on CPU backend" (the r06
    run_north_star_10m_int8 CPU-capture failure). Every public entry
    resolves `interpret=None` through this probe."""
    from elasticsearch_tpu.ops import dispatch
    return not dispatch.is_accelerator_backend()


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return default_interpret() if interpret is None else bool(interpret)


def _reduce_packed(p, out_ref):
    """64-deep pure-max chain over lane-aligned [Q, 128] chunks. Mosaic
    cannot lane-split reshapes, but elementwise max of aligned static
    slices is native VPU."""
    acc = p[:, 0:BINS_PER_TILE]
    for t in range(1, BIN_SIZE):
        acc = jnp.maximum(acc, p[:, t * BINS_PER_TILE:(t + 1) * BINS_PER_TILE])
    out_ref[:] = acc


def _make_kernel(clamp: bool):
    def _kernel(q_ref, c_ref, v_ref, t_ref, out_ref):
        """v_ref: {0,1} validity row; t_ref: precomputed chunk-index pattern
        (j // 128 per column). Shift positive so IEEE ordering == integer
        ordering; invalid (padding) columns multiply to 0 and never win."""
        scores = jax.lax.dot_general(
            q_ref[:], c_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if clamp:
            scores = jnp.clip(scores, -CLAMP, CLAMP)
        s = (scores + SHIFT) * v_ref[:]
        p = (jax.lax.bitcast_convert_type(s, jnp.int32) & MASK) | t_ref[:]
        _reduce_packed(p, out_ref)

    return _kernel


def _int8_kernel(q_ref, c_ref, qs_ref, vs_ref, t_ref, out_ref):
    """int8 MXU path: s8 x s8 -> s32 matmul, dequant with per-query scale
    (qs_ref [Q, 1]) and per-row scale pre-multiplied into vs_ref
    ([1, BLOCK_N] = row_scale * validity, so padding still zeroes out)."""
    dots = jax.lax.dot_general(
        q_ref[:], c_ref[:], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    s = dots.astype(jnp.float32) * qs_ref[:]
    s = jnp.clip(s * vs_ref[:] + SHIFT * jnp.minimum(vs_ref[:] * 1e30, 1.0),
                 0.0, SHIFT + CLAMP)
    p = (jax.lax.bitcast_convert_type(s, jnp.int32) & MASK) | t_ref[:]
    _reduce_packed(p, out_ref)


_KERNEL_CLAMPED = _make_kernel(clamp=True)
_KERNEL_COSINE = _make_kernel(clamp=False)


def _bf16x2(x):
    """Split f32 into (hi, lo) bf16 parts with hi + lo ≈ x to ~2^-16
    relative — two full-rate bf16 MXU passes recover near-f32 dot
    precision (the classic bf16x2 trick) without the ~6-pass cost of a
    Precision.HIGHEST f32 matmul on TPU."""
    xf = x.astype(jnp.float32)
    hi = xf.astype(jnp.bfloat16)
    lo = (xf - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _rescore_scores(q, corpus: Corpus, gather):
    """Near-exact candidate scores [Q, C] for a gathered candidate set.

    `gather(arr)` maps a corpus-aligned array ([N_pad, D] or [N_pad]) to
    its candidate gather ([Q, C, D] / [Q, C]).

    Precision story — this is what makes the "rescoring may only help"
    invariant hold (base picks ⊆ candidate set, and a near-exact
    re-ranking of a superset can only match or beat the base): the query
    is bf16x2-split (error ~2^-16, vs the kernel's int8/bf16-rounded
    query), int8 candidate values in [-127, 127] are EXACT in bf16 so the
    MXU passes introduce no candidate-side error, per-row scales are
    applied to the [Q, C] scores in f32, and the optional residual level
    cuts the remaining int8 quantization error to ~1/127² of max|row|.
    f32-stored corpora split candidates bf16x2 as well (4 passes).
    Candidates stay bf16 end-to-end, so gather bytes are half an f32
    reconstruction.
    """
    q_hi, q_lo = _bf16x2(q)

    def dot(c):
        kw = dict(preferred_element_type=jnp.float32)
        return (jnp.einsum("qd,qcd->qc", q_hi, c, **kw)
                + jnp.einsum("qd,qcd->qc", q_lo, c, **kw))

    if corpus.matrix.dtype == jnp.int8:
        s = dot(gather(corpus.matrix).astype(jnp.bfloat16)) \
            * gather(corpus.scales)
        if corpus.residual is not None:
            s = s + dot(gather(corpus.residual).astype(jnp.bfloat16)) \
                * gather(corpus.residual_scales)
        return s
    cand = gather(corpus.matrix)
    if cand.dtype == jnp.bfloat16:
        return dot(cand)
    c_hi, c_lo = _bf16x2(cand)
    return dot(c_hi) + dot(c_lo)


def _row_gather(rows):
    """gather() over explicit row ids [Q, C]."""
    return lambda arr: arr[rows]


def _bin_gather(tile_idx, lane_idx, nq, b, d):
    """gather() over whole [BIN_SIZE]-row bins (coarse block transfers,
    far cheaper on HBM than row-level gathers). tile_idx/lane_idx: [Q, B]
    bin coordinates; gathered shapes flatten to [Q, B*BIN_SIZE(, D)]."""
    def g(arr):
        n_pad = arr.shape[0]
        n_tiles = n_pad // BLOCK_N
        if arr.ndim == 2:
            r = arr.reshape(n_tiles, BIN_SIZE, BINS_PER_TILE, d)
            return r[tile_idx, :, lane_idx, :].reshape(nq, b * BIN_SIZE, d)
        r = arr.reshape(n_tiles, BIN_SIZE, BINS_PER_TILE)
        return r[tile_idx, :, lane_idx].reshape(nq, b * BIN_SIZE)
    return g


def _decode(packed, k):
    """Packed [Q, n_tiles*BPT] int32 -> (scores [Q,k], global ids [Q,k]).

    Column layout: global id = tile_base + t*BINS_PER_TILE + bin_lane,
    where t is the packed chunk index and bin_lane the output column
    within its tile."""
    ncols = packed.shape[1]
    cols = jnp.arange(ncols, dtype=jnp.int32)[None, :]
    tile_base = (cols // BINS_PER_TILE) * BLOCK_N
    bin_lane = cols % BINS_PER_TILE
    t = packed & ((1 << IDX_BITS) - 1)
    cand_s = jax.lax.bitcast_convert_type(
        packed & jnp.int32(MASK), jnp.float32) - SHIFT
    cand_i = tile_base + t * BINS_PER_TILE + bin_lane
    vals, pos = jax.lax.top_k(cand_s, k)
    return vals, jnp.take_along_axis(cand_i, pos, axis=1)


def _tile_patterns(n_pad: int, num_valid) -> tuple:
    valid = (jnp.arange(n_pad, dtype=jnp.int32)
             < num_valid).astype(jnp.float32).reshape(1, n_pad)
    tpat = jnp.broadcast_to(
        (jnp.arange(BLOCK_N, dtype=jnp.int32)
         // BINS_PER_TILE).reshape(1, BLOCK_N),
        (1, BLOCK_N))
    return valid, tpat


def _binned_impl(queries, corpus, k: int, metric: str, interpret: bool):
    packed, _q = _binned_packed(queries, corpus, metric, interpret)
    return _decode(packed, k)


def _grid_binned(statics, sigs) -> bool:
    return (dispatch.is_query_bucket(sigs[0][0][0])
            and dispatch.in_k_grid(int(statics["k"]),
                                   limit=sigs[1][0][0]))


dispatch.DISPATCH.register("knn.binned", _binned_impl,
                           static_argnames=("k", "metric", "interpret"),
                           grid_check=_grid_binned)


def binned_knn_search(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    interpret: Optional[bool] = None,
):
    """Approximate (recall ≈ 1 - C(k,2)·BIN_SIZE/N) top-k.

    Supports dot-metric corpora (cosine pre-normalized / dot_product) in
    bf16/f32 or int8 storage; callers route l2 / filtered / tiny corpora
    to the exact XLA path. Returns (raw_scores [Q, k], ids [Q, k]).
    interpret=None auto-detects (interpret mode off TPU backends).
    """
    return dispatch.call("knn.binned", queries, corpus, k=k, metric=metric,
                         interpret=_resolve_interpret(interpret))


def _rescored_impl(queries, corpus, k: int, metric: str,
                   rescore_bins: int, interpret: bool):
    packed, q = _binned_packed(queries, corpus, metric, interpret)
    nq, ncols = packed.shape
    cols = jnp.arange(ncols, dtype=jnp.int32)[None, :]
    bin_base = (cols // BINS_PER_TILE) * BLOCK_N + cols % BINS_PER_TILE
    cand_s = jax.lax.bitcast_convert_type(
        packed & jnp.int32(MASK), jnp.float32) - SHIFT
    r = min(rescore_bins, ncols)
    _, bin_pos = jax.lax.top_k(cand_s, r)                       # [Q, R]
    base = jnp.take_along_axis(
        jnp.broadcast_to(bin_base, (nq, ncols)), bin_pos, axis=1)
    # a bin's rows stride by BINS_PER_TILE within its tile
    d = corpus.matrix.shape[1]
    tile_idx = base // BLOCK_N                                  # [Q, R]
    lane_idx = base % BLOCK_N                                   # bin lane
    row_ids = base[:, :, None] + (
        jnp.arange(BIN_SIZE, dtype=jnp.int32)
        * BINS_PER_TILE)[None, None, :]
    flat_ids = row_ids.reshape(nq, r * BIN_SIZE)                # [Q, C]
    # the query stays UNQUANTIZED here (the kernel's main pass quantizes
    # it to int8): removing the query-side quantization error is where
    # the recall headroom comes from (see _rescore_scores)
    scores = _rescore_scores(
        q, corpus, _bin_gather(tile_idx, lane_idx, nq, r, d))
    valid = flat_ids < corpus.num_valid
    scores = jnp.where(valid, scores, -jnp.inf)
    vals, pos = jax.lax.top_k(scores, k)
    return vals, jnp.take_along_axis(flat_ids, pos, axis=1)


dispatch.DISPATCH.register(
    "knn.binned_rescored", _rescored_impl,
    static_argnames=("k", "metric", "rescore_bins", "interpret"),
    grid_check=_grid_binned)


def binned_knn_search_rescored(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    rescore_bins: int = 16,
    interpret: Optional[bool] = None,
):
    """Binned pass + re-scoring of the top bins' member rows with the
    UNQUANTIZED query.

    The binned kernel keeps one candidate per 64-row bin and (for int8
    corpora) quantizes the query; both cost recall. The top
    `rescore_bins` bins per query re-score all their member rows with
    the full-precision query (bin gather + bf16 einsum). Measured on
    v5e: +0.007 recall@10 on clustered 1M x 768 int8 at ~6 ms/batch-256
    (corpus-size independent, gather-bound) — worthwhile headroom when
    the recall gate is tight, a real tax on small corpora."""
    return dispatch.call("knn.binned_rescored", queries, corpus, k=k,
                         metric=metric, rescore_bins=rescore_bins,
                         interpret=_resolve_interpret(interpret))


def _rescored_packed_impl(queries, corpus, k: int, metric: str,
                          rescore_candidates: int, interpret: bool):
    packed, q = _binned_packed(queries, corpus, metric, interpret)
    nq, ncols = packed.shape
    cand_s = jax.lax.bitcast_convert_type(
        packed & jnp.int32(MASK), jnp.float32) - SHIFT
    c = min(rescore_candidates, ncols)
    _, pos = jax.lax.top_k(cand_s, c)                        # [Q, C] cols
    sel = jnp.take_along_axis(packed, pos, axis=1)
    tile_base = (pos // BINS_PER_TILE) * BLOCK_N
    lane = pos % BINS_PER_TILE
    t = sel & ((1 << IDX_BITS) - 1)
    rows = tile_base + t * BINS_PER_TILE + lane              # [Q, C]
    scores = _rescore_scores(q, corpus, _row_gather(rows))
    valid = rows < corpus.num_valid
    scores = jnp.where(valid, scores, -jnp.inf)
    vals, p2 = jax.lax.top_k(scores, k)
    return vals, jnp.take_along_axis(rows, p2, axis=1)


dispatch.DISPATCH.register(
    "knn.binned_rescored_packed", _rescored_packed_impl,
    static_argnames=("k", "metric", "rescore_candidates", "interpret"),
    grid_check=_grid_binned)


def binned_knn_search_rescored_packed(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    rescore_candidates: int = 128,
    interpret: Optional[bool] = None,
):
    """Binned pass + re-scoring of the top PACKED candidates with the
    unquantized query.

    Unlike `binned_knn_search_rescored` (which re-reads whole 64-row bins,
    ~200 MB/batch of gathers), this reuses the exact winner row each packed
    column already identifies: the top `rescore_candidates` columns decode
    to row ids, and only those rows ([Q, C, D], ~25 MB/batch at C=128) are
    re-scored in bf16. Removes the query-side int8 quantization error at a
    few percent of the bin-rescore's bandwidth; bin-collision loss (second
    winner inside one bin) stays, so the ceiling is between the base and
    bin-rescored variants."""
    return dispatch.call("knn.binned_rescored_packed", queries, corpus,
                         k=k, metric=metric,
                         rescore_candidates=rescore_candidates,
                         interpret=_resolve_interpret(interpret))


def _rescored_hybrid_impl(queries, corpus, k: int, metric: str,
                          rescore_bins: int, rescore_candidates: int,
                          interpret: bool):
    packed, q = _binned_packed(queries, corpus, metric, interpret)
    nq, ncols = packed.shape
    cand_s = jax.lax.bitcast_convert_type(
        packed & jnp.int32(MASK), jnp.float32) - SHIFT

    d = corpus.matrix.shape[1]
    cols_all = jnp.arange(ncols, dtype=jnp.int32)[None, :]
    bin_base_all = (cols_all // BINS_PER_TILE) * BLOCK_N \
        + cols_all % BINS_PER_TILE

    # whole-bin members for the top rescore_bins bins
    b = min(rescore_bins, ncols)
    _, bin_pos = jax.lax.top_k(cand_s, b)
    base = jnp.take_along_axis(
        jnp.broadcast_to(bin_base_all, (nq, ncols)), bin_pos, axis=1)
    tile_idx = base // BLOCK_N
    lane_idx = base % BLOCK_N
    bin_rows = (base[:, :, None]
                + (jnp.arange(BIN_SIZE, dtype=jnp.int32)
                   * BINS_PER_TILE)[None, None, :]).reshape(nq, b * BIN_SIZE)
    bin_scores = _rescore_scores(
        q, corpus, _bin_gather(tile_idx, lane_idx, nq, b, d))

    # packed winner rows beyond those bins
    c = min(rescore_candidates, ncols)
    _, pos = jax.lax.top_k(cand_s, c)
    sel = jnp.take_along_axis(packed, pos, axis=1)
    tb = (pos // BINS_PER_TILE) * BLOCK_N
    lane = pos % BINS_PER_TILE
    t = sel & ((1 << IDX_BITS) - 1)
    pk_rows = tb + t * BINS_PER_TILE + lane
    pk_scores = _rescore_scores(q, corpus, _row_gather(pk_rows))

    rows = jnp.concatenate([bin_rows, pk_rows], axis=1)
    scores = jnp.concatenate([bin_scores, pk_scores], axis=1)
    valid = rows < corpus.num_valid
    # duplicate rows (a packed winner inside a rescored bin) must not fill
    # two top-k slots: keep the FIRST occurrence
    order_cols = jnp.arange(rows.shape[1], dtype=jnp.int32)[None, :]
    first = rows[:, :, None] == rows[:, None, :]
    dup = (first & (order_cols[:, None, :] < order_cols[:, :, None])).any(2)
    scores = jnp.where(valid & ~dup, scores, -jnp.inf)
    vals, p2 = jax.lax.top_k(scores, k)
    return vals, jnp.take_along_axis(rows, p2, axis=1)


dispatch.DISPATCH.register(
    "knn.binned_rescored_hybrid", _rescored_hybrid_impl,
    static_argnames=("k", "metric", "rescore_bins", "rescore_candidates",
                     "interpret"),
    grid_check=_grid_binned)


def binned_knn_search_rescored_hybrid(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    rescore_bins: int = 4,
    rescore_candidates: int = 128,
    interpret: Optional[bool] = None,
):
    """Binned pass + hybrid re-score: the top few WHOLE bins (recovers
    same-bin collision losses where true neighbors concentrate) plus the
    top packed candidate rows (removes query-quantization error broadly).
    ~1/4 of the 16-bin rescore's gather traffic for most of its recall."""
    return dispatch.call("knn.binned_rescored_hybrid", queries, corpus,
                         k=k, metric=metric, rescore_bins=rescore_bins,
                         rescore_candidates=rescore_candidates,
                         interpret=_resolve_interpret(interpret))


def _binned_packed(queries, corpus, metric, interpret):
    n_pad, d = corpus.matrix.shape
    if n_pad % BLOCK_N != 0:
        raise ValueError(f"corpus rows {n_pad} not divisible by {BLOCK_N}")
    q = _prep_queries(queries, metric)
    nq = q.shape[0]
    n_tiles = n_pad // BLOCK_N
    valid, tpat = _tile_patterns(n_pad, corpus.num_valid)

    if corpus.matrix.dtype == jnp.int8:
        # symmetric per-query quantization (the codec registry's one
        # int8 recipe, in-trace twin); dequant inside the kernel
        from elasticsearch_tpu.quant import codec as quant_codec
        q8, qscale = quant_codec.quantize_queries_int8_jnp(q)
        row_scale_valid = (corpus.scales.reshape(1, n_pad) * valid)
        packed = pl.pallas_call(
            _int8_kernel,
            grid=(n_tiles,),
            in_specs=[
                pl.BlockSpec((nq, d), lambda i: (0, 0)),
                pl.BlockSpec((BLOCK_N, d), lambda i: (i, 0)),
                pl.BlockSpec((nq, 1), lambda i: (0, 0)),
                pl.BlockSpec((1, BLOCK_N), lambda i: (0, i)),
                pl.BlockSpec((1, BLOCK_N), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((nq, BINS_PER_TILE), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct(
                (nq, n_tiles * BINS_PER_TILE), jnp.int32),
            interpret=interpret,
        )(q8, corpus.matrix, qscale.astype(jnp.float32),
          row_scale_valid, tpat)
        return packed, q

    qb = q.astype(jnp.bfloat16)
    mb = corpus.matrix.astype(jnp.bfloat16)
    kernel = _KERNEL_COSINE if metric == sim.COSINE else _KERNEL_CLAMPED
    packed = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((nq, d), lambda i: (0, 0)),
            pl.BlockSpec((BLOCK_N, d), lambda i: (i, 0)),
            pl.BlockSpec((1, BLOCK_N), lambda i: (0, i)),
            pl.BlockSpec((1, BLOCK_N), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nq, BINS_PER_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((nq, n_tiles * BINS_PER_TILE), jnp.int32),
        interpret=interpret,
    )(qb, mb, valid, tpat)
    return packed, q
