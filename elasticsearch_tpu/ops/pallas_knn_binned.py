"""Binned-reduction Pallas kNN: the peak-throughput path.

The TPU-KNN recipe (Chern et al., "TPU-KNN: K Nearest Neighbor Search at
Peak FLOP/s", 2022 — PAPERS.md pattern): instead of exact top-k inside the
scan, keep only the max of every BIN_SIZE-column bin — one packed VPU
reduction per tile, fully fused behind the MXU matmul in VMEM — then one
small `lax.top_k` over the [Q, n_bins] candidates. A bin can hold at most
one of the true top-k, so recall@k ≈ 1 - C(k,2)/n_bins (≈0.997 for k=10,
2048 bins over 1M docs); BASELINE's gate is recall@10 ≥ 0.95.

Score+index travel together through the reduction by packing the bin-local
column index into the low mantissa bits of the (positively-shifted) f32
score — max over the packed int32 is simultaneously argmax.

Grid: one step per corpus tile of BLOCK_N rows; each step writes its
(Q, BINS_PER_TILE) packed maxima to its own output column block, so there is
no cross-step carry at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.knn import Corpus, _prep_queries

BLOCK_N = 8192
BIN_SIZE = 64
BINS_PER_TILE = BLOCK_N // BIN_SIZE   # 128 — one aligned lane tile
IDX_BITS = 6                          # log2(BIN_SIZE)
# cosine scores live in [-1, 1]; dot products are clamped into this window
SHIFT = 4.0
CLAMP = 3.0


def _make_kernel(clamp: bool):
    def _kernel(q_ref, c_ref, v_ref, out_ref):
        """Bins are STRIDED (column j belongs to bin j % 128): the per-bin
        max reduces as 64 elementwise maxes of contiguous lane-aligned
        [Q, 128] chunks — Mosaic cannot lane-split reshapes, but elementwise
        max of aligned slices is native VPU.

        Validity comes in as a precomputed {0,1} row vector sliced per tile
        (one broadcast multiply) instead of a per-tile iota+compare+where —
        this is the hot VPU path, and every saved [Q, BLOCK_N] pass is ~10%
        of kernel time. The clamp is compiled out for cosine, where
        normalization already bounds |score| ≤ ~1."""
        q = q_ref[:]
        c = c_ref[:]
        scores = jax.lax.dot_general(
            q, c, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if clamp:
            scores = jnp.clip(scores, -CLAMP, CLAMP)
        # shift positive so IEEE ordering == integer ordering; invalid
        # (padding) columns multiply to 0 and can never win a bin
        s = (scores + SHIFT) * v_ref[:]
        p = jax.lax.bitcast_convert_type(s, jnp.int32)
        mask = jnp.int32(~((1 << IDX_BITS) - 1))

        def chunk(t):
            # static slice (python unroll): dynamic_slice on values is not
            # lowerable in Mosaic
            piece = p[:, t * BINS_PER_TILE:(t + 1) * BINS_PER_TILE]
            return (piece & mask) | t

        acc = chunk(0)
        for t in range(1, BIN_SIZE):
            acc = jnp.maximum(acc, chunk(t))
        out_ref[:] = acc

    return _kernel


_KERNEL_CLAMPED = _make_kernel(clamp=True)
_KERNEL_COSINE = _make_kernel(clamp=False)


@functools.partial(jax.jit, static_argnames=("k", "metric", "interpret"))
def binned_knn_search(
    queries: jax.Array,
    corpus: Corpus,
    k: int,
    metric: str = sim.COSINE,
    interpret: bool = False,
):
    """Approximate (recall ≈ 1 - C(k,2)·BIN_SIZE/N) top-k.

    Supports dot-metric corpora (cosine pre-normalized / dot_product);
    callers route l2 / filtered / tiny corpora to the exact XLA path.
    Returns (raw_scores [Q, k], ids [Q, k]).
    """
    n_pad, d = corpus.matrix.shape
    if n_pad % BLOCK_N != 0:
        raise ValueError(f"corpus rows {n_pad} not divisible by {BLOCK_N}")
    q = _prep_queries(queries, metric)
    nq = q.shape[0]
    mat = corpus.matrix
    if mat.dtype == jnp.int8:
        mat = mat.astype(jnp.bfloat16) * corpus.scales[:, None].astype(jnp.bfloat16)
    qb = q.astype(jnp.bfloat16)
    mb = mat.astype(jnp.bfloat16)

    n_tiles = n_pad // BLOCK_N
    valid = (jnp.arange(n_pad, dtype=jnp.int32)
             < corpus.num_valid).astype(jnp.float32).reshape(1, n_pad)
    kernel = _KERNEL_COSINE if metric == sim.COSINE else _KERNEL_CLAMPED
    packed = pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((nq, d), lambda i: (0, 0)),
            pl.BlockSpec((BLOCK_N, d), lambda i: (i, 0)),
            pl.BlockSpec((1, BLOCK_N), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((nq, BINS_PER_TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((nq, n_tiles * BINS_PER_TILE), jnp.int32),
        interpret=interpret,
    )(qb, mb, valid)

    # column layout: global id = tile_base + t*BINS_PER_TILE + bin_lane,
    # where t is the packed chunk index and bin_lane the output column
    # within its tile
    ncols = packed.shape[1]
    cols = jnp.arange(ncols, dtype=jnp.int32)[None, :]
    tile_base = (cols // BINS_PER_TILE) * BLOCK_N
    bin_lane = cols % BINS_PER_TILE
    t = packed & ((1 << IDX_BITS) - 1)
    cand_s = jax.lax.bitcast_convert_type(
        packed & jnp.int32(~((1 << IDX_BITS) - 1)), jnp.float32) - SHIFT
    cand_i = tile_base + t * BINS_PER_TILE + bin_lane
    vals, pos = jax.lax.top_k(cand_s, k)
    return vals, jnp.take_along_axis(cand_i, pos, axis=1)
