"""Keystore CLI (reference: `distribution/tools/keystore-cli` —
create/list/add/remove subcommands).

Usage:
    python -m elasticsearch_tpu.keystore_cli create [--path P] [--password]
    python -m elasticsearch_tpu.keystore_cli list   [--path P] [--password]
    python -m elasticsearch_tpu.keystore_cli add NAME [--path P] [--stdin]
    python -m elasticsearch_tpu.keystore_cli remove NAME [--path P]
"""

from __future__ import annotations

import argparse
import getpass
import os
import sys

from elasticsearch_tpu.common.keystore import KeyStore

DEFAULT_PATH = os.environ.get("TPU_SEARCH_KEYSTORE",
                              "config/tpu_search.keystore")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="keystore_cli")
    parser.add_argument("command",
                        choices=["create", "list", "add", "remove"])
    parser.add_argument("name", nargs="?")
    parser.add_argument("--data", default=None,
                        help="node data path — the keystore lives at "
                             "<data>/config/tpu_search.keystore, where the "
                             "node looks for it at boot")
    parser.add_argument("--path", default=None,
                        help="explicit keystore file path (overrides --data)")
    parser.add_argument("--password", action="store_true",
                        help="prompt for a keystore passphrase")
    parser.add_argument("--stdin", action="store_true",
                        help="read the secret value from stdin")
    args = parser.parse_args(argv)
    if args.path is None:
        args.path = (os.path.join(args.data, "config", "tpu_search.keystore")
                     if args.data else DEFAULT_PATH)

    password = ""
    if args.password:
        password = getpass.getpass("Keystore password: ")

    if args.command == "create":
        if os.path.exists(args.path):
            print(f"keystore already exists at [{args.path}]",
                  file=sys.stderr)
            return 1
        KeyStore.create(args.path, password)
        print(f"Created keystore [{args.path}]")
        return 0

    ks = KeyStore.load(args.path, password)
    if args.command == "list":
        for name in ks.list():
            print(name)
        return 0
    if not args.name:
        print("setting name required", file=sys.stderr)
        return 1
    if args.command == "add":
        if args.stdin:
            value = sys.stdin.readline().rstrip("\n")
        else:
            value = getpass.getpass(f"Value for [{args.name}]: ")
        ks.set(args.name, value)
        ks.save()
        return 0
    if args.command == "remove":
        ks.remove(args.name)
        ks.save()
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
