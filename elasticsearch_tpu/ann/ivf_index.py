"""IVF partition layout over the stored corpus.

Builds the bucketed, padded-to-tile partition matrices the pruned kernel
(`ops/knn_ivf.py`) scores, from the same host vectors `vectors/store.py`
feeds `ops/knn.build_corpus`:

  * centroids train on device (`ann/kmeans.py`), then rows place into
    capacity-capped buckets: first-choice partition when it has room,
    else the nearest partition that does (displacement). The cap bounds
    the padded tile size — one oversized partition would tax every probe
    of every query — and total capacity (`nlist * cap >= slack * n`)
    guarantees placement;
  * incremental `add` appends into the host mirror of the bucket layout
    and re-uploads lazily at the next search; adds that miss their
    first-choice partition count as displaced, and once displaced + spill
    exceed `retrain_threshold` of the corpus (or the corpus outgrows the
    trained layout) `needs_retrain` flips — the store then rebuilds from
    scratch like any refresh re-sync;
  * int8 storage reuses `ops/quantization.quantize_int8_np` per row;
    sq-norms ride along for l2.

Row ids stored in the layout are *device-corpus rows* (indices into the
flat `Corpus` matrix), so IVF results join the engine's row maps exactly
like exhaustive results do.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from elasticsearch_tpu.ann import kmeans as kmeans_lib
from elasticsearch_tpu.ops import similarity as sim
from elasticsearch_tpu.ops.quantization import quantize_int8_np
from elasticsearch_tpu.quant import codec as quant_codec

# partition capacity is padded to this many rows (f32 sublane tile)
CAP_PAD = 8
# capacity slack over the perfectly-balanced size: bounds both padding
# waste and the displacement rate
DEFAULT_SLACK = 1.5
# corpora below nlist * this never benefit from pruning: stay exhaustive
MIN_ROWS_PER_LIST = 8


def _routing_matrix(centroids: np.ndarray, metric: str) -> np.ndarray:
    """Centroids as the query-time router sees them: unit-normalized for
    cosine (spherical routing: max-dot == nearest-angle), raw otherwise."""
    if metric == sim.COSINE:
        norms = np.linalg.norm(centroids, axis=-1, keepdims=True)
        return centroids / np.maximum(norms, 1e-30)
    return centroids


def _routing_scores(x: np.ndarray, centroids: np.ndarray,
                    metric: str) -> np.ndarray:
    """[n, nlist] bigger-is-better routing scores, same convention as
    `ops/knn_ivf.route` so build-time placement and query-time probing
    agree by construction."""
    dots = x @ centroids.T
    if metric == sim.L2_NORM:
        c_sq = np.einsum("kd,kd->k", centroids, centroids)
        x_sq = np.einsum("nd,nd->n", x, x)
        return 2.0 * dots - x_sq[:, None] - c_sq[None, :]
    return dots


class IVFIndex:
    """Host mirror + device pytree of one field's partition layout."""

    def __init__(self, centroids: np.ndarray, cap: int, metric: str,
                 dtype: str, retrain_threshold: float = 0.2):
        nlist, dims = centroids.shape
        self.metric = metric
        self.dtype = dtype
        self.dims = dims
        self.nlist = nlist
        self.cap = cap
        self.retrain_threshold = float(retrain_threshold)
        self.centroids = _routing_matrix(
            np.asarray(centroids, dtype=np.float32), metric)
        # host mirrors of the bucket layout
        self.part_vecs = np.zeros((nlist, cap, dims), dtype=np.float32)
        self.part_rows = np.full((nlist, cap), -1, dtype=np.int32)
        self.counts = np.zeros(nlist, dtype=np.int64)
        self.trained_on = 0   # corpus size the centroids were trained on
        self.displaced = 0    # rows not in their first-choice partition
        self.spilled = 0      # rows that found no capacity at all
        self._device = None   # lazy IVFPartitions pytree
        # lazy mesh-resident ShardedIVF pytrees, one per mesh the
        # router dispatches on (with dp > 1 the full serving mesh
        # AND each dp-group submesh can carry IVF traffic); bounded
        # by dp + 1 entries, dropped whole on any add()
        self._device_sharded = {}

    # ------------------------------------------------------------- build

    @property
    def total(self) -> int:
        return int(self.counts.sum())

    @property
    def scored_rows_per_probe(self) -> int:
        """Padded rows the kernel scores per probed partition."""
        return self.cap

    def scored_fraction(self, nprobe: int) -> float:
        """Upper bound on the corpus fraction scored per query."""
        if self.total == 0:
            return 1.0
        return min(1.0, nprobe * self.cap / self.total)

    @property
    def needs_retrain(self) -> bool:
        total = self.total
        if total == 0:
            return False
        if self.spilled > 0:
            return True
        if (self.displaced + self.spilled) / total > self.retrain_threshold:
            return True
        # the layout was sized for trained_on rows; growth past the
        # capacity headroom degrades routing even without displacement
        return self.trained_on > 0 and total > 2 * self.trained_on

    def _place(self, vecs: np.ndarray, rows: np.ndarray,
               count_displaced: bool = True) -> None:
        """Greedy capacity-capped placement: first-choice when it has
        room, else nearest-with-room among the top candidates.

        The first-choice pass is vectorized per partition (one slice
        write per bucket); only capacity overflow walks the per-row
        fallback loop — a few % of rows at the default slack."""
        if len(rows) == 0:
            return
        rows = np.asarray(rows, dtype=np.int32)
        scores = _routing_scores(vecs, self.centroids, self.metric)
        first = np.argmin(-scores, axis=1)  # argmax, ties to lowest pid
        order = np.argsort(first, kind="stable")
        bounds = np.searchsorted(first[order], np.arange(self.nlist + 1))
        leftover = []
        for pid in range(self.nlist):
            grp = order[bounds[pid]:bounds[pid + 1]]
            if len(grp) == 0:
                continue
            c = int(self.counts[pid])
            take = grp[: max(0, self.cap - c)]
            if len(take):
                self.part_vecs[pid, c:c + len(take)] = vecs[take]
                self.part_rows[pid, c:c + len(take)] = rows[take]
                self.counts[pid] = c + len(take)
            leftover.extend(grp[len(take):])

        if leftover:
            leftover = np.asarray(leftover)
            n_choices = min(self.nlist, 8)
            sub = scores[leftover]
            choice = np.argpartition(-sub, n_choices - 1,
                                     axis=1)[:, :n_choices] \
                if n_choices < self.nlist else \
                np.tile(np.arange(self.nlist), (len(leftover), 1))
            ordc = np.take_along_axis(sub, choice, axis=1).argsort(axis=1)
            choice = np.take_along_axis(choice, ordc[:, ::-1], axis=1)
            for i, ri in enumerate(leftover):
                placed = False
                for pid in choice[i][1:]:  # [0] is the full first choice
                    c = int(self.counts[pid])
                    if c < self.cap:
                        self.part_vecs[pid, c] = vecs[ri]
                        self.part_rows[pid, c] = rows[ri]
                        self.counts[pid] = c + 1
                        if count_displaced:
                            self.displaced += 1
                        placed = True
                        break
                if not placed:
                    # every candidate bucket is full: fall back to the
                    # emptiest partition anywhere, else record a spill
                    pid = int(np.argmin(self.counts))
                    c = int(self.counts[pid])
                    if c < self.cap:
                        self.part_vecs[pid, c] = vecs[ri]
                        self.part_rows[pid, c] = rows[ri]
                        self.counts[pid] = c + 1
                        if count_displaced:
                            self.displaced += 1
                    else:
                        self.spilled += 1
        self._device = None
        self._device_sharded = {}

    def clone(self) -> "IVFIndex":
        """Deep copy of the layout (trained centroids + bucket mirrors,
        counters included) with the lazy device pytrees RESET. The
        segments merge scheduler extends a clone with the merged delta
        while the original keeps serving — in-place `add` would mutate
        the host mirror a concurrent search is uploading (copy-on-write,
        like every other mid-merge install)."""
        new = IVFIndex.__new__(IVFIndex)
        new.metric = self.metric
        new.dtype = self.dtype
        new.dims = self.dims
        new.nlist = self.nlist
        new.cap = self.cap
        new.retrain_threshold = self.retrain_threshold
        new.centroids = self.centroids        # immutable post-train
        new.part_vecs = self.part_vecs.copy()
        new.part_rows = self.part_rows.copy()
        new.counts = self.counts.copy()
        new.trained_on = self.trained_on
        new.displaced = self.displaced
        new.spilled = self.spilled
        new._device = None
        new._device_sharded = {}
        return new

    def add(self, vecs: np.ndarray, rows: np.ndarray) -> None:
        """Incremental add (post-build refresh delta): place into the host
        mirror; the device pytree refreshes lazily at the next search."""
        vecs = np.asarray(vecs, dtype=np.float32)
        if self.metric == sim.COSINE:
            norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
            vecs = vecs / np.maximum(norms, 1e-30)
        self._place(vecs, np.asarray(rows, dtype=np.int32))

    # ------------------------------------------------------------ device

    def device_partitions(self):
        """The IVFPartitions pytree, uploading the host mirror on first
        use and after any add()."""
        if self._device is not None:
            return self._device
        import jax.numpy as jnp

        from elasticsearch_tpu.ops.knn_ivf import IVFPartitions

        valid = self.part_rows >= 0
        part_sq = np.einsum("kcd,kcd->kc", self.part_vecs, self.part_vecs)
        if self.dtype == "int8":
            flat = self.part_vecs.reshape(-1, self.dims)
            q8, scales = quantize_int8_np(flat)
            parts = jnp.asarray(q8.reshape(self.nlist, self.cap, self.dims))
            part_scales = jnp.asarray(
                np.where(valid, scales.reshape(self.nlist, self.cap), 0.0)
                .astype(np.float32))
        elif self.dtype in quant_codec.PACKED_ENCODINGS:
            # packed rungs (int4 nibbles / binary sign bits): encode the
            # bucketed layout through the codec registry — padding slots
            # zero their scale so the score kernels mask them like int8
            codec = quant_codec.get(self.dtype)
            enc = codec.encode_np(self.part_vecs.reshape(-1, self.dims))
            w = codec.packed_width(self.dims)
            parts = jnp.asarray(enc.data.reshape(self.nlist, self.cap, w))
            part_scales = jnp.asarray(
                np.where(valid, enc.scales.reshape(self.nlist, self.cap),
                         0.0).astype(np.float32))
        else:
            mm = jnp.bfloat16 if self.dtype == "bf16" else jnp.float32
            parts = jnp.asarray(self.part_vecs, dtype=mm)
            part_scales = jnp.asarray(valid.astype(np.float32))
        self._device = IVFPartitions(
            centroids=jnp.asarray(self.centroids),
            centroid_sq=jnp.asarray(
                np.einsum("kd,kd->k", self.centroids, self.centroids)
                .astype(np.float32)),
            parts=parts,
            part_scales=part_scales,
            part_sq=jnp.asarray(part_sq.astype(np.float32)),
            part_rows=jnp.asarray(self.part_rows))
        return self._device

    def device_partitions_sharded(self, mesh):
        """The mesh-sharded pytree (`parallel/sharded_ivf.ShardedIVF`):
        posting lists split over the shard axis by partition id,
        centroids replicated. Cached per layout generation like the
        single-device pytree; invalidated by any add()."""
        cached = self._device_sharded.get(mesh)
        if cached is not None:
            return cached
        from elasticsearch_tpu.parallel.sharded_ivf import (
            build_sharded_partitions)
        sharded = build_sharded_partitions(self, mesh)
        return self._device_sharded.setdefault(mesh, sharded)


def export_layout(index: IVFIndex) -> dict:
    """The trained layout as a corpus-independent dict: centroids +
    shape + counters, NOT the bucket mirrors (those are corpus-sized
    and reconstruct deterministically by re-placing the rows). This is
    the IVF block durable elasticity snapshots — restore re-places
    instead of re-training k-means."""
    return {
        "nlist": int(index.nlist), "cap": int(index.cap),
        "dims": int(index.dims), "metric": index.metric,
        "dtype": index.dtype,
        "retrain_threshold": float(index.retrain_threshold),
        "trained_on": int(index.trained_on),
        # already routing-normalized at train time
        "centroids": np.asarray(index.centroids, dtype=np.float32).copy(),
    }


def layout_compatible(layout: dict, n: int, dims: int, metric: str,
                      dtype: str) -> bool:
    """Can a restored layout serve `n` rows of this field without an
    immediate retrain? Mirrors `needs_retrain`'s growth gate plus the
    hard capacity bound — an incompatible layout falls back to a fresh
    `build_ivf_index` (counted as a train, which is the point of the
    check: never serve from a layout that would spill)."""
    try:
        trained_on = int(layout.get("trained_on", 0))
        return (int(layout["dims"]) == int(dims)
                and layout["metric"] == metric
                and layout["dtype"] == dtype
                and n <= int(layout["nlist"]) * int(layout["cap"])
                and 0 < trained_on and n <= 2 * trained_on)
    except (KeyError, TypeError, ValueError):
        return False


def ivf_from_layout(layout: dict, vectors: np.ndarray,
                    rows: Optional[np.ndarray] = None) -> IVFIndex:
    """Rebuild an IVFIndex from an exported layout WITHOUT re-training:
    the restored centroids route, rows re-place greedily exactly like
    the initial build (same chunking, displacement not counted). With
    the same vectors in the same order this reproduces the layout the
    source trained — restored probes score the same buckets."""
    vectors = np.asarray(vectors, dtype=np.float32)
    n, dims = vectors.shape
    if not layout_compatible(layout, n, dims, layout["metric"],
                             layout["dtype"]):
        raise ValueError("IVF layout incompatible with corpus")
    if rows is None:
        rows = np.arange(n, dtype=np.int32)
    if layout["metric"] == sim.COSINE:
        norms = np.linalg.norm(vectors, axis=-1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-30)
    index = IVFIndex.__new__(IVFIndex)
    index.metric = layout["metric"]
    index.dtype = layout["dtype"]
    index.dims = dims
    index.nlist = int(layout["nlist"])
    index.cap = int(layout["cap"])
    index.retrain_threshold = float(layout["retrain_threshold"])
    index.centroids = np.asarray(layout["centroids"], dtype=np.float32)
    index.part_vecs = np.zeros((index.nlist, index.cap, dims),
                               dtype=np.float32)
    index.part_rows = np.full((index.nlist, index.cap), -1,
                              dtype=np.int32)
    index.counts = np.zeros(index.nlist, dtype=np.int64)
    index.trained_on = 0
    index.displaced = 0
    index.spilled = 0
    index._device = None
    index._device_sharded = {}
    rows = np.asarray(rows, dtype=np.int32)
    chunk = 131_072
    for lo in range(0, n, chunk):
        index._place(vectors[lo:lo + chunk], rows[lo:lo + chunk],
                     count_displaced=False)
    index.trained_on = int(layout.get("trained_on") or n)
    return index


def pick_nlist(n: int, dims: int) -> int:
    """Default partition count: ~sqrt(n) rounded to a power of two, the
    Faiss guidance that balances route cost (nlist·D) against scored rows
    (n/nlist·nprobe·D) — equal at nlist ≈ sqrt(n·nprobe)."""
    if n <= 0:
        return 1
    target = max(1, int(np.sqrt(n)))
    return 1 << max(0, int(round(np.log2(target))))


def build_ivf_index(vectors: np.ndarray, rows: Optional[np.ndarray] = None,
                    *, metric: str = sim.COSINE, nlist: Optional[int] = None,
                    dtype: str = "bf16", seed: int = 0,
                    slack: float = DEFAULT_SLACK,
                    retrain_threshold: float = 0.2,
                    train_iters: int = 8) -> IVFIndex:
    """Train + build the partition layout for one corpus snapshot.

    vectors: [n, d] raw host vectors (cosine normalization happens here,
    matching `ops/knn.build_corpus`).
    rows:    [n] device-corpus row ids these vectors occupy (defaults to
    arange — the store always builds IVF over the same extraction that
    built the flat corpus, so row i of one is row i of the other).
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, dims = vectors.shape
    if rows is None:
        rows = np.arange(n, dtype=np.int32)
    if nlist is None:
        nlist = pick_nlist(n, dims)
    nlist = max(1, min(int(nlist), max(1, n // MIN_ROWS_PER_LIST)))

    if metric == sim.COSINE:
        norms = np.linalg.norm(vectors, axis=-1, keepdims=True)
        vectors = vectors / np.maximum(norms, 1e-30)

    centroids = kmeans_lib.train_kmeans(vectors, nlist, seed=seed,
                                        iters=train_iters)
    cap = int(np.ceil(n / nlist * slack))
    cap = max(CAP_PAD, ((cap + CAP_PAD - 1) // CAP_PAD) * CAP_PAD)
    index = IVFIndex(centroids, cap, metric, dtype,
                     retrain_threshold=retrain_threshold)
    # initial build places into freshly-trained buckets: overflow into a
    # neighbor partition here is layout slack, not drift — don't let it
    # trip the retrain gate the layout was just built with. Chunked so the
    # [chunk, nlist] routing-score matrix stays bounded at corpus scale.
    rows = np.asarray(rows, dtype=np.int32)
    chunk = 131_072
    for lo in range(0, n, chunk):
        index._place(vectors[lo:lo + chunk], rows[lo:lo + chunk],
                     count_displaced=False)
    index.trained_on = n
    return index
