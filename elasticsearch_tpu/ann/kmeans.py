"""On-device mini-batch k-means for IVF partition training.

Trains the `nlist` routing centroids of the `tpu_ivf` engine directly from
the stored corpus, entirely as jit-compiled device programs:

  * k-means++ seeding (Arthur & Vassilvitskii, 2007) over a bounded
    training sample — each next seed is drawn proportional to its squared
    distance from the chosen set, the spread that makes Lloyd converge in
    the handful of iterations we give it;
  * mini-batch Lloyd updates (Sculley, 2010): per-batch assignment is one
    [B, nlist] matmul + argmax, the centroid update a segment-sum with
    per-center decaying learning rates — O(B·nlist·D) per step regardless
    of corpus size;
  * a soft balance penalty: assignment cost adds
    `alpha * mean_d2 * (count_c / expected - 1)` so persistently
    over-full centers repel new members. IVF wants *bounded* partition
    sizes (the padded bucket layout pays for the largest partition), not
    perfectly equal ones, so the hard cap lives in the index build
    (`ivf_index.py`) and this only keeps the tail short.

Everything is deterministic given `seed` — tests and the recall gate rely
on that.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import dispatch


def _kmeans_pp_init_impl(key: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """k-means++ seeding: [n, d] sample → [k, d] initial centroids."""
    n = x.shape[0]
    x_sq = jnp.sum(x * x, axis=-1)

    k0, kloop = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centroids = jnp.zeros((k, x.shape[1]), dtype=x.dtype).at[0].set(x[first])

    def d2_to(c):
        # squared distance via the dot expansion (keeps the MXU in play)
        return jnp.maximum(
            x_sq - 2.0 * (x @ c) + jnp.sum(c * c), 0.0)

    def body(i, carry):
        cents, min_d2, kk = carry
        kk, ksel = jax.random.split(kk)
        # sample ∝ D²(x); log-space categorical avoids a normalize pass
        logits = jnp.log(jnp.maximum(min_d2, 1e-30))
        nxt = jax.random.categorical(ksel, logits)
        cents = cents.at[i].set(x[nxt])
        min_d2 = jnp.minimum(min_d2, d2_to(x[nxt]))
        return cents, min_d2, kk

    centroids, _, _ = jax.lax.fori_loop(
        1, k, body, (centroids, d2_to(x[first]), kloop))
    return centroids


def _assign_blocks_impl(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """Nearest-centroid ids [n] for rows [n, d] (plain L2 assignment —
    for unit-normalized cosine data this equals max-dot routing)."""
    c_sq = jnp.sum(centroids * centroids, axis=-1)
    dots = jax.lax.dot_general(
        x, centroids, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    # argmin ||x-c||² = argmax (x·c - ||c||²/2); ||x||² is constant per row
    return jnp.argmax(dots - 0.5 * c_sq[None, :], axis=-1).astype(jnp.int32)


def _minibatch_epoch_impl(carry, batches, nlist: int, balance_alpha: float):
    """One scan over the stacked mini-batches [S, B, d]."""

    def step(carry, batch):
        cents, counts = carry
        c_sq = jnp.sum(cents * cents, axis=-1)
        dots = jax.lax.dot_general(
            batch, cents, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        score = dots - 0.5 * c_sq[None, :]
        if balance_alpha > 0.0:
            # soft balance: persistently crowded centers cost extra,
            # scaled by the current mean intra-cluster spread so the
            # penalty tracks the data's own distance scale
            expected = jnp.maximum(jnp.sum(counts) / nlist, 1.0)
            mean_d2 = jnp.mean(jnp.maximum(
                jnp.sum(batch * batch, axis=-1)[:, None] - 2.0 * score,
                0.0))
            score = score - (balance_alpha * mean_d2
                             * (counts / expected - 1.0))[None, :]
        assign = jnp.argmax(score, axis=-1)
        one_hot = jax.nn.one_hot(assign, nlist, dtype=jnp.float32)
        batch_counts = jnp.sum(one_hot, axis=0)
        batch_sums = jax.lax.dot_general(
            one_hot, batch, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        new_counts = counts + batch_counts
        # per-center learning rate 1/total_count (Sculley eq. 1): the
        # center is the running mean of every row ever assigned to it
        lr = batch_counts / jnp.maximum(new_counts, 1.0)
        target = batch_sums / jnp.maximum(batch_counts[:, None], 1.0)
        cents = cents + lr[:, None] * (target - cents)
        return (cents, new_counts), None

    return jax.lax.scan(step, carry, batches)[0]


# Training kernels route through the shape-bucketed dispatcher like every
# other device program (tpulint TPU001): training shapes are bounded by
# construction (`sample`/`batch_size` caps), so the AOT cache stays small,
# and the dispatcher's counters make a runaway-retrace regression visible
# in `_nodes/stats indices.dispatch` instead of silent. No grid_check:
# training is an index-build path, not a serving shape — it must never
# trip the strict closed-grid gate.
dispatch.DISPATCH.register("kmeans.pp_init", _kmeans_pp_init_impl,
                           static_argnames=("k",))
dispatch.DISPATCH.register("kmeans.assign", _assign_blocks_impl)
dispatch.DISPATCH.register("kmeans.epoch", _minibatch_epoch_impl,
                           static_argnames=("nlist", "balance_alpha"))


def kmeans_pp_init(key: jax.Array, x: jax.Array, k: int) -> jax.Array:
    """k-means++ seeding: [n, d] sample → [k, d] initial centroids."""
    return dispatch.call("kmeans.pp_init", key, x, k=k)


def assign_blocks(x: jax.Array, centroids: jax.Array) -> jax.Array:
    """Nearest-centroid ids [n] for rows [n, d]."""
    return dispatch.call("kmeans.assign", x, centroids)


def train_kmeans(vectors: np.ndarray, nlist: int, *, iters: int = 8,
                 batch_size: int = 4096, sample: int = 262_144,
                 seed: int = 0, balance_alpha: float = 0.25) -> np.ndarray:
    """Train `nlist` centroids from host vectors; returns [nlist, d] f32.

    The training sample is bounded (`sample` rows) so training cost is
    independent of corpus size; `iters` epochs of mini-batch Lloyd over a
    reshuffled sample each epoch.
    """
    vectors = np.asarray(vectors, dtype=np.float32)
    n, d = vectors.shape
    if nlist < 1:
        raise ValueError(f"nlist must be >= 1, got {nlist}")
    if n < nlist:
        raise ValueError(f"cannot train {nlist} centroids from {n} rows")

    rng = np.random.default_rng(seed)
    n_sample = min(n, max(sample, nlist * 4))
    idx = rng.choice(n, size=n_sample, replace=False) if n_sample < n \
        else np.arange(n)
    x = jnp.asarray(vectors[idx])

    key = jax.random.PRNGKey(seed)
    k_init, k_shuf = jax.random.split(key)
    seed_rows = min(n_sample, max(nlist * 32, 4096))
    centroids = kmeans_pp_init(k_init, x[:seed_rows], nlist)

    batch_size = min(batch_size, n_sample)
    steps = n_sample // batch_size
    counts = jnp.zeros((nlist,), dtype=jnp.float32)
    for _ in range(max(iters, 1)):
        k_shuf, k_epoch = jax.random.split(k_shuf)
        perm = jax.random.permutation(k_epoch, n_sample)[: steps * batch_size]
        batches = x[perm].reshape(steps, batch_size, d)
        centroids, counts = dispatch.call(
            "kmeans.epoch", (centroids, counts), batches,
            nlist=nlist, balance_alpha=balance_alpha)
    return np.asarray(centroids, dtype=np.float32)
