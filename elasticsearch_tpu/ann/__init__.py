"""Partitioned ANN engine (`index.knn.engine: tpu_ivf`).

IVF in the style of Faiss IVF-Flat (Johnson et al., 2019) and ScaNN's
partitioned search (Guo et al., 2020), re-shaped for the MXU: a tiny
centroid matmul routes each query to `nprobe` of `nlist` k-means
partitions, then a dense per-partition matmul + `lax.top_k` scores only
~`nprobe/nlist` of the corpus — trading a small, *measured* recall budget
for an order-of-magnitude FLOP/HBM reduction over the exhaustive scan in
`ops/knn.py`.

Layout is gather-free at the row level: partitions are stored bucketed and
padded to a common tile-aligned capacity (`[nlist, cap, D]`), so pruned
scoring is block `take` + batched matmul — no per-row gathers ever touch
HBM.

  kmeans.py     on-device mini-batch k-means (k-means++ seeding, soft
                balance penalty) that trains the `nlist` centroids
  ivf_index.py  partition layout over the stored corpus: capped bucketed
                build, incremental add with displacement/spill accounting
                and a retrain threshold, int8 via ops/quantization
  router.py     query-time engine: centroid routing, nprobe selection
                (`"auto"` tunes against a held-out sample to a recall
                target), per-phase route/score/merge timings, and the
                exhaustive-fallback escape hatch

The device kernel itself lives in `ops/knn_ivf.py` beside its exhaustive
sibling `ops/knn.py`.
"""

from elasticsearch_tpu.ann.ivf_index import IVFIndex, build_ivf_index
from elasticsearch_tpu.ann.router import IVFRouter

__all__ = ["IVFIndex", "IVFRouter", "build_ivf_index"]
